"""Round-3 advisor findings, closed with a test each:

(a) AdminServer refuses non-loopback binds without a shared secret, and
    a configured secret gates every command (service/admin.py).
(b) AuthCache.get cannot re-insert a verdict computed before
    invalidate_all() — generation counter (service/auth.py).
(c) nodetool truncatehints deletes hint files under the HintsService
    lock (cluster/hints.py truncate, tools/nodetool.py).
"""
import threading

import pytest

from cassandra_tpu.service.auth import AuthCache


# ---------------------------------------------------------- (a) admin --

def test_admin_refuses_wide_bind_without_secret():
    from cassandra_tpu.service.admin import AdminServer
    with pytest.raises(ValueError, match="secret"):
        AdminServer(node=None, host="0.0.0.0", port=0)


def test_admin_secret_gates_commands(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.service.admin import AdminServer, admin_call
    c = LocalCluster(1, str(tmp_path), rf=1)
    srv = AdminServer(c.nodes[0], secret="s3kr1t")
    try:
        with pytest.raises(RuntimeError, match="admin secret"):
            admin_call("127.0.0.1", srv.port, "version")
        with pytest.raises(RuntimeError, match="admin secret"):
            admin_call("127.0.0.1", srv.port, "version", secret="wrong")
        out = admin_call("127.0.0.1", srv.port, "version",
                         secret="s3kr1t")
        assert out["release"].startswith("cassandra-tpu")
    finally:
        srv.close()
        c.shutdown()


# ------------------------------------------------------ (b) auth cache --

def test_authcache_invalidate_beats_inflight_load():
    cache = AuthCache(validity=60.0)
    loaded = threading.Event()
    release = threading.Event()
    result = {}

    def slow_loader():
        loaded.set()
        release.wait(5.0)
        return "STALE-VERDICT"

    t = threading.Thread(
        target=lambda: result.setdefault(
            "v", cache.get("k", slow_loader)))
    t.start()
    assert loaded.wait(5.0)
    # role/grant mutation lands while the verdict is mid-computation
    cache.invalidate_all()
    release.set()
    t.join(5.0)
    assert result["v"] == "STALE-VERDICT"   # caller still gets its value
    # ...but the stale verdict must NOT have been cached: a fresh get
    # re-loads instead of serving the pre-invalidation verdict
    assert cache.get("k", lambda: "FRESH") == "FRESH"


def test_authcache_normal_hit_still_caches():
    cache = AuthCache(validity=60.0)
    assert cache.get("k", lambda: "v1") == "v1"
    assert cache.get("k", lambda: "v2") == "v1"   # served from cache


# --------------------------------------------------- (c) truncatehints --

def test_truncatehints_under_service_lock(tmp_path):
    from cassandra_tpu.cluster.hints import HintsService
    from cassandra_tpu.cluster.ring import Endpoint
    from cassandra_tpu.storage.mutation import Mutation
    from cassandra_tpu.tools import nodetool

    svc = HintsService(str(tmp_path))
    a, b = Endpoint("nodeA"), Endpoint("nodeB")
    import uuid
    m = Mutation(uuid.uuid4(), b"pk")
    m.add(b"", 0, b"", b"v", ts=1)
    svc.store(a, m)
    svc.store(b, m)
    assert svc.has_hints(a) and svc.has_hints(b)

    class FakeNode:
        hints = svc

    out = nodetool.truncatehints(FakeNode(), endpoint="nodeA")
    assert out == {"truncated_files": 1}
    assert not svc.has_hints(a) and svc.has_hints(b)
    # holding the service lock blocks the truncate until released —
    # i.e. it cannot race a store()/dispatch() critical section
    done = threading.Event()
    with svc._lock:
        t = threading.Thread(target=lambda: (svc.truncate(), done.set()))
        t.start()
        assert not done.wait(0.2)
    t.join(5.0)
    assert done.is_set()
    assert not svc.has_hints(b)
