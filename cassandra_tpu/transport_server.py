"""CQL native protocol server — the client-facing socket endpoint.

Reference counterpart: transport/Server.java + Dispatcher.java:104 +
CQLMessageHandler.java (the v4/v5 binary protocol on port 9042, spec:
doc/native_protocol_v4.spec in the reference tree).

Implemented subset (protocol v4 framing):
  STARTUP -> READY (or AUTHENTICATE -> AUTH_RESPONSE -> AUTH_SUCCESS
  with PasswordAuthenticator semantics when auth is enabled)
  OPTIONS -> SUPPORTED
  QUERY / PREPARE / EXECUTE -> RESULT (Void / Rows / SetKeyspace /
  Prepared / SchemaChange) or ERROR
  paging: page_size + paging_state flags round-trip
  bound values: wire bytes deserialize against the target column's type
  at bind time (WireValue marker consumed by cql.execution.bind_term)

Result metadata declares types inferred from the Python values with a
matching encoding, so any decoder that honours the metadata reads the
rows correctly.
"""
from __future__ import annotations

import struct
import threading
import socket

from .cql.processor import QueryProcessor

VERSION_REQ = 0x04
VERSION_RSP = 0x84

OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002
RESULT_SET_KEYSPACE = 0x0003
RESULT_PREPARED = 0x0004
RESULT_SCHEMA_CHANGE = 0x0005

ERR_SERVER = 0x0000
ERR_PROTOCOL = 0x000A
ERR_BAD_CREDENTIALS = 0x0100
ERR_INVALID = 0x2200


class WireValue(bytes):
    """A bound value still in wire encoding; bind_term deserializes it
    against the statement's target type."""


# --------------------------------------------------------- body primitives --

def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">I", len(b)) + b


def _bytes(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _read_string(buf: bytes, pos: int) -> tuple[str, int]:
    (n,) = struct.unpack_from(">H", buf, pos)
    return buf[pos + 2:pos + 2 + n].decode(), pos + 2 + n


def _read_long_string(buf: bytes, pos: int) -> tuple[str, int]:
    (n,) = struct.unpack_from(">I", buf, pos)
    return buf[pos + 4:pos + 4 + n].decode(), pos + 4 + n


def _read_bytes(buf: bytes, pos: int):
    (n,) = struct.unpack_from(">i", buf, pos)
    pos += 4
    if n < 0:
        return None, pos
    return bytes(buf[pos:pos + n]), pos + n


def _read_string_map(buf: bytes, pos: int) -> tuple[dict, int]:
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    out = {}
    for _ in range(n):
        k, pos = _read_string(buf, pos)
        v, pos = _read_string(buf, pos)
        out[k] = v
    return out, pos


# ------------------------------------------------------- result encoding ---

def _infer_type(v):
    """(option_id, encoder) inferred from the Python value — metadata and
    encoding stay consistent with each other."""
    import datetime
    import uuid as uuid_mod
    if isinstance(v, bool):
        return 0x04, lambda x: b"\x01" if x else b"\x00"
    if isinstance(v, int):
        return 0x02, lambda x: struct.pack(">q", x)       # bigint
    if isinstance(v, float):
        return 0x07, lambda x: struct.pack(">d", x)       # double
    if isinstance(v, uuid_mod.UUID):
        return 0x0C, lambda x: x.bytes
    if isinstance(v, bytes):
        return 0x03, lambda x: x
    if isinstance(v, datetime.datetime):
        return 0x0B, lambda x: struct.pack(
            ">q", int(x.timestamp() * 1000))
    return 0x0D, lambda x: str(x).encode()                # varchar


def _encode_rows(rs) -> bytes:
    names = rs.column_names
    rows = rs.rows
    # per-column type from the first non-null value (varchar fallback)
    col_types = []
    for i in range(len(names)):
        sample = next((r[i] for r in rows if r[i] is not None), None)
        col_types.append(_infer_type(sample))
    flags = 0x0001                       # global table spec
    paging = getattr(rs, "paging_state", None)
    if paging is not None:
        flags |= 0x0002                  # has_more_pages
    body = bytearray()
    body += struct.pack(">i", RESULT_ROWS)
    body += struct.pack(">I", flags)
    body += struct.pack(">i", len(names))
    if paging is not None:
        body += _bytes(paging)
    body += _string("") + _string("")    # keyspace/table (opaque here)
    for name, (tid, _enc) in zip(names, col_types):
        body += _string(name)
        body += struct.pack(">H", tid)
    body += struct.pack(">i", len(rows))
    for r in rows:
        for v, (_tid, enc) in zip(r, col_types):
            body += _bytes(None if v is None else enc(v))
    return bytes(body)


class CQLServer:
    """Threaded native-protocol endpoint over a backend (StorageEngine or
    cluster Node) — transport/Server.java role."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 tls=None):
        """tls: a cluster.tls.TLSConfig — client_encryption_options
        role: connections are TLS, with client certs demanded only when
        the config sets require_client_auth."""
        self.backend = backend
        self._tls_ctx = tls.server_context() if tls else None
        # ONE processor for the whole server: prepared-statement ids are
        # server-global like the reference's (drivers prepare on one
        # connection and execute on another); keyspace/user stay
        # per-connection via the state dict
        self.processor = QueryProcessor(backend)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(64)
        self.port = self._listen.getsockname()[1]
        self._closed = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"cql-server-{self.port}").start()

    def close(self) -> None:
        self._closed = True
        try:
            self._listen.close()
        except OSError:
            pass

    # ------------------------------------------------------------ transport

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listen.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_raw, args=(sock,),
                             daemon=True).start()

    def _serve_raw(self, sock) -> None:
        # TLS handshake happens on the per-connection thread — a slow
        # or plaintext client must not stall the accept loop
        if self._tls_ctx is not None:
            import ssl
            try:
                sock = self._tls_ctx.wrap_socket(sock, server_side=True)
            except (ssl.SSLError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                return
        self._serve(sock)

    @staticmethod
    def _read_exact(sock, n: int) -> bytes | None:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return bytes(buf)

    def _serve(self, sock: socket.socket) -> None:
        processor = self.processor
        state = {"keyspace": None, "user": None, "authed": False}
        auth = getattr(self.backend, "auth", None)
        need_auth = auth is not None and auth.enabled
        try:
            while not self._closed:
                hdr = self._read_exact(sock, 9)
                if hdr is None:
                    return
                _ver, _flags, stream, opcode = struct.unpack(">BBhB",
                                                             hdr[:5])
                (length,) = struct.unpack(">I", hdr[5:9])
                if length > (256 << 20):
                    return
                body = self._read_exact(sock, length) if length else b""
                if body is None:
                    return
                try:
                    op, rsp = self._dispatch(processor, state, need_auth,
                                             auth, opcode, body)
                except Exception as e:
                    code = ERR_INVALID if isinstance(e, ValueError) \
                        else ERR_SERVER
                    op, rsp = OP_ERROR, struct.pack(">i", code) \
                        + _string(f"{type(e).__name__}: {e}")
                sock.sendall(struct.pack(">BBhBI", VERSION_RSP, 0, stream,
                                         op, len(rsp)) + rsp)
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    # ------------------------------------------------------------- opcodes

    def _dispatch(self, processor, state, need_auth, auth, opcode, body):
        if opcode == OP_OPTIONS:
            return OP_SUPPORTED, struct.pack(">H", 1) + \
                _string("CQL_VERSION") + struct.pack(">H", 1) + \
                _string("3.4.5")
        if opcode == OP_STARTUP:
            if need_auth:
                return OP_AUTHENTICATE, _string(
                    "org.apache.cassandra.auth.PasswordAuthenticator")
            state["authed"] = True
            return OP_READY, b""
        if opcode == OP_AUTH_RESPONSE:
            token, _ = _read_bytes(body, 0)
            parts = (token or b"").split(b"\x00")
            if len(parts) >= 3:
                user, pw = parts[1].decode(), parts[2].decode()
                try:
                    auth.authenticate(user, pw)
                except Exception:
                    return OP_ERROR, struct.pack(
                        ">i", ERR_BAD_CREDENTIALS) + _string(
                        "bad credentials")
                state["user"] = user
                state["authed"] = True
                return OP_AUTH_SUCCESS, _bytes(None)
            return OP_ERROR, struct.pack(">i", ERR_BAD_CREDENTIALS) \
                + _string("malformed SASL token")
        if not state["authed"]:
            return OP_ERROR, struct.pack(">i", ERR_PROTOCOL) \
                + _string("STARTUP required")
        if opcode == OP_QUERY:
            query, pos = _read_long_string(body, 0)
            return self._run(processor, state, query, body, pos)
        if opcode == OP_PREPARE:
            query, _ = _read_long_string(body, 0)
            qid = processor.prepare(query)
            prep = processor._prepared[qid]
            n_binds = getattr(prep.statement, "n_markers", 0)
            rsp = bytearray()
            rsp += struct.pack(">i", RESULT_PREPARED)
            rsp += struct.pack(">H", len(qid)) + qid
            # bind metadata: declared as BLOB — the server deserializes
            # wire bytes against the real column type at bind time, so
            # clients pass pre-serialized values (documented subset)
            rsp += struct.pack(">Ii", 0x0001, n_binds)   # flags, count
            rsp += struct.pack(">i", 0)                   # pk_count
            rsp += _string("") + _string("")              # global spec
            for i in range(n_binds):
                rsp += _string(f"p{i}") + struct.pack(">H", 0x03)
            # result metadata: clients re-read it from each RESULT
            rsp += struct.pack(">Ii", 0, 0)
            return OP_RESULT, bytes(rsp)
        if opcode == OP_EXECUTE:
            (n,) = struct.unpack_from(">H", body, 0)
            qid = bytes(body[2:2 + n])
            pos = 2 + n
            if processor._prepared.get(qid) is None:
                return OP_ERROR, struct.pack(">i", ERR_INVALID) \
                    + _string("unknown prepared statement")
            return self._run(processor, state, None, body, pos, qid=qid)
        return OP_ERROR, struct.pack(">i", ERR_PROTOCOL) \
            + _string(f"unsupported opcode {opcode}")

    def _run(self, processor, state, query, body: bytes, pos: int,
             qid: bytes | None = None):
        _consistency, = struct.unpack_from(">H", body, pos)
        pos += 2
        flags = body[pos]
        pos += 1
        params: tuple = ()
        page_size = None
        paging_state = None
        if flags & 0x01:                 # values
            (nv,) = struct.unpack_from(">H", body, pos)
            pos += 2
            vals = []
            for _ in range(nv):
                b, pos = _read_bytes(body, pos)
                vals.append(None if b is None else WireValue(b))
            params = tuple(vals)
        if flags & 0x04:                 # page_size
            (page_size,) = struct.unpack_from(">i", body, pos)
            pos += 4
        if flags & 0x08:                 # paging_state
            paging_state, pos = _read_bytes(body, pos)
        if qid is not None:   # EXECUTE: cached statement, no re-parse
            rs = processor.execute_prepared(
                qid, params, state["keyspace"], user=state["user"],
                page_size=page_size, paging_state=paging_state)
        else:
            rs = processor.process(query, params, state["keyspace"],
                                   user=state["user"],
                                   page_size=page_size,
                                   paging_state=paging_state)
        new_ks = getattr(rs, "keyspace", None)
        if new_ks is not None:
            state["keyspace"] = new_ks
            return OP_RESULT, struct.pack(">i", RESULT_SET_KEYSPACE) \
                + _string(new_ks)
        if not rs.column_names:
            return OP_RESULT, struct.pack(">i", RESULT_VOID)
        return OP_RESULT, _encode_rows(rs)
