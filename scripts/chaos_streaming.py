#!/usr/bin/env python
"""CI check (tier-2, like chaos_storage.py): streaming chaos drill —
a deterministic seeded dataset moves between in-process nodes while
faultfs chokes the stream checkpoints, and every session must end in
the state the robustness contract mandates.

Drills, in order, each asserting the policy-mandated end state:

  1. latency on stream.net: the transfer completes anyway and the
     landed components are sha256-identical to a clean control run;
  2. disconnect on stream.net (chunks dropped on the floor): the
     sender's retransmit window recovers, the session completes, and
     the digests still match the control;
  3. EIO at the stream.land TOC write (the commit point): the session
     fails, ZERO new sstables become visible, and the restart sweep
     (lifecycle.replay_directory) removes the orphaned components;
  4. sender killed mid-session: the receiver's durable watermark
     survives, resume_incomplete() re-requests only the tail, and the
     result is byte-identical to the control;
  5. bootstrap under latency chaos: a 4th node joins while stream.net
     is degraded; the join completes and a CL=ALL read of every seeded
     row still succeeds afterwards.

Everything is disarmed at exit — a final clean transfer must again be
digest-identical to the control (zero divergence once disarmed).

Run as a script (exit 1 on violation); tests/test_streaming.py covers
the same paths unit-by-unit.
"""
from __future__ import annotations

import hashlib
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_ROWS = 200
MIN_TOKEN = -(1 << 63)
MAX_TOKEN = (1 << 63) - 1


def _gen_hashes(cfs, gens):
    """{component: sha256} for the given generations — component
    contents never embed the generation, so two landings of the same
    source compare equal regardless of local gen numbers."""
    gens = set(int(g) for g in gens)
    out = {}
    for fn in sorted(os.listdir(cfs.directory)):
        parts = fn.split("-", 2)
        if len(parts) == 3 and parts[1].isdigit() \
                and int(parts[1]) in gens:
            with open(os.path.join(cfs.directory, fn), "rb") as f:
                out[parts[2]] = hashlib.sha256(f.read()).hexdigest()
    return out


def _acked_count(node):
    import json
    base = os.path.join(node.engine.data_dir, "streaming")
    n = 0
    if os.path.isdir(base):
        for sid in os.listdir(base):
            mpath = os.path.join(base, sid, "meta.json")
            apath = os.path.join(base, sid, "acked.log")
            if os.path.exists(mpath) and os.path.exists(apath):
                with open(mpath) as f:
                    if json.load(f).get("role") != "receiver":
                        continue
                with open(apath) as f:
                    n += sum(1 for _ in f)
    return n


def run_drill(base_dir: str) -> list[str]:
    """Run every drill; returns human-readable violations (empty=pass)."""
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    from cassandra_tpu.cluster.stream_session import StreamManager
    from cassandra_tpu.utils import faultfs

    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    # small chunks so every drill spans many STREAM_CHUNK round trips
    StreamManager.CHUNK_SIZE = 1024
    StreamManager.WINDOW = 4
    StreamManager.RETRANSMIT_BASE = 0.05

    c = LocalCluster(3, base_dir, rf=3)
    try:
        for nd in c.nodes:
            nd.proxy.timeout = 5.0
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        c.node(1).default_cl = ConsistencyLevel.ALL
        for i in range(N_ROWS):
            s.execute(f"INSERT INTO kv (k, v) "
                      f"VALUES ({i}, '{'x' * 64}{i}')")
        n1, n2, n3 = c.node(1), c.node(2), c.node(3)
        n1.engine.store("ks", "kv").flush()

        def full_stream(dst, timeout=60.0):
            return dst.streams.stream_range(
                n1.endpoint, "ks", "kv", MIN_TOKEN, MAX_TOKEN,
                timeout=timeout)

        # control: a clean transfer's component digests
        ctl = full_stream(n2)
        control = _gen_hashes(n2.engine.store("ks", "kv"), ctl["gens"])
        need(control and "TOC.txt" in control,
             "control transfer landed nothing")

        # ------------------------------------------ drill 1: latency
        faultfs.arm("stream.net", "latency", delay_s=0.01)
        res = full_stream(n3)
        fired = faultfs.GLOBAL.fires("stream.net")
        faultfs.disarm()
        need(fired > 0, "latency drill never crossed the fault point")
        got = _gen_hashes(n3.engine.store("ks", "kv"), res["gens"])
        need(got == control,
             "latency drill: landed digests diverge from control")

        # --------------------------------------- drill 2: disconnect
        faultfs.arm("stream.net", "disconnect", times=4)
        res = full_stream(n3)
        fired = faultfs.GLOBAL.fires("stream.net")
        faultfs.disarm()
        need(fired > 0, "disconnect drill never dropped a chunk")
        got = _gen_hashes(n3.engine.store("ks", "kv"), res["gens"])
        need(got == control,
             "disconnect drill: retransmitted digests diverge")

        # ------------------------------ drill 3: EIO at the TOC write
        cfs3 = n3.engine.store("ks", "kv")
        before = {t.desc.generation for t in cfs3.live_sstables()}
        faultfs.arm("stream.land", "error", path_substr="TOC.txt")
        try:
            full_stream(n3, timeout=15.0)
            need(False, "EIO-at-TOC transfer did not fail")
        except Exception:
            pass
        faultfs.disarm()
        cfs3.reload_sstables()
        need({t.desc.generation
              for t in cfs3.live_sstables()} == before,
             "failed landing leaked a visible sstable (TOC written?)")
        from cassandra_tpu.storage.lifecycle import replay_directory
        replay_directory(cfs3.directory)
        orphans = [fn for fn in os.listdir(cfs3.directory)
                   if len(p := fn.split("-", 2)) == 3
                   and p[1].isdigit() and int(p[1]) not in before]
        need(orphans == [],
             f"restart sweep left orphan components: {orphans}")

        # --------------------------- drill 4: kill sender, then resume
        faultfs.arm("stream.net", "latency", delay_s=0.02)
        holder: dict = {}

        def bg():
            try:
                holder["res"] = full_stream(n3, timeout=3.0)
            except Exception as e:
                holder["err"] = e

        th = threading.Thread(target=bg, daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        while _acked_count(n3) < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        need(_acked_count(n3) >= 3, "no watermark before the kill")
        c.stop_node(1)
        faultfs.disarm()
        th.join(timeout=15)
        need("err" in holder, "session survived a dead sender?")
        c.restart_node(1)
        # drill 3's failed session stayed durable (by design), so the
        # sweep picks BOTH it and the killed-sender session up here
        resumed = n3.streams.resume_incomplete(timeout=60.0)
        need(resumed and all("error" not in r for r in resumed),
             f"resume after sender kill failed: {resumed}")
        for r in resumed:
            got = _gen_hashes(cfs3, r.get("gens", []))
            need(got == control,
                 "resumed transfer: digests diverge from control")

        # --------------------- drill 5: bootstrap under latency chaos
        faultfs.arm("stream.net", "latency", delay_s=0.005)
        c.add_node()
        faultfs.disarm()
        s1 = c.session(1)
        s1.keyspace = "ks"
        c.node(1).default_cl = ConsistencyLevel.ALL
        missing = [i for i in range(N_ROWS)
                   if not s1.execute(
                       f"SELECT v FROM kv WHERE k = {i}").rows]
        need(missing == [],
             f"rows unreadable at ALL after chaotic join: {missing[:5]}")

        # ------------------------- disarmed re-run: zero divergence
        res = full_stream(n3)
        got = _gen_hashes(cfs3, res["gens"])
        need(got == control,
             "disarmed re-run diverges from control")
        need(not faultfs.GLOBAL.active,
             "fault points left armed at drill end")
    finally:
        c.shutdown()
    return errs


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="ctpu-chaos-stream-") as d:
        errs = run_drill(d)
    for msg in errs:
        print(msg, file=sys.stderr)
    if errs:
        print(f"FAIL: {len(errs)} violation(s)", file=sys.stderr)
        return 1
    print("streaming chaos drill: all sessions held (latency + "
          "disconnect retransmit, TOC-gated atomic landing + orphan "
          "sweep, kill/resume byte identity, chaotic bootstrap)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
