"""Typed config system (config.py — the DatabaseDescriptor role):
unit-spec parsing, validated loading, runtime-mutable settings with
listeners, and wiring into the engine's compaction throttle/guardrails."""
import pytest

from cassandra_tpu.config import (Config, ConfigError, Settings,
                                  parse_duration, parse_rate, parse_storage)


def test_duration_spec():
    assert parse_duration("10s") == 10.0
    assert parse_duration("200ms") == 0.2
    assert parse_duration("2h") == 7200.0
    assert parse_duration("3d") == 3 * 86400.0
    assert parse_duration(500) == 0.5          # bare number: default ms
    with pytest.raises(ConfigError):
        parse_duration("10 parsecs")


def test_storage_spec():
    assert parse_storage("16KiB") == 16 * 1024
    assert parse_storage("32MiB") == 32 * 1024 ** 2
    assert parse_storage("1GiB") == 1024 ** 3
    assert parse_storage(512) == 512
    with pytest.raises(ConfigError):
        parse_storage("16KB")   # reference rejects non-binary units too


def test_rate_spec():
    assert parse_rate("64MiB/s") == 64.0
    assert parse_rate("512KiB/s") == 0.5
    assert parse_rate(24) == 24.0
    with pytest.raises(ConfigError):
        parse_rate("64MiB")


def test_load_defaults_match_reference():
    c = Config()
    assert c.compaction_throughput == 64.0          # cassandra.yaml:1243
    assert c.commitlog_sync == "periodic"
    assert c.num_tokens == 16
    assert c.stream_throughput_outbound == 24.0
    assert c.read_request_timeout == 5.0
    assert c.write_request_timeout == 2.0


def test_load_parses_and_validates():
    c = Config.load({"compaction_throughput": "128MiB/s",
                     "commitlog_sync_period": "5s",
                     "commitlog_segment_size": "16MiB",
                     "phi_convict_threshold": 10,
                     "hinted_handoff_enabled": False})
    assert c.compaction_throughput == 128.0
    assert c.commitlog_sync_period == 5.0
    assert c.commitlog_segment_size == 16 * 1024 ** 2
    assert c.phi_convict_threshold == 10.0
    assert c.hinted_handoff_enabled is False


def test_load_rejects_unknown_and_mistyped():
    with pytest.raises(ConfigError, match="unknown config key"):
        Config.load({"compaction_thruput": "64MiB/s"})
    with pytest.raises(ConfigError):
        Config.load({"num_tokens": "sixteen"})
    with pytest.raises(ConfigError):
        Config.load({"cluster_name": 7})
    with pytest.raises(ConfigError):
        Config.load({"hinted_handoff_enabled": "yes"})


def test_settings_mutability_and_listeners():
    s = Settings()
    seen = []
    s.on_change("compaction_throughput", seen.append)
    s.set("compaction_throughput", "16MiB/s")
    assert s.get("compaction_throughput") == 16.0
    assert seen == [16.0]
    with pytest.raises(ConfigError, match="not mutable"):
        s.set("cluster_name", "nope")
    with pytest.raises(ConfigError, match="unknown setting"):
        s.set("no_such", 1)
    rows = dict((n, (v, m)) for n, v, m in s.all())
    assert rows["compaction_throughput"] == ("16.0", True)
    assert rows["cluster_name"][1] is False


def test_engine_wiring(tmp_path):
    from cassandra_tpu.storage.engine import StorageEngine

    s = Settings(Config.load({"compaction_throughput": "32MiB/s",
                              "guardrails": {"tables_fail_threshold": 7}}))
    eng = StorageEngine(str(tmp_path), durable_writes=False, settings=s)
    assert eng.compactions.limiter.rate == 32 * 2 ** 20
    assert eng.guardrails.tables_fail_threshold == 7
    # hot reload reaches the running limiter
    s.set("compaction_throughput", "8MiB/s")
    assert eng.compactions.limiter.rate == 8 * 2 ** 20
    # 0 = unthrottled
    s.set("compaction_throughput", 0)
    assert eng.compactions.limiter.rate == 0


def test_guardrails_from_config_rejects_unknown(tmp_path):
    from cassandra_tpu.storage.engine import StorageEngine

    s = Settings(Config.load({"guardrails": {"tables_warn_treshold": 1}}))
    with pytest.raises(ConfigError, match="unknown guardrail"):
        StorageEngine(str(tmp_path), durable_writes=False, settings=s)


def test_guardrails_value_types_fail_startup():
    from cassandra_tpu.storage.guardrails import Guardrails

    with pytest.raises(ConfigError, match="expected int"):
        Guardrails.from_config({"tombstones_warn_per_read": "1000"})
    with pytest.raises(ConfigError, match="expected int"):
        Guardrails.from_config({"tables_fail_threshold": True})


def test_bool_rejected_by_specs():
    with pytest.raises(ConfigError):
        parse_duration(True)
    with pytest.raises(ConfigError):
        parse_storage(True)
    with pytest.raises(ConfigError):
        parse_rate(False)
    with pytest.raises(ConfigError):
        Config.load({"read_request_timeout": True})


def test_listener_removal():
    s = Settings()
    seen = []
    s.on_change("compaction_throughput", seen.append)
    s.remove_listener("compaction_throughput", seen.append)
    s.set("compaction_throughput", 1)
    assert seen == []


def test_per_operation_timeouts_wired(tmp_path):
    """Coordinator takes read/write/range timeouts from config and tracks
    hot updates; the blanket `timeout` alias sets all three."""
    from cassandra_tpu.cluster.node import LocalCluster

    c = LocalCluster(1, str(tmp_path), rf=1)
    try:
        node = c.nodes[0]
        s = node.engine.settings
        s.set("read_request_timeout", "700ms")
        s.set("write_request_timeout", "300ms")
        s.set("range_request_timeout", "9s")
        assert node.proxy.read_timeout == pytest.approx(0.7)
        assert node.proxy.write_timeout == pytest.approx(0.3)
        assert node.proxy.range_timeout == pytest.approx(9.0)
        node.proxy.timeout = 1.5
        assert (node.proxy.read_timeout, node.proxy.write_timeout,
                node.proxy.range_timeout) == (1.5, 1.5, 1.5)
    finally:
        c.shutdown()
