"""Recursive-descent CQL parser.

Reference counterpart: src/antlr/Parser.g (cql3 grammar). Covers the DML
and DDL surface of this round: SELECT / INSERT / UPDATE / DELETE / BATCH /
CREATE (KEYSPACE, TABLE, INDEX, TYPE) / DROP / ALTER TABLE / TRUNCATE /
USE, with USING TTL/TIMESTAMP, IF [NOT] EXISTS, collections, bind markers.
"""
from __future__ import annotations

from . import ast
from .lexer import Token, tokenize


class ParseError(ValueError):
    pass


class Parser:
    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.i = 0
        self.n_markers = 0

    # ------------------------------------------------------------ helpers --

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_kw(self, *words: str) -> str:
        t = self.next()
        if t.kind != "KEYWORD" or t.value not in words:
            raise ParseError(f"expected {'/'.join(words).upper()}, got {t}")
        return t.value

    def accept_kw(self, *words: str) -> str | None:
        t = self.peek()
        if t.kind == "KEYWORD" and t.value in words:
            self.i += 1
            return t.value
        return None

    def expect_op(self, op: str) -> None:
        t = self.next()
        if t.kind != "OP" or t.value != op:
            raise ParseError(f"expected {op!r}, got {t}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "OP" and t.value == op:
            self.i += 1
            return True
        return False

    def accept_ident(self, word: str) -> bool:
        t = self.peek()
        if t.kind == "IDENT" and t.value == word:
            self.i += 1
            return True
        return False

    def ident(self) -> str:
        t = self.next()
        if t.kind == "IDENT":
            return t.value
        if t.kind == "KEYWORD" and t.value in ("key", "type", "timestamp",
                                               "ttl", "list", "index", "role",
                                               "user", "counter", "token",
                                               "options", "custom", "view",
                                               "function", "aggregate",
                                               "returns", "language",
                                               "trigger"):
            return t.value  # unreserved keywords usable as identifiers
        raise ParseError(f"expected identifier, got {t}")

    def qualified_name(self) -> tuple[str | None, str]:
        a = self.ident()
        if self.accept_op("."):
            return a, self.ident()
        return None, a

    # --------------------------------------------------------------- terms --

    def term(self):
        t = self.peek()
        if t.kind == "MARKER":
            self.next()
            m = ast.BindMarker(self.n_markers, t.value)
            self.n_markers += 1
            return m
        if t.kind in ("INT", "FLOAT", "STRING", "UUID", "HEX"):
            self.next()
            return ast.Literal(t.value, t.kind.lower())
        if t.kind == "KEYWORD" and t.value in ("null",):
            self.next()
            return ast.Literal(None, "null")
        if t.kind == "IDENT" and t.value in ("true", "false"):
            self.next()
            return ast.Literal(t.value == "true", "bool")
        if t.kind == "OP" and t.value == "[":
            self.next()
            items = self._term_list("]")
            return ast.CollectionLiteral("list", items)
        if t.kind == "OP" and t.value == "{":
            self.next()
            return self._map_or_set()
        if t.kind == "OP" and t.value == "(":
            self.next()
            items = self._term_list(")")
            return ast.CollectionLiteral("tuple", items)
        if t.kind in ("IDENT", "KEYWORD"):
            name = self.ident()
            if self.accept_op("("):
                args = self._term_list(")")
                return ast.FunctionCall(name, args)
            return ast.Literal(name, "ident")  # e.g. column ref in SET x = y
        raise ParseError(f"unexpected term {t}")

    def _term_list(self, closing: str) -> list:
        items = []
        if self.accept_op(closing):
            return items
        while True:
            items.append(self.term())
            if self.accept_op(closing):
                return items
            self.expect_op(",")

    def _map_value_after_colon(self, first):
        """Parse map pairs where the first key was already consumed. Note:
        ':name' lexes as a named bind marker, which is exactly CQL's
        meaning for an unquoted word in value position."""
        pairs = [(first, self.term())]
        while self.accept_op(","):
            k = self.term()
            if not self.accept_op(":"):
                t = self.peek()
                if t.kind == "MARKER" and t.value is not None:
                    pass  # ':name' marker doubles as ': name'
                else:
                    raise ParseError(f"expected ':' in map literal, got {t}")
            pairs.append((k, self.term()))
        self.expect_op("}")
        return ast.CollectionLiteral("map", pairs)

    def _map_or_set(self):
        if self.accept_op("}"):
            return ast.CollectionLiteral("map", [])  # {} is empty map/set
        first = self.term()
        if self.accept_op(":"):
            return self._map_value_after_colon(first)
        t = self.peek()
        if t.kind == "MARKER" and t.value is not None:
            return self._map_value_after_colon(first)
        items = [first]
        while self.accept_op(","):
            items.append(self.term())
        self.expect_op("}")
        return ast.CollectionLiteral("set", items)

    # ---------------------------------------------------------- statements --

    def parse_statement(self):
        t = self.peek()
        if t.kind != "KEYWORD":
            raise ParseError(f"expected statement, got {t}")
        kw = t.value
        fn = {
            "select": self.select, "insert": self.insert,
            "update": self.update, "delete": self.delete,
            "begin": self.batch, "create": self.create,
            "drop": self.drop, "alter": self.alter,
            "truncate": self.truncate, "use": self.use,
            "grant": self.grant, "revoke": self.grant,
            "list": self.list_stmt, "add": self.add_identity,
        }.get(kw)
        if fn is None:
            raise ParseError(f"unsupported statement {kw.upper()}")
        stmt = fn()
        self.accept_op(";")
        t = self.peek()
        if t.kind != "EOF":
            raise ParseError(f"trailing input at {t}")
        try:
            stmt.n_markers = self.n_markers   # bind-variable count for
        except Exception:                     # prepared-statement metadata
            pass
        return stmt

    # SELECT
    def select(self):
        self.expect_kw("select")
        json = False
        t = self.peek()
        if t.kind == "IDENT" and t.value == "json":
            # 'json' only acts as the modifier when another selector
            # follows — `SELECT json FROM t` must keep reading a column
            # named json (the reference grammar backtracks the same way)
            nxt = self.toks[self.i + 1]
            if not (nxt.kind == "KEYWORD" and nxt.value == "from") \
                    and not (nxt.kind == "OP" and nxt.value in (",", "(")):
                self.next()
                json = True
        distinct = bool(self.accept_kw("distinct"))
        selectors = []
        if self.accept_op("*"):
            selectors.append(("*", None))
        else:
            while True:
                sel = self._selector()
                alias = None
                if self.accept_kw("as"):
                    alias = self.ident()
                selectors.append((sel, alias))
                if not self.accept_op(","):
                    break
        self.expect_kw("from")
        ks, table = self.qualified_name()
        where = []
        if self.accept_kw("where"):
            where = self._relations()
        group_by = []
        if self.accept_ident("group"):
            self.expect_kw("by")
            while True:
                group_by.append(self.ident())
                if not self.accept_op(","):
                    break
        order = []
        ann = None
        if self.accept_kw("order"):
            self.expect_kw("by")
            col = self.ident()
            if self.accept_ident("ann"):
                # SAI vector search: ORDER BY v ANN OF [..] (CEP-30 syntax)
                self.expect_kw("of")
                ann = (col, self.term())
            else:
                while True:
                    desc = False
                    if self.accept_kw("desc"):
                        desc = True
                    else:
                        self.accept_kw("asc")
                    order.append((col, desc))
                    if not self.accept_op(","):
                        break
                    col = self.ident()
        per_partition = None
        limit = None
        if self.accept_kw("per"):
            self.expect_kw("partition")
            self.expect_kw("limit")
            per_partition = self.term()
        if self.accept_kw("limit"):
            limit = self.term()
        allow = False
        if self.accept_kw("allow"):
            self.expect_kw("filtering")
            allow = True
        return ast.SelectStatement(ks, table, selectors, where, order, ann,
                                   group_by, limit, per_partition, allow,
                                   distinct, json)

    def _selector(self):
        t = self.peek()
        if t.kind in ("IDENT", "KEYWORD"):
            name = self.ident()
            if self.accept_op("("):
                if self.accept_op("*"):
                    self.expect_op(")")
                    return ast.FunctionCall(name, ["*"])
                args = self._term_list(")")
                return ast.FunctionCall(name, args)
            return name
        raise ParseError(f"bad selector {t}")

    def _relations(self) -> list:
        rels = []
        while True:
            rels.append(self._relation())
            if not self.accept_kw("and"):
                break
        return rels

    def _relation(self):
        col = self.ident()
        key = None
        if self.accept_op("["):
            key = self.term()
            self.expect_op("]")
        t = self.next()
        if t.kind == "KEYWORD" and t.value == "in":
            self.expect_op("(")
            vals = self._term_list(")")
            return ast.Relation(col, "IN", vals)
        if t.kind == "KEYWORD" and t.value == "like":
            return ast.Relation(col, "LIKE", self.term())
        if t.kind == "KEYWORD" and t.value == "contains":
            if self.accept_kw("key"):
                return ast.Relation(col, "CONTAINS_KEY", self.term())
            return ast.Relation(col, "CONTAINS", self.term())
        if t.kind == "OP" and t.value in ("=", "<", "<=", ">", ">=", "!="):
            r = ast.Relation(col, t.value, self.term())
            if key is not None:
                r = ast.Relation(col, f"[{t.value}]", (key, r.value))
            return r
        raise ParseError(f"bad relation operator {t}")

    # INSERT
    def insert(self):
        self.expect_kw("insert")
        self.expect_kw("into")
        ks, table = self.qualified_name()
        if self.accept_ident("json"):
            payload = self.term()     # string literal or bind marker
            ine = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                ine = True
            ttl, ts = self._using()
            stmt = ast.InsertStatement(ks, table, [], [], ine, ttl, ts)
            stmt.json = True
            stmt.json_payload = payload
            return stmt
        self.expect_op("(")
        cols = []
        while True:
            cols.append(self.ident())
            if self.accept_op(")"):
                break
            self.expect_op(",")
        self.expect_kw("values")
        self.expect_op("(")
        vals = self._term_list(")")
        if len(vals) != len(cols):
            raise ParseError("column/value count mismatch")
        ine = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            ine = True
        ttl, ts = self._using()
        return ast.InsertStatement(ks, table, cols, vals, ine, ttl, ts)

    def _using(self):
        ttl = ts = None
        if self.accept_kw("using"):
            while True:
                w = self.expect_kw("ttl", "timestamp")
                if w == "ttl":
                    ttl = self.term()
                else:
                    ts = self.term()
                if not self.accept_kw("and"):
                    break
        return ttl, ts

    # UPDATE
    def update(self):
        self.expect_kw("update")
        ks, table = self.qualified_name()
        ttl, ts = self._using()
        self.expect_kw("set")
        ops = []
        while True:
            ops.append(self._update_op())
            if not self.accept_op(","):
                break
        self.expect_kw("where")
        where = self._relations()
        if_exists = False
        conditions = []
        if self.accept_kw("if"):
            if self.accept_kw("exists"):
                if_exists = True
            else:
                conditions = self._relations()
        return ast.UpdateStatement(ks, table, ops, where, if_exists,
                                   conditions, ttl, ts)

    def _update_op(self):
        col = self.ident()
        if self.accept_op("["):
            key = self.term()
            self.expect_op("]")
            self.expect_op("=")
            return ast.UpdateOp(col, "put_index", self.term(), key)
        t = self.next()
        if t.kind == "OP" and t.value == "=":
            # col = col + x / col = col - x / col = x + col / col = x
            save = self.i
            first = self.term()
            if isinstance(first, ast.Literal) and first.kind == "ident" \
                    and first.value == col:
                if self.accept_op("+"):
                    return ast.UpdateOp(col, "add", self.term())
                if self.accept_op("-"):
                    return ast.UpdateOp(col, "sub", self.term())
                self.i = save
                first = self.term()
                return ast.UpdateOp(col, "set", first)
            if self.accept_op("+"):
                self.term()  # the column ref on the right: x + col
                return ast.UpdateOp(col, "prepend", first)
            return ast.UpdateOp(col, "set", first)
        if t.kind == "OP" and t.value in ("+=", "-="):
            return ast.UpdateOp(col, "add" if t.value == "+=" else "sub",
                                self.term())
        raise ParseError(f"bad SET op {t}")

    # DELETE
    def delete(self):
        self.expect_kw("delete")
        cols = []
        if not (self.peek().kind == "KEYWORD"
                and self.peek().value == "from"):
            while True:
                name = self.ident()
                if self.accept_op("["):
                    key = self.term()
                    self.expect_op("]")
                    cols.append((name, key))
                else:
                    cols.append(name)
                if not self.accept_op(","):
                    break
        self.expect_kw("from")
        ks, table = self.qualified_name()
        ts = None
        if self.accept_kw("using"):
            self.expect_kw("timestamp")
            ts = self.term()
        self.expect_kw("where")
        where = self._relations()
        if_exists = False
        conditions = []
        if self.accept_kw("if"):
            if self.accept_kw("exists"):
                if_exists = True
            else:
                conditions = self._relations()
        return ast.DeleteStatement(ks, table, cols, where, if_exists,
                                   conditions, ts)

    # BATCH
    def batch(self):
        self.expect_kw("begin")
        kind = self.accept_kw("unlogged", "counter", "logged") or "logged"
        self.expect_kw("batch")
        ttl, ts = self._using()
        stmts = []
        while not (self.peek().kind == "KEYWORD"
                   and self.peek().value == "apply"):
            kw = self.peek().value
            fn = {"insert": self.insert, "update": self.update,
                  "delete": self.delete}.get(kw)
            if fn is None:
                raise ParseError(f"only DML allowed in batch, got {kw}")
            stmts.append(fn())
            self.accept_op(";")
        self.expect_kw("apply")
        self.expect_kw("batch")
        return ast.BatchStatement(kind, stmts, ts)

    # CREATE
    def create(self):
        self.expect_kw("create")
        what = self.next()
        if what.kind == "KEYWORD" and what.value == "keyspace":
            return self._create_keyspace()
        if what.kind == "KEYWORD" and what.value == "table":
            return self._create_table()
        if what.kind == "KEYWORD" and what.value == "index":
            return self._create_index(custom=False)
        if what.kind == "KEYWORD" and what.value == "custom":
            self.expect_kw("index")
            return self._create_index(custom=True)
        if what.kind == "KEYWORD" and what.value == "type":
            return self._create_type()
        if what.kind == "KEYWORD" and what.value in ("role", "user"):
            return self._create_role()
        if what.kind == "KEYWORD" and what.value == "materialized":
            self.expect_kw("view")
            return self._create_view()
        if what.kind == "KEYWORD" and what.value == "or":
            self.expect_kw("replace")
            nxt = self.expect_kw("function", "aggregate")
            if nxt == "function":
                return self._create_function(or_replace=True)
            return self._create_aggregate(or_replace=True)
        if what.kind == "KEYWORD" and what.value == "function":
            return self._create_function()
        if what.kind == "KEYWORD" and what.value == "aggregate":
            return self._create_aggregate()
        if what.kind == "KEYWORD" and what.value == "trigger":
            return self._create_trigger()
        raise ParseError(f"unsupported CREATE {what}")

    def _create_trigger(self):
        # CREATE TRIGGER [IF NOT EXISTS] name ON [ks.]table USING '<src>'
        ine = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            ine = True
        name = self.ident()
        self.expect_kw("on")
        ks, table = self.qualified_name()
        self.expect_kw("using")
        src = self.next()
        if src.kind != "STRING":
            raise ParseError("USING expects a quoted trigger source")
        return ast.CreateTriggerStatement(ks, table, name, src.value,
                                          if_not_exists=ine)

    def _create_function(self, or_replace: bool = False):
        """CREATE [OR REPLACE] FUNCTION [IF NOT EXISTS] name
        (arg type, ...) RETURNS type LANGUAGE <lang> AS '<body>'
        (cql3/functions/UDFunction grammar subset)."""
        ine = self._if_not_exists()
        ks, name = self.qualified_name()
        self.expect_op("(")
        arg_names, arg_types = [], []
        if not self.accept_op(")"):
            while True:
                arg_names.append(self.ident())
                arg_types.append(self._type_string())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        self.expect_kw("returns")
        returns = self._type_string()
        self.expect_kw("language")
        language = self.ident()
        self.expect_kw("as")
        t = self.next()
        if t.kind != "STRING":
            raise ParseError(f"expected function body string, got {t}")
        return ast.CreateFunctionStatement(ks, name, arg_names, arg_types,
                                           returns, language, t.value,
                                           or_replace, ine)

    def _create_aggregate(self, or_replace: bool = False):
        """CREATE [OR REPLACE] AGGREGATE name (type) SFUNC f STYPE t
        [FINALFUNC g] [INITCOND x] (UDAggregate grammar subset)."""
        ks, name = self.qualified_name()
        self.expect_op("(")
        arg_type = self._type_string()
        self.expect_op(")")
        if not self.accept_ident("sfunc"):
            raise ParseError("expected SFUNC")
        sfunc = self.ident()
        if not self.accept_ident("stype"):
            raise ParseError("expected STYPE")
        stype = self._type_string()
        finalfunc = None
        initcond = None
        if self.accept_ident("finalfunc"):
            finalfunc = self.ident()
        if self.accept_ident("initcond"):
            t = self.next()
            if t.kind in ("INT", "FLOAT", "STRING"):
                initcond = t.value
            else:
                raise ParseError(f"bad INITCOND {t}")
        return ast.CreateAggregateStatement(ks, name, arg_type, sfunc,
                                            stype, finalfunc, initcond,
                                            or_replace)

    def _set_literal(self) -> list:
        """{'a', 'b'} — the set form used by ACCESS TO DATACENTERS /
        ACCESS FROM CIDRS role options."""
        self.expect_op("{")
        out: list = []
        if self.accept_op("}"):
            return out
        while True:
            t = self.next()
            if t.kind not in ("STRING", "IDENT"):
                raise ParseError(f"expected set element, got {t}")
            out.append(str(t.value))
            if self.accept_op("}"):
                return out
            self.expect_op(",")

    def _role_options(self):
        """WITH password = '..' AND superuser = true AND
        ACCESS TO DATACENTERS {'dc1'} AND ACCESS FROM CIDRS {'office'}
        (auth/CassandraRoleManager role options + CEP-33 access)."""
        password = None
        superuser = None
        datacenters = None
        cidr_groups = None
        while True:
            if self.accept_ident("access"):
                if self.accept_kw("from"):
                    if not self.accept_ident("cidrs"):
                        raise ParseError("expected CIDRS after ACCESS FROM")
                    cidr_groups = self._set_literal()
                else:
                    if not (self.accept_kw("to") or self.accept_ident("to")):
                        raise ParseError("expected TO or FROM after ACCESS")
                    if self.accept_kw("all") or self.accept_ident("all"):
                        if not self.accept_ident("datacenters"):
                            raise ParseError("expected DATACENTERS")
                        datacenters = []   # clear the restriction
                    else:
                        if not self.accept_ident("datacenters"):
                            raise ParseError("expected DATACENTERS")
                        datacenters = self._set_literal()
            else:
                opt = self.ident()
                self.expect_op("=")
                v = self._option_value()
                if opt == "password":
                    password = str(v)
                elif opt == "superuser":
                    superuser = bool(v)
            if not self.accept_kw("and"):
                break
        return password, superuser, datacenters, cidr_groups

    def _create_role(self):
        ine = self._if_not_exists()
        name = self.ident()
        password = None
        superuser = False
        datacenters = cidr_groups = None
        if self.accept_kw("with"):
            password, superuser, datacenters, cidr_groups = \
                self._role_options()
            superuser = bool(superuser)
        return ast.RoleStatement("create", name, password, superuser, ine,
                                 datacenters=datacenters,
                                 cidr_groups=cidr_groups)

    def _alter_role(self):
        name = self.ident()
        self.expect_kw("with")
        password, superuser, datacenters, cidr_groups = \
            self._role_options()
        return ast.RoleStatement("alter", name, password, superuser,
                                 datacenters=datacenters,
                                 cidr_groups=cidr_groups)

    def add_identity(self):
        """ADD IDENTITY '<identity>' TO ROLE 'r' (mTLS, CEP-34)."""
        self.expect_kw("add")
        if not self.accept_ident("identity"):
            raise ParseError("expected IDENTITY after ADD")
        t = self.next()
        if t.kind != "STRING":
            raise ParseError("expected identity string")
        if not (self.accept_kw("to") or self.accept_ident("to")):
            raise ParseError("expected TO ROLE")
        self.expect_kw("role")
        r = self.next()
        if r.kind not in ("STRING", "IDENT"):
            raise ParseError("expected role name")
        return ast.IdentityStatement("add", str(t.value), str(r.value))

    def grant(self):
        revoke = bool(self.accept_kw("revoke"))
        if not revoke:
            self.expect_kw("grant")
        t = self.next()
        perm = str(t.value).upper()
        if perm == "ALL":
            self.accept_ident("permissions")   # GRANT ALL [PERMISSIONS]
        self.expect_kw("on")
        if self.accept_kw("keyspace"):
            resource = self.ident()
        else:
            # ALL KEYSPACES / TABLE ks.t (table scope maps to its keyspace)
            w = self.next()
            if str(w.value) == "all":
                self.next()   # 'keyspaces'
                resource = "all keyspaces"
            elif str(w.value) == "table":
                ks, tb = self.qualified_name()
                if ks is None:
                    raise ParseError(
                        "GRANT/REVOKE ON TABLE requires a qualified "
                        "ks.table name")
                resource = ks
            else:
                resource = str(w.value)
        self.expect_kw("from" if revoke else "to")
        role = self.ident()
        return ast.GrantStatement(perm, resource, role, revoke)

    def list_stmt(self):
        self.expect_kw("list")
        t = self.next()
        if str(t.value) in ("roles", "users", "role", "user"):
            return ast.ListRolesStatement()
        raise ParseError(f"unsupported LIST {t}")

    def _if_not_exists(self) -> bool:
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            return True
        return False

    def _create_keyspace(self):
        ine = self._if_not_exists()
        name = self.ident()
        replication = {"class": "SimpleStrategy", "replication_factor": 1}
        durable = True
        if self.accept_kw("with"):
            while True:
                opt = self.ident()
                self.expect_op("=")
                val = self._option_value()
                if opt == "replication":
                    replication = val
                elif opt == "durable_writes":
                    durable = bool(val)
                if not self.accept_kw("and"):
                    break
        return ast.CreateKeyspaceStatement(name, replication, durable, ine)

    def _option_value(self):
        t = self.peek()
        if t.kind == "OP" and t.value == "{":
            self.next()
            out = {}
            if self.accept_op("}"):
                return out
            while True:
                k = self.next()
                if k.kind not in ("STRING", "IDENT"):
                    raise ParseError(f"bad option key {k}")
                self._expect_colon_or_marker()
                v = self.next()
                if v.kind not in ("STRING", "INT", "FLOAT", "IDENT"):
                    raise ParseError(f"bad option value {v}")
                out[str(k.value)] = v.value
                if self.accept_op("}"):
                    return out
                self.expect_op(",")
        t = self.next()
        if t.kind in ("STRING", "INT", "FLOAT"):
            return t.value
        if t.kind == "IDENT" and t.value in ("true", "false"):
            return t.value == "true"
        if t.kind in ("IDENT",):
            return t.value
        if t.kind == "UUID":
            # CREATE TABLE ... WITH id = <uuid> (explicit table id)
            return str(t.value)
        raise ParseError(f"bad option value {t}")

    def _expect_colon_or_marker(self):
        # ':' followed by an identifier-like value lexes as MARKER; undo it
        t = self.next()
        if t.kind == "OP" and t.value == ":":
            return
        if t.kind == "MARKER" and t.value is not None:
            # re-inject the marker's name as an IDENT token
            self.toks.insert(self.i, Token("IDENT", t.value, t.pos))
            return
        raise ParseError(f"expected ':', got {t}")

    def _create_table(self):
        ine = self._if_not_exists()
        ks, name = self.qualified_name()
        self.expect_op("(")
        columns = []
        pk: list[str] = []
        ck: list[str] = []
        while True:
            if self.accept_kw("primary"):
                self.expect_kw("key")
                self.expect_op("(")
                if self.accept_op("("):   # composite partition key
                    while True:
                        pk.append(self.ident())
                        if self.accept_op(")"):
                            break
                        self.expect_op(",")
                else:
                    pk.append(self.ident())
                while self.accept_op(","):
                    ck.append(self.ident())
                self.expect_op(")")
            else:
                cname = self.ident()
                ctype = self._type_string()
                static = bool(self.accept_kw("static"))
                inline_pk = False
                if self.accept_kw("primary"):
                    self.expect_kw("key")
                    pk.append(cname)
                    inline_pk = True
                columns.append((cname, ctype, static))
            if self.accept_op(")"):
                break
            self.expect_op(",")
        order = {}
        options = {}
        if self.accept_kw("with"):
            while True:
                if self.accept_ident("clustering"):
                    self.expect_kw("order")
                    self.expect_kw("by")
                    self.expect_op("(")
                    while True:
                        col = self.ident()
                        desc = bool(self.accept_kw("desc"))
                        if not desc:
                            self.accept_kw("asc")
                        order[col] = desc
                        if self.accept_op(")"):
                            break
                        self.expect_op(",")
                else:
                    opt = self.ident()
                    self.expect_op("=")
                    options[opt] = self._option_value()
                if not self.accept_kw("and"):
                    break
        return ast.CreateTableStatement(ks, name, columns, pk, ck, order,
                                        options, ine)

    def _type_string(self) -> str:
        """Consume a type expression, returning its flat string form."""
        t = self.next()
        if t.kind not in ("IDENT", "KEYWORD"):
            raise ParseError(f"expected type, got {t}")
        s = str(t.value)
        if self.accept_op("<"):
            parts = []
            depth = 1
            while depth:
                tt = self.next()
                if tt.kind == "OP" and tt.value == "<":
                    depth += 1
                    parts.append("<")
                elif tt.kind == "OP" and tt.value == ">":
                    depth -= 1
                    if depth:
                        parts.append(">")
                elif tt.kind == "OP" and tt.value == ",":
                    parts.append(", ")
                elif tt.kind in ("IDENT", "KEYWORD", "INT"):
                    parts.append(str(tt.value))
                else:
                    raise ParseError(f"bad type token {tt}")
            s += "<" + "".join(parts) + ">"
        return s

    def _create_view(self):
        """CREATE MATERIALIZED VIEW [IF NOT EXISTS] name AS
        SELECT cols FROM base WHERE <pk IS NOT NULL ...>
        PRIMARY KEY ((..), ..) — cql3/statements/schema/
        CreateViewStatement grammar subset."""
        ine = self._if_not_exists()
        ks, name = self.qualified_name()
        self.expect_kw("as")
        self.expect_kw("select")
        selected = []
        if self.accept_op("*"):
            selected = ["*"]
        else:
            while True:
                selected.append(self.ident())
                if not self.accept_op(","):
                    break
        self.expect_kw("from")
        bks, btable = self.qualified_name()
        if self.accept_kw("where"):
            # the standard guards: <col> IS NOT NULL [AND ...]
            while True:
                self.ident()
                self.expect_kw("is")
                self.expect_kw("not")
                self.expect_kw("null")
                if not self.accept_kw("and"):
                    break
        self.expect_kw("primary")
        self.expect_kw("key")
        pk, ck = self._primary_key_spec()
        return ast.CreateViewStatement(ks, name, bks, btable, selected,
                                       pk, ck, ine)

    def _primary_key_spec(self):
        """((a, b), c, d) or (a, b, c): partition key + clustering."""
        self.expect_op("(")
        pk = []
        if self.accept_op("("):
            while True:
                pk.append(self.ident())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        else:
            pk.append(self.ident())
        ck = []
        while self.accept_op(","):
            ck.append(self.ident())
        self.expect_op(")")
        return pk, ck

    def _create_index(self, custom: bool):
        ine = self._if_not_exists()
        name = None
        if not (self.peek().kind == "KEYWORD"
                and self.peek().value == "on"):
            name = self.ident()
            ine = ine or self._if_not_exists()
        self.expect_kw("on")
        ks, table = self.qualified_name()
        self.expect_op("(")
        col = self.ident()
        self.expect_op(")")
        cls = None
        if custom:
            self.expect_kw("using")
            cls = self.next().value
        opts = {}
        if self.accept_kw("with"):
            self.expect_kw("options")
            self.expect_op("=")
            opts = self._option_value() or {}
        return ast.CreateIndexStatement(name, ks, table, col, cls, ine,
                                        options=opts)

    def _create_type(self):
        ine = self._if_not_exists()
        ks, name = self.qualified_name()
        self.expect_op("(")
        fields = []
        while True:
            fname = self.ident()
            ftype = self._type_string()
            fields.append((fname, ftype))
            if self.accept_op(")"):
                break
            self.expect_op(",")
        return ast.CreateTypeStatement(ks, name, fields, ine)

    # DROP / ALTER / TRUNCATE / USE
    def drop(self):
        self.expect_kw("drop")
        if self.accept_ident("identity"):
            t = self.next()
            if t.kind != "STRING":
                raise ParseError("expected identity string")
            return ast.IdentityStatement("drop", str(t.value), None)
        what = self.next().value
        if what in ("role", "user"):
            ife = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                ife = True
            return ast.RoleStatement("drop", self.ident(),
                                     if_not_exists=ife)
        if what == "materialized":
            self.expect_kw("view")
            what = "view"
        if what == "trigger":
            # DROP TRIGGER [IF EXISTS] name ON [ks.]table
            ife = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                ife = True
            tname = self.ident()
            self.expect_kw("on")
            ks, table = self.qualified_name()
            return ast.DropTriggerStatement(ks, table, tname,
                                            if_exists=ife)
        if what not in ("keyspace", "table", "index", "type", "view",
                        "function", "aggregate"):
            raise ParseError(f"unsupported DROP {what}")
        ife = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            ife = True
        ks, name = self.qualified_name()
        return ast.DropStatement(what, ks, name, ife)

    def alter(self):
        self.expect_kw("alter")
        if self.accept_kw("role") or self.accept_kw("user"):
            return self._alter_role()
        self.expect_kw("table")
        ks, name = self.qualified_name()
        if self.accept_kw("add"):
            cols = []
            paren = self.accept_op("(")
            while True:
                cname = self.ident()
                ctype = self._type_string()
                cols.append((cname, ctype))
                if not self.accept_op(","):
                    break
            if paren:
                self.expect_op(")")
            return ast.AlterTableStatement(ks, name, "add", cols)
        if self.accept_kw("drop"):
            cols = [self.ident()]
            while self.accept_op(","):
                cols.append(self.ident())
            return ast.AlterTableStatement(ks, name, "drop", cols)
        if self.accept_kw("with"):
            options = {}
            while True:
                opt = self.ident()
                self.expect_op("=")
                options[opt] = self._option_value()
                if not self.accept_kw("and"):
                    break
            return ast.AlterTableStatement(ks, name, "with", [], options)
        raise ParseError("unsupported ALTER TABLE action")

    def truncate(self):
        self.expect_kw("truncate")
        self.accept_kw("table")
        ks, table = self.qualified_name()
        return ast.TruncateStatement(ks, table)

    def use(self):
        self.expect_kw("use")
        return ast.UseStatement(self.ident())


def parse(text: str):
    return Parser(text).parse_statement()
