"""knob-wiring: every `mutable=True` knob in config.py must actually be
wired — `nodetool setX` succeeding while nothing re-reads the value is
a silent lie to the operator (the `slow_query_log_timeout` bug class,
caught by hand in PR 9).

Wiring evidence, anywhere in cassandra_tpu/ outside config.py:

  * an `on_change("<knob>", ...)` listener registration, or
  * a `.get("<knob>")` settings read, or
  * an attribute re-read site `<something>.<knob>` (the per-use pattern:
    `self.settings.config.read_request_timeout` at request time).

A knob with none of these is reported at its config.py declaration
line; a deliberate exception carries its reason there:

    some_knob: int = mut(0)   # + an allow(knob-wiring) comment w/ reason
"""
from __future__ import annotations

import ast

from ..report import Violation

NAME = "knob-wiring"

CONFIG_MOD = "cassandra_tpu.config"


def mutable_knobs(index, config_mod: str = CONFIG_MOD) -> list[tuple]:
    """[(knob name, line)] for every mutable field of the Config
    dataclass."""
    mod = index.modules.get(config_mod)
    if mod is None:
        return []
    cfg = mod.classes.get("Config")
    if cfg is None:
        return []
    out = []
    for stmt in cfg.node.body:
        if not (isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and isinstance(stmt.value, ast.Call)):
            continue
        call = stmt.value
        fname = call.func.id if isinstance(call.func, ast.Name) else None
        mutable = False
        if fname == "mut":
            mutable = True
        elif fname in ("spec", "field"):
            for kw in call.keywords:
                if kw.arg == "mutable" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is True:
                    mutable = True
                if kw.arg == "metadata" and \
                        isinstance(kw.value, ast.Dict):
                    for k, v in zip(kw.value.keys, kw.value.values):
                        if isinstance(k, ast.Constant) \
                                and k.value == "mutable" \
                                and isinstance(v, ast.Constant) \
                                and v.value is True:
                            mutable = True
        if mutable:
            out.append((stmt.target.id, stmt.lineno))
    return out


def _wired_names(index, config_mod: str) -> set[str]:
    """Every knob name with wiring evidence outside config.py.

    Evidence = an attribute re-read site (`cfg.<knob>`) or the knob's
    name as a STRING CONSTANT (`on_change("<knob>", ...)`,
    `.get("<knob>")`, name tuples driving listener loops). Knob names
    are long and distinctive, so a stray constant collision is
    unlikely — but `tools/` is excluded: nodetool's settings get/set
    side-doors mention every knob without wiring anything (the
    `slow_query_log_timeout` lesson: only its side-door worked)."""
    wired: set[str] = set()
    for mod in index.modules.values():
        if mod.name == config_mod \
                or mod.name.startswith("cassandra_tpu.tools"):
            continue
        docstrings = {node.value for node in ast.walk(mod.tree)
                      if isinstance(node, ast.Expr)
                      and isinstance(node.value, ast.Constant)}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                wired.add(node.attr)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node not in docstrings:
                wired.add(node.value)
    return wired


def run(index, config_mod: str = CONFIG_MOD) -> list[Violation]:
    knobs = mutable_knobs(index, config_mod)
    if not knobs:
        return []
    wired = _wired_names(index, config_mod)
    relpath = index.modules[config_mod].relpath
    out = []
    for name, line in knobs:
        if name not in wired:
            out.append(Violation(
                NAME, relpath, line,
                f"mutable knob `{name}` has no on_change listener, "
                f".get(\"{name}\") read, or attribute re-read site "
                f"anywhere outside config.py — `nodetool set` would "
                f"silently change nothing"))
    return out
