"""Test configuration: force an 8-device virtual CPU mesh so sharding tests
run without TPU hardware (mirrors the reference's in-JVM dtest approach of
simulating a cluster in one process; see SURVEY.md section 4)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
