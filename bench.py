"""Headline benchmark: STCS major-compaction throughput.

Mirrors the reference's measurement (BASELINE.md): cassandra-stress-style
data (default columns are blob() = uniform random bytes, matching the
reference stress defaults; CTPU_BENCH_TEXT=1 for compressible text) ->
N sstables -> major compaction; throughput = input bytes / wall seconds,
the "Read Throughput" the reference logs per compaction
(db/compaction/CompactionTask.java:252-266). vs_baseline compares against
the reference's default compaction_throughput throttle of 64 MiB/s
(conf/cassandra.yaml:1243) — the reference repo publishes no absolute
numbers (BASELINE.json.published = {}).

Engine selection (CTPU_BENCH_ENGINE = native | device | numpy):
  native  C++ k-way streaming merge + inline reconcile (default here).
  device  the TPU kernel (ops/merge.py v3 truncated-key planes: ~6 B/cell
          pushed, 1 B/cell pulled, pipelined rounds).
  numpy   the reference host implementation (executable spec).
All three are tested bit-identical (tests/test_merge_device.py,
tests/test_merge_fastpath.py, tests/test_host_merge.py). The default is
`native` because THIS environment reaches the chip through a tunnel
whose measured warm bandwidth is ~15-20 MiB/s (idle-backend pushes run
at 0.6-1.7 GiB/s; they collapse ~20x once any sizable program has
executed) AND the host has one core — so the device path's remaining
~0.4s link wait cannot beat the C++ merge's 0.06s. The v3 layout took
the device engine from 24 to ~73 MiB/s on this link (BASELINE.md has
the full accounting + the untunneled-chip projection); CompactionTask
takes engine= per deployment. Phase timings are in detail.phases.

Prints ONE json line. The device kernel is warmed on a separate copy of
the data so compile time is excluded.
"""
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

N_RUNS = 4
CELLS_PER_RUN = 262_144
VALUE_BYTES = 64
N_PARTITIONS = 4096


def build_inputs(data_dir, table, seed):
    from cassandra_tpu.storage import cellbatch as cb
    from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
    from cassandra_tpu.tools import bulk

    rng = np.random.default_rng(seed)
    os.makedirs(data_dir, exist_ok=True)
    total = 0
    for run in range(N_RUNS):
        n = CELLS_PER_RUN
        # zipf-ish overlap across runs: same partition space, random rows
        pk = rng.integers(0, N_PARTITIONS, n)
        ck = rng.integers(1, 10_000, n)
        # cassandra-stress default columns are blob() — uniform random
        # bytes (tools/stress SettingsCommand defaults); CTPU_BENCH_TEXT=1
        # switches to compressible lowercase text instead
        if os.environ.get("CTPU_BENCH_TEXT", "0") == "1":
            vals = rng.integers(97, 122, (n, VALUE_BYTES), dtype=np.uint8)
        else:
            vals = rng.integers(0, 256, (n, VALUE_BYTES), dtype=np.uint8)
        ts = rng.integers(1, 1 << 40, n).astype(np.int64)
        batch = bulk.build_int_batch(table, pk, ck, vals, ts)
        merged = cb.merge_sorted([batch])
        w = SSTableWriter(Descriptor(data_dir, run + 1), table,
                          estimated_partitions=N_PARTITIONS)
        w.append(merged)
        stats = w.finish()
        total += stats["n_cells"]
    return total


def run_compaction(base_dir, table, seed):
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore

    cfs = ColumnFamilyStore(table, base_dir, commitlog=None)
    build_inputs(cfs.directory, table, seed)
    cfs.reload_sstables()
    inputs = cfs.tracker.view()
    engine = os.environ.get("CTPU_BENCH_ENGINE", "native")
    task = CompactionTask(cfs, inputs, engine=engine,
                          use_device=engine == "device")
    t0 = time.time()
    stats = task.execute()
    stats["wall"] = time.time() - t0
    stats["profile"] = {k: round(v, 3)
                        for k, v in sorted(task.profile.items())}
    return stats


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from cassandra_tpu.ops.codec import CompressionParams
    from cassandra_tpu.schema import TableParams, make_table

    table = make_table(
        "bench", "stress", pk=["id"], ck=["c"],
        cols={"id": "int", "c": "int", "v": "blob"},
        params=TableParams(compression=CompressionParams("LZ4Compressor")))

    engine = os.environ.get("CTPU_BENCH_ENGINE", "native")
    base = tempfile.mkdtemp(prefix="ctpu-bench-")
    try:
        run_compaction(os.path.join(base, "warm"), table, seed=1)  # compile
        stats = run_compaction(os.path.join(base, "timed"), table, seed=2)
        mib = stats["bytes_read"] / 2**20
        mib_s = mib / stats["wall"]
        result = {
            "metric": "compaction MiB/s (STCS major, 4-way, LZ4 16KiB, "
                      + engine + " engine)",
            "value": round(mib_s, 2),
            "unit": "MiB/s",
            "vs_baseline": round(mib_s / 64.0, 2),
            "detail": {
                "cells_read": stats["cells_read"],
                "cells_written": stats["cells_written"],
                "bytes_read": stats["bytes_read"],
                "bytes_written": stats["bytes_written"],
                "seconds": round(stats["wall"], 3),
                "phases": stats["profile"],
            },
        }
        print(json.dumps(result))
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
