"""Guardrails: operator-configured limits and warnings.

Reference counterpart: db/guardrails/Guardrails.java — thresholds that
warn or fail operations before they hurt the node (tables per keyspace,
batch size, tombstones per read, partition size ...).
"""
from __future__ import annotations

from dataclasses import dataclass, field


class GuardrailViolation(Exception):
    pass


@dataclass
class Guardrails:
    tables_warn_threshold: int = 150
    tables_fail_threshold: int = 500
    batch_statements_warn: int = 50
    batch_statements_fail: int = 500
    tombstones_warn_per_read: int = 1000
    tombstones_fail_per_read: int = 100_000
    collection_size_warn_bytes: int = 5 * 1024 * 1024
    in_select_cartesian_fail: int = 100
    warnings: list = field(default_factory=list)

    @classmethod
    def from_config(cls, overrides: dict | None) -> "Guardrails":
        """Build from the config `guardrails:` block; unknown keys AND
        mis-typed values fail startup (GuardrailsOptions validation)."""
        import dataclasses as _dc

        from ..config import ConfigError
        overrides = overrides or {}
        fields = {f.name: f for f in _dc.fields(cls) if f.name != "warnings"}
        bad = set(overrides) - set(fields)
        if bad:
            raise ConfigError(f"unknown guardrail keys: {sorted(bad)}")
        coerced = {}
        for k, v in overrides.items():
            want = fields[k].type
            if want in ("int", int):
                if isinstance(v, bool) or not isinstance(v, int):
                    raise ConfigError(f"guardrail {k}: expected int, "
                                      f"got {v!r}")
            coerced[k] = v
        return cls(**coerced)

    def _warn(self, msg: str) -> None:
        self.warnings.append(msg)
        if len(self.warnings) > 100:
            self.warnings.pop(0)

    def check_table_count(self, n: int) -> None:
        if n >= self.tables_fail_threshold:
            raise GuardrailViolation(
                f"too many tables ({n} >= {self.tables_fail_threshold})")
        if n >= self.tables_warn_threshold:
            self._warn(f"table count {n} above warn threshold")

    def check_batch_size(self, n: int) -> None:
        if n > self.batch_statements_fail:
            raise GuardrailViolation(
                f"batch with {n} statements (fail threshold "
                f"{self.batch_statements_fail})")
        if n > self.batch_statements_warn:
            self._warn(f"batch with {n} statements above warn threshold")

    def check_tombstones(self, n: int, where: str) -> None:
        if n > self.tombstones_fail_per_read:
            raise GuardrailViolation(
                f"read scanned {n} tombstones in {where} "
                "(TombstoneOverwhelmingException role)")
        if n > self.tombstones_warn_per_read:
            self._warn(f"read scanned {n} tombstones in {where}")

    def check_in_cartesian(self, n: int) -> None:
        if n > self.in_select_cartesian_fail:
            raise GuardrailViolation(
                f"IN restriction expands to {n} partitions")
