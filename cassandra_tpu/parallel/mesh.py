"""Multi-chip data plane: token-range sharding over a jax device mesh.

Design (SURVEY.md section 5.7): the reference parallelises compaction
within a node via UCS's ShardManager (db/compaction/ShardManager.java:33 —
token-range shards compacted independently) and across the cluster by
ownership. The TPU formulation is the same idea on a device mesh: the
token ring is split into one contiguous range per device, each device
runs the merge/reconcile kernel on its shard, and the shard outputs
concatenate — in token order — into exactly the single-device merge.

Two execution paths share the boundary planner:

  per-device dispatch (_run_sharded, the data-plane path): each shard's
      operands are committed to its own mesh device and the jitted merge
      program is driven from a dedicated host thread, so the S
      executions genuinely overlap (measured: the PJRT CPU client
      serializes executions dispatched from ONE thread even across
      devices — ready-times walk up linearly; driven from S threads
      they overlap). Each shard pads to its own power-of-two bucket,
      so a skewed shard no longer inflates every other shard's padded
      program the way the old [S, N_max] layout did.
  shard_map (sharded_merge_step, the one-program demo kernel): the
      original SPMD formulation, kept as the driver's jittable
      multi-chip step and for deployments where one fused program
      beats S dispatches.

Boundary planning (the ShardManager.computeBoundaries role) lives in
the jax-free sibling module `boundaries.py` — count-weighted over
DISTINCT cells (see its docstring for the why) — and is re-exported
here so existing `parallel.mesh` imports keep working; host-engine
mesh paths import from `parallel.boundaries` directly to avoid this
module's jax import.

The per-shard stats every path records land in the `mesh.*` metrics
group (service/metrics.py -> Prometheus): shard cells, device wall
time, shard imbalance.
"""
from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.merge import merge_reconcile_kernel
from ..storage.cellbatch import (DEATH_FLAGS, FLAG_COMPLEX_DEL,
                                 FLAG_EXPIRING, CellBatch)
from .boundaries import (_BIAS, batch_tokens_u64,  # noqa: F401
                         boundaries_from_indexes, boundaries_to_ranges,
                         distinct_token_weights, plan_token_boundaries,
                         record_shard_metrics, shard_imbalance)


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"mesh needs {n_devices} devices, backend "
                f"{jax.default_backend()!r} has {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("shard",))


# ---------------------------------------------------- boundary planning --
# (planners live in boundaries.py — jax-free — and are re-exported
# above; the split below is the mesh-side consumer)

def compute_shards(cat: CellBatch, n_shards: int, boundaries=None):
    """Assign every cell to its token-range shard. Returns (bounds,
    shard_of, pos_in_shard, members). boundaries=None plans
    distinct-weighted ones from the batch itself."""
    n = len(cat)
    tok = batch_tokens_u64(cat)
    if boundaries is None:
        uniq, w = distinct_token_weights(cat)
        boundaries = plan_token_boundaries(uniq, w, n_shards)
    bounds = np.asarray(boundaries, dtype=np.uint64)
    shard_of = np.searchsorted(bounds, tok, side="left").astype(np.int32)
    pos_in_shard = np.zeros(n, dtype=np.int64)
    members: list[np.ndarray] = []
    for s in range(n_shards):
        idx = np.flatnonzero(shard_of == s)
        members.append(idx)
        pos_in_shard[idx] = np.arange(len(idx))
    return bounds, shard_of, pos_in_shard, members


# ------------------------------------------------------------- host split --

def shard_batch(cat: CellBatch, n_shards: int, gc_before: int = 0,
                now: int = 0, boundaries=None):
    """Split a concatenated (unsorted) batch into n token-range shards of
    equal padded size and build the [S, N] operand arrays for
    sharded_merge_step (the one-program shard_map path). Returns
    (operands, shard_of, position_in_shard, shard_members) so the host
    can map kernel outputs back to cells."""
    n = len(cat)
    _bounds, shard_of, pos_in_shard, shard_members = compute_shards(
        cat, n_shards, boundaries)
    counts = np.bincount(shard_of, minlength=n_shards)
    N = max(1024, int(1 << int(np.ceil(np.log2(max(counts.max(), 1))))))

    K = cat.n_lanes
    S = n_shards
    lanes = np.full((S, N, K), 0xFFFFFFFF, dtype=np.uint32)
    valid = np.ones((S, N), dtype=np.uint32)
    ts_h = np.zeros((S, N), dtype=np.uint32)
    ts_l = np.zeros((S, N), dtype=np.uint32)
    death = np.zeros((S, N), dtype=np.uint32)
    cdel = np.zeros((S, N), dtype=np.uint32)
    ldt = np.zeros((S, N), dtype=np.int32)
    expiring = np.zeros((S, N), dtype=np.uint32)
    purge = np.full((S, N), 0xFFFFFFFF, dtype=np.uint32)

    with np.errstate(over="ignore"):
        uts = cat.ts.astype(np.uint64) ^ np.uint64(1 << 63)
    for s in range(S):
        idx = shard_members[s]
        c = len(idx)
        lanes[s, :c] = cat.lanes[idx]
        valid[s, :c] = 0
        ts_h[s, :c] = (uts[idx] >> np.uint64(32)).astype(np.uint32)
        ts_l[s, :c] = (uts[idx] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        death[s, :c] = (cat.flags[idx] & DEATH_FLAGS) != 0
        cdel[s, :c] = (cat.flags[idx] & FLAG_COMPLEX_DEL) != 0
        ldt[s, :c] = cat.ldt[idx]
        expiring[s, :c] = (cat.flags[idx] & FLAG_EXPIRING) != 0

    operands = {
        "lanes": lanes, "valid": valid, "ts_h": ts_h, "ts_l": ts_l,
        "death": death, "cdel": cdel, "ldt": ldt,
        "expiring": expiring, "purge_h": purge, "purge_l": purge.copy(),
        "gc_before": np.int32(gc_before), "now": np.int32(now),
    }
    return operands, shard_of, pos_in_shard, shard_members


# ----------------------------------------------------------- device step --

_step_cache: dict = {}


def sharded_merge_step(mesh: Mesh):
    """Build (or fetch the cached) jitted sharded compaction step for a
    mesh. Input operands carry a leading shard axis partitioned over the
    mesh; each device sorts and reconciles its token range locally, then
    global stats (cells kept, tombstones purged) are psum'd across the
    mesh. Cached per device tuple so repeated rounds reuse one jit
    program (compiles are expensive on this box)."""
    key = tuple(id(d) for d in mesh.devices.flat)
    cached = _step_cache.get(key)
    if cached is not None:
        return cached

    def per_shard(operands):
        # operands arrive with a leading axis of local size 1
        local = {k: (v[0] if getattr(v, "ndim", 0) > 0 else v)
                 for k, v in operands.items()}
        perm, packed = merge_reconcile_kernel(local)
        kept = jnp.sum((packed & 1).astype(jnp.int32))
        dropped = jnp.sum((local["valid"] == 0).astype(jnp.int32)) - kept
        stats = jnp.stack([kept, dropped])
        stats = jax.lax.psum(stats, axis_name="shard")
        return perm[None], packed[None], stats

    arr_spec = P("shard")
    scalar_spec = P()
    in_specs = ({k: (arr_spec if k not in ("gc_before", "now")
                     else scalar_spec)
                 for k in ("lanes", "valid", "ts_h", "ts_l", "death",
                           "cdel", "ldt", "expiring", "purge_h", "purge_l",
                           "gc_before", "now")},)
    out_specs = (arr_spec, arr_spec, P())

    step = jax.jit(jax.shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))
    _step_cache[key] = step
    return step


# ------------------------------------------------ per-device dispatch --

def _shard_bucket(n: int) -> int:
    b = 1024
    while b < n:
        b <<= 1
    return b


@jax.jit
def _shard_merge_program(operands):
    """One shard's whole merge as ONE program (traced LSD sort +
    reconcile): jit caches per (shapes, device), so S same-shaped
    shards on S devices compile once per device and stay warm across
    rounds."""
    return merge_reconcile_kernel(operands)


def _pack_shard_operands(cat: CellBatch, idx: np.ndarray,
                         gc_before: int, now: int) -> dict:
    """Kernel operand arrays for one shard, padded to the shard's OWN
    power-of-two bucket (the [S, N_max] layout paid every shard the
    skew of the largest one)."""
    c = len(idx)
    N = _shard_bucket(c)
    K = cat.n_lanes
    lanes = np.full((N, K), 0xFFFFFFFF, dtype=np.uint32)
    lanes[:c] = cat.lanes[idx]
    valid = np.ones(N, dtype=np.uint32)
    valid[:c] = 0
    with np.errstate(over="ignore"):
        uts = cat.ts[idx].astype(np.uint64) ^ np.uint64(1 << 63)
    ts_h = np.zeros(N, dtype=np.uint32)
    ts_l = np.zeros(N, dtype=np.uint32)
    ts_h[:c] = (uts >> np.uint64(32)).astype(np.uint32)
    ts_l[:c] = (uts & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    death = np.zeros(N, dtype=np.uint32)
    death[:c] = (cat.flags[idx] & DEATH_FLAGS) != 0
    cdel = np.zeros(N, dtype=np.uint32)
    cdel[:c] = (cat.flags[idx] & FLAG_COMPLEX_DEL) != 0
    ldt = np.zeros(N, dtype=np.int32)
    ldt[:c] = cat.ldt[idx]
    expiring = np.zeros(N, dtype=np.uint32)
    expiring[:c] = (cat.flags[idx] & FLAG_EXPIRING) != 0
    purge = np.full(N, 0xFFFFFFFF, dtype=np.uint32)
    return {
        "lanes": lanes, "valid": valid, "ts_h": ts_h, "ts_l": ts_l,
        "death": death, "cdel": cdel, "ldt": ldt, "expiring": expiring,
        "purge_h": purge, "purge_l": purge.copy(),
        "gc_before": np.int32(gc_before), "now": np.int32(now),
    }


def _run_sharded(cat: CellBatch, mesh: Mesh, gc_before: int, now: int,
                 boundaries=None):
    """split -> per-device dispatch -> host tie-break. Each shard's
    program is committed to its own mesh device and DRIVEN FROM ITS OWN
    HOST THREAD: the PJRT client serializes executions dispatched from
    one thread even across devices (measured: ready-times walk up
    linearly), while thread-driven executions overlap. Returns the full
    per-shard state (keep/perm/masks in shard-padded [S, N] layout,
    member index lists, (kept, dropped) stats) plus per-shard device
    wall seconds."""
    from ..ops.merge import host_tiebreak, unpack_masks

    n_shards = mesh.devices.size
    devices = list(mesh.devices.flat)
    _bounds, shard_of, pos, members = compute_shards(cat, n_shards,
                                                     boundaries)
    results: list = [None] * n_shards
    walls = [0.0] * n_shards
    errors: list[BaseException] = []

    def run_shard(s: int) -> None:
        idx = members[s]
        if len(idx) == 0:
            return
        try:
            ops_np = _pack_shard_operands(cat, idx, gc_before, now)
            t0 = time.perf_counter()
            jop = {k: jax.device_put(v, devices[s])
                   for k, v in ops_np.items()}
            perm_d, packed_d = _shard_merge_program(jop)
            perm = np.asarray(perm_d)
            packed = np.asarray(packed_d)
            walls[s] = time.perf_counter() - t0
            results[s] = (perm, packed)
        except BaseException as e:   # surfaced after join
            errors.append(e)

    from ..service.profiling import GLOBAL as _kprof
    t_all = time.perf_counter()
    live = [s for s in range(n_shards) if len(members[s])]
    if len(live) <= 1:
        for s in live:
            run_shard(s)
    else:
        threads = [threading.Thread(target=run_shard, args=(s,),
                                    name=f"mesh-shard-{s}")
                   for s in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0]
    dispatch_s = time.perf_counter() - t_all
    _kprof.record_dispatch(
        "merge.sharded_step",
        (n_shards, (len(cat), cat.n_lanes)),
        dispatch_s)
    _kprof.record_execute("merge.sharded_step", max(walls) if walls
                          else 0.0)

    # assemble the shard-padded [S, N] view (N = largest shard bucket)
    N = max((_shard_bucket(len(members[s])) for s in live), default=1024)
    keep = np.zeros((n_shards, N), dtype=bool)
    amb = np.zeros((n_shards, N), dtype=bool)
    expired = np.zeros((n_shards, N), dtype=bool)
    shadowed = np.zeros((n_shards, N), dtype=bool)
    perm = np.zeros((n_shards, N), dtype=np.int32)
    for s in live:
        p, packed = results[s]
        k, a, e, sh = unpack_masks(packed)
        w = len(p)
        keep[s, :w] = k
        amb[s, :w] = a
        expired[s, :w] = e
        shadowed[s, :w] = sh
        perm[s, :w] = p
    # equal-(identity, ts) winners need the exact death/value rules — per
    # shard, map sorted positions back into cat and resolve on host.
    for s in live:
        c = len(members[s])
        if c == 0 or not amb[s, :c].any():
            continue
        perm_real = members[s][perm[s, :c]]
        host_tiebreak(cat, perm_real, keep[s, :c], amb[s, :c],
                      shadowed[s, :c], expired[s, :c], gc_before, None)
    kept = sum(int(keep[s, :len(members[s])].sum()) for s in live)
    stats = np.array([kept, len(cat) - kept], dtype=np.int64)
    record_shard_metrics([len(members[s]) for s in range(n_shards)],
                         walls)
    return (keep, perm, expired, shadowed, stats, shard_of, pos, members,
            walls, dispatch_s)


def run_sharded_merge(cat: CellBatch, mesh: Mesh, gc_before: int = 0,
                      now: int = 0, boundaries=None):
    """Host orchestration: split -> per-device step -> host tie-break ->
    per-shard outputs. Returns (keep [S,N] numpy, perm [S,N],
    stats (kept, dropped), shard_of, pos_in_shard)."""
    keep, perm, _, _, stats, shard_of, pos, _, _, _ = _run_sharded(
        cat, mesh, gc_before, now, boundaries)
    return keep, perm, stats, shard_of, pos


def materialize_sharded_merge(cat: CellBatch, mesh: Mesh,
                              gc_before: int = 0, now: int = 0,
                              boundaries=None,
                              walls_out: list | None = None,
                              dispatch_out: list | None = None
                              ) -> list[CellBatch]:
    """Per-shard merged CellBatches, token-ordered: shard s holds exactly
    the cells whose token falls in its range, reconciled, sorted. The
    concatenation equals the single-device merge output bit-for-bit, and
    each element can feed its own SSTableWriter — the ShardManager model
    (db/compaction/ShardManager.java:33: disjoint token shards feed
    independent writers). walls_out (optional list) receives the
    per-shard device wall seconds; dispatch_out receives the one-element
    [elapsed seconds] of the whole concurrent dispatch (first thread
    start to last join) — the denominator an overlap proof needs (the
    per-shard walls alone cannot distinguish overlap from a sequential
    loop)."""
    from ..ops.merge import finalize_merged

    (keep, perm, expired, shadowed, _, _, _, members, walls,
     dispatch_s) = _run_sharded(cat, mesh, gc_before, now, boundaries)
    if walls_out is not None:
        walls_out[:] = walls
    if dispatch_out is not None:
        dispatch_out[:] = [dispatch_s]
    out: list[CellBatch] = []
    for s in range(len(members)):
        c = len(members[s])
        if c == 0:
            out.append(CellBatch.empty(cat.n_lanes))
            continue
        perm_real = members[s][perm[s, :c]]
        out.append(finalize_merged(cat, perm_real, keep[s, :c],
                                   expired[s, :c], shadowed[s, :c]))
    return out


def sharded_compact_to_sstables(batches: list[CellBatch], table, mesh,
                                directory: str, generation_base: int = 0,
                                gc_before: int = 0, now: int = 0,
                                shards: list[CellBatch] | None = None):
    """One compaction round over the mesh, landing one sstable per shard:
    merge the input CellBatches sharded across devices, then write each
    shard's reconciled output through a real SSTableWriter. Pass
    precomputed `shards` (from materialize_sharded_merge) to skip the
    merge. Returns the list of (Descriptor, stats) for non-empty shards."""
    from ..storage.sstable.format import Descriptor
    from ..storage.sstable.writer import SSTableWriter

    import os

    if shards is None:
        cat = CellBatch.concat(batches)
        shards = materialize_sharded_merge(cat, mesh, gc_before, now)
    results = []
    try:
        for s, shard in enumerate(shards):
            if len(shard) == 0:
                continue
            desc = Descriptor(directory, generation_base + s)
            w = SSTableWriter(desc, table)
            try:
                w.append(shard)
                stats = w.finish()
            except BaseException:
                w.abort()
                raise
            results.append((desc, stats))
    except BaseException:
        # all-or-nothing round (LifecycleTransaction semantics): a failed
        # shard write must not leave earlier shards' sstables behind as a
        # partial compaction output
        for desc, _stats in results:
            for p in desc.all_paths():
                if os.path.exists(p):
                    os.remove(p)
        raise
    return results
