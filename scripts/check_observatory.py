#!/usr/bin/env python
"""CI check (tier-2): the workload observatory — retained metrics
history, per-table amplification accounting, cluster-wide telemetry
(docs/observability.md layer 5).

Leg 1 (engine): a deterministic engine run (3 flushed generations +
a major compaction, on-demand history samples between phases) must
leave

  - `system_views.metrics_history` populated (raw rows for
    `storage.writes` and the per-table counters, non-negative derived
    rates, coarse rows after enough raw samples);
  - the WA/SA gauges arithmetically reconciled against the run's
    ACTUAL byte counters: write_amplification ==
    (bytes_flushed + bytes_compacted_out) / bytes_ingested from the
    same `cfs.metrics` dict, space_amplification == live partition
    instances / distinct partitions recomputed from the live
    sstables' partition-token directories (1.0 after the major
    compaction);
  - `nodetool tablestats` / `tablehistograms` carrying the new
    blocks, `compaction_history` bounded by its knob (newest kept),
    and an on-demand flight-recorder bundle carrying a non-empty
    `metrics_history` window plus the `pipeline_ledger` table.

Leg 2 (cluster): `nodetool clusterstats` over a 3-node RF=3
LocalCluster returns one row per node with fresh peer snapshots; after
one node goes dark the pull STILL returns within its bound (no hang on
the messaging dispatch worker), the dark node's row carries its last
known snapshot with a staleness stamp, and the coordinator still
serves traffic afterwards.

Exit 0 = clean; exit 1 prints each violation.
"""
from __future__ import annotations

import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _recompute_sa(cfs) -> float:
    live = cfs.live_sstables()
    total = sum(s.n_partitions for s in live)
    if total == 0:
        return 1.0
    toks = np.concatenate([np.asarray(s.partition_tokens)
                           for s in live if s.n_partitions > 0])
    return total / max(len(np.unique(toks)), 1)


def check_engine_leg(base_dir: str) -> list[str]:
    from cassandra_tpu.config import Config, Settings
    from cassandra_tpu.cql import Session
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.service import diagnostics
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.tools import nodetool

    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    settings = Settings(Config.load({"compaction_history_entries": 2,
                                     "compaction_throughput": 0}))
    eng = StorageEngine(base_dir, Schema(), commitlog_sync="batch",
                        settings=settings)
    try:
        s = Session(eng)
        s.execute("CREATE KEYSPACE obs WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE obs")
        s.execute("CREATE TABLE t (k int PRIMARY KEY, v text)")
        cfs = eng.store("obs", "t")
        svc = eng.metrics_history
        need(not svc.enabled,
             "sampler thread running with the knob off (zero-cost rule)")
        for gen in range(3):
            for i in range(48):
                s.execute(f"INSERT INTO t (k, v) VALUES ({i}, "
                          f"'g{gen}-{i}')")
            cfs.flush()
            svc.sample()
        # 3 overlapping generations: SA must read the overlap
        sa_overlapped = cfs.amplification()["space_amplification"]
        need(sa_overlapped > 1.5,
             f"3 full-overlap generations read SA {sa_overlapped}")
        stats = eng.compactions.major_compaction(cfs)
        need(stats is not None and stats["inputs"] == 3,
             f"major compaction saw {stats and stats['inputs']} inputs")
        svc.sample()

        # --- WA/SA reconcile EXACTLY against the run's own counters
        m = cfs.metrics
        amp = cfs.amplification()
        need(m["bytes_ingested"] > 0 and m["bytes_flushed"] > 0
             and m["bytes_compacted_in"] > 0
             and m["bytes_compacted_out"] > 0,
             f"byte counters not all populated: {m}")
        need(m["bytes_compacted_in"] == stats["bytes_read"]
             and m["bytes_compacted_out"] == stats["bytes_written"],
             "compaction byte counters diverge from the task stats")
        wa = (m["bytes_flushed"] + m["bytes_compacted_out"]) \
            / m["bytes_ingested"]
        need(amp["write_amplification"] == round(wa, 6),
             f"WA gauge {amp['write_amplification']} != recomputed "
             f"{round(wa, 6)}")
        sa = _recompute_sa(cfs)
        need(amp["space_amplification"] == round(sa, 6),
             f"SA gauge {amp['space_amplification']} != recomputed "
             f"{round(sa, 6)}")
        need(amp["space_amplification"] == 1.0,
             f"post-major-compaction SA {amp['space_amplification']}"
             " != 1.0")

        # --- history vtable populated; rates sane
        vt = eng.virtual_tables.get("system_views", "metrics_history")
        rows = vt.rows()
        need(rows, "metrics_history vtable is empty after samples")
        writes_rows = [r for r in rows
                       if r["name"] == "table.obs.t.writes"
                       and r["resolution"] == "raw"]
        need(len(writes_rows) == 4,
             f"expected 4 raw samples of table.obs.t.writes, got "
             f"{len(writes_rows)}")
        need(all(r["rate_per_s"] >= 0.0 for r in rows),
             "negative derived rate in metrics_history")
        need(writes_rows[-1]["last"] == 144.0,
             f"history last writes sample {writes_rows[-1]['last']}"
             " != 144")

        # --- nodetool surfaces
        ts = nodetool.tablestats(eng)["obs.t"]
        for key in ("write_amplification", "space_amplification",
                    "bytes_ingested", "bytes_compacted_out"):
            need(key in ts, f"tablestats lacks {key}")
        th = nodetool.tablehistograms(eng, "obs", "t")["obs.t"]
        need("read_latency" in th and "sstables_per_read" in th,
             f"tablehistograms lacks the hist block: {sorted(th)}")
        mh = nodetool.metricshistory(eng, name="table.obs.t.writes",
                                     rate=True)
        need(len(mh["buckets"]) == 4,
             "nodetool metricshistory bucket count wrong")

        # --- compaction_history bounded, newest kept
        for i in range(4):
            cfs.compaction_history.append({"marker": i})
        need(len(cfs.compaction_history) == 2
             and list(cfs.compaction_history)[-1]["marker"] == 3,
             "compaction_history not bounded newest-kept at knob=2")
        settings.set("compaction_history_entries", 1)
        need(len(cfs.compaction_history) == 1,
             "compaction_history_entries hot-set did not rebind")

        # --- bundle carries the history window + ledger table
        import json as _json
        path = eng.flight_recorder.dump("observatory_check")
        with open(path) as fh:
            bundle = _json.load(fh)
        win = bundle.get("metrics_history", {})
        need(bool(win) and any(win.values()),
             "bundle metrics_history window empty")
        need("pipeline_ledger" in bundle,
             "bundle lacks pipeline_ledger")
    finally:
        eng.close()
        diagnostics.GLOBAL.reset()
    return errs


def check_cluster_leg(base_dir: str) -> list[str]:
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    from cassandra_tpu.tools import nodetool

    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    c = LocalCluster(3, base_dir, rf=3)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("CREATE TABLE ks.t (k int PRIMARY KEY, v text)")
        c.node(1).default_cl = ConsistencyLevel.ALL
        s.keyspace = "ks"
        for i in range(24):
            s.execute(f"INSERT INTO ks.t (k, v) VALUES ({i}, 'v{i}')")
        cs = nodetool.clusterstats(c.node(1), timeout=2.0)
        need(len(cs["nodes"]) == 3,
             f"clusterstats rows {len(cs['nodes'])} != 3")
        need(cs["keyspaces"].get("ks", {}).get("rf") == 3,
             "clusterstats not RF-aware for ks")
        by_ep = {r["endpoint"]: r for r in cs["nodes"]}
        need(all(r["fresh"] and r["snapshot"] is not None
                 for r in cs["nodes"]),
             "healthy cluster pull returned stale/absent snapshots")
        need(by_ep["node2"]["snapshot"]["tables"]
             .get("ks.t", {}).get("writes", 0) >= 24,
             "peer snapshot lacks replica write counts")
        # --- dark node: bounded pull, staleness stamp, no hang
        c.stop_node(3)
        t0 = time.monotonic()
        cs2 = nodetool.clusterstats(c.node(1), timeout=1.0)
        took = time.monotonic() - t0
        need(took < 5.0, f"pull with a dark node took {took:.1f}s")
        row3 = {r["endpoint"]: r for r in cs2["nodes"]}["node3"]
        need(row3["fresh"] is False,
             "dark node reported a fresh snapshot")
        need(row3["snapshot"] is not None
             and row3["stale_s"] is not None and row3["stale_s"] > 0,
             "dark node lost its last-known snapshot/staleness stamp")
        # the dispatch worker survived: the coordinator still serves
        # (QUORUM — 2 of 3 replicas are up)
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        rs = s.execute("SELECT v FROM ks.t WHERE k = 1")
        need(len(list(rs)) == 1,
             "coordinator stopped serving after the dark-node pull")
    finally:
        c.shutdown()
    return errs


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    errs = []
    with tempfile.TemporaryDirectory() as d:
        errs += check_engine_leg(os.path.join(d, "engine"))
        errs += check_cluster_leg(os.path.join(d, "cluster"))
    if errs:
        print("check_observatory: FAIL", file=sys.stderr)
        for e in errs:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print("check_observatory: history rings, WA/SA reconciliation and "
          "cluster telemetry OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
