"""ctpulint check registry. Each check is `run(index) -> [Violation]`;
the driver (scripts/check_static.py) owns suppression filtering and
exit-code policy."""
from . import (clock_discipline, knob_wiring, lock_order, loop_blocking,
               worker_loops)

# name -> (module, one-line description printed by --list / docs)
CHECKS = {
    "lock-order": (
        lock_order,
        "static lock-acquisition graph across the call graph must be "
        "acyclic"),
    "loop-blocking": (
        loop_blocking,
        "no fsync/sleep/wait/join reachable from transport event-loop "
        "callbacks or under the gossip lock"),
    "knob-wiring": (
        knob_wiring,
        "every mutable=True config knob has an on_change listener or a "
        "per-use re-read site"),
    "worker-loops": (
        worker_loops,
        "daemon worker loops are guarded so an exception cannot kill "
        "them silently"),
    "clock-discipline": (
        clock_discipline,
        "clock-injectable / sim-patched modules never bind the real "
        "clock"),
}


def run_all(index, names=None):
    out = []
    for name, (mod, _desc) in CHECKS.items():
        if names is None or name in names:
            out.extend(mod.run(index))
    return out
