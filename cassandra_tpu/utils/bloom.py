"""Bloom filter over partition keys, built in batch.

Reference semantics: utils/BloomFilter.java:31 — k indexes derived from
murmur3 x64/128 as (h1 + i*h2) mod bits (Kirsch-Mitzenmacher double
hashing), bitset in utils/obs/OffHeapBitSet. Here the bitset is a numpy
uint64 array and adds/queries are vectorised over whole key batches — the
flush path hashes every partition key in one call (see
storage/sstable/writer.py)."""
from __future__ import annotations

import math
import struct

import numpy as np

from . import murmur3


def optimal_params(n: int, fp_rate: float) -> tuple[int, int]:
    """(bits, k) for n elements at the target false-positive rate."""
    n = max(n, 1)
    bits = max(64, int(math.ceil(-n * math.log(fp_rate) / (math.log(2) ** 2))))
    bits = (bits + 63) // 64 * 64
    k = max(1, int(round(bits / n * math.log(2))))
    return bits, min(k, 20)


class BloomFilter:
    def __init__(self, bits: int, k: int):
        self.bits = bits
        self.k = k
        self.words = np.zeros(bits // 64, dtype=np.uint64)

    @classmethod
    def create(cls, n: int, fp_rate: float = 0.01) -> "BloomFilter":
        return cls(*optimal_params(n, fp_rate))

    def _indexes(self, h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
        i = np.arange(self.k, dtype=np.uint64)
        with np.errstate(over="ignore"):
            idx = (h1[:, None] + i[None, :] * h2[:, None]) % np.uint64(self.bits)
        return idx

    def add_batch(self, keys: list[bytes]) -> None:
        if not keys:
            return
        h1, h2 = murmur3.hash128_batch(keys)
        idx = self._indexes(h1, h2).ravel()
        np.bitwise_or.at(self.words, (idx >> np.uint64(6)).astype(np.int64),
                         np.uint64(1) << (idx & np.uint64(63)))

    def add(self, key: bytes) -> None:
        self.add_batch([key])

    def might_contain_batch(self, keys: list[bytes]) -> np.ndarray:
        if not keys:
            return np.zeros(0, dtype=bool)
        h1, h2 = murmur3.hash128_batch(keys)
        idx = self._indexes(h1, h2)
        w = self.words[(idx >> np.uint64(6)).astype(np.int64)]
        hit = (w >> (idx & np.uint64(63))) & np.uint64(1)
        return hit.all(axis=1)

    def might_contain(self, key: bytes) -> bool:
        return bool(self.might_contain_batch([key])[0])

    # ------------------------------------------------------------- serde --

    def serialize(self) -> bytes:
        head = struct.pack("<QII", self.bits, self.k, 0)
        return head + self.words.tobytes()

    @classmethod
    def deserialize(cls, data: bytes) -> "BloomFilter":
        bits, k, _ = struct.unpack_from("<QII", data, 0)
        bf = cls(bits, k)
        bf.words = np.frombuffer(data, dtype=np.uint64, offset=16).copy()
        return bf
