"""Device-side LZ4 block compression for the compaction write path.

LUDA's endgame (PAPERS.md, arxiv 2004.03054): compaction blocks leave
the accelerator already compressed and the host io thread is reduced
to a pwrite pump. The precondition is determinism — every
check_compaction_ab.py leg must stay byte-identical for any pool size
× device on/off — so the native encoder (ops/native/codec.cpp
`lz4_compress`) is a fixed POLICY, not a heuristic: at each visited
position take the longest forward run over the DISTANCES candidate
set (ties → smallest distance), accept iff ≥ MINMATCH, else advance
one byte. A hash-table matcher's output depends on probe/insertion
order, which a data-parallel scan cannot replay; the policy's argmax
is order-free and maps to one vectorized shifted-equality pass per
candidate distance — a single fused jax program over the device
pending buffer (lane shuffle + order check + both match scans).

The LZ4 wire emission (greedy parse + token stream) is inherently
sequential but cheap — O(emitted sequences), not O(bytes × distances)
— so it runs host-side from the pulled (best_len, best_d) arrays.

Three implementations, one contract:
  native  lz4_compress (codec.cpp)          — host CompressorPool legs
  numpy   match_scan_np + emit_block        — reference; payload block
  jax     segment_scan_kernel + emit_block  — device META/lane blocks
Byte equality across all three is pinned by tests/test_device_compress
and the check_compaction_ab.py `device_compress*` legs.
"""
from __future__ import annotations

import zlib

import numpy as np
import jax
import jax.numpy as jnp

MINMATCH = 4

# Must stay identical to LZ4_DIST in ops/native/codec.cpp: all short
# lags 1..64 (columnar 25-byte META strides, shuffled lane byte-planes,
# periodic text) plus power-of-two long lags up to the format's 64KiB
# window. Ascending order is load-bearing: ties resolve to the
# SMALLEST distance.
DISTANCES = tuple(range(1, 65)) + (128, 256, 512, 1024, 2048, 4096,
                                   8192, 16384, 32768)


# ------------------------------------------------------------- scans -----

def match_scan_np(src: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Reference policy match scan: for every position, the longest
    forward run over DISTANCES (ties → smallest d). Runs shorter than
    MINMATCH may appear in best_len; the parse ignores them, so the
    native encoder's 4-byte prefilter and this full scan emit the same
    sequences."""
    src = np.asarray(src, dtype=np.uint8).reshape(-1)
    n = src.size
    best_len = np.zeros(n, dtype=np.int64)
    best_d = np.zeros(n, dtype=np.int64)
    idx = np.arange(n, dtype=np.int64)
    for d in DISTANCES:
        if d >= n:
            break
        e = src[d:] == src[:-d]
        nxt = np.where(e, n, idx[d:])
        nxt = np.minimum.accumulate(nxt[::-1])[::-1]
        run = nxt - idx[d:]
        bl = best_len[d:]
        upd = run > bl
        bl[upd] = run[upd]
        best_d[d:][upd] = d
    return best_len, best_d


def _policy_scan(src, n):
    """Traced body of the policy scan; one shifted-equality pass +
    reversed cummin per candidate distance (the python loop unrolls
    over the static distance table)."""
    idx = jnp.arange(n, dtype=jnp.int32)
    best_len = jnp.zeros((n,), dtype=jnp.int32)
    best_d = jnp.zeros((n,), dtype=jnp.int32)
    for d in DISTANCES:
        if d >= n:
            break
        e = jnp.zeros((n,), dtype=jnp.bool_).at[d:].set(
            src[d:] == src[:-d])
        nxt = jnp.where(e, jnp.int32(n), idx)
        nxt = jax.lax.cummin(nxt, axis=0, reverse=True)
        run = nxt - idx
        upd = run > best_len
        best_len = jnp.where(upd, run, best_len)
        best_d = jnp.where(upd, jnp.int32(d), best_d)
    return best_len, best_d


@jax.jit
def _scan_kernel(src):
    return _policy_scan(src, src.shape[0])


@jax.jit
def segment_scan_kernel(meta_u8, lanes_u32):
    """The fused device program for one full segment: lane shuffle to
    byte planes (segment_pack's byte_transpose, via the LE u32→u8
    bitcast), the u32-lexicographic order check, and the policy match
    scan over both compressible device-resident blocks. Returns
    (planes, meta_best_len, meta_best_d, lane_best_len, lane_best_d,
    order_ok)."""
    n, k = lanes_u32.shape
    planes = jax.lax.bitcast_convert_type(lanes_u32, jnp.uint8)
    planes = planes.reshape(n, 4 * k).T.reshape(-1)
    a = lanes_u32[:-1]
    b = lanes_u32[1:]
    neq = a != b
    firstc = jnp.argmax(neq, axis=1)
    rows = jnp.arange(n - 1)
    bad = neq.any(axis=1) & (b[rows, firstc] < a[rows, firstc])
    order_ok = ~bad.any()
    mbl, mbd = _policy_scan(meta_u8, meta_u8.shape[0])
    lbl, lbd = _policy_scan(planes, planes.shape[0])
    return planes, mbl, mbd, lbl, lbd, order_ok


# ---------------------------------------------------------- emission -----

def emit_block(src, best_len, best_d, cap: int):
    """LZ4 block-format emission from policy match arrays. Returns the
    compressed bytes, or None when the output would overrun `cap` —
    including the native encoder's slightly conservative per-sequence
    `need` bound, replicated exactly so the compress-vs-raw decision
    lands on the same side at the boundary."""
    src = np.asarray(src, dtype=np.uint8).reshape(-1)
    n = src.size
    if n == 0:
        return b"\x00" if cap >= 1 else None
    mem = src.tobytes()
    out = bytearray()
    pos = 0
    anchor = 0
    mf = n - 12
    if mf > 0:
        bl = np.asarray(best_len, dtype=np.int64)[:mf]
        bd = np.asarray(best_d, dtype=np.int64)[:mf]
        cand = np.flatnonzero(bl >= MINMATCH)
        while True:
            j = int(np.searchsorted(cand, pos))
            if j >= cand.size:
                break
            p = int(cand[j])
            m = int(bl[p])
            # clamp to the literal tail; p < n-12 keeps m >= MINMATCH
            if m > n - 5 - p:
                m = n - 5 - p
            d = int(bd[p])
            lit = p - anchor
            ml = m - MINMATCH
            need = 1 + lit // 255 + 1 + lit + 2 + ml // 255 + 1
            if len(out) + need > cap:
                return None
            out.append(((15 if lit >= 15 else lit) << 4)
                       | (15 if ml >= 15 else ml))
            if lit >= 15:
                l = lit - 15
                while l >= 255:
                    out.append(255)
                    l -= 255
                out.append(l)
            out += mem[anchor:p]
            out.append(d & 0xFF)
            out.append(d >> 8)
            if ml >= 15:
                l = ml - 15
                while l >= 255:
                    out.append(255)
                    l -= 255
                out.append(l)
            pos = p + m
            anchor = pos
    lit = n - anchor
    need = 1 + lit // 255 + 1 + lit
    if len(out) + need > cap:
        return None
    out.append((15 if lit >= 15 else lit) << 4)
    if lit >= 15:
        l = lit - 15
        while l >= 255:
            out.append(255)
            l -= 255
        out.append(l)
    out += mem[anchor:]
    return bytes(out)


def compress_np(data, cap: int | None = None):
    """Full numpy reference: scan + emit. Equals the native
    lz4_compress byte-for-byte (tests pin this)."""
    src = np.frombuffer(bytes(data), dtype=np.uint8)
    if cap is None:
        cap = src.size + src.size // 255 + 16
    bl, bd = match_scan_np(src)
    return emit_block(src, bl, bd, cap)


def compress_jax(data, cap: int | None = None):
    """Device scan + host emit (test entry; production goes through
    segment_scan_kernel so the whole segment is one program)."""
    src = np.frombuffer(bytes(data), dtype=np.uint8)
    if cap is None:
        cap = src.size + src.size // 255 + 16
    if src.size == 0:
        return emit_block(src, src, src, cap)
    bl, bd = _scan_kernel(jnp.asarray(src))
    return emit_block(src, np.asarray(bl), np.asarray(bd), cap)


# ------------------------------------------------------ segment pack -----

def pack_device_segment(meta, planes, scans, payload, attempt,
                        max_compressed_length: int):
    """segment_pack's compress-or-raw placement, replicated from device
    scan results: returns (total, sizes, crcs, parts) where parts are
    the stored bytes of the (META, lanes, payload) blocks in order.
    `planes` is the lane block already shuffled to byte planes (its
    stored form); `scans` carries the device (best_len, best_d) pairs
    for META and planes, and the payload block — host memory — scans
    through the numpy reference on demand. The compress-vs-raw rule is
    segment_pack's verbatim: compressed iff the emission fits
    cap = min(srcLen, max_compressed_length) AND is shorter than both
    bounds."""
    maxlen = int(max_compressed_length)
    blocks = ((meta, scans[0]), (planes, scans[1]), (payload, None))
    parts, sizes, crcs = [], [], []
    for (blk, scan), att in zip(blocks, attempt):
        raw = np.asarray(blk, dtype=np.uint8).reshape(-1)
        stored = None
        if att:
            cap = min(raw.size, maxlen)
            if scan is None:
                scan = match_scan_np(raw)
            c = emit_block(raw, scan[0], scan[1], cap)
            if c is not None and len(c) < raw.size and len(c) < maxlen:
                stored = c
        if stored is None:
            stored = raw.tobytes()
        parts.append(stored)
        sizes.append(len(stored))
        crcs.append(zlib.crc32(stored))
    return sum(sizes), sizes, crcs, parts
