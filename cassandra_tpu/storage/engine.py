"""Node-local storage engine: schema + commitlog + per-table stores.

Reference counterpart: the Keyspace.apply path (db/Keyspace.java:475 —
commitlog add, then memtable put) plus CassandraDaemon.setup's commitlog
recovery (service/CassandraDaemon.java:268,339).
"""
from __future__ import annotations

import os
import threading

from ..schema import Schema, TableMetadata
from ..utils import timeutil
from .commitlog import CommitLog
from .mutation import Mutation
from .table import ColumnFamilyStore


class StorageEngine:
    def __init__(self, data_dir: str, schema: Schema | None = None,
                 durable_writes: bool = True,
                 commitlog_sync: str = "periodic",
                 flush_threshold: int | None = None,
                 auth_enabled: bool = False,
                 audit_log_path: str | None = None,
                 keystore_dir: str | None = None,
                 commitlog_archive_dir: str | None = None,
                 encrypt_commitlog: bool = False,
                 commitlog_compression: str | None = None,
                 settings=None):
        """keystore_dir enables TDE: an EncryptionContext is installed
        node-wide (tables opt in via WITH encryption = {'enabled': true};
        encrypt_commitlog covers the WAL). commitlog_archive_dir turns on
        the segment archiver for point-in-time restore. settings: a
        config.Settings (DatabaseDescriptor role); defaults apply when
        omitted."""
        from ..config import Settings
        self.settings = settings or Settings()
        self.data_dir = data_dir
        self.schema = schema or Schema()
        self.durable = durable_writes
        self.flush_threshold = flush_threshold
        # inline threshold-flush stalls paid by writers, THIS engine
        # only (the storage.write_stall histogram is process-global;
        # the native-transport overload signal needs an engine-scoped
        # count so one node's stall can't shed a co-hosted node's
        # traffic)
        self.write_stalls = 0
        os.makedirs(data_dir, exist_ok=True)
        self.encryption_ctx = None
        if keystore_dir:
            from . import encryption as enc_mod
            existing = enc_mod.get_context()
            if existing is not None and \
                    os.path.realpath(existing.keystore_dir) != \
                    os.path.realpath(keystore_dir):
                # the context is process-level state (the reference's
                # DatabaseDescriptor role) and a cluster must share one
                # keystore anyway — streamed sstables land encrypted and
                # every replica needs the keys. Two different keystores
                # in one process would silently cross-encrypt.
                raise enc_mod.EncryptionError(
                    f"an EncryptionContext for "
                    f"{existing.keystore_dir!r} is already installed; "
                    f"in-process nodes must share one keystore")
            if existing is None:
                enc_mod.set_context(enc_mod.EncryptionContext(keystore_dir))
            self.encryption_ctx = enc_mod.get_context()
        # storage failure policies (FSErrorHandler/JVMStabilityInspector
        # role; storage/failures.py): created BEFORE the commitlog and
        # the stores so every disk/commit error from first open onward
        # funnels into one policy decision
        from .failures import FailureHandler
        self.failures = FailureHandler(self.settings)
        from .cdc import CDCLog
        self.cdc = CDCLog(os.path.join(data_dir, "cdc_raw"))
        self.commitlog = CommitLog(
            os.path.join(data_dir, "commitlog"),
            sync_mode=commitlog_sync,
            archive_dir=commitlog_archive_dir,
            encrypt=encrypt_commitlog,
            compression=commitlog_compression
            or (self.settings.get("commitlog_compression") or None),
            group_window_ms=self.settings.get(
                "commitlog_sync_group_window") * 1000.0,
            failure_handler=self.failures) \
            if durable_writes else None
        # nodetool enablebackup: flushed sstables hardlink into
        # <table>/backups/ (incremental_backups role). Set BEFORE any
        # store opens — replay at startup creates stores that read it.
        # Seeded from (and hot-following) the incremental_backups knob;
        # nodetool enablebackup/disablebackup still writes the
        # attribute directly.
        self.incremental_backup = bool(
            self.settings.get("incremental_backups"))
        self._backup_listener = \
            lambda v: setattr(self, "incremental_backup", bool(v))
        self.settings.on_change("incremental_backups",
                                self._backup_listener)
        # full-query log (fql/FullQueryLogger role): a second audit
        # stream capturing EVERY statement when enabled
        self.fql_log = None
        self.stores: dict = {}  # table_id -> ColumnFamilyStore
        self._lock = threading.RLock()
        # background compaction (CompactionManager role): flushes enqueue
        # the store; daemons turn the worker on via enable_auto(), tests
        # drain explicitly with run_pending()
        from ..compaction.manager import CompactionManager
        # NOTE the default is the REFERENCE default (64 MiB/s,
        # cassandra.yaml:1243) — out-of-the-box nodes are throttled like
        # the reference; bench.py drives CompactionTask directly and is
        # unaffected. `compaction_throughput: 0` disables. The modern
        # knob name compaction_throughput_mib_per_sec takes precedence
        # when set (>= 0).
        tput = self.settings.get("compaction_throughput_mib_per_sec")
        if tput < 0:
            tput = self.settings.get("compaction_throughput")
        self.compactions = CompactionManager(
            throughput_mib_s=tput, auto=False,
            concurrent=self.settings.get("concurrent_compactors"))
        # hot-reload: `nodetool setcompactionthroughput` /
        # `setconcurrentcompactors` / settings table. Either knob change
        # re-resolves the pair under the documented precedence (modern
        # name wins when set), so a legacy-knob write can never clobber
        # a set compaction_throughput_mib_per_sec.

        def _resolve_throughput(_v):
            mib = self.settings.get("compaction_throughput_mib_per_sec")
            if mib < 0:
                mib = self.settings.get("compaction_throughput")
            self.compactions.set_throughput(mib)

        self._throttle_listener = _resolve_throughput
        self.settings.on_change("compaction_throughput",
                                self._throttle_listener)
        self.settings.on_change("compaction_throughput_mib_per_sec",
                                self._throttle_listener)
        self._compactor_listener = \
            self.compactions.set_concurrent_compactors
        self.settings.on_change("concurrent_compactors",
                                self._compactor_listener)
        # compressor pool (compaction + flush write legs): apply the
        # configured size now and hot-resize on knob changes — mid-
        # flight compactions pick the new worker count up immediately
        # (the pool is shared process state, like the row cache)
        from .sstable import compress_pool as _compress_pool
        self._compressor_listener = _compress_pool.configure
        self.settings.on_change("compaction_compressor_threads",
                                self._compressor_listener)
        _compress_pool.configure(
            self.settings.get("compaction_compressor_threads"))
        # mesh execution mode (compaction shards + batched/range read
        # fan-out): the worker POOL is process-global like the
        # compressor pool, but the demand is ENGINE-OWNED — the pool
        # sizes to the max across co-hosted engines and each engine's
        # stores/tasks route by THIS engine's knob (mesh_devices_fn),
        # so one node's knob never flips a co-hosted node's data plane.
        # Hot-reloadable; in-flight compactions pick the new width up
        # on their next task.
        from ..parallel import fanout as _mesh_fanout
        self._mesh_listener = \
            lambda n: _mesh_fanout.configure(n, owner=self)
        self.settings.on_change("compaction_mesh_devices",
                                self._mesh_listener)
        _mesh_fanout.configure(
            self.settings.get("compaction_mesh_devices"), owner=self)
        self.compactions.mesh_devices_fn = self._mesh_devices

        # group-commit window hot-reload (nodetool/settings vtable)
        def _resolve_group_window(v):
            if self.commitlog is not None:
                self.commitlog.group_window_ms = float(v) * 1000.0

        self._group_window_listener = _resolve_group_window
        self.settings.on_change("commitlog_sync_group_window",
                                self._group_window_listener)
        # row cache capacity: either knob change re-resolves under the
        # documented precedence (row_cache_size_mib wins when >= 0)
        from .row_cache import GLOBAL as _row_cache
        from .row_cache import resolve_capacity as _rc_capacity

        def _resolve_row_cache(_v):
            _row_cache.set_capacity(_rc_capacity(self.settings))

        self._rowcache_listener = _resolve_row_cache
        self.settings.on_change("row_cache_size", self._rowcache_listener)
        self.settings.on_change("row_cache_size_mib",
                                self._rowcache_listener)
        _resolve_row_cache(None)
        # key cache capacity: the byte-denominated key_cache_size knob
        # maps onto the shared LRU's entry capacity (KeyCache documents
        # the per-entry estimate); process-global like the row cache
        from .key_cache import GLOBAL as _key_cache
        self._keycache_listener = _key_cache.set_capacity_bytes
        self.settings.on_change("key_cache_size",
                                self._keycache_listener)
        _key_cache.set_capacity_bytes(
            self.settings.get("key_cache_size"))
        self._load_schema()
        self._schema_listener = lambda s: self._save_schema()
        self.schema.listeners.append(self._schema_listener)
        self._register_existing()
        if self.commitlog:
            self._replay()
        from .batchlog import Batchlog
        self.batchlog = Batchlog(os.path.join(data_dir, "batchlog"))
        self._replay_batchlog()
        from ..index import IndexManager
        self.indexes = IndexManager(self)
        from ..service.triggers import TriggerManager
        self.triggers = TriggerManager(os.path.join(data_dir, "triggers"))
        # audit/FQL stream (service/audit.py); None = disabled
        self.audit_log = None
        if audit_log_path:
            from ..service.audit import AuditLog
            self.audit_log = AuditLog(audit_log_path)
        self._restore_indexes()
        from .virtual import build_engine_virtuals
        self.virtual_tables = build_engine_virtuals(self)
        from ..service.auth import AuthService
        self.auth = AuthService(
            data_dir, enabled=auth_enabled,
            cache_validity=self.settings.get("auth_cache_validity"))
        self._auth_validity_listener = \
            lambda v: setattr(self.auth.cache, "validity", float(v))
        self.settings.on_change("auth_cache_validity",
                                self._auth_validity_listener)
        from .guardrails import Guardrails
        self.guardrails = Guardrails.from_config(
            self.settings.config.guardrails)
        # the top-level tombstone knobs are the yaml-parity surface for
        # the per-read tombstone guardrails (TombstoneOverwhelming
        # thresholds): they bind initially and on hot set, UNLESS the
        # guardrails block pinned its own values (the specific block
        # wins over the legacy flat knob, load-time or runtime)
        _g_raw = self.settings.config.guardrails

        def _bind_tombstones(_v):
            if "tombstones_warn_per_read" not in _g_raw:
                self.guardrails.tombstones_warn_per_read = int(
                    self.settings.get("tombstone_warn_threshold"))
            if "tombstones_fail_per_read" not in _g_raw:
                self.guardrails.tombstones_fail_per_read = int(
                    self.settings.get("tombstone_failure_threshold"))

        self._tombstone_listener = _bind_tombstones
        self.settings.on_change("tombstone_warn_threshold",
                                self._tombstone_listener)
        self.settings.on_change("tombstone_failure_threshold",
                                self._tombstone_listener)
        _bind_tombstones(None)
        from ..service.monitoring import QueryMonitor
        self.monitor = QueryMonitor(
            threshold_ms=self.settings.get("slow_query_log_timeout")
            * 1000.0,
            capacity=self.settings.get("slow_query_log_entries"))
        # slow-query ring capacity AND threshold are live knobs now,
        # not constructor constants (nodetool / settings vtable)
        self._slowlog_listener = self.monitor.set_capacity
        self.settings.on_change("slow_query_log_entries",
                                self._slowlog_listener)
        self._slowlog_threshold_listener = \
            lambda v: setattr(self.monitor, "threshold_ms",
                              float(v) * 1000.0)
        self.settings.on_change("slow_query_log_timeout",
                                self._slowlog_threshold_listener)
        # completed request traces (system_traces role): explicit
        # TRACING ON sessions and trace_probability-sampled ones
        from ..service.tracing import TraceStore
        self.trace_store = TraceStore()
        # diagnostic event bus + flight recorder
        # (service/diagnostics.py): the bus is process-global like the
        # metrics registry and gated by the mutable
        # diagnostic_events_enabled knob; the recorder is engine-scoped
        # and dumps its black-box bundle on terminal failure-policy
        # transitions and quarantines (storage/failures.py wiring).
        from ..service import diagnostics
        # per-ENGINE demand on the process-global bus (the mesh-knob
        # demand pattern): this engine's knob flipping off withdraws
        # only ITS demand — a co-hosted engine whose knob is still on
        # keeps the bus (and its own black box) running
        self._diag_listener = \
            lambda v: diagnostics.GLOBAL.set_demand(id(self), v)
        self.settings.on_change("diagnostic_events_enabled",
                                self._diag_listener)
        diagnostics.GLOBAL.set_demand(
            id(self), self.settings.get("diagnostic_events_enabled"))
        self.flight_recorder = diagnostics.FlightRecorder(engine=self)
        self.failures.flight_recorder = self.flight_recorder
        # schema changes are diagnostic events too (the listener list
        # already fires on every DDL mutation)
        self._schema_diag_listener = lambda s: diagnostics.publish(
            "schema.change",
            keyspaces=len(getattr(s, "keyspaces", {})))
        self.schema.listeners.append(self._schema_diag_listener)
        # SLO layer (service/slo.py): p99 objectives + error budgets
        # over the front-door latency hists, breach artifacts through
        # the flight recorder above. Poll-driven — no background thread
        # unless a caller start()s one; targets hot-reload through the
        # mutable slo_targets knob.
        from ..service.slo import default_service
        self.slo = default_service(self)
        self._slo_targets_listener = self.slo.set_targets
        self.settings.on_change("slo_targets", self._slo_targets_listener)
        # metrics-history sampler (service/history.py, the workload
        # observatory): engine-scoped retained time series over the
        # metrics registry + this engine's gauges. Zero-cost while the
        # mutable metrics_history_enabled knob is off (no thread); the
        # flight recorder still takes one on-demand sample at dump
        # time so bundles always carry a history window.
        from ..service.history import MetricsHistoryService
        self.metrics_history = MetricsHistoryService(
            engine=self,
            interval_s=self.settings.get("metrics_history_interval"))
        self._history_enabled_listener = self.metrics_history.set_enabled
        self.settings.on_change("metrics_history_enabled",
                                self._history_enabled_listener)
        self._history_interval_listener = \
            self.metrics_history.set_interval
        self.settings.on_change("metrics_history_interval",
                                self._history_interval_listener)
        if self.settings.get("metrics_history_enabled"):
            self.metrics_history.start()

        # adaptive compaction controller (control/loop.py, ROADMAP
        # item 1): the observe/decide/actuate loop over the history
        # rings and amplification gauges above. Engine-scoped and
        # zero-cost while the mutable adaptive_compaction_enabled knob
        # is off (no decision thread; tick() stays callable on demand).
        # Actuation goes only through Settings.set(source="controller")
        # and the ColumnFamilyStore.set_compaction_params seam.
        from ..control.loop import AdaptiveCompactionController
        self.controller = AdaptiveCompactionController(
            engine=self,
            interval_s=self.settings.get("adaptive_compaction_interval"))
        self._controller_enabled_listener = self.controller.set_enabled
        self.settings.on_change("adaptive_compaction_enabled",
                                self._controller_enabled_listener)
        self._controller_interval_listener = self.controller.set_interval
        self.settings.on_change("adaptive_compaction_interval",
                                self._controller_interval_listener)
        if self.settings.get("adaptive_compaction_enabled"):
            self.controller.start()

        # continuous profiler (service/sampler.py + the device-program
        # registry in service/profiling.py, observability layer 6).
        # Both are process-global — threads and the accelerator are
        # process-wide — so the enable knob follows the diagnostic-bus
        # demand pattern (this engine's knob adds/withdraws only ITS
        # demand) and the interval/budget knobs land on the shared
        # singletons (last writer wins, like the shared device).
        from ..service import profiling as _profiling
        from ..service import sampler as _sampler
        self._profiler_enabled_listener = \
            lambda v: _sampler.GLOBAL.set_demand(id(self), v)
        self.settings.on_change("profiler_enabled",
                                self._profiler_enabled_listener)
        self._profiler_interval_listener = _sampler.GLOBAL.set_interval
        self.settings.on_change("profiler_interval",
                                self._profiler_interval_listener)
        self._retrace_budget_listener = \
            _profiling.GLOBAL.set_retrace_budget
        self.settings.on_change("profiler_retrace_budget",
                                self._retrace_budget_listener)
        _sampler.GLOBAL.set_interval(
            self.settings.get("profiler_interval"))
        _profiling.GLOBAL.set_retrace_budget(
            self.settings.get("profiler_retrace_budget"))
        _sampler.GLOBAL.set_demand(
            id(self), self.settings.get("profiler_enabled"))

        # compaction-history ring bound: every store's per-compaction
        # stats deque follows the mutable compaction_history_entries
        # knob (newest kept); stores opened later inherit it in
        # _open_store
        def _set_ch_capacity(v):
            for cfs in list(self.stores.values()):
                cfs.set_compaction_history_capacity(v)

        self._ch_capacity_listener = _set_ch_capacity
        self.settings.on_change("compaction_history_entries",
                                self._ch_capacity_listener)

    def _mesh_devices(self) -> int:
        """This engine's mesh width (its knob, not the shared pool's —
        the pool sizes to the max across co-hosted engines; routing is
        always by the owning engine's own setting)."""
        return max(int(self.settings.get("compaction_mesh_devices")), 0)

    def _decode_ahead(self) -> bool:
        """This engine's `compaction_decode_ahead` knob — read by its
        tasks EVERY ROUND (compaction/task.py), so the hot reload needs
        no listener and a mid-compaction flip takes effect at the next
        round boundary. Engine-scoped like the mesh knob: a co-hosted
        engine's setting never flips this engine's prefetch."""
        return bool(self.settings.get("compaction_decode_ahead"))

    def _device_compress(self) -> bool:
        """This engine's `compaction_device_compress` knob — read by
        its device-resident tasks' writers PER SEGMENT, so the hot
        reload needs no listener and a mid-compaction flip moves the
        compress work between device and host at the next segment
        boundary (output bytes identical either way)."""
        return bool(self.settings.get("compaction_device_compress"))

    def _scan_device_filter(self) -> bool:
        """This engine's `scan_device_filter` knob — read by
        scan_filtered PER SEGMENT, so the hot reload needs no listener
        and a mid-scan flip moves the predicate/aggregate kernels
        between device and host at the next segment boundary (results
        identical either way)."""
        return bool(self.settings.get("scan_device_filter"))

    def _eager_index_build(self, cfs, reader) -> None:
        """Build attached-index components for a NEW sstable in the
        writer tail (flush/compaction) instead of on first query — the
        restart scan storm the lazy path pays (counted as
        index.lazy_builds) never happens for sstables born here."""
        idx = getattr(self, "indexes", None)
        if idx is not None:
            idx.build_eager(cfs.table, reader)

    @property
    def _schema_path(self) -> str:
        return os.path.join(self.data_dir, "schema.json")

    def _load_schema(self) -> None:
        """Restore persisted DDL (role of the reference's system_schema
        tables: schema survives restarts without the client re-issuing
        CREATEs)."""
        import json
        from ..schema import load_schema_dict
        if os.path.exists(self._schema_path):
            with open(self._schema_path) as f:
                load_schema_dict(self.schema, json.load(f))

    def _save_schema(self) -> None:
        import json
        from ..schema import schema_to_dict
        dump = schema_to_dict(self.schema)
        idx = getattr(self, "indexes", None)
        if idx is not None:
            dump["indexes"] = [
                {"keyspace": ks, "table": tb, "column": col, "name": nm,
                 **idx.meta.get((ks, tb, col), {})}
                for (ksn, nm), (ks, tb, col) in idx.by_name.items()]
        trig = getattr(self, "triggers", None)
        if trig is not None:
            dump["triggers"] = trig.to_list()
        tmp = self._schema_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dump, f)
        os.replace(tmp, self._schema_path)

    def _restore_indexes(self) -> None:
        import json
        if not os.path.exists(self._schema_path):
            return
        with open(self._schema_path) as f:
            dump = json.load(f)
        for d in dump.get("indexes", []):
            try:
                t = self.schema.get_table(d["keyspace"], d["table"])
                self.indexes.create(t, d["column"], d["name"],
                                    custom_class=d.get("custom_class"),
                                    options=d.get("options"),
                                    if_not_exists=True)
            except KeyError:
                pass  # table dropped since
        self.triggers.load_list(dump.get("triggers", []))

    def _register_existing(self) -> None:
        for ks in self.schema.keyspaces.values():
            for t in ks.tables.values():
                self._open_store(t)

    def _open_store(self, t: TableMetadata) -> ColumnFamilyStore:
        cfs = ColumnFamilyStore(t, self.data_dir, self.commitlog,
                                flush_threshold=self.flush_threshold,
                                memtable_shards=self.settings.get(
                                    "memtable_shards") or None,
                                failures=self.failures)
        cfs.backup_enabled = lambda: self.incremental_backup
        cfs.mesh_devices_fn = self._mesh_devices
        cfs.decode_ahead_fn = self._decode_ahead
        cfs.device_compress_fn = self._device_compress
        cfs.scan_device_filter_fn = self._scan_device_filter
        cfs.index_build_fn = lambda reader, _cfs=cfs: \
            self._eager_index_build(_cfs, reader)
        cfs.set_compaction_history_capacity(
            self.settings.get("compaction_history_entries"))
        self.compactions.register(cfs)
        self.stores[t.id] = cfs
        return cfs

    # ------------------------------------------------------------- schema --

    def add_table(self, t: TableMetadata) -> ColumnFamilyStore:
        with self._lock:
            self.schema.add_table(t)
            return self._open_store(t)

    def drop_table(self, keyspace: str, name: str) -> None:
        with self._lock:
            t = self.schema.get_table(keyspace, name)
            cfs = self.stores.pop(t.id)
            cfs.truncate()
            self.schema.drop_table(keyspace, name)
            if self.commitlog:
                self.commitlog.forget_table(t.id)

    def store(self, keyspace: str, name: str) -> ColumnFamilyStore:
        t = self.schema.get_table(keyspace, name)
        return self.stores[t.id]

    def store_by_id(self, table_id) -> ColumnFamilyStore:
        return self.stores[table_id]

    # -------------------------------------------------------------- write --

    def apply(self, mutation: Mutation, durable: bool = True) -> None:
        """Keyspace.apply: commitlog first, then memtable (one atomic unit
        vs concurrent flushes); flush when the memtable crosses its
        threshold."""
        self.failures.check_can_write()
        cfs = self.stores.get(mutation.table_id)
        if cfs is None:
            raise KeyError(f"unknown table id {mutation.table_id}")
        from ..service.metrics import GLOBAL
        from ..service.tracing import active, trace
        GLOBAL.incr("storage.writes")
        if active() is not None:
            trace(f"Appending to commitlog and memtable "
                  f"({len(mutation.ops)} ops)")
        if cfs.table.params.cdc:
            # durable CDC record BEFORE the memtable apply — a write the
            # consumer never sees must not exist (CommitLogSegmentManagerCDC
            # ordering); a full cdc_raw FAILS the write like the reference
            self.cdc.append(mutation)
        from ..service.metrics import Timer
        with Timer(cfs.write_hist):
            cfs.apply(mutation, self.commitlog, durable)
        self._maybe_flush(cfs)

    def _maybe_flush(self, cfs) -> None:
        """Threshold flush, timed as a WRITE STALL: the writer that
        trips should_flush pays the flush inline (the backpressure the
        reference applies by blocking on memtable cleanup), and
        storage.write_stall makes that stall observable — the pipelined
        flush exists to shrink exactly this histogram."""
        if cfs.should_flush():
            from ..service.metrics import GLOBAL, Timer
            self.write_stalls += 1
            with Timer(GLOBAL.hist("storage.write_stall")):
                cfs.flush()

    def apply_batch(self, mutations, durable: bool = True) -> None:
        """Batched Keyspace.apply (the write fast lane for coordinator /
        messaging / replay batches): mutations grouped per table, each
        group paying ONE commitlog lock+sync barrier
        (CommitLog.add_batch) and ONE memtable shard-lock pass
        (Memtable.apply_batch) instead of a full cycle per mutation."""
        if not mutations:
            return
        self.failures.check_can_write()
        from ..service.metrics import GLOBAL, Timer
        from ..service.tracing import active, trace
        GLOBAL.incr("storage.writes", len(mutations))
        if active() is not None:
            trace(f"Batch-appending {len(mutations)} mutation(s) to "
                  f"commitlog and memtable")
        groups: dict = {}
        for m in mutations:
            cfs = self.stores.get(m.table_id)
            if cfs is None:
                raise KeyError(f"unknown table id {m.table_id}")
            groups.setdefault(m.table_id, (cfs, []))[1].append(m)
        for cfs, ms in groups.values():
            if cfs.table.params.cdc:
                for m in ms:
                    self.cdc.append(m)
            with Timer(cfs.write_hist):
                cfs.apply_batch(ms, self.commitlog, durable)
            self._maybe_flush(cfs)

    # ------------------------------------------------------------- replay --

    def restore_point_in_time(self, archive_dir: str,
                              pit_micros: int) -> int:
        """Replay archived commitlog segments, applying every mutation
        whose newest cell timestamp is <= pit_micros (CommitLogArchiver
        restore_point_in_time semantics). Run against a node restored
        from a snapshot (or empty) BEFORE serving traffic; returns
        mutations applied. Applied writes go through the normal apply
        path, so they re-log durably."""
        applied = 0
        for _pos, mutation in CommitLog.replay_archived(archive_dir):
            if mutation.ops and max(op[4] for op in mutation.ops) \
                    > pit_micros:
                continue
            if self.schema.table_by_id(mutation.table_id) is None:
                continue
            self.apply(mutation)
            applied += 1
        return applied

    def _replay(self) -> None:
        """Boot recovery: re-apply intact commitlog records to memtables
        (CommitLogReplayer semantics), then flush and clear the log.
        Mutations apply in per-table chunks through the batched fast
        lane (one shard-lock pass per chunk; no re-logging — the
        records are already on disk)."""
        replayed = 0
        chunk: list[Mutation] = []
        chunk_cfs = None

        def _drain():
            if chunk_cfs is not None and chunk:
                chunk_cfs.apply_batch(chunk, commitlog=None)

        for pos, mutation in self.commitlog.replay():
            cfs = self.stores.get(mutation.table_id)
            if cfs is None:
                continue  # table dropped since the write
            if cfs is not chunk_cfs or len(chunk) >= 512:
                _drain()
                chunk, chunk_cfs = [], cfs
            chunk.append(mutation)
            replayed += 1
        _drain()
        for cfs in self.stores.values():
            if not cfs.memtable.is_empty:
                cfs.flush()
        # everything recovered (or belonging to dropped tables) is dealt
        # with; reclaim all pre-existing segments
        self.commitlog.delete_segments_before(
            self.commitlog.current_position().segment_id)

    def _replay_batchlog(self) -> None:
        """Finish batches interrupted by a crash (BatchlogManager.replay)
        — each stored batch re-applies through the batched fast lane."""
        for bid, muts in self.batchlog.pending():
            self.apply_batch([m for m in muts
                              if self.schema.table_by_id(m.table_id)
                              is not None])
            self.batchlog.remove(bid)

    # --------------------------------------------------------------- misc --

    def flush_all(self) -> None:
        for cfs in list(self.stores.values()):
            cfs.flush()

    def close(self) -> None:
        try:
            self.schema.listeners.remove(self._schema_listener)
        except ValueError:
            pass
        try:
            self.schema.listeners.remove(self._schema_diag_listener)
        except ValueError:
            pass
        self.settings.remove_listener("slow_query_log_entries",
                                      self._slowlog_listener)
        self.settings.remove_listener("slow_query_log_timeout",
                                      self._slowlog_threshold_listener)
        self.settings.remove_listener("diagnostic_events_enabled",
                                      self._diag_listener)
        self.settings.remove_listener("slo_targets",
                                      self._slo_targets_listener)
        self.slo.stop()
        self.settings.remove_listener("metrics_history_enabled",
                                      self._history_enabled_listener)
        self.settings.remove_listener("metrics_history_interval",
                                      self._history_interval_listener)
        self.settings.remove_listener("compaction_history_entries",
                                      self._ch_capacity_listener)
        self.metrics_history.stop()
        self.settings.remove_listener("adaptive_compaction_enabled",
                                      self._controller_enabled_listener)
        self.settings.remove_listener("adaptive_compaction_interval",
                                      self._controller_interval_listener)
        self.controller.stop()
        self.settings.remove_listener("profiler_enabled",
                                      self._profiler_enabled_listener)
        self.settings.remove_listener("profiler_interval",
                                      self._profiler_interval_listener)
        self.settings.remove_listener("profiler_retrace_budget",
                                      self._retrace_budget_listener)
        # withdraw this engine's bus + sampler demands (a closed engine
        # must not keep a process-global service running for nobody)
        from ..service import diagnostics
        from ..service import sampler as _sampler
        diagnostics.GLOBAL.set_demand(id(self), False)
        _sampler.GLOBAL.set_demand(id(self), False)
        self.flight_recorder.close()
        self.settings.remove_listener("compaction_throughput",
                                      self._throttle_listener)
        self.settings.remove_listener("compaction_throughput_mib_per_sec",
                                      self._throttle_listener)
        self.settings.remove_listener("concurrent_compactors",
                                      self._compactor_listener)
        self.settings.remove_listener("compaction_compressor_threads",
                                      self._compressor_listener)
        self.settings.remove_listener("compaction_mesh_devices",
                                      self._mesh_listener)
        # a closing engine's lane demand must not keep the shared pool
        # sized for it (or keep mesh mode on for nobody)
        from ..parallel import fanout as _mesh_fanout
        _mesh_fanout.configure(0, owner=self)
        self.settings.remove_listener("commitlog_sync_group_window",
                                      self._group_window_listener)
        self.settings.remove_listener("row_cache_size",
                                      self._rowcache_listener)
        self.settings.remove_listener("row_cache_size_mib",
                                      self._rowcache_listener)
        self.settings.remove_listener("key_cache_size",
                                      self._keycache_listener)
        self.settings.remove_listener("incremental_backups",
                                      self._backup_listener)
        self.settings.remove_listener("auth_cache_validity",
                                      self._auth_validity_listener)
        self.settings.remove_listener("tombstone_warn_threshold",
                                      self._tombstone_listener)
        self.settings.remove_listener("tombstone_failure_threshold",
                                      self._tombstone_listener)
        self.failures.close()
        self.compactions.close()
        if self.commitlog:
            self.commitlog.close()
        if self.audit_log is not None:
            self.audit_log.close()
        for cfs in self.stores.values():
            for sst in cfs.live_sstables():
                sst.close()
