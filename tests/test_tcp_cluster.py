"""Real-network cluster: three OS PROCESSES form a cluster over the TCP
transport (gossip, quorum reads/writes, replica kill) — the seam VERDICT
round 1 called out: until two processes can cluster over sockets,
"distributed" is simulated. Reference: net/MessagingService.java:208,
net/HandshakeProtocol.java."""
import json
import os
import signal
import socket
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

from cassandra_tpu.cluster import wire
from cassandra_tpu.cluster.messaging import Message
from cassandra_tpu.cluster.ring import Endpoint, even_tokens

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- wire codec --

def test_wire_roundtrip():
    ep = Endpoint("n1", "dc1", "r1", "127.0.0.1", 9999)
    payloads = [
        None, True, False, 0, -1, 1 << 40, -(1 << 70), 3.5, "text",
        b"bytes", ("a", 1, b"x"), [1, 2, 3], {"k": (1, 2), b"b": None},
        np.arange(12, dtype=np.uint32).reshape(3, 4),
        np.array([1.5, 2.5]), ep,
        {"lanes": np.zeros((2, 13), np.uint32), "sorted": True,
         "pk_map": {b"k": b"v"}},
    ]
    for p in payloads:
        m = Message("READ_REQ", p, ep, ep, id=7, reply_to=3)
        got = wire.decode_message(wire.encode_message(m))
        assert got.verb == m.verb and got.id == 7 and got.reply_to == 3
        if isinstance(p, np.ndarray):
            np.testing.assert_array_equal(got.payload, p)
        elif isinstance(p, dict) and "lanes" in p:
            np.testing.assert_array_equal(got.payload["lanes"], p["lanes"])
            assert got.payload["pk_map"] == p["pk_map"]
        else:
            assert got.payload == p


def test_wire_rejects_garbage():
    with pytest.raises((ValueError, IndexError)):
        wire.decode_message(b"\xff\xff\xff")


# ------------------------------------------------------- 3-process cluster --

def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


TABLE_ID = uuid.uuid5(uuid.NAMESPACE_DNS, "ctpu.test.kv")
DDL = [
    "CREATE KEYSPACE ks WITH replication = "
    "{'class': 'SimpleStrategy', 'replication_factor': 3}",
    f"CREATE TABLE ks.kv (k int PRIMARY KEY, v text) "
    f"WITH id = {TABLE_ID}",
]


@pytest.mark.slow
def test_three_process_cluster(tmp_path):
    ports = _free_ports(3)
    tokens = even_tokens(3, vnodes=4)
    names = ["node1", "node2", "node3"]
    eps = [Endpoint(n, host="127.0.0.1", port=p)
           for n, p in zip(names, ports)]

    def peer_cfg(i):
        return {"name": names[i], "host": "127.0.0.1", "port": ports[i],
                "tokens": tokens[i]}

    procs = []
    try:
        for i in (1, 2):
            cfg = {
                **peer_cfg(i),
                "data_dir": str(tmp_path / names[i]),
                "peers": [peer_cfg(j) for j in range(3) if j != i],
                "seeds": ["node1"],
                "gossip_interval": 0.1,
                "jax_platform": "cpu",
                "ddl": DDL,
            }
            cfile = tmp_path / f"{names[i]}.json"
            cfile.write_text(json.dumps(cfg))
            p = subprocess.Popen(
                [sys.executable, "-m", "cassandra_tpu.tools.noded",
                 str(cfile)],
                cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
        # wait for READY from both daemons
        for p in procs:
            line = p.stdout.readline()
            assert line.startswith("READY"), (line, p.stderr.read())

        # node1 runs IN-PROCESS so the test can drive a Session
        from cassandra_tpu.cluster.node import Node
        from cassandra_tpu.cluster.replication import ConsistencyLevel
        from cassandra_tpu.cluster.ring import Ring
        from cassandra_tpu.cluster.tcp import TcpTransport
        from cassandra_tpu.schema import Schema

        ring = Ring()
        for ep, toks in zip(eps, tokens):
            ring.add_node(ep, toks)
        node = Node(eps[0], str(tmp_path / "node1"), Schema(), ring,
                    TcpTransport(), seeds=[eps[0]], gossip_interval=0.1)
        node.cluster_nodes = [node]
        s = node.session()
        for stmt in DDL:
            s.execute(stmt)
        node.gossiper.start()
        s.keyspace = "ks"

        # gossip convergence over real sockets
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(node.gossiper.is_alive(e) for e in eps[1:]):
                break
            time.sleep(0.2)
        assert all(node.gossiper.is_alive(e) for e in eps[1:]), \
            "gossip never converged over TCP"

        node.default_cl = ConsistencyLevel.QUORUM
        for i in range(10):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'val{i}')")
        assert s.execute("SELECT v FROM kv WHERE k = 3").rows \
            == [("val3",)]
        # ALL proves every process holds the data
        node.default_cl = ConsistencyLevel.ALL
        assert s.execute("SELECT v FROM kv WHERE k = 7").rows \
            == [("val7",)]

        # kill one replica process outright: quorum must survive
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=10)
        node.default_cl = ConsistencyLevel.QUORUM
        node.proxy.timeout = 3.0
        s.execute("INSERT INTO kv (k, v) VALUES (99, 'after-kill')")
        assert s.execute("SELECT v FROM kv WHERE k = 99").rows \
            == [("after-kill",)]
        # ALL cannot be satisfied any more
        node.default_cl = ConsistencyLevel.ALL
        with pytest.raises(Exception):
            s.execute("INSERT INTO kv (k, v) VALUES (100, 'x')")
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_delete_range_duplicate_bound_rejected(tmp_path):
    from cassandra_tpu.cql import Session
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    eng = StorageEngine(str(tmp_path / "d"), Schema(),
                        commitlog_sync="batch")
    try:
        s = Session(eng)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE t (k int, c int, PRIMARY KEY (k, c))")
        with pytest.raises(Exception, match="lower bound"):
            s.execute("DELETE FROM t WHERE k = 1 AND c > 5 AND c > 2")
    finally:
        eng.close()


@pytest.mark.slow
def test_ddl_replicates_across_processes(tmp_path):
    """TCM-lite: DDL issued on one node AFTER startup reaches the other
    OS processes through the epoch log, with agreed table ids — writes
    routed by id work cluster-wide (tcm/ClusterMetadata role)."""
    import time

    from cassandra_tpu.cluster.node import Node
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    from cassandra_tpu.cluster.ring import Ring
    from cassandra_tpu.cluster.schema_sync import SchemaSync
    from cassandra_tpu.cluster.tcp import TcpTransport
    from cassandra_tpu.schema import Schema

    ports = _free_ports(3)
    tokens = even_tokens(3, vnodes=4)
    names = ["node1", "node2", "node3"]
    eps = [Endpoint(n, host="127.0.0.1", port=p)
           for n, p in zip(names, ports)]

    def peer_cfg(i):
        return {"name": names[i], "host": "127.0.0.1", "port": ports[i],
                "tokens": tokens[i]}

    procs = []
    try:
        for i in (1, 2):
            cfg = {**peer_cfg(i),
                   "data_dir": str(tmp_path / names[i]),
                   "peers": [peer_cfg(j) for j in range(3) if j != i],
                   "seeds": ["node1"], "gossip_interval": 0.1,
                   "jax_platform": "cpu", "ddl": []}
            cfile = tmp_path / f"{names[i]}.json"
            cfile.write_text(json.dumps(cfg))
            p = subprocess.Popen(
                [sys.executable, "-m", "cassandra_tpu.tools.noded",
                 str(cfile)],
                cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
        for p in procs:
            line = p.stdout.readline()
            assert line.startswith("READY"), (line, p.stderr.read())

        ring = Ring()
        for ep, toks in zip(eps, tokens):
            ring.add_node(ep, toks)
        node = Node(eps[0], str(tmp_path / "node1"), Schema(), ring,
                    TcpTransport(), seeds=[eps[0]], gossip_interval=0.1)
        node.cluster_nodes = [node]
        node.schema_sync = SchemaSync(node, str(tmp_path / "node1"))
        node.gossiper.start()
        s = node.session()

        deadline = time.time() + 15
        while time.time() < deadline:
            if all(node.gossiper.is_alive(e) for e in eps[1:]):
                break
            time.sleep(0.2)

        # DDL issued NOW — no pre-agreed config schema, no WITH id
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        time.sleep(1.0)   # pushes drain

        node.default_cl = ConsistencyLevel.ALL   # proves ALL nodes
        for i in range(6):                       # learned the table
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'd{i}')")
        node.default_cl = ConsistencyLevel.QUORUM
        got = {r[0] for r in s.execute("SELECT k FROM kv").rows}
        assert got == set(range(6))
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


# ------------------------------------------------ CMS-committed DDL --

def test_ddl_commits_through_cms(tmp_path):
    """Every DDL epoch is decided by Paxos over the CMS replica set
    (min(3) lowest-named nodes — cluster/cms.py). A NON-member issues a
    statement: it is forwarded to a CMS member, Paxos-committed, applied
    locally from the ack (visible the moment execute() returns), and
    every node's log records the committing CMS member as coordinator."""
    import time as _t

    from cassandra_tpu.cluster.messaging import LocalTransport
    from cassandra_tpu.cluster.node import Node
    from cassandra_tpu.cluster.ring import Ring
    from cassandra_tpu.cluster.schema_sync import SchemaSync
    from cassandra_tpu.schema import Schema

    names = ("node1", "node2", "node3", "node4")
    eps = [Endpoint(n, host="127.0.0.1", port=0) for n in names]
    tokens = even_tokens(4, vnodes=4)
    transport = LocalTransport()
    ring = Ring()
    for ep, toks in zip(eps, tokens):
        ring.add_node(ep, toks)
    nodes = []
    try:
        for ep in eps:
            n = Node(ep, str(tmp_path / ep.name), Schema(), ring,
                     transport, seeds=[eps[0]], gossip_interval=0.05)
            n.cluster_nodes = [n]
            n.schema_sync = SchemaSync(n, str(tmp_path / ep.name))
            n.gossiper.start()
            nodes.append(n)
        cms_names = {m.name
                     for m in nodes[0].schema_sync.cms.members()}
        assert cms_names == {"node1", "node2", "node3"}
        deadline = _t.time() + 10
        while _t.time() < deadline:
            if all(nodes[3].is_alive(e) for e in eps[:3]):
                break
            _t.sleep(0.05)

        s = nodes[3].session()   # NOT a CMS member: must forward
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("CREATE TABLE ks.kv (k int PRIMARY KEY, v text)")

        # synchronously visible on the issuing node with the
        # coordinator-assigned table id
        t_origin = nodes[3].schema.get_table("ks", "kv")
        assert nodes[3].schema_sync.epoch == 2
        # the committing CMS member applied it too and both logs agree
        # on the coordinator (a CMS member, never the issuer)
        deadline = _t.time() + 10
        while _t.time() < deadline:
            try:
                if all(n.schema_sync.epoch >= 2 for n in nodes[:3]):
                    break
            except Exception:
                pass
            _t.sleep(0.05)
        for n in nodes[:3]:
            assert n.schema.get_table("ks", "kv").id == t_origin.id
        coords = {n.schema_sync._entry_at(2)[4] for n in nodes}
        assert len(coords) == 1 and coords < cms_names | {None}, coords

        # prepared DDL coordinates identically (no local-only bypass)
        qid = s.prepare("CREATE TABLE ks.kv2 (k int PRIMARY KEY)")
        s.execute_prepared(qid)
        assert nodes[3].schema.get_table("ks", "kv2").id \
            == nodes[0].schema.get_table("ks", "kv2").id
        assert nodes[3].schema_sync.epoch == 3
    finally:
        for n in nodes:
            n.shutdown()
