"""Round-5 nodetool breadth: every new command drives real machinery —
this exercises each against a live cluster so signature or wiring rot
fails loudly (the reference's 161-command tail, tools/nodetool/)."""
import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.tools import nodetool


@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(2, str(tmp_path), rf=2)
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 2}")
    s.execute("CREATE TABLE ks.t (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    c.node(1).default_cl = ConsistencyLevel.ALL
    for i in range(40):
        s.execute(f"INSERT INTO ks.t (k, c, v) VALUES ({i % 5}, {i}, "
                  f"'v{i}')")
    c.nodes[0].engine.store("ks", "t").flush()
    yield c
    c.shutdown()


def run(c, cmd, **kw):
    return nodetool.run_command(cmd, node=c.nodes[0], **kw)


def test_ring_and_observability_commands(cluster):
    rings = run(cluster, "describering", keyspace="ks")
    assert rings and all(len(r["endpoints"]) == 2 for r in rings)
    fd = run(cluster, "failuredetectorinfo")
    assert any(e["alive"] for e in fd)
    assert "collections" in run(cluster, "gcstats")
    assert "request" in run(cluster, "proxyhistograms")
    th = run(cluster, "tablehistograms")
    assert "ks.t" in th and th["ks.t"]["sstables"] >= 1
    top = run(cluster, "toppartitions", keyspace="ks", table="t", k=3)
    assert top and top[0]["cells"] >= top[-1]["cells"]
    assert run(cluster, "rangekeysample", keyspace="ks", table="t")
    assert "ks.t" in run(cluster, "datapaths")
    cms = run(cluster, "cmsadmin")
    assert "members" in cms or cms.get("cms") is None


def test_toggles(cluster):
    n = cluster.nodes[0]
    run(cluster, "pausehandoff")
    assert n.hints.enabled is False
    run(cluster, "resumehandoff")
    assert n.hints.enabled is True
    run(cluster, "disablehintsfordc", dc="dc9")
    assert "dc9" in n.hints.disabled_dcs
    run(cluster, "enablehintsfordc", dc="dc9")
    assert run(cluster, "setmaxhintwindow", ms=1234) == \
        {"max_hint_window_ms": 1234}
    assert run(cluster, "getmaxhintwindow") == {"max_hint_window_ms": 1234}
    # node1 IS the seed (gossiper filters itself out of its own list);
    # node2 sees it
    assert nodetool.run_command("getseeds",
                                node=cluster.nodes[1]) == ["node1"]
    run(cluster, "disablegossip")
    assert not n.gossiper.is_running()
    run(cluster, "enablegossip")
    assert n.gossiper.is_running()


def test_hint_window_gates_new_hints(cluster):
    """A target dead longer than max_hint_window gets NO new hints
    (StorageProxy.shouldHint semantics)."""
    n = cluster.nodes[0]
    victim = cluster.nodes[1].endpoint
    cluster.stop_node(2)
    import time
    deadline = time.time() + 15
    while time.time() < deadline and n.is_alive(victim):
        time.sleep(0.05)
    assert not n.is_alive(victim)
    run(cluster, "setmaxhintwindow", ms=1)   # window in the past
    time.sleep(0.01)
    s = cluster.session(1)
    s.keyspace = "ks"
    n.default_cl = ConsistencyLevel.ONE
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (1, 999, 'late')")
    assert not n.hints.has_hints(victim)
    run(cluster, "setmaxhintwindow", ms=3600 * 1000)
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (1, 998, 'hinted')")
    assert n.hints.has_hints(victim)


def test_audit_and_fql_runtime_toggle(cluster, tmp_path):
    n = cluster.nodes[0]
    out = run(cluster, "enablefullquerylog")
    assert out["fql"] == "enabled"
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (7, 7, 'fql')")
    import os
    path = run(cluster, "getfullquerylog")["path"]
    with open(path) as f:
        content = f.read()
    assert "fql" in content or "Insert" in content
    run(cluster, "resetfullquerylog")
    assert run(cluster, "getfullquerylog")["enabled"] is False
    assert not os.path.exists(path)
    out = run(cluster, "enableauditlog")
    assert out["audit"] == "enabled"
    run(cluster, "disableauditlog")
    assert run(cluster, "getauditlog")["enabled"] is False


def test_backup_and_compaction_commands(cluster):
    n = cluster.nodes[0]
    run(cluster, "enablebackup")
    assert run(cluster, "statusbackup")["incremental_backup"] is True
    s = cluster.session(1)
    s.keyspace = "ks"
    for i in range(10):
        s.execute(f"INSERT INTO ks.t (k, c, v) VALUES (9, {100 + i}, "
                  f"'b{i}')")
    cfs = n.engine.store("ks", "t")
    cfs.flush()
    import os
    bdir = os.path.join(cfs.directory, "backups")
    assert os.path.isdir(bdir) and os.listdir(bdir)
    run(cluster, "disablebackup")
    thr = run(cluster, "setcompactionthreshold", keyspace="ks",
              table="t", min_threshold=3, max_threshold=16)
    assert thr == {"min_threshold": 3, "max_threshold": 16}
    assert run(cluster, "forcecompact", keyspace="ks", table="t")
    st = run(cluster, "stop")
    assert st["stopped"] is True and st["signalled"] == 0  # none in flight


def test_schema_and_cache_commands(cluster):
    rl = run(cluster, "reloadlocalschema")
    assert rl["epoch"] is None or rl["epoch"] >= 2
    run(cluster, "invalidatepermissionscache")
    run(cluster, "setcachecapacity", chunk_bytes=32 << 20)
    assert run(cluster, "replaybatchlog")["replayed_batches"] >= 0
    vb = run(cluster, "viewbuildstatus")
    assert isinstance(vb, list)
    assert run(cluster, "reloadtriggers")["triggers"] in (
        "reloaded", "no trigger service")


def test_registry_size():
    assert len(nodetool.COMMANDS) >= 115, len(nodetool.COMMANDS)


def test_import_command(cluster, tmp_path):
    """nodetool import: external sstables copied under fresh
    generations and loaded."""
    import numpy as np

    from cassandra_tpu.storage import cellbatch as cb
    from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
    from cassandra_tpu.tools import bulk
    n = cluster.nodes[0]
    table = n.schema.get_table("ks", "t")
    ext = str(tmp_path / "ext")
    import os
    os.makedirs(ext)
    rng = np.random.default_rng(5)
    batch = cb.merge_sorted([bulk.build_int_batch(
        table, rng.integers(100, 120, 50), rng.integers(0, 50, 50),
        rng.integers(97, 122, (50, 4), dtype=np.uint8),
        rng.integers(1, 1 << 30, 50).astype(np.int64))])
    w = SSTableWriter(Descriptor(ext, 1), table)
    w.append(batch)
    w.finish()
    out = nodetool.run_command("import", engine=n.engine,
                               keyspace="ks", table="t", directory=ext)
    assert out["imported_sstables"] == 1
    s = cluster.session(1)
    s.keyspace = "ks"
    assert s.execute(
        "SELECT count(*) FROM ks.t WHERE k = 105").rows[0][0] >= 0


def test_reloadtriggers_then_write(cluster, tmp_path):
    """Regression: after reloadtriggers clears the compiled-fn cache,
    the next triggered write lazily re-imports instead of KeyError."""
    import os
    n = cluster.nodes[0]
    tdir = n.engine.triggers.directory
    os.makedirs(tdir, exist_ok=True)
    with open(os.path.join(tdir, "audit_trg.py"), "w") as f:
        f.write("def fire(table, mutation, backend):\n    return None\n")
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TRIGGER trg ON ks.t USING 'audit_trg:fire'")
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (2, 500, 'a')")
    out = run(cluster, "reloadtriggers")
    assert out["triggers"] == "reloaded"
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (2, 501, 'b')")
    assert s.execute("SELECT v FROM ks.t WHERE k = 2 AND c = 501"
                     ).rows == [("b",)]
    s.execute("DROP TRIGGER trg ON ks.t")


def test_disablehandoff_blocks_any_ack(cluster):
    """Regression: with handoff disabled, a CL.ANY write to dead
    replicas must NOT ack on a silently-dropped hint."""
    import time

    from cassandra_tpu.cluster.coordinator import TimeoutException
    n = cluster.nodes[0]
    victim = cluster.nodes[1].endpoint
    cluster.stop_node(2)
    deadline = time.time() + 15
    while time.time() < deadline and n.is_alive(victim):
        time.sleep(0.05)
    run(cluster, "disablehandoff")
    s = cluster.session(1)
    s.keyspace = "ks"
    n.default_cl = ConsistencyLevel.ANY
    n.proxy.timeout = 1.0
    # some keys' replica sets include the dead node; find one where the
    # write would need the hint-ack (RF=2: both replicas = node1+node2,
    # so ANY is satisfied by the local apply — exercise shouldn't hint):
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (3, 700, 'x')")
    assert not n.hints.has_hints(victim)   # nothing silently stored
    run(cluster, "enablehandoff")
    s.execute("INSERT INTO ks.t (k, c, v) VALUES (3, 701, 'y')")
    assert n.hints.has_hints(victim)
