"""CellBatch merge/reconcile semantics tests.

These encode the reference's reconciliation rules (db/rows/Cells.java:68
reconcile, db/DeletionTime.java deletes, db/partitions/PurgeFunction.java)
as executable spec for both the numpy and the device merge paths."""
import numpy as np
import pytest

from cassandra_tpu.schema import (COL_REGULAR_BASE, COL_ROW_DEL,
                                  COL_PARTITION_DEL, make_table)
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.utils.timeutil import NO_DELETION_TIME

T = make_table("ks", "t", pk=["id"], ck=["c"],
               cols={"id": "int", "c": "int", "v": "text", "w": "text"})
V = COL_REGULAR_BASE      # column id of 'v' (sorted regulars: v, w)
W = COL_REGULAR_BASE + 1
IDT = T.columns["id"].cql_type
CT = T.columns["c"].cql_type


def pk(i):
    return IDT.serialize(i)


def ck(i):
    return T.serialize_clustering([i])


def build(cells):
    """cells: list of tuples (kind, args...) appended to a builder."""
    b = cb.CellBatchBuilder(T)
    for c in cells:
        kind = c[0]
        getattr(b, kind)(*c[1:])
    return b.seal()


def summarize(batch):
    """{(pk_lane_key, ck_bytes, column, path): (value, ts, dead)}"""
    out = {}
    C = batch.n_lanes - 9
    for i in range(len(batch)):
        ckb, path, val = batch.cell_payload(i)
        col = int(batch.lanes[i, 6 + C])
        key = (batch.partition_key(i), ckb, col, path)
        assert key not in out, f"duplicate cell {key}"
        dead = bool(batch.flags[i] & (cb.FLAG_TOMBSTONE | cb.FLAG_PARTITION_DEL
                                      | cb.FLAG_ROW_DEL))
        out[key] = (val, int(batch.ts[i]), dead)
    return out


def test_newest_wins():
    b1 = build([("add_cell", pk(1), ck(1), V, b"old", 100)])
    b2 = build([("add_cell", pk(1), ck(1), V, b"new", 200)])
    m = cb.merge_sorted([b1, b2])
    s = summarize(m)
    assert len(s) == 1
    assert list(s.values())[0] == (b"new", 200, False)


def test_tombstone_beats_data_at_equal_ts():
    b1 = build([("add_cell", pk(1), ck(1), V, b"data", 100)])
    b2 = build([("add_tombstone", pk(1), ck(1), V, 100, 1000)])
    m = cb.merge_sorted([b1, b2])  # gc_before=0: tombstone not purgeable
    s = summarize(m)
    (val, ts, dead), = s.values()
    assert dead and ts == 100


def test_larger_value_wins_at_equal_ts():
    b1 = build([("add_cell", pk(1), ck(1), V, b"aaa", 100)])
    b2 = build([("add_cell", pk(1), ck(1), V, b"zzz", 100)])
    for order in ([b1, b2], [b2, b1]):
        m = cb.merge_sorted(order)
        (val, _, _), = summarize(m).values()
        assert val == b"zzz"


def test_value_tiebreak_beyond_prefix():
    # equal 4-byte prefix, differ at byte 5 — prefix lane can't separate
    b1 = build([("add_cell", pk(1), ck(1), V, b"abcdA", 100)])
    b2 = build([("add_cell", pk(1), ck(1), V, b"abcdZ", 100)])
    m = cb.merge_sorted([b1, b2])
    (val, _, _), = summarize(m).values()
    assert val == b"abcdZ"


def test_row_deletion_shadows_older_only():
    b = build([
        ("add_cell", pk(1), ck(1), V, b"old", 100),
        ("add_cell", pk(1), ck(1), W, b"newer", 300),
        ("add_row_deletion", pk(1), ck(1), 200, 1000),
        ("add_cell", pk(1), ck(2), V, b"other-row", 100),
    ])
    m = cb.merge_sorted([b])
    s = summarize(m)
    vals = {v[0] for v in s.values()}
    assert b"old" not in vals          # ts 100 <= deletion 200
    assert b"newer" in vals            # ts 300 > 200
    assert b"other-row" in vals        # different row untouched
    assert any(k[2] == COL_ROW_DEL for k in s)  # marker kept


def test_partition_deletion_shadows_rows_and_row_deletions():
    b = build([
        ("add_cell", pk(1), ck(1), V, b"dead", 100),
        ("add_row_deletion", pk(1), ck(2), 150, 1000),   # superseded
        ("add_partition_deletion", pk(1), 200, 1000),
        ("add_cell", pk(1), ck(3), V, b"alive", 300),
        ("add_cell", pk(2), ck(1), V, b"other", 100),    # other partition
    ])
    m = cb.merge_sorted([b])
    s = summarize(m)
    vals = {v[0] for v in s.values()}
    assert vals == {b"", b"alive", b"other"}
    assert not any(k[2] == COL_ROW_DEL for k in s)       # rd superseded
    assert any(k[2] == COL_PARTITION_DEL for k in s)     # pd kept


def test_partition_deletion_equal_ts_deletes():
    # DeletionTime.deletes: cell.ts <= markedForDeleteAt
    b = build([
        ("add_partition_deletion", pk(1), 200, 1000),
        ("add_cell", pk(1), ck(1), V, b"equal-ts", 200),
    ])
    s = summarize(cb.merge_sorted([b]))
    assert {v[0] for v in s.values()} == {b""}


def test_ttl_expiry_and_purge():
    b = build([("add_cell", pk(1), ck(1), V, b"exp", 100, 10, 1000)])
    # not expired yet
    m = cb.merge_sorted([b], now=1005)
    (_, _, dead), = summarize(m).values()
    assert not dead
    # expired at now=1020 -> tombstone (kept: gc_before 0)
    b2 = build([("add_cell", pk(1), ck(1), V, b"exp", 100, 10, 1000)])
    m = cb.merge_sorted([b2], now=1020)
    (_, _, dead), = summarize(m).values()
    assert dead
    # expired AND beyond gc grace -> purged entirely
    b3 = build([("add_cell", pk(1), ck(1), V, b"exp", 100, 10, 1000)])
    m = cb.merge_sorted([b3], now=5000, gc_before=2000)
    assert len(m) == 0


def test_purge_respects_overlap_guard():
    b = build([("add_tombstone", pk(1), ck(1), V, 500, 100)])
    # purgeable_ts <= tombstone ts: an overlapping sstable may hold older
    # data this tombstone still shadows -> must keep
    guard = lambda s: np.full(len(s), 400, dtype=np.int64)
    m = cb.merge_sorted([b], gc_before=1000, purgeable_ts_fn=guard)
    assert len(m) == 1
    # no overlap (+inf): purge
    m = cb.merge_sorted([b], gc_before=1000)
    assert len(m) == 0
    # overlap min-ts above tombstone ts: purge allowed
    guard2 = lambda s: np.full(len(s), 600, dtype=np.int64)
    m = cb.merge_sorted([b], gc_before=1000, purgeable_ts_fn=guard2)
    assert len(m) == 0


def test_ordering_across_partitions_and_clusterings():
    cells = []
    for i in range(20):
        for c in range(5):
            cells.append(("add_cell", pk(i), ck(c), V, f"{i}:{c}".encode(), 100))
    m = cb.merge_sorted([build(cells)])
    # lanes must be non-decreasing lexicographically
    lanes = m.lanes
    for i in range(1, len(m)):
        a, b_ = lanes[i - 1].tolist(), lanes[i].tolist()
        assert a <= b_, i
    # within a partition, clustering values ascend
    last = {}
    for i in range(len(m)):
        p = m.partition_key(i)
        ckb, _, _ = m.cell_payload(i)
        if p in last:
            assert ckb >= last[p]
        last[p] = ckb


def test_desc_clustering_order():
    Td = make_table("ks", "td", pk=["id"], ck=["c"], desc={"c"},
                    cols={"id": "int", "c": "int", "v": "text"})
    b = cb.CellBatchBuilder(Td)
    for c in (1, 3, 2):
        b.add_cell(pk(7), Td.serialize_clustering([c]), COL_REGULAR_BASE,
                   str(c).encode(), 100)
    m = cb.merge_sorted([b.seal()])
    vals = [m.cell_payload(i)[2] for i in range(len(m))]
    assert vals == [b"3", b"2", b"1"]  # DESC


def test_static_row_sorts_first():
    Ts = make_table("ks", "ts", pk=["id"], ck=["c"], statics={"s"},
                    cols={"id": "int", "c": "int", "v": "text", "s": "text"})
    b = cb.CellBatchBuilder(Ts)
    s_id = Ts.columns["s"].column_id
    v_id = Ts.columns["v"].column_id
    b.add_cell(pk(1), Ts.serialize_clustering([0]), v_id, b"row", 100)
    b.add_cell(pk(1), b"", s_id, b"static", 100)   # static: empty clustering
    m = cb.merge_sorted([b.seal()])
    first_ck, _, first_val = m.cell_payload(0)
    assert first_ck == b"" and first_val == b"static"


def test_multicell_paths_are_distinct_cells():
    b1 = build([("add_cell", pk(1), ck(1), V, b"e1", 100, 0, 0, b"p1"),
                ("add_cell", pk(1), ck(1), V, b"e2", 100, 0, 0, b"p2")])
    b2 = build([("add_cell", pk(1), ck(1), V, b"e1-new", 200, 0, 0, b"p1")])
    m = cb.merge_sorted([b1, b2])
    s = summarize(m)
    assert len(s) == 2
    by_path = {k[3]: v[0] for k, v in s.items()}
    assert by_path == {b"p1": b"e1-new", b"p2": b"e2"}


def test_row_liveness_merge():
    b1 = build([("add_row_liveness", pk(1), ck(1), 100)])
    b2 = build([("add_row_liveness", pk(1), ck(1), 200),
                ("add_row_deletion", pk(1), ck(1), 150, 1000)])
    m = cb.merge_sorted([b1, b2])
    s = summarize(m)
    # liveness ts 200 survives the ts-150 deletion; marker also kept
    lives = [v for k, v in s.items() if k[2] == cb.COL_ROW_LIVENESS] \
        if hasattr(cb, "COL_ROW_LIVENESS") else \
        [v for k, v in s.items() if k[2] == 2]
    assert lives and lives[0][1] == 200


def test_idempotent_remerge():
    b = build([
        ("add_cell", pk(1), ck(1), V, b"x", 100),
        ("add_cell", pk(1), ck(1), V, b"y", 200),
        ("add_tombstone", pk(2), ck(1), V, 50, 100),
    ])
    m1 = cb.merge_sorted([b])
    m2 = cb.merge_sorted([m1])
    assert summarize(m1) == summarize(m2)
    np.testing.assert_array_equal(m1.lanes, m2.lanes)


def test_expiring_beats_live_at_equal_ts():
    """CASSANDRA-14592 (Cells.resolveRegular): at equal ts, an
    expiring-or-tombstone cell beats a live cell regardless of value
    order — otherwise reconciliation flips when the TTL later expires."""
    live = build([("add_cell", pk(1), ck(1), V, b"zzz", 100)])
    ttl = build([("add_cell", pk(1), ck(1), V, b"aaa", 100, 1000, 0)])
    for order in ([live, ttl], [ttl, live]):
        m = cb.merge_sorted(order, now=0)
        (val, _, _), = summarize(m).values()
        assert val == b"aaa"


def test_pure_tombstone_beats_expiring_at_equal_ts():
    ttl = build([("add_cell", pk(1), ck(1), V, b"zzz", 100, 1000, 0)])
    tomb = build([("add_tombstone", pk(1), ck(1), V, 100, 50)])
    for order in ([ttl, tomb], [tomb, ttl]):
        m = cb.merge_sorted(order, now=0)
        (val, _, dead), = summarize(m).values()
        assert dead and val == b""


def test_larger_ldt_wins_between_expiring_at_equal_ts():
    # both expiring, same ts: larger localDeletionTime wins even when the
    # value bytes would order the other way
    a = build([("add_cell", pk(1), ck(1), V, b"zzz", 100, 500, 0)])
    b = build([("add_cell", pk(1), ck(1), V, b"aaa", 100, 900, 0)])
    for order in ([a, b], [b, a]):
        m = cb.merge_sorted(order, now=0)
        (val, _, _), = summarize(m).values()
        assert val == b"aaa"
