"""Slow-query reporting (db/monitoring role).

Reference counterpart: db/monitoring/MonitoringTask.java — operations
exceeding slow_query_log_timeout are collected and periodically
reported. Here the QueryProcessor times every statement; anything over
the threshold lands in a bounded ring surfaced through the
`system_views.slow_queries` virtual table and the
`cql.slow_queries` metric. Threshold is mutable at runtime
(nodetool setslowquerythreshold role)."""
from __future__ import annotations

import threading
from collections import deque

from ..utils import timeutil


class QueryMonitor:
    def __init__(self, threshold_ms: float = 500.0, capacity: int = 100):
        self.threshold_ms = threshold_ms
        self._entries: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._ids = 0

    def record(self, query: str, seconds: float,
               keyspace: str | None = None,
               trace_session: str | None = None) -> None:
        ms = seconds * 1000.0
        if ms < self.threshold_ms:
            return
        from .metrics import GLOBAL
        GLOBAL.incr("cql.slow_queries")
        with self._lock:
            self._ids += 1
            self._entries.append({
                "id": self._ids,
                "query": query[:500],
                "keyspace": keyspace,
                "duration_ms": round(ms, 3),
                "at": timeutil.now_micros() // 1000,
                # set when the slow statement ran traced/sampled — links
                # the entry to its system_traces timeline
                "trace_session": trace_session,
            })

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._entries)
