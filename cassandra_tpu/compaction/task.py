"""CompactionTask: the streaming device-merge rewrite of N sstables.

Reference counterpart: db/compaction/CompactionTask.java:114 (runMayThrow;
the hot loop :207-225 `while (ci.hasNext()) writer.append(ci.next())`),
CompactionIterator.java:90 (merge + purge pipeline) and
CompactionController.java:55 (purgeability from overlapping sources).

Formulation: instead of a row-at-a-time heap, each round buffers one
batch per input run, finds the safe merge boundary (min of the runs'
buffered maxima), merges everything below it in ONE engine call, and
hands the result to a pipelined writer thread (compression + file I/O
overlap the next round's decode + merge). Three interchangeable,
bit-identical merge engines:

  device  ops/merge.py — the TPU kernel (LSD radix sort + segmented-scan
          reconcile); big rounds amortise link latency.
  native  ops/native/merge.cpp — C++ k-way streaming merge with inline
          reconcile (the CompactionIterator formulation in native code);
          wins when the accelerator link is bandwidth-bound.
  numpy   storage/cellbatch.py — the executable spec.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..ops import merge as dmerge
from ..storage import cellbatch as cb
from ..storage.lifecycle import LifecycleTransaction
from ..storage.sstable import Descriptor, SSTableReader, SSTableWriter
from ..utils import timeutil


def _lane_keys(batch: cb.CellBatch) -> np.ndarray:
    """Rows as fixed-width byte strings (lexicographic == lane order)."""
    K = batch.n_lanes
    return np.ascontiguousarray(batch.lanes.astype(">u4")).view(
        f"S{4 * K}").ravel()


def _full_key(batch: cb.CellBatch, i: int) -> bytes:
    """Row i's lane key as exactly 4*K bytes. numpy S-dtype strips trailing
    NUL bytes; comparisons re-pad, but PREFIX SLICING must not see a
    shortened string — always pad before [:16]."""
    K = batch.n_lanes
    return bytes(_lane_keys(batch)[i]).ljust(4 * K, b"\x00")


class _Cursor:
    """Buffered scanner over one input sstable.

    Merge rounds are PARTITION-ALIGNED: deletion markers sort at the start
    of their partition/row, so reconcile is only correct when a round sees
    whole partitions (the reference's CompactionIterator merges per
    partition for the same reason). A partition larger than one segment is
    buffered whole — acceptable for round 1; the reference streams within
    partitions via its row index.

    (A background decode-prefetch thread was tried here early on and
    measured a net LOSS: the serial compress leg monopolized the GIL's
    contended windows, so the extra decode thread only fought pack/
    gather for them. With the compress leg on the GIL-releasing worker
    pool that contention is gone, and CompactionTask.decode_ahead now
    runs exactly that prefetch — the task's helper thread fills these
    buffers between rounds via fill_to, never concurrently with the
    round's own cursor access.)"""

    def __init__(self, reader: SSTableReader, prof: dict | None = None,
                 led=None):
        self._it = reader.scanner()
        self.prof = prof
        # pipeline ledger `compaction`/`decode` stage (led): every
        # fetch bills the SAME dt to the profile and to the stage's
        # busy seconds, so bench.py's reconcile proves them equal by
        # construction
        self.led = led
        # which phase bucket _fetch bills: the decode-ahead thread bills
        # its overlapped fills to 'decode_ahead' so 'io_decode' keeps
        # meaning time the MERGE thread stalled waiting on decode
        self.prof_key = "io_decode"
        self.bufs: list[cb.CellBatch] = []
        self.exhausted = False
        self._fetch()

    def _fetch(self) -> bool:
        t0 = time.perf_counter()
        try:
            self.bufs.append(next(self._it))
            return True
        except StopIteration:
            self.exhausted = True
            return False
        finally:
            dt = time.perf_counter() - t0
            if self.prof is not None:
                key = self.prof_key
                self.prof[key] = self.prof.get(key, 0.0) + dt
            if self.led is not None:
                self.led.add_busy(dt)
                if self.bufs and not self.exhausted:
                    b = self.bufs[-1]
                    self.led.add_items(
                        1, b.payload.nbytes + b.lanes.nbytes)

    @property
    def has_data(self) -> bool:
        return bool(self.bufs)

    @property
    def buffered_cells(self) -> int:
        return sum(len(b) for b in self.bufs)

    def fill_to(self, n_cells: int) -> None:
        """Buffer segments until ~n_cells are held (or input exhausted).
        Large rounds amortise the per-round device round-trip latency —
        the dominant warm-path cost through the tunneled chip."""
        while not self.exhausted and self.buffered_cells < n_cells:
            if not self._fetch():
                return

    def last_key(self) -> bytes:
        return _full_key(self.bufs[-1], -1)

    def extend_past_partition(self, prefix16: bytes) -> None:
        """Buffer more segments until the buffered data no longer ENDS
        inside the given partition (or the input is exhausted). Segments
        accumulate in a list — concat happens once, at slice time."""
        while self.bufs and self.last_key()[:16] == prefix16:
            if not self._fetch():
                return

    def split_at(self, boundary: bytes) -> cb.CellBatch | None:
        """Take cells with key <= boundary from the buffer; refill when the
        whole buffer is consumed."""
        if not self.bufs:
            return None
        buf = self.bufs[0] if len(self.bufs) == 1 \
            else cb.CellBatch.concat(self.bufs)
        buf.sorted = True
        keys = _lane_keys(buf)
        idx = int(np.searchsorted(keys, np.bytes_(boundary), side="right"))
        if idx == 0:
            self.bufs = [buf]
            return None
        if idx >= len(buf):
            self.bufs = []
            self._fetch()
            return buf
        head = buf.slice_range(0, idx)
        tail = buf.slice_range(idx, len(buf))
        self.bufs = [tail]
        return head


class CompactionController:
    """Purge decisions: a tombstone may only be dropped if no source
    OUTSIDE the compaction could still hold older shadowed data for its
    partition (CompactionController.java:61-121 maxPurgeableTimestamp).

    The overlap set is re-read per batch — a flush landing mid-compaction
    produces a new sstable (and the construction-time memtable is checked
    too), so concurrently-written older-timestamp data can never be purged
    against (the reference refreshes overlaps once a minute for the same
    reason)."""

    def __init__(self, cfs, compacting: list[SSTableReader]):
        self.cfs = cfs
        self.compacting_gens = {r.desc.generation for r in compacting}
        self.memtable_at_start = cfs.memtable

    def _overlapping(self) -> list[SSTableReader]:
        return [s for s in self.cfs.live_sstables()
                if s.desc.generation not in self.compacting_gens]

    def purgeable_ts_fn(self, batch: cb.CellBatch) -> np.ndarray:
        n = len(batch)
        out = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
        overlapping = self._overlapping()
        mems = {id(m): m for m in (self.memtable_at_start,
                                   self.cfs.memtable)}.values()
        mems = [m for m in mems if not m.is_empty]
        if not overlapping and not mems:
            return out
        lane4 = batch.lanes[:, :4]
        part_new = np.ones(n, dtype=bool)
        part_new[1:] = (lane4[1:] != lane4[:-1]).any(axis=1)
        part_id = np.cumsum(part_new) - 1
        starts = np.flatnonzero(part_new)
        per_part = np.full(len(starts), np.iinfo(np.int64).max,
                           dtype=np.int64)
        for j, s in enumerate(starts):
            pk = batch.partition_key(int(s))
            lo = np.iinfo(np.int64).max
            for src in overlapping:
                if src.might_contain(pk) and src.min_ts is not None:
                    lo = min(lo, src.min_ts)
            if any(m.contains(pk) for m in mems):
                lo = min(lo, 0)  # memtable data is never purged against
            per_part[j] = lo
        return per_part[part_id]


class CompactionTask:
    # cells merged per round. Device rounds target just under 2^18 cells:
    # big enough to amortise dispatch latency, small enough that >=4
    # rounds pipeline (submit round N+1 while N's result is in flight, so
    # link transfers overlap host decode/gather/write), and sized so the
    # padded program shape is almost always exactly 2^18 — one compiled
    # program, warm after the first round.
    # ~2 rounds per 1M-cell compaction: through a tunneled link the
    # per-round trip latency (~67 ms measured) dominates, so fewer,
    # larger rounds win as long as >= 2 keep the decode/write pipeline
    # overlapped (scripts/device_accounting.py sweeps this)
    ROUND_CELLS_DEVICE = (1 << 19) - (1 << 15)
    PIPELINE_DEPTH = 3
    # the host engines want SMALL rounds: per-round cost is near zero and
    # many rounds let the pipelined writer thread overlap compression +
    # file I/O with the next round's decode + merge.
    ROUND_CELLS_HOST = 1 << 17

    def __init__(self, cfs, inputs: list[SSTableReader],
                 max_output_bytes: int | None = None,
                 level: int = 0, use_device: bool | None = None,
                 round_cells: int | None = None,
                 engine: str | None = None,
                 limiter=None, progress=None,
                 pipelined_io: bool = True,
                 compress_pool=None,
                 decode_ahead: bool | None = None,
                 mesh_devices: int | None = None,
                 device_resident: bool | None = None,
                 device_compress: bool | None = None,
                 drop_only: bool = False):
        """engine: 'device' (TPU kernel), 'native' (C++ streaming merge),
        'numpy' (reference path). All three are tested bit-identical.
        Default (engine=None, use_device unset): the native engine when
        the library is available, else numpy — the measured winner when
        the accelerator link is bandwidth-bound (BASELINE.md); pass
        engine='device' (or use_device=True) on deployments with a
        locally attached chip.

        limiter: a utils.ratelimit.RateLimiter debited per round with the
        round's share of on-disk input bytes (compaction_throughput).
        progress: a compaction.executor.CompactionProgress the task
        updates as it runs (nodetool compactionstats / the
        compactions_in_progress virtual table).
        pipelined_io: thread the output's disk writes behind the
        compress stage (SSTableWriter threaded_io) — the write leg of
        the decode→merge→pack→compress→io_write pipeline. Output bytes
        are identical either way; disable to keep everything on two
        threads.
        compress_pool: the compressor-worker pool for the writers'
        parallel-compress leg. None (default) = the shared process
        pool sized by compaction_compressor_threads; 0 = keep the
        serial compress thread; a compress_pool.CompressorPool pins an
        explicit pool (bench sweeps, tests). Output bytes identical for
        every choice.
        decode_ahead: prefetch-decode round k+1's input segments on a
        helper thread while round k merges and the pool compresses —
        profitable now that the compress leg no longer contends for
        the GIL (an earlier prefetch attempt lost to exactly that, see
        _Cursor). None = inherit the owning ENGINE's hot-reloadable
        `compaction_decode_ahead` knob (default on), re-read EVERY
        ROUND so a mid-compaction flip stops or restarts the prefetch
        thread at the next round boundary; an explicit True/False pins
        it for this task. Host engines under pipelined_io only — the
        device engine keeps its own submit/collect pipelining.
        mesh_devices: the mesh execution mode (docs/multichip.md) —
        the compaction is token-range sharded by count-weighted
        boundaries planned from the input sstables' partition indexes
        and the per-shard decode->merge fans across N mesh lanes
        (engine='device': each shard's kernel committed to its own
        jax device; host engines: one GIL-releasing worker thread per
        lane). Shard results drain IN TOKEN ORDER through the same
        compress-pool/threaded-io writer, so output bytes are
        identical to the serial path for every N (token-range shard
        order IS identity-lane order — no reshuffle). None = inherit
        the `compaction_mesh_devices` knob (parallel/fanout.py);
        0 = force serial.
        device_resident: device-engine rounds stay END-TO-END on the
        jax device (ops/device_write.py): one fused program runs sort +
        reconcile + purge + kept-cell compaction, the columns stay in a
        device pending buffer across rounds, segments cut on-device and
        a second fused kernel serializes each META block — the host
        receives only finished blocks (plus the ragged payload, which
        never leaves it). Rounds the device cannot reproduce exactly
        (equal-ts ties, kept expired cells, counters, range bounds)
        fall back per round to the pinned host materialization, so
        output bytes are identical to the serial host path always
        (scripts/check_compaction_ab.py device legs). None = on for
        engine='device'; ignored for host engines and under the mesh
        execution mode (mesh shards drain through the host writer).
        device_compress: device-side block compression for the
        device-resident lane's full segments (ops/device_compress.py)
        — the fused policy-scan kernel compresses META + lanes on the
        device and the host io thread becomes a pwrite pump. None =
        inherit the engine's hot-reloadable `compaction_device_compress`
        knob, re-read by the writer PER SEGMENT (a mid-compaction flip
        moves the work at the next segment boundary); True/False pins
        it. Output bytes are identical for every choice — the native
        packer runs the same deterministic policy encoder.
        """
        self.cfs = cfs
        self.inputs = inputs
        self.max_output_bytes = max_output_bytes
        self.level = level
        self.use_device = bool(use_device)
        self.limiter = limiter
        self.progress = progress
        self.pipelined_io = pipelined_io
        if engine is None:
            if use_device:
                engine = "device"
            elif use_device is False:
                engine = "numpy"
            else:
                from ..ops import host_merge
                engine = "native" if host_merge.available() else "numpy"
        self.engine = engine
        if compress_pool is None:
            from ..storage.sstable.compress_pool import get_pool
            self.compress_pool = get_pool() if pipelined_io else None
        elif isinstance(compress_pool, int):
            if compress_pool != 0:
                # a worker COUNT belongs on the knob or an explicit
                # CompressorPool — silently running serial instead
                # would be an invisible perf misconfiguration
                raise ValueError(
                    "compress_pool takes a CompressorPool, None (shared "
                    "pool) or 0 (serial compress); to pin a worker "
                    "count pass CompressorPool(n)")
            self.compress_pool = None      # 0: serial compress
        else:
            self.compress_pool = compress_pool
        # tri-state: None = knob-inherited (resolved per round by
        # _decode_ahead_enabled), True/False = pinned for this task
        self.decode_ahead = decode_ahead
        self.mesh_devices = mesh_devices
        if device_resident is None:
            device_resident = self.engine == "device"
        self.device_resident = device_resident
        # tri-state like decode_ahead: None = inherit the owning
        # engine's hot-reloadable `compaction_device_compress` knob
        # (re-read PER SEGMENT by the writer), True/False = pinned for
        # this task (AB legs / bench sweeps). Only consulted by the
        # device-resident write lane; output bytes identical always.
        self.device_compress = device_compress
        self.round_cells = round_cells or (
            self.ROUND_CELLS_DEVICE if self.engine == "device"
            else self.ROUND_CELLS_HOST)
        # drop_only: the selecting strategy asserts every input is a
        # fully-expired tombstone sstable safe to delete without a
        # rewrite (TWCS expired drop). execute() re-verifies the guard
        # against the CURRENT live set/memtable and falls back to the
        # normal merge (which purges correctly) if anything changed
        # between selection and execution.
        self.drop_only = bool(drop_only)
        # per-phase wall seconds, accumulated across rounds (published by
        # bench.py -- the breakdown the perf work navigates by)
        self.profile: dict = {}

    def _effective_mesh_devices(self) -> int:
        """The mesh width this task runs at: the explicit mesh_devices=
        argument wins; None inherits the owning ENGINE's hot-reloadable
        `compaction_mesh_devices` knob via the store (0 = serial) —
        never a co-hosted engine's — falling back to the process demand
        for standalone stores."""
        if self.mesh_devices is not None:
            return max(int(self.mesh_devices), 0)
        fn = getattr(self.cfs, "mesh_devices_fn", None)
        if fn is not None:
            return max(int(fn()), 0)
        from ..parallel import fanout
        return fanout.mesh_devices()

    def _decode_ahead_enabled(self) -> bool:
        """Whether the decode-ahead prefetch should be running RIGHT
        NOW: the explicit decode_ahead= argument wins; None inherits
        the owning engine's hot-reloadable `compaction_decode_ahead`
        knob via the store (never a co-hosted engine's), defaulting on
        for standalone stores. The serial round loop re-reads this
        every round, so a mid-compaction knob flip stops or restarts
        the helper thread at the next round boundary — round
        boundaries and output bytes are identical either way (the
        pf_done handshake guarantees it)."""
        if self.decode_ahead is not None:
            return bool(self.decode_ahead)
        if not self.pipelined_io or self.engine == "device":
            return False
        fn = getattr(self.cfs, "decode_ahead_fn", None)
        return bool(fn()) if fn is not None else True

    def _device_compress_gate(self):
        """The writer's per-segment device-compress gate: False when
        this task has no device-resident lane; a pinned bool when
        device_compress= was explicit; else the owning store's
        hot-reloadable `compaction_device_compress` closure (never a
        co-hosted engine's), falling back to the config default for
        standalone stores. The writer re-reads a callable gate per
        segment, so mid-compaction knob flips land on segment
        boundaries."""
        if not self.device_resident:
            return False
        if self.device_compress is not None:
            return bool(self.device_compress)
        fn = getattr(self.cfs, "device_compress_fn", None)
        if fn is not None:
            return fn
        from ..config import Config
        return lambda: bool(Config().compaction_device_compress)

    def _engine_merge_fn(self, prof: dict | None,
                         defer_gather: bool = False):
        """The host-merge closure for this task's engine — the ONE place
        the native/numpy dispatch lives, shared by the serial round loop
        and the mesh lanes so the two paths can never diverge on merge
        semantics. Returns None for the device engine (its rounds go
        through submit/collect). prof: where the native merge bills its
        phase timings — run() passes the task profile, the mesh lanes
        pass a per-shard dict (folded under a lock; concurrent lanes
        must not race on the shared profile). defer_gather: the serial
        round loop defers the native merge's output gather to the
        writer thread (host_merge.LazyMergedBatch) so it overlaps the
        next round's decode + merge; mesh lanes keep it in-lane (their
        parallelism already covers it)."""
        if self.engine == "device":
            return None
        if self.engine == "native":
            from ..ops.host_merge import merge_sorted_native

            def merge_fn(slices, **kw):
                return merge_sorted_native(slices, prof=prof,
                                           defer_gather=defer_gather,
                                           **kw)
            return merge_fn
        return cb.merge_sorted

    # in-flight shard window beyond the mesh width: one extra so the
    # drain thread always has a completed shard to feed the writer
    # while every lane computes
    MESH_WINDOW_SLACK = 1

    def _mesh_produce(self, n_devices: int, wq, controller,
                      gc_before: int, now: int, werr,
                      bytes_per_cell: float) -> bool:
        """Mesh execution mode: token-range shard the whole rewrite by
        count-weighted boundaries planned from the input sstables'
        partition indexes, fan per-shard decode->merge across
        n_devices mesh lanes, and drain the merged shards IN TOKEN
        ORDER into the writer queue. Token-range shard order is
        identity-lane order, so the drained stream — and therefore
        every output byte — is identical to the serial round loop.
        bytes_per_cell: run()'s on-disk byte/cell ratio (throttle +
        progress accounting). Returns False (caller runs the serial
        path) when the inputs expose no index samples to plan from."""
        from ..parallel import fanout as fanout_mod
        from ..parallel.boundaries import (boundaries_from_indexes,
                                           boundaries_to_ranges,
                                           record_shard_metrics)

        prof = self.profile
        cfs = self.cfs
        progress = self.progress
        t_plan = time.perf_counter()
        cells_read = sum(r.n_cells for r in self.inputs)
        # shard count: at least one per lane, sized so a shard is about
        # one serial round (bounded memory per in-flight shard)
        n_shards = max(n_devices, -(-cells_read // self.round_cells))
        n_shards = min(int(n_shards), 4096)
        bounds = boundaries_from_indexes(self.inputs, n_shards)
        if bounds is None:
            return False
        ranges = boundaries_to_ranges(bounds, n_shards)
        # exact per-shard INPUT cells from the partition directories
        # (throttle + progress accounting in on-disk byte terms)
        shard_in_cells = np.zeros(n_shards, dtype=np.int64)
        signed_bounds = np.array([hi for (_lo, hi) in ranges[:-1]],
                                 dtype=np.int64)
        for r in self.inputs:
            if r.n_partitions == 0:
                continue
            part_cells = np.diff(np.append(r._part_cell0, r.n_cells))
            ps = np.searchsorted(signed_bounds, r.partition_tokens,
                                 side="left")
            np.add.at(shard_in_cells, ps, part_cells)
        prof["mesh_plan"] = prof.get("mesh_plan", 0.0) \
            + (time.perf_counter() - t_plan)

        devices = None
        if self.engine == "device":
            import jax
            devs = jax.devices()
            devices = [devs[i % len(devs)] for i in range(n_devices)]

        def merge_shard(slices, shard_prof):
            # the same per-engine dispatch run() uses — one source of
            # merge semantics for both paths (byte identity depends on
            # it); only the prof sink differs (per-shard, lock-folded)
            fn = self._engine_merge_fn(shard_prof)
            return fn(slices, gc_before=gc_before, now=now,
                      purgeable_ts_fn=controller.purgeable_ts_fn)

        import queue as _queue

        from ..service import tracing
        from ..utils import pipeline_ledger

        mesh_led = pipeline_ledger.ledger("mesh")
        led_decode = mesh_led.stage("decode")
        led_merge = mesh_led.stage("merge")
        # shard dispatch/completion under the active trace session (the
        # thread driving the compaction; lanes have no contextvar)
        trace_st = tracing.active()

        slots: list = [None] * n_shards
        evs = [threading.Event() for _ in range(n_shards)]
        errs: list = [None] * n_shards
        walls = [0.0] * n_shards
        busy = [0.0] * n_shards
        decoded_cells = [0] * n_shards
        stop = threading.Event()
        # plain Semaphore: a worker that bails between claim and acquire
        # during an abort may leave the drain's release unmatched —
        # harmless here, but BoundedSemaphore would raise and mask the
        # real error
        sem = threading.Semaphore(n_devices + self.MESH_WINDOW_SLACK)
        shard_q: _queue.Queue = _queue.Queue()
        for s in range(n_shards):
            shard_q.put(s)
        prof_lock = threading.Lock()
        self._mesh_completion_order: list[int] = []
        # merged-but-undrained shards: the mesh pipeline's inbound
        # queue to the writer drain (high-water = how far lanes ran
        # ahead of the token-order drain)
        ready_count = [0]

        def run_shard(s: int) -> None:
            shard_prof: dict = {}
            try:
                delay = fanout_mod._TEST_SHARD_DELAY
                if delay:
                    time.sleep(delay.get(s, 0.0))
                if trace_st is not None:
                    trace_st.add(f"Mesh shard {s} dispatched "
                                 f"({int(shard_in_cells[s])} cell(s))")
                if self.limiter is not None:
                    # stop cuts the throttle sleep short AND refunds the
                    # debit: an aborted task's debt must not throttle
                    # the re-planned replacement
                    t_thr = time.perf_counter()
                    self.limiter.acquire(
                        int(shard_in_cells[s] * bytes_per_cell),
                        cancel=stop)
                    # throttle sleeps are decode-stage stalls in the
                    # ledger (paid before the lane touches data)
                    led_decode.add_stall(time.perf_counter() - t_thr)
                if stop.is_set():   # abort: drop the shard, exit fast
                    return
                lo, hi = ranges[s]
                t0 = time.perf_counter()
                slices = []
                for r in self.inputs:
                    if stop.is_set():
                        return
                    w = r.scan_tokens(lo, hi)
                    if w is not None and len(w):
                        slices.append(w)
                t1 = time.perf_counter()
                shard_prof["mesh_decode"] = t1 - t0
                decoded_cells[s] = sum(len(x) for x in slices)
                merged = None
                if slices and not stop.is_set():
                    if devices is not None:
                        h = dmerge.submit_merge(
                            slices, gc_before=gc_before, now=now,
                            purgeable_ts_fn=controller.purgeable_ts_fn,
                            device=devices[s % n_devices])
                        merged = dmerge.collect_merge(h)
                    else:
                        merged = merge_shard(slices, shard_prof)
                walls[s] = time.perf_counter() - t1
                shard_prof["mesh_merge"] = walls[s]
                # busy = decode + merge, throttle sleeps excluded: the
                # lane-exclusive work an overlap measure sums
                busy[s] = time.perf_counter() - t0
                slots[s] = merged
                # per-stage ledger accounting (the same numbers the
                # shard_prof folds into the task profile, accumulated
                # process-wide under pipeline `mesh`)
                led_decode.add_busy(shard_prof.get("mesh_decode", 0.0))
                led_decode.add_items(
                    1, int(shard_in_cells[s] * bytes_per_cell))
                led_merge.add_busy(walls[s])
                led_merge.add_items(decoded_cells[s])
                if trace_st is not None:
                    trace_st.add(f"Mesh shard {s} complete "
                                 f"({decoded_cells[s]} cell(s) merged)")
            except BaseException as e:
                errs[s] = e
                stop.set()
            finally:
                with prof_lock:
                    for k, v in shard_prof.items():
                        prof[k] = prof.get(k, 0.0) + v
                    self._mesh_completion_order.append(s)
                    ready_count[0] += 1
                    led_merge.note_queue(ready_count[0])
                evs[s].set()

        def work_loop() -> None:
            while not stop.is_set():
                try:
                    s = shard_q.get_nowait()
                except _queue.Empty:
                    return
                acquired = False
                while not stop.is_set():
                    if sem.acquire(timeout=0.1):
                        acquired = True
                        break
                if not acquired:   # stopping: settle the shard's event
                    evs[s].set()
                    return
                run_shard(s)

        # daemon: lanes only read inputs and merge in memory (the
        # writer owns every on-disk mutation), so a straggler must not
        # block process exit after an abort already abandoned it
        workers = [threading.Thread(target=work_loop,
                                    name=f"compact-mesh-{i}",
                                    daemon=True)
                   for i in range(min(n_devices, n_shards))]
        t_fan = time.perf_counter()
        for t in workers:
            t.start()
        try:
            for s in range(n_shards):
                if werr:     # writer died: fail fast
                    break
                abort = getattr(cfs, "compaction_abort", None)
                if (abort is not None and abort.is_set()) or \
                        (progress is not None and progress.stop_requested):
                    raise RuntimeError(
                        "compaction stopped by operator request")
                evs[s].wait()
                if errs[s] is not None:
                    raise errs[s]
                merged = slots[s]
                slots[s] = None
                with prof_lock:
                    ready_count[0] -= 1
                sem.release()
                if progress is not None:
                    progress.set_phase("merge")
                    progress.add_read(
                        int(shard_in_cells[s] * bytes_per_cell))
                if merged is not None and len(merged):
                    wq.put(merged)
        finally:
            stop.set()
            for t in workers:
                t.join(timeout=30.0)
        record_shard_metrics(decoded_cells, walls)
        # per-shard forensics for bench.py / the multichip entry:
        # sum(busy)/produce_seconds > 1 proves the lanes actually
        # overlapped (busy is lane-EXCLUSIVE decode+merge work; a
        # 1-lane run measures ~1 by construction), the cell spread is
        # the planner's balance
        self.mesh_shard_walls = walls
        self.mesh_shard_busy = busy
        self.mesh_produce_seconds = time.perf_counter() - t_fan
        self.mesh_shard_cells = decoded_cells
        return True

    def _handle_corrupt_input(self, exc: BaseException) -> None:
        """Corruption surfacing mid-compaction aborts ONLY this task
        (the lifecycle txn already rolled back); route the failing
        input through the store's disk failure policy so best_effort
        quarantines it and the strategy re-plans without it
        (CompactionManager re-selects after the quarantine)."""
        from ..storage.sstable.reader import CorruptSSTableError
        if not isinstance(exc, CorruptSSTableError):
            return
        failures = getattr(self.cfs, "failures", None)
        if failures is None:
            return
        bad = None
        if exc.descriptor is not None:
            bad = next((r for r in self.inputs
                        if r.desc == exc.descriptor), None)
        path = bad.desc.path("Data.db") if bad is not None else ""
        policy = failures.handle_corruption(exc, path)
        if policy == "best_effort" and bad is not None:
            self.cfs.quarantine_sstable(bad, exc)

    def _drop_safe(self) -> bool:
        """Re-verify the fully-expired drop guard at EXECUTE time (the
        selecting strategy checked at selection; a flush or an
        out-of-order write may have landed since): every input all
        expired tombstones past gc grace, a quiet memtable, and no
        other live sstable holding data as old as the input's newest
        cell within its token span (dropping the tombstones must not
        resurrect anything they shadow)."""
        cfs = self.cfs
        gc_before = timeutil.now_seconds() - \
            cfs.table.params.gc_grace_seconds
        if not cfs.memtable.is_empty:
            return False
        in_ids = {id(r) for r in self.inputs}
        others = [o for o in cfs.live_sstables() if id(o) not in in_ids]
        for s in self.inputs:
            if s.max_ldt is None or s.max_ldt >= gc_before:
                return False
            if s.n_tombstones < s.n_cells:
                return False
            if any(o.min_ts is not None and s.max_ts is not None
                   and o.min_ts <= s.max_ts
                   and o.min_token() <= s.max_token()
                   and s.min_token() <= o.max_token()
                   for o in others):
                return False
        return True

    def _execute_drop(self) -> dict:
        """Rewrite-free expired drop: obsolete the inputs in one
        lifecycle txn and swap them out of the live view — no decode,
        no merge, no output writer. Zero compacted bytes land on the
        amplification counters: that IS the point of the drop."""
        cfs = self.cfs
        t0 = time.time()
        cells_read = sum(r.n_cells for r in self.inputs)
        txn = LifecycleTransaction(cfs.directory)
        for r in self.inputs:
            txn.track_obsolete(r.desc.generation)
        txn.commit()
        cfs.tracker.replace(self.inputs, [])
        if cfs.row_cache is not None:
            cfs.row_cache.clear()
        for r in self.inputs:
            r.release()
        stats = {
            "inputs": len(self.inputs), "outputs": 0,
            "bytes_read": 0, "bytes_written": 0,
            "cells_read": cells_read, "cells_written": 0,
            "seconds": time.time() - t0,
            "read_mib_s": 0.0, "write_mib_s": 0.0,
            "dropped": True,
        }
        rec = getattr(cfs, "record_compaction", None)
        if rec is not None:
            rec(stats)
        elif cfs.compaction_history is not None:
            cfs.compaction_history.append(stats)
        return stats

    def execute(self) -> dict:
        """Run the compaction; returns stats (reference logs these at
        CompactionTask.java:252-266)."""
        if self.drop_only and self._drop_safe():
            return self._execute_drop()
        cfs = self.cfs
        table = cfs.table
        t0 = time.time()
        gc_before = timeutil.now_seconds() - table.params.gc_grace_seconds
        now = timeutil.now_seconds()
        controller = CompactionController(cfs, self.inputs)
        prof = self.profile
        # pipeline `compaction` gains a `decode` stage: cursor fetches
        # (inline AND decode-ahead) bill busy, the merge thread's
        # prefetch waits bill stall, the prefetch thread's parked time
        # bills idle, and queue_hwm records how many segments decode
        # ran ahead of the merge (docs/observability.md)
        from ..utils import pipeline_ledger
        led_decode = pipeline_ledger.ledger("compaction").stage("decode")
        # None for the device engine: its rounds go through
        # submit/collect. The serial loop defers the output gather to
        # the writer thread (it drains the wq FIFO on one thread, so
        # materialization order — and output bytes — are unchanged).
        merge_fn = self._engine_merge_fn(prof, defer_gather=True)

        txn = LifecycleTransaction(cfs.directory)
        writers: list[SSTableWriter] = []
        new_readers: list[SSTableReader] = []
        bytes_read = sum(r.data_size for r in self.inputs)
        cells_read = sum(r.n_cells for r in self.inputs)
        cells_written = 0

        def new_writer() -> SSTableWriter:
            gen = cfs.next_generation()
            desc = Descriptor(cfs.directory, gen)
            txn.track_new(gen)
            w = SSTableWriter(desc, table,
                              estimated_partitions=max(
                                  sum(r.n_partitions for r in self.inputs), 16),
                              prof=prof, threaded_io=self.pipelined_io,
                              compress_pool=self.compress_pool,
                              metrics_group="compaction",
                              device_compress=self._device_compress_gate())
            w.level = self.level
            # outputs carry the MINIMUM repairedAt of the inputs
            # (CompactionTask.getMinRepairedAt): mixing repaired with
            # unrepaired demotes to unrepaired, never promotes
            w.repaired_at = min(r.repaired_at for r in self.inputs)
            writers.append(w)
            return w

        # pipelined write stage: compression + file I/O run on a worker
        # thread (ctypes FFI and FileIO release the GIL) while the main
        # thread decodes and merges the next round — the reference gets
        # the same overlap from the kernel's writeback cache; here it is
        # explicit. Queue depth 2 bounds buffered memory.
        import queue

        wq: queue.Queue = queue.Queue(maxsize=2)
        werr: list[BaseException] = []
        # credited: bytes of the CURRENT writer already added to
        # progress — in parallel-compress mode data_offset() trails
        # appends, so finish()'s pool drain must credit the tail too.
        # resident: device-resident rounds flow as DeviceRound objects
        # through a DeviceWriteLane instead of writer.append ("lane").
        wstate = {"writer": None, "cells": 0, "credited": 0,
                  "resident": False, "lane": None}

        progress = self.progress

        def flush_lane():
            lane = wstate["lane"]
            if lane is not None:
                lane.flush()
                wstate["lane"] = None

        def write_loop():
            # pack/compress stage of the pipeline: writer.append cuts
            # segments, serializes their blocks and (parallel-compress
            # mode) fans them out to the compressor pool, whose results
            # re-sequence through the writer's ordered completion queue
            # onto its I/O thread — the stages decode+merge / pack /
            # compress-pool / io_write all overlap. In device-resident
            # mode the rounds arrive as DeviceRound column sets and the
            # segment cut + META serialize happen ON DEVICE through the
            # write lane; the writer sees only finished blocks. Phase
            # timings land in prof as 'serialize', 'compress' and
            # 'io_write'. Progress + the output-size cut-over read the
            # writer's PUBLISHED offset (data_offset()), never private
            # state another thread is mutating.
            try:
                while True:
                    merged = wq.get()
                    if merged is None:
                        # the sentinel is already consumed: a raise out
                        # of the lane flush must land in werr and
                        # RETURN (the generic except below drains the
                        # queue waiting for a sentinel that will never
                        # come — the producer already sent it)
                        try:
                            flush_lane()
                        except BaseException as e:
                            werr.append(e)
                        return
                    if hasattr(merged, "materialize"):
                        # deferred native-merge gather: runs HERE, on
                        # the writer thread, overlapping the producer's
                        # next round (host_merge.LazyMergedBatch)
                        merged = merged.materialize()
                    w = wstate["writer"]
                    if wstate["resident"]:
                        lane = wstate["lane"]
                        if lane is None:
                            from ..ops.device_write import DeviceWriteLane
                            lane = wstate["lane"] = DeviceWriteLane(w)
                        lane.append(merged)
                    else:
                        w.append(merged)
                    if progress is not None:
                        off = w.data_offset()
                        progress.add_written(off - wstate["credited"])
                        wstate["credited"] = off
                    wstate["cells"] += len(merged)
                    if self.max_output_bytes and \
                            wstate["writer"].data_offset() >= \
                            self.max_output_bytes:
                        # roll the output (MaxSSTableSizeWriter role).
                        # In parallel mode the published offset trails
                        # in-flight segments, so the roll lands late by
                        # a bounded amount — finish() drains the pool
                        # (and the drained tail is credited below).
                        # The lane's pending partial flushes into the
                        # finishing writer first — exactly the cells
                        # finish() would cut from host pending.
                        w = wstate["writer"]
                        flush_lane()
                        w.finish()
                        if progress is not None:
                            progress.add_written(
                                w.data_offset() - wstate["credited"])
                        new_readers.append(SSTableReader(w.desc, table))
                        wstate["writer"] = new_writer()
                        wstate["credited"] = 0
            except BaseException as e:   # surfaced after join
                werr.append(e)
                wstate["lane"] = None
                while True:              # drain so the producer never blocks
                    if wq.get() is None:
                        return

        # device engine: keep rounds in flight (async dispatch) so the
        # accelerator link overlaps host decode + gather + write
        from collections import deque

        pending: deque = deque()

        def collect_oldest():
            if wstate["resident"]:
                from ..ops.device_write import collect_merge_resident
                merged = collect_merge_resident(pending.popleft())
            else:
                merged = dmerge.collect_merge(pending.popleft())
            if len(merged):
                wq.put(merged)

        # throttle + progress work in on-disk byte terms: each round
        # consumed cells are mapped back to their share of the input
        # files' bytes, so compaction_throughput limits disk read rate
        # (the reference debits its limiter per scanned partition) and
        # progress.bytes_read converges on total_bytes exactly
        bytes_per_cell = bytes_read / max(cells_read, 1)

        # decode-ahead stage (LUDA's overlap of decode k+1 with merge k):
        # a helper thread refills the cursors' segment buffers while the
        # merge engine reconciles the current round and the pool
        # compresses its output. Strictly handshaked — the helper only
        # touches cursors between pf_done.clear() and pf_done.set(), and
        # the main loop waits on pf_done before every cursor access — so
        # round boundaries (and output bytes) are identical either way.
        pf_q = None
        pf_thread = None
        pf_done = threading.Event()
        pf_done.set()
        pf_err: list[BaseException] = []

        def prefetch_loop():
            while True:
                with led_decode.idle():   # parked between prefetches
                    per = pf_q.get()
                if per is None:
                    return
                try:
                    for c in cursors:
                        if not c.exhausted:
                            c.prof_key = "decode_ahead"
                            try:
                                c.fill_to(per)
                            finally:
                                c.prof_key = "io_decode"
                except BaseException as e:   # surfaced next round
                    pf_err.append(e)
                finally:
                    # prefetch-queue high water: segments buffered
                    # ahead of the merge (how far decode ran ahead)
                    led_decode.note_queue(
                        sum(len(c.bufs) for c in cursors))
                    pf_done.set()

        def stop_prefetch():
            if pf_thread is not None:
                pf_q.put(None)
                pf_thread.join(timeout=30.0)

        wthread = None
        try:
            if progress is not None:
                progress.set_phase("decode")
            wstate["writer"] = new_writer()
            wthread = threading.Thread(target=write_loop, name="compact-w")
            wthread.start()
            # mesh execution mode: shard the rewrite by token range and
            # fan decode+merge across the mesh lanes; the serial round
            # loop below is skipped (its cursor list stays empty). Falls
            # back to the serial path when no boundaries can be planned.
            mesh_done = False
            mesh_n = self._effective_mesh_devices()
            if mesh_n >= 1:
                if progress is not None:
                    progress.set_phase("mesh_plan")
                mesh_done = self._mesh_produce(mesh_n, wq, controller,
                                               gc_before, now, werr,
                                               bytes_per_cell)
            # device-resident rounds only make sense for the serial
            # device round loop: mesh shards drain host CellBatches
            # through the unchanged writer (token-order contract)
            wstate["resident"] = (self.engine == "device"
                                  and self.device_resident
                                  and not mesh_done)
            cursors = [] if mesh_done \
                else [_Cursor(r, prof, led=led_decode)
                      for r in self.inputs]
            # the decode-ahead thread starts (and stops, and restarts)
            # from the knob check at the top of each round — see below
            while True:
                if werr:       # writer died: fail fast, don't keep merging
                    break
                abort = getattr(cfs, "compaction_abort", None)
                if (abort is not None and abort.is_set()) or \
                        (progress is not None and progress.stop_requested):
                    # nodetool stop: cooperative cancel between rounds
                    # (per-task via the progress handle under the
                    # executor; the legacy shared event covers tasks
                    # driven without one); the lifecycle txn below never
                    # commits, so the partial output rolls back on the
                    # crash-safe path
                    raise RuntimeError(
                        "compaction stopped by operator request")
                # cursors are shared with the decode-ahead helper: wait
                # out any in-flight prefetch before touching them (the
                # wait is the merge thread BLOCKED ON decode — the
                # ledger bills it as a decode-stage stall)
                t_pf = time.perf_counter()
                pf_done.wait()
                if pf_thread is not None:
                    led_decode.add_stall(time.perf_counter() - t_pf)
                if pf_err:
                    raise pf_err[0]
                # hot-reloadable `compaction_decode_ahead`: re-resolved
                # every round, so a mid-compaction flip OFF retires the
                # helper thread here (the prefetch in flight already
                # handshook out above) and a flip ON starts it — round
                # boundaries, and therefore output bytes, are identical
                # under any flip sequence
                if not mesh_done:
                    want_da = self._decode_ahead_enabled()
                    if pf_thread is not None and not want_da:
                        stop_prefetch()
                        pf_thread = None
                    elif pf_thread is None and want_da:
                        pf_q = queue.Queue()
                        pf_thread = threading.Thread(
                            target=prefetch_loop,
                            name="compact-prefetch", daemon=True)
                        pf_thread.start()
                active = [c for c in cursors if c.has_data]
                if not active:
                    break
                # buffer a full round's worth per cursor first, THEN find
                # the partition-aligned boundary: the minimal buffered-
                # through key, extended so no cursor's buffer ends INSIDE
                # that key's partition; merge everything up to the
                # partition end (full key width padded with 0xFF)
                per_cursor = max(self.round_cells // len(active), 1)
                for c in active:
                    c.fill_to(per_cursor)
                prefix16 = min(c.last_key() for c in active)[:16]
                for c in cursors:
                    c.extend_past_partition(prefix16)
                K = self.inputs[0].K
                boundary = prefix16 + b"\xff" * (4 * K - 16)
                slices = []
                for c in cursors:
                    s = c.split_at(boundary)
                    if s is not None and len(s):
                        slices.append(s)
                if not slices:
                    continue
                if pf_thread is not None and \
                        any(not c.exhausted for c in cursors):
                    # round k's inputs are sliced off: decode round
                    # k+1's segments while k merges + compresses
                    pf_done.clear()
                    pf_q.put(per_cursor)
                round_bytes = int(sum(len(s) for s in slices)
                                  * bytes_per_cell)
                if progress is not None:
                    progress.set_phase("merge")
                    progress.add_read(round_bytes)
                if self.limiter is not None:
                    self.limiter.acquire(round_bytes)
                if self.engine == "device":
                    if wstate["resident"]:
                        from ..ops.device_write import \
                            submit_merge_resident
                        pending.append(submit_merge_resident(
                            slices, gc_before=gc_before, now=now,
                            purgeable_ts_fn=controller.purgeable_ts_fn,
                            prof=prof))
                    else:
                        pending.append(dmerge.submit_merge(
                            slices, gc_before=gc_before, now=now,
                            purgeable_ts_fn=controller.purgeable_ts_fn,
                            prof=prof))
                    while len(pending) >= self.PIPELINE_DEPTH:
                        collect_oldest()
                else:
                    merged = merge_fn(slices, gc_before=gc_before, now=now,
                                      purgeable_ts_fn=controller.purgeable_ts_fn)
                    if len(merged):
                        wq.put(merged)
            stop_prefetch()
            pf_thread = None
            while pending:
                collect_oldest()
            wq.put(None)
            wthread.join()
            if werr:
                raise werr[0]
            cells_written = wstate["cells"]
            writer = wstate["writer"]
            if progress is not None:
                progress.set_phase("seal")
            tw = time.perf_counter()
            writer.finish()
            prof["seal"] = prof.get("seal", 0.0) + \
                (time.perf_counter() - tw)
            if progress is not None:
                # the final pool drain's tail (write_loop is joined,
                # so "credited" is stable here)
                progress.add_written(
                    writer.data_offset() - wstate["credited"])
            new_readers.append(SSTableReader(writer.desc, table))
            for r in self.inputs:
                txn.track_obsolete(r.desc.generation)
            # empty outputs (everything purged) die in the same txn
            live_new = []
            for r in new_readers:
                if r.n_cells > 0:
                    live_new.append(r)
                else:
                    r.close()
                    txn.track_obsolete(r.desc.generation)
            # COMMIT first (a failure here must roll back cleanly while the
            # tracker still serves the inputs), then swap the live view;
            # input files may already be unlinked but their open fds keep
            # serving in-flight reads. Inputs are RELEASED, not closed
            # (reference SSTableReader ref-counting, utils/concurrent/Ref).
            txn.commit()
            cfs.tracker.replace(self.inputs, live_new)
            if cfs.row_cache is not None:
                # compaction-generation change: the read fast lane pins
                # cached merges to the sstable set they were computed
                # from (storage/row_cache.py invalidation contract)
                cfs.row_cache.clear()
            for r in self.inputs:
                r.release()
            if getattr(cfs, "index_build_fn", None) is not None:
                # eager attached-index components for the outputs, so
                # the first indexed query after compaction never pays
                # the build storm (build_eager never raises)
                for r in live_new:
                    cfs.index_build_fn(r)
        except BaseException as exc:
            pending.clear()
            stop_prefetch()
            if wthread is not None and wthread.is_alive():
                # blocking put is safe: the consumer is either processing
                # or draining toward the sentinel — put_nowait could drop
                # the sentinel on a full queue and leave the thread stuck
                wq.put(None)
                wthread.join(timeout=30.0)
            for w in writers:
                try:
                    w.abort()
                except Exception:
                    pass
            for r in new_readers:
                r.close()
            txn.abort()   # no-op if the COMMIT record already landed
            self._handle_corrupt_input(exc)
            raise

        dt = time.time() - t0
        if prof:
            # per-phase wall seconds aggregate process-wide: the
            # system_views.device_profile vtable and bench.py's
            # kernel_profile section read them alongside kernel stats
            from ..service.profiling import GLOBAL as kprof
            kprof.add_phases(prof)
        bytes_written = sum(r.data_size for r in new_readers)
        stats = {
            "inputs": len(self.inputs),
            "outputs": len([r for r in new_readers if r.n_cells > 0]),
            "bytes_read": bytes_read,
            "bytes_written": bytes_written,
            "cells_read": cells_read,
            "cells_written": cells_written,
            "seconds": dt,
            "read_mib_s": bytes_read / dt / 2**20 if dt > 0 else 0,
            "write_mib_s": bytes_written / dt / 2**20 if dt > 0 else 0,
        }
        # history ring + amplification counters in one locked fold
        # (storage/table.py record_compaction: the append shares a
        # lock with the capacity-knob swap, and the byte totals also
        # land on the monotonic counters that survive ring eviction);
        # bare test doubles without the method keep the raw append
        rec = getattr(cfs, "record_compaction", None)
        if rec is not None:
            rec(stats)
        elif cfs.compaction_history is not None:
            cfs.compaction_history.append(stats)
        return stats
