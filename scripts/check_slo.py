#!/usr/bin/env python
"""CI check (tier-2, alongside check_diagnostics.py): the SLO layer
turns a latency regression into an actionable artifact, deterministically.

Drill (`--smoke`, also the default): an engine with the diagnostic bus
enabled gets an SLO service with an INJECTED clock and an objective
whose percentile source is injected too — so the breach, the recovery,
the re-breach and the budget exhaustion are all forced exactly, no
timing dependence. Assertions:

  - a compliant→breach transition publishes a typed `slo.breach` event
    carrying the objective, the observed p99, the target and the
    attribution context (the matrix's scenario id);
  - the breach triggers a flight-recorder dump whose bundle is
    well-formed JSON and CARRIES the `slo.breach` event (published
    before the dump, so the recorder's ring has it) plus the scenario
    id — the self-contained black box every SLO violation ships with;
  - dump dedup is pinned: a recover→re-breach inside the recorder's
    dedup window publishes a second `slo.breach` but does NOT dump a
    second bundle; past the window it dumps again;
  - error-budget accounting: breach-seconds burn the budget, crossing
    zero publishes `slo.budget_exhausted` exactly once (latched),
    replenish past zero unlatches;
  - the `slo.*` counters and the `system_views.slos` vtable agree with
    the service state, and `nodetool slostats` runs a live check;
  - the hot-reload path: a `slo_targets` settings write retargets an
    existing objective and registers a new per-CL one.

Exit 0 = clean; exit 1 prints each violation.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def run_check(base_dir: str) -> list[str]:
    from cassandra_tpu.config import Config, Settings
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.service import diagnostics
    from cassandra_tpu.service.diagnostics import FlightRecorder
    from cassandra_tpu.service.metrics import GLOBAL as METRICS
    from cassandra_tpu.service.slo import SLObjective, SLOService
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.tools import nodetool

    errs: list[str] = []

    def need(cond, msg):
        if not cond:
            errs.append(msg)

    diagnostics.GLOBAL.clear()
    settings = Settings(Config.load({"diagnostic_events_enabled": True}))
    eng = StorageEngine(base_dir, Schema(), commitlog_sync="periodic",
                        settings=settings)
    clock = _Clock()
    svc = SLOService(engine=eng, clock=clock)
    # the recorder shares the injected clock so the dedup window is
    # driven, not waited out
    svc.recorder = FlightRecorder(engine=eng, clock=clock)
    p99 = {"v": 1_000.0}   # injected percentile source (us)
    obj = svc.register(SLObjective(
        "smoke_latency", hist="client_requests.read", target_ms=10.0,
        budget_s=2.0, window_s=20.0, source=lambda: p99["v"]))
    svc.set_context(scenario="slo-smoke:leg1")
    try:
        # --- healthy check: no events, budget full
        svc.check()
        need(not obj.breaching, "healthy check reported breaching")
        need(obj.budget_remaining_s == 2.0,
             "healthy check touched the budget")

        # --- breach: event published, bundle dumped, both well-formed
        breaches0 = METRICS.counter("slo.breaches")
        p99["v"] = 50_000.0
        clock.t += 1.0
        svc.check()
        need(obj.breaching, "p99 50ms vs target 10ms did not breach")
        evs = diagnostics.GLOBAL.events("slo.breach")
        need(len(evs) == 1, f"expected 1 slo.breach event, got {len(evs)}")
        if evs:
            f = evs[-1].fields
            need(f.get("objective") == "smoke_latency"
                 and f.get("scenario") == "slo-smoke:leg1"
                 and f.get("p99_us") == 50_000.0
                 and f.get("target_us") == 10_000.0,
                 f"breach event fields malformed: {f}")
        need(METRICS.counter("slo.breaches") == breaches0 + 1,
             "slo.breaches counter did not advance")
        dumps = list(svc.recorder.dumps)
        need(len(dumps) == 1,
             f"breach dumped {len(dumps)} bundles, expected 1")
        if dumps:
            with open(dumps[0]) as fh:
                bundle = json.load(fh)   # malformed JSON raises
            need(bundle["reason"] == "slo_breach_smoke_latency",
                 f"bundle reason {bundle.get('reason')!r}")
            bevs = [e for e in bundle.get("events", [])
                    if e.get("type") == "slo.breach"]
            need(bool(bevs), "bundle does not carry the slo.breach event")
            need(any(e.get("scenario") == "slo-smoke:leg1"
                     for e in bevs),
                 "bundle breach event lacks the scenario id")
            need(bundle.get("trigger", {}).get("scenario")
                 == "slo-smoke:leg1",
                 "bundle trigger lacks the scenario id")
            need("metrics" in bundle.get("final", {}),
                 "bundle lacks the final metrics capture")
            # observatory: every bundle carries a non-empty retained
            # metrics-history window (a dump-time sample guarantees
            # at least the moment-of point even with the sampler off)
            # and the pipeline-ledger stage table
            mh = bundle.get("metrics_history", {})
            need(bool(mh) and any(pts for pts in mh.values()),
                 "bundle metrics-history window is empty")
            need("pipeline_ledger" in bundle,
                 "bundle lacks the pipeline-ledger stage table")

        # --- budget burn while breaching; exhaustion publishes once
        clock.t += 1.5
        svc.check()
        need(abs(obj.budget_remaining_s - 0.5) < 1e-6,
             f"1.5s of breach burned to {obj.budget_remaining_s}, "
             "expected 0.5")
        clock.t += 0.5
        svc.check()
        need(obj.exhausted and obj.budget_remaining_s == 0.0,
             "budget did not exhaust at exactly 0")
        exh = diagnostics.GLOBAL.events("slo.budget_exhausted")
        need(len(exh) == 1,
             f"expected 1 slo.budget_exhausted, got {len(exh)}")
        clock.t += 1.0
        svc.check()   # still breaching, still exhausted
        need(len(diagnostics.GLOBAL.events("slo.budget_exhausted")) == 1,
             "exhaustion latched state re-published")

        # --- recover, then re-breach INSIDE the dedup window: second
        # breach event, but NO second bundle
        p99["v"] = 1_000.0
        clock.t += 0.2
        svc.check()
        need(not obj.breaching, "recovery not detected")
        need(len(diagnostics.GLOBAL.events("slo.recover")) == 1,
             "no slo.recover event")
        clock.t += 0.2   # one compliant interval replenishes
        svc.check()
        need(obj.budget_remaining_s > 0.0 and not obj.exhausted,
             "replenish did not unlatch exhaustion")
        p99["v"] = 50_000.0
        clock.t += 0.2   # still inside the recorder's 5s dedup window
        svc.check()
        need(obj.breaching and obj.breaches == 2,
             "re-breach transition missed")
        need(len(diagnostics.GLOBAL.events("slo.breach")) == 2,
             "re-breach did not publish a second event")
        breach_bundles = [p for p in svc.recorder.dumps
                          if "slo_breach_" in p]
        need(len(breach_bundles) == 1,
             "re-breach inside the dedup window dumped a second "
             f"breach bundle ({breach_bundles})")
        # the exhaustion crossing dumped under its own reason — that
        # artifact must exist alongside, not instead
        need(any("slo_budget_exhausted_" in p
                 for p in svc.recorder.dumps),
             "budget exhaustion did not dump its own bundle")
        # the dedup check needs the second breach within the window of
        # the dump; rewind-free: trigger again explicitly
        need(svc.recorder.trigger("slo_breach_smoke_latency") is None,
             "dedup window did not coalesce a same-reason dump")
        clock.t += FlightRecorder.DEDUP_WINDOW_S + 1.0
        p99["v"] = 1_000.0
        svc.check()
        p99["v"] = 50_000.0
        clock.t += 0.1
        svc.check()
        need(len([p for p in svc.recorder.dumps
                  if "slo_breach_" in p]) >= 2,
             "a breach past the dedup window did not dump again")

        # --- hot-reload: retarget via the settings knob + register a
        # per-CL objective by name
        settings.set("slo_targets", {"client_requests.read": 5,
                                     "client_requests.read.quorum": 7})
        ro = eng.slo.objective("client_requests.read")
        rq = eng.slo.objective("client_requests.read.quorum")
        need(ro is not None and ro.target_us == 5_000.0,
             "slo_targets knob did not retarget an existing objective")
        need(rq is not None and rq.target_us == 7_000.0,
             "slo_targets knob did not register a per-CL objective")

        # --- surfaces: vtable rows match service state; slostats runs
        vt = eng.virtual_tables.get("system_views", "slos")
        rows = {r["objective"]: r for r in vt.rows()}
        need("client_requests.read" in rows,
             "system_views.slos lacks the default read objective")
        st = nodetool.slostats(eng)
        need(any(v["objective"] == "client_requests.read"
                 for v in st["objectives"]),
             "nodetool slostats lacks the default read objective")
    finally:
        svc.recorder.close()
        eng.close()
        diagnostics.GLOBAL.reset()
    return errs


def main() -> int:
    # --smoke is the (only) mode; accepted explicitly so CI invocations
    # read like the other tier-2 drills
    with tempfile.TemporaryDirectory() as d:
        errs = run_check(d)
    if errs:
        print("check_slo: FAIL", file=sys.stderr)
        for e in errs:
            print(f"  - {e}", file=sys.stderr)
        return 1
    print("check_slo: breach -> event -> bundle path OK "
          "(dedup + budget math pinned)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
