"""Per-sstable attached index components — the SAI storage model.

Reference counterpart: index/sai/ (StorageAttachedIndex: every sstable
carries its own index component, built at flush/compaction time or on
first use, dropped with the sstable). No global rebuild ever happens: a
restart reopens components from disk, and an sstable that appears through
any path (flush, compaction, anticompaction, streaming, bulk load) gets
its component built once from that sstable alone.

Formats (little-endian, CRC-trailed, 4-byte magic = format version; a
component with an older/unknown magic or any parse error loads as None
and is simply rebuilt from its sstable — the worst case of format
evolution is one re-scan):
  equality  "EQI1" [u32 n][records: vint vlen, v, vint pklen, pk,
            vint cklen, ck]
  vector    "VEC2" [u32 n][u32 dim][f32 matrix n*dim][i64 ts]*n
            [locators: vint pklen, pk, vint cklen, ck]*n
  zonemap   "ZMP1" [u32 n_segments][u32 n_columns]
            [u32 col_id, u8 kind]*n_columns, then per column
            [u64 kmin]*nseg [u64 kmax]*nseg [u32 live]*nseg
            [u32 dead]*nseg — keys are the monotone u64 scan keys of
            ops/device_scan.py; an empty segment is (U64_MAX, 0)
All end with [u32 crc32(body)].
"""
from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from ..schema import TableMetadata
from ..utils import varint as vi


def component_path(desc, column_id: int) -> str:
    return os.path.join(desc.directory,
                        f"{desc.version}-{desc.generation}"
                        f"-Index_{column_id}.db")


def iter_column_cells(batch, column_id: int):
    """(value, pk, ck) for every LIVE cell of the column in a CellBatch
    (dead cells carry no value worth indexing; stale entries are filtered
    at read time by re-checking the base row). Shared by the sstable
    component builders and the memtable query path."""
    from ..storage.cellbatch import DEATH_FLAGS
    C = batch.n_lanes - 9
    cols = batch.lanes[:, 6 + C]
    hits = np.flatnonzero((cols == column_id)
                          & ((batch.flags & DEATH_FLAGS) == 0))
    for i in hits:
        ck, _path, value = batch.cell_payload(int(i))
        if value:
            yield value, batch.partition_key(int(i)), ck, \
                int(batch.ts[int(i)])


def _scan_column(reader, table: TableMetadata, column_id: int):
    for seg in reader.scanner():
        yield from iter_column_cells(seg, column_id)


def _write(path: str, body: bytes) -> None:
    import threading
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(body)
        f.write(struct.pack("<I", zlib.crc32(body)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read(path: str) -> bytes | None:
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return None
    if len(data) < 4:
        return None
    body, crc = data[:-4], struct.unpack("<I", data[-4:])[0]
    if zlib.crc32(body) != crc:
        return None   # torn write: caller rebuilds
    return body


# ---------------------------------------------------------------- equality --

def build_equality(reader, table: TableMetadata, column_id: int) -> str:
    path = component_path(reader.desc, column_id)
    out = bytearray()
    n = 0
    recs = bytearray()
    for value, pk, ck, _ts in _scan_column(reader, table, column_id):
        vi.write_unsigned_vint(len(value), recs)
        recs += value
        vi.write_unsigned_vint(len(pk), recs)
        recs += pk
        vi.write_unsigned_vint(len(ck), recs)
        recs += ck
        n += 1
    out += b"EQI1"
    out += struct.pack("<I", n)
    out += recs
    _write(path, bytes(out))
    return path


def load_equality(path: str) -> dict[bytes, list] | None:
    body = _read(path)
    if body is None or body[:4] != b"EQI1":
        return None
    try:
        return _parse_equality(body)
    except (ValueError, IndexError, struct.error):
        return None   # malformed: rebuild


def _parse_equality(body: bytes) -> dict[bytes, list]:
    (n,) = struct.unpack_from("<I", body, 4)
    pos = 8
    out: dict[bytes, list] = {}
    for _ in range(n):
        ln, pos = vi.read_unsigned_vint(body, pos)
        v = bytes(body[pos:pos + ln])
        pos += ln
        ln, pos = vi.read_unsigned_vint(body, pos)
        pk = bytes(body[pos:pos + ln])
        pos += ln
        ln, pos = vi.read_unsigned_vint(body, pos)
        ck = bytes(body[pos:pos + ln])
        pos += ln
        out.setdefault(v, []).append((pk, ck))
    return out


# ------------------------------------------------------------------ vector --

def build_vector(reader, table: TableMetadata, column_id: int,
                 dim: int) -> str:
    path = component_path(reader.desc, column_id)
    rows = []
    tss = []
    locs = bytearray()
    for value, pk, ck, ts in _scan_column(reader, table, column_id):
        rows.append(np.frombuffer(value, dtype=">f4").astype(np.float32))
        tss.append(ts)
        vi.write_unsigned_vint(len(pk), locs)
        locs += pk
        vi.write_unsigned_vint(len(ck), locs)
        locs += ck
    mat = np.stack(rows) if rows else np.zeros((0, dim), np.float32)
    out = bytearray()
    out += b"VEC2"
    out += struct.pack("<II", len(rows), dim)
    out += mat.astype("<f4").tobytes()
    out += np.asarray(tss, dtype="<i8").tobytes()
    out += locs
    _write(path, bytes(out))
    return path


def load_vector(path: str):
    """(matrix float32 [n, dim], ts int64 [n], [(pk, ck)] locators)."""
    body = _read(path)
    if body is None or body[:4] != b"VEC2":
        return None
    try:
        return _parse_vector(body)
    except (ValueError, IndexError, struct.error):
        return None   # malformed: rebuild


def _parse_vector(body: bytes):
    n, dim = struct.unpack_from("<II", body, 4)
    pos = 12
    mat = np.frombuffer(body, dtype="<f4", count=n * dim,
                        offset=pos).reshape(n, dim).astype(np.float32)
    pos += 4 * n * dim
    tss = np.frombuffer(body, dtype="<i8", count=n, offset=pos).copy()
    pos += 8 * n
    keys = []
    for _ in range(n):
        ln, pos = vi.read_unsigned_vint(body, pos)
        pk = bytes(body[pos:pos + ln])
        pos += ln
        ln, pos = vi.read_unsigned_vint(body, pos)
        ck = bytes(body[pos:pos + ln])
        pos += ln
        keys.append((pk, ck))
    return mat, tss, keys


# -------------------------------------------------------------------- text --
# SASI role (index/sasi): analyzed text terms -> locators, one CRC-trailed
# component per sstable like the equality/vector components. The analyzer
# is the SASI StandardAnalyzer subset: lowercase, split on
# non-alphanumeric runs. PREFIX mode indexes the whole lowercased value
# instead (SASI's non-tokenizing analyzer) for LIKE 'abc%'.

_TOKEN_RE = None


def analyze(value: bytes, mode: str) -> set[bytes]:
    global _TOKEN_RE
    if _TOKEN_RE is None:
        import re
        _TOKEN_RE = re.compile(r"[0-9a-z]+")
    text = value.decode("utf-8", "ignore").lower()
    if mode == "PREFIX":
        return {text.encode()} if text else set()
    return {t.encode() for t in _TOKEN_RE.findall(text)}


def text_component_path(desc, column_id: int) -> str:
    return os.path.join(desc.directory,
                        f"{desc.version}-{desc.generation}"
                        f"-Text_{column_id}.db")


def build_text(reader, table: TableMetadata, column_id: int,
               mode: str) -> str:
    path = text_component_path(reader.desc, column_id)
    recs = bytearray()
    n = 0
    for value, pk, ck, _ts in _scan_column(reader, table, column_id):
        for term in analyze(value, mode):
            vi.write_unsigned_vint(len(term), recs)
            recs += term
            vi.write_unsigned_vint(len(pk), recs)
            recs += pk
            vi.write_unsigned_vint(len(ck), recs)
            recs += ck
            n += 1
    out = bytearray()
    out += b"TXI1"
    out += struct.pack("<I", n)
    out += recs
    _write(path, bytes(out))
    return path


def load_text(path: str) -> dict[bytes, list] | None:
    body = _read(path)
    if body is None or body[:4] != b"TXI1":
        return None
    try:
        return _parse_equality(body)   # identical record layout
    except (ValueError, IndexError, struct.error):
        return None


# ---------------------------------------------------------------- zone map --
# One component per sstable bounding every segment's live cells per
# supported column in the u64 scan-key space (ops/device_scan.py), so
# analytical scans prune segments — or the whole sstable — without
# decoding them. Built in the writer tail at flush/compaction; the EQI1
# rebuild contract applies (parse error / stale segment count -> rebuilt
# from the sstable once). Encrypted sstables never get one: plaintext
# min/max keys would leak TDE-protected values.

_KIND_CODES = {"i64": 0, "f64": 1, "bool": 2, "prefix": 3}
_KIND_NAMES = {v: k for k, v in _KIND_CODES.items()}


def zonemap_path(desc) -> str:
    return os.path.join(desc.directory,
                        f"{desc.version}-{desc.generation}-ZoneMap.db")


class ZoneMap:
    """Per-segment (min key, max key, live, dead) bounds per column."""

    __slots__ = ("n_segments", "cols")

    def __init__(self, n_segments: int, cols: dict):
        self.n_segments = n_segments
        #: col_id -> (kind, kmin u64[nseg], kmax u64[nseg],
        #:            live u32[nseg], dead u32[nseg])
        self.cols = cols

    @staticmethod
    def from_entries(zone_cols, per_segment) -> "ZoneMap":
        """zone_cols: [(col_id, kind, width)]; per_segment: one
        [(kmin, kmax, live, dead)] row per segment, zone_cols order."""
        n_seg = len(per_segment)
        cols = {}
        for j, (cid, kind, _w) in enumerate(zone_cols):
            cols[cid] = (
                kind,
                np.array([per_segment[s][j][0] for s in range(n_seg)],
                         dtype=np.uint64),
                np.array([per_segment[s][j][1] for s in range(n_seg)],
                         dtype=np.uint64),
                np.array([per_segment[s][j][2] for s in range(n_seg)],
                         dtype=np.uint32),
                np.array([per_segment[s][j][3] for s in range(n_seg)],
                         dtype=np.uint32),
            )
        return ZoneMap(n_seg, cols)

    def keep_mask(self, pred) -> np.ndarray:
        """bool[n_segments]: segments that may match pred and must be
        decoded. A column the map does not cover (or whose stored kind
        no longer matches the schema) keeps everything."""
        ent = self.cols.get(pred.col_id)
        if ent is None or ent[0] != pred.kind:
            return np.ones(self.n_segments, dtype=bool)
        from ..ops import device_scan as ds
        return ds.prune_keep_mask(ent[1], ent[2], ent[3], pred)


def write_zonemap(path: str, zone_cols, per_segment) -> str:
    n_seg = len(per_segment)
    out = bytearray()
    out += b"ZMP1"
    out += struct.pack("<II", n_seg, len(zone_cols))
    for cid, kind, _w in zone_cols:
        out += struct.pack("<IB", cid, _KIND_CODES[kind])
    zm = ZoneMap.from_entries(zone_cols, per_segment)
    for cid, _kind, _w in zone_cols:
        _k, kmin, kmax, live, dead = zm.cols[cid]
        out += kmin.astype("<u8").tobytes()
        out += kmax.astype("<u8").tobytes()
        out += live.astype("<u4").tobytes()
        out += dead.astype("<u4").tobytes()
    _write(path, bytes(out))
    return path


def load_zonemap(path: str,
                 expected_segments: int | None = None) -> ZoneMap | None:
    body = _read(path)
    if body is None or body[:4] != b"ZMP1":
        return None
    try:
        n_seg, n_cols = struct.unpack_from("<II", body, 4)
        if expected_segments is not None and n_seg != expected_segments:
            return None   # stale (format evolution / partial copy): rebuild
        pos = 12
        hdr = []
        for _ in range(n_cols):
            cid, code = struct.unpack_from("<IB", body, pos)
            pos += 5
            hdr.append((cid, _KIND_NAMES[code]))
        cols = {}
        for cid, kind in hdr:
            kmin = np.frombuffer(body, "<u8", n_seg, pos).astype(np.uint64)
            pos += 8 * n_seg
            kmax = np.frombuffer(body, "<u8", n_seg, pos).astype(np.uint64)
            pos += 8 * n_seg
            live = np.frombuffer(body, "<u4", n_seg, pos).astype(np.uint32)
            pos += 4 * n_seg
            dead = np.frombuffer(body, "<u4", n_seg, pos).astype(np.uint32)
            pos += 4 * n_seg
            cols[cid] = (kind, kmin, kmax, live, dead)
        return ZoneMap(n_seg, cols)
    except (ValueError, KeyError, IndexError, struct.error):
        return None   # malformed: rebuild


def build_zonemap(reader, table: TableMetadata, write: bool = True) -> ZoneMap:
    """Rebuild a sstable's zone map from its decoded segments (the slow
    path a missing/torn/stale component falls back to — one re-scan,
    like the EQI1 contract)."""
    from ..ops import device_scan as ds
    zone_cols = ds.zonemap_columns(table)
    per_seg = []
    for s in range(reader.n_segments):
        b = reader._read_segment(s)
        C = b.n_lanes - 9
        per_seg.append(ds.segment_zone_entries(
            zone_cols, b.lanes[:, 6 + C], b.flags,
            np.asarray(b.val_start), np.asarray(b.off[1:]),
            np.asarray(b.payload)))
    zm = ZoneMap.from_entries(zone_cols, per_seg)
    if write and not reader.released:
        try:
            write_zonemap(zonemap_path(reader.desc), zone_cols, per_seg)
        except OSError:
            pass   # read-only media: serve the in-memory map
    return zm


def zonemap_for(reader, table: TableMetadata) -> ZoneMap | None:
    """The reader's zone map, cached on the reader: disk component if
    fresh, else rebuilt once (counted). None for encrypted sstables."""
    if getattr(reader, "_enc", None) is not None:
        return None
    cached = getattr(reader, "_zonemap_cache", None)
    if cached is not None:
        return cached or None          # False = negative cache
    zm = load_zonemap(zonemap_path(reader.desc), reader.n_segments)
    if zm is None:
        from ..service.metrics import GLOBAL as _M
        _M.incr("scan.zonemap_rebuilds")
        try:
            zm = build_zonemap(reader, table)
        except Exception:
            zm = None   # corrupt sstable surfaces through the scan itself
    reader._zonemap_cache = zm if zm is not None else False
    return zm
