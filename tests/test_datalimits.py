"""DataLimits pushdown + short-read protection.

Replicas truncate reads at the pushed row limit (cells up to the
limit-th live row ship; the rest stays home — db/filter/DataLimits.java:44),
so LIMIT 1 on a huge partition moves bytes proportional to the LIMIT.
Because each replica truncates on its own view, one replica's tombstones
can shadow another's contributions and leave the merged result short:
the coordinator re-queries with doubled limits until the target count is
met or no replica was truncated
(service/reads/ShortReadPartitionsProtection.java:40).
"""
import numpy as np
import pytest

from cassandra_tpu.cluster.messaging import Verb
from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.storage.cellbatch import (CellBatchBuilder, DataLimits,
                                             live_row_count, merge_sorted,
                                             truncate_live_rows)
from cassandra_tpu.schema import COL_REGULAR_BASE, make_table


# ------------------------------------------------------------- unit ----

def _mk_table():
    return make_table("ks", "t", pk=["k"], ck=["c"],
                      cols={"k": "int", "c": "int", "v": "text"})


def _batch(table, rows, pk_val=1, dead=()):
    """rows: list of c values; dead: subset emitted as tombstones."""
    b = CellBatchBuilder(table)
    pk = table.columns["k"].cql_type.serialize(pk_val)
    for c in rows:
        ck = table.serialize_clustering([c])
        if c in dead:
            b.add_tombstone(pk, ck, COL_REGULAR_BASE, ts=2, ldt=100)
        else:
            b.add_cell(pk, ck, COL_REGULAR_BASE, f"v{c}".encode(), ts=1)
    return merge_sorted([b.seal()])


def test_truncate_counts_live_rows_only():
    t = _mk_table()
    batch = _batch(t, rows=[1, 2, 3, 4, 5, 6], dead=(1, 2, 3))
    # 3 dead rows first, then live 4,5,6: limit 2 must keep the dead
    # prefix (merge needs those tombstones) plus live rows 4 and 5
    out, more = truncate_live_rows(batch, DataLimits(row_limit=2))
    assert more
    assert live_row_count(out) == 2
    # tombstones before the cutoff survived
    from cassandra_tpu.storage.cellbatch import DEATH_FLAGS
    assert int(((out.flags & DEATH_FLAGS) != 0).sum()) == 3
    # no truncation when the partition has fewer live rows than asked
    out2, more2 = truncate_live_rows(batch, DataLimits(row_limit=10))
    assert not more2 and len(out2) == len(batch)


def test_truncate_per_partition():
    t = _mk_table()
    b1 = _batch(t, rows=[1, 2, 3], pk_val=1)
    b2 = _batch(t, rows=[1, 2, 3], pk_val=2)
    cat = merge_sorted([b1, b2])
    out, more = truncate_live_rows(cat, DataLimits(per_partition=1))
    assert more and live_row_count(out) == 2   # one row from EACH pk


# ------------------------------------------------------- distributed ----

@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(2, str(tmp_path), rf=2)
    for n in c.nodes:
        n.proxy.timeout = 2.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 2}")
    s.execute("USE ks")
    yield c
    c.shutdown()


def _payload_cells(msg):
    """Cell count inside a limited READ_RSP/RANGE_RSP data payload
    (digests and unlimited responses return 0)."""
    p = msg.payload
    if isinstance(p, tuple) and isinstance(p[0], dict):
        return len(p[0]["ts"])
    return 0


def test_limit_bounds_bytes_on_the_wire(cluster):
    """LIMIT 2 over a 200-row partition: every replica data response
    carries cells for at most LIMIT(+static pad) rows, never the whole
    partition."""
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE big (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for c_ in range(200):
        s.execute(f"INSERT INTO big (k, c, v) VALUES (1, {c_}, 'v{c_}')")
    # one row misses node2: the digest mismatch forces a full-data round,
    # so node2 must ship an actual (limited) data response over the wire
    victim = cluster.nodes[1].endpoint
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    n1.default_cl = ConsistencyLevel.ONE
    s.execute("INSERT INTO big (k, c, v) VALUES (1, 0, 'v0')")
    rule["remaining"] = 0
    shipped = []
    cluster.filters.intercept(
        lambda m: shipped.append(_payload_cells(m))
        if m.verb == Verb.READ_RSP else None)
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT c, v FROM big WHERE k = 1 LIMIT 2").rows
    assert rows == [(0, "v0"), (1, "v1")]
    data_sizes = [n for n in shipped if n > 0]
    assert data_sizes, "expected at least one remote data response"
    # 2 cells per CQL row (value + row liveness); the unlimited
    # partition would ship ~400 cells
    assert max(data_sizes) <= 2 * 2, data_sizes
    cluster.filters.clear()


def test_short_read_protection_recovers_shadowed_rows(cluster):
    """node1 holds only tombstones for rows 0..7 (8 dead rows, 1 live);
    node2 holds rows 0..9 live. A QUORUM LIMIT 3 initially merges too
    few live rows (node2's contribution is truncated at 3, all shadowed)
    — short-read re-query with doubled limits must converge on the true
    survivors 8, 9."""
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE sr (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for c_ in range(10):
        s.execute(f"INSERT INTO sr (k, c, v) VALUES (1, {c_}, 'v{c_}')")
    # deletions of rows 0..7 reach only node1
    victim = cluster.nodes[1].endpoint
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    n1.default_cl = ConsistencyLevel.ONE
    for c_ in range(8):
        s.execute(f"DELETE FROM sr WHERE k = 1 AND c = {c_}")
    rule["remaining"] = 0
    from cassandra_tpu.service.metrics import GLOBAL
    before = GLOBAL.counter("reads.short_read_retries")
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT c, v FROM sr WHERE k = 1 LIMIT 3").rows
    assert rows == [(8, "v8"), (9, "v9")]
    assert GLOBAL.counter("reads.short_read_retries") > before


def test_short_read_no_resurrection_past_truncation(cluster):
    """A truncated replica vouches only for rows up to its LAST shipped
    row: a stale live row contributed by the OTHER replica beyond that
    frontier must not satisfy the limit (the shadowing tombstone sits
    in the truncated tail). node1: tombstone c=1 (newer) + live 1..4;
    node2: tombstone c=3 (newer) + live 1..4. Truth: survivors 2, 4.
    A frontier-blind stop condition returns (2, 3-stale)."""
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE rz (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for c_ in range(1, 5):
        s.execute(f"INSERT INTO rz (k, c, v) VALUES (1, {c_}, 'v{c_}') "
                  f"USING TIMESTAMP 10")
    n1.default_cl = ConsistencyLevel.ONE
    # DELETE c=1 lands only on node1 (the coordinator itself)
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ,
                                to=cluster.nodes[1].endpoint)
    s.execute("DELETE FROM rz USING TIMESTAMP 20 WHERE k = 1 AND c = 1")
    rule["remaining"] = 0
    # DELETE c=3 lands only on node2
    s2 = cluster.session(2)
    s2.keyspace = "ks"
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ,
                                to=cluster.nodes[0].endpoint)
    cluster.node(2).default_cl = ConsistencyLevel.ONE
    s2.execute("DELETE FROM rz USING TIMESTAMP 20 WHERE k = 1 AND c = 3")
    rule["remaining"] = 0
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT c, v FROM rz WHERE k = 1 LIMIT 2").rows
    assert rows == [(2, "v2"), (4, "v4")], rows


def test_per_partition_limit_pushdown_multi_pk(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE pp (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for k in (1, 2):
        for c_ in range(50):
            s.execute(f"INSERT INTO pp (k, c, v) VALUES ({k}, {c_}, 'x')")
    # diverge one row so the digest mismatch forces remote DATA responses
    victim = cluster.nodes[1].endpoint
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    n1.default_cl = ConsistencyLevel.ONE
    s.execute("INSERT INTO pp (k, c, v) VALUES (1, 0, 'x')")
    s.execute("INSERT INTO pp (k, c, v) VALUES (2, 0, 'x')")
    rule["remaining"] = 0
    shipped = []
    cluster.filters.intercept(
        lambda m: shipped.append(_payload_cells(m))
        if m.verb == Verb.READ_RSP else None)
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT k, c FROM pp WHERE k IN (1, 2) "
                     "PER PARTITION LIMIT 2").rows
    assert sorted(rows) == [(1, 0), (1, 1), (2, 0), (2, 1)]
    data_sizes = [n for n in shipped if n > 0]
    # 2 partitions x PER PARTITION LIMIT 2 rows x 2 cells/row; the
    # unlimited read would ship ~200 cells
    assert data_sizes and max(data_sizes) <= 2 * 2 * 2, data_sizes
    cluster.filters.clear()


def test_pushdown_skipped_when_filters_present(cluster):
    """A non-key filter means fetched rows aren't result rows: the limit
    must NOT be pushed (the replica would count rows the filter later
    drops)."""
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE f (k int, c int, v int, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for c_ in range(20):
        s.execute(f"INSERT INTO f (k, c, v) VALUES (1, {c_}, {c_ % 2})")
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT c FROM f WHERE k = 1 AND v = 1 LIMIT 3 "
                     "ALLOW FILTERING").rows
    assert rows == [(1,), (3,), (5,)]


def test_range_scan_limit_bounds_bytes(cluster):
    """SELECT ... LIMIT n over a full scan: each arc's replicas truncate
    at the pushed limit, so RANGE responses are bounded by the LIMIT,
    not the arc (DataLimits over RangeCommands)."""
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE rng (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    for k in range(50):
        for c_ in range(10):
            s.execute(f"INSERT INTO rng (k, c, v) VALUES ({k}, {c_}, "
                      f"'v{k}x{c_}')")
    shipped = []
    cluster.filters.intercept(
        lambda m: shipped.append(_payload_cells(m))
        if m.verb == Verb.RANGE_RSP else None)
    n1.default_cl = ConsistencyLevel.ONE
    rows = s.execute("SELECT k, c FROM rng LIMIT 4").rows
    assert len(rows) == 4
    data_sizes = [n for n in shipped if n > 0]
    # 2 cells per row; without pushdown a window ships its whole arc
    # (hundreds of cells)
    if data_sizes:       # remote arcs only exist when node2 owns some
        assert max(data_sizes) <= 4 * 2, data_sizes
    cluster.filters.clear()
    # correctness at QUORUM with divergent tombstones (range SRP)
    victim = cluster.nodes[1].endpoint
    rule = cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    for c_ in range(10):
        s.execute(f"DELETE FROM rng WHERE k = 7 AND c = {c_}")
    rule["remaining"] = 0
    n1.default_cl = ConsistencyLevel.QUORUM
    rows = s.execute("SELECT k, c FROM rng LIMIT 200").rows
    ks = {r[0] for r in rows}
    assert 7 not in ks and len(rows) == 200
