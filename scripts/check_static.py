#!/usr/bin/env python
"""ctpulint tier-2 driver: the concurrency & invariant static-analysis
suite (cassandra_tpu/analysis/) + the witness-armed engine smoke.

    check_static.py            all five AST checks, then arm the
                               runtime LockWitness over the
                               deterministic engine smoke shared with
                               check_metric_names.py (dynamic lock
                               orders the AST cannot see)
    check_static.py --fast     AST-only: no engine boot, ~1s — the
                               pre-commit shape
    check_static.py --explain  also print every active allowlist entry
                               with its reason (the allowlist is
                               documentation; this is its audit)
    check_static.py --list     print the check catalog

Exit 0 = clean. Any unallowlisted violation, any `allow()` missing its
reason=, or a LockOrderError under the armed smoke exits 1 with
file:line per finding. Policy: docs/static-analysis.md.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_ast_checks(explain: bool) -> int:
    from cassandra_tpu.analysis import checks
    from cassandra_tpu.analysis.report import (apply_suppressions,
                                               reasonless)
    from cassandra_tpu.analysis.walker import ProjectIndex

    index = ProjectIndex.build()
    violations = checks.run_all(index)
    supps = index.suppressions()
    meta = reasonless(supps)
    remaining = apply_suppressions(violations, supps) + meta

    rc = 0
    if remaining:
        print("ctpulint violations:", file=sys.stderr)
        for v in sorted(remaining, key=lambda v: (v.path, v.line)):
            print(f"  {v}", file=sys.stderr)
        rc = 1
    suppressed = [v for v in violations if v.suppressed_by is not None]
    unused = [s for s in supps if s.reason and not s.used]
    print(f"ctpulint: {len(checks.CHECKS)} checks, "
          f"{len(violations) + len(meta)} findings, "
          f"{len(suppressed)} allowlisted, "
          f"{len(remaining)} violations")
    if unused:
        print("note: stale allowlist entries (matched nothing):")
        for s in unused:
            print(f"  {s}")
    if explain or "--explain" in sys.argv:
        used = [s for s in supps if s.used]
        if used:
            print("active allowlist:")
            for s in sorted(used, key=lambda s: (s.path, s.line)):
                print(f"  {s}")
    return rc


def run_witness_smoke() -> int:
    """Arm the LockWitness, then drive the deterministic engine smoke
    check_metric_names.py uses — every witnessed lock created by the
    engine records its acquisition edges; a cycle-closing acquisition
    raises with both stacks."""
    from cassandra_tpu.utils import lockwitness

    lockwitness.reset()
    lockwitness.arm()
    try:
        import check_metric_names
        check_metric_names.smoke_emitted()
    except lockwitness.LockOrderError as e:
        print(f"LockWitness cycle under the engine smoke:\n{e}",
              file=sys.stderr)
        return 1
    finally:
        lockwitness.disarm()
    graph = lockwitness.graph_snapshot()
    n_edges = sum(len(v) for v in graph.values())
    print(f"LockWitness smoke OK: {len(graph)} holder locks, "
          f"{n_edges} acquisition edges, no cycle")
    lockwitness.reset()
    return 0


def main() -> int:
    if "--list" in sys.argv:
        from cassandra_tpu.analysis import checks
        for name, (_mod, desc) in checks.CHECKS.items():
            print(f"  {name:18s} {desc}")
        return 0
    rc = run_ast_checks("--explain" in sys.argv)
    if "--fast" not in sys.argv:
        rc = run_witness_smoke() or rc
    if rc == 0:
        print("ctpulint OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
