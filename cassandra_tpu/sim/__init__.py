from .scheduler import SimCluster, SimScheduler, SimTransport, simulated

__all__ = ["SimCluster", "SimScheduler", "SimTransport", "simulated"]
