"""The truncated-key device fast path (ops/merge.py v3) must be
bit-identical to the numpy spec. It activates only for sorted runs with no
deletions/counters; these tests construct qualifying rounds — including
timestamps that collide in the truncated (ts >> 24) space, where exact
ordering is resolved host-side — and verify both the result and that the
fast path was actually taken."""
import random

import numpy as np
import pytest

from cassandra_tpu.ops import merge as dmerge
from cassandra_tpu.schema import COL_REGULAR_BASE, make_table
from cassandra_tpu.storage import cellbatch as cb

T = make_table("ks", "t", pk=["id"], ck=["c"],
               cols={"id": "int", "c": "int", "v": "text", "w": "text"})
IDT = T.columns["id"].cql_type


def pk(i):
    return IDT.serialize(i)


def ck(i):
    return T.serialize_clustering([i])


def assert_equal_batches(a, b):
    assert len(a) == len(b)
    np.testing.assert_array_equal(a.lanes, b.lanes)
    np.testing.assert_array_equal(a.ts, b.ts)
    np.testing.assert_array_equal(a.ldt, b.ldt)
    np.testing.assert_array_equal(a.flags, b.flags)
    np.testing.assert_array_equal(a.payload, b.payload)
    np.testing.assert_array_equal(a.off, b.off)


def sorted_live_batches(seed, n_batches=4, n_cells=400, n_parts=16,
                        n_cks=8, collide=True, ttl_frac=0.0):
    """Batches of live (optionally expiring) cells, individually sorted
    and deduped (each run goes through the spec merge, as sstable-backed
    runs are). With collide=True timestamps cluster so many distinct ts
    fall in the same ts>>24 bucket AND some are exactly equal."""
    rng = random.Random(seed)
    out = []
    base = 1 << 30
    for _ in range(n_batches):
        b = cb.CellBatchBuilder(T)
        for _ in range(n_cells):
            p = pk(rng.randrange(n_parts))
            c = ck(rng.randrange(n_cks))
            col = COL_REGULAR_BASE + rng.randrange(2)
            if collide:
                # low 24 bits only (always same bucket) or exact dup ts
                ts = base + rng.choice(
                    [rng.randrange(1 << 24), rng.randrange(4)])
            else:
                ts = rng.randrange(1, 1 << 40)
            val = rng.choice([b"a", b"zz", b"abcd1", b"abcd2", b"x" * 9])
            if rng.random() < ttl_frac:
                b.add_cell(p, c, col, val, ts, ttl=rng.randrange(1, 30),
                           now=rng.randrange(0, 40))
            else:
                b.add_cell(p, c, col, val, ts)
        out.append(cb.merge_sorted([b.seal()]))
    return out


def assert_fast(batches):
    h = dmerge.submit_merge(batches)
    assert h.mode == "fast", h.mode
    return dmerge.collect_merge(h)


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_collision_equivalence(seed):
    batches = sorted_live_batches(seed)
    ref = cb.merge_sorted(batches)
    dev = assert_fast(batches)
    assert_equal_batches(ref, dev)


@pytest.mark.parametrize("seed", [5, 6])
def test_wide_ts_equivalence(seed):
    batches = sorted_live_batches(seed, collide=False)
    ref = cb.merge_sorted(batches)
    dev = assert_fast(batches)
    assert_equal_batches(ref, dev)


@pytest.mark.parametrize("seed", [7, 8])
def test_ttl_expiry_and_purge(seed):
    batches = sorted_live_batches(seed, ttl_frac=0.3)
    ref = cb.merge_sorted(batches, gc_before=35, now=30)
    dev = dmerge.merge_sorted_device(batches, gc_before=35, now=30)
    assert_equal_batches(ref, dev)
    guard = lambda s: (s.ts % 7) * (1 << 28)
    ref = cb.merge_sorted(batches, gc_before=35, now=30,
                          purgeable_ts_fn=guard)
    dev = dmerge.merge_sorted_device(batches, gc_before=35, now=30,
                                     purgeable_ts_fn=guard)
    assert_equal_batches(ref, dev)


def test_equal_ts_value_tiebreak():
    """Equal (identity, ts): larger value wins, beyond the 4-byte prefix."""
    outs = []
    for vals in ((b"abcdA", b"abcdZ"), (b"abcdZ", b"abcdA")):
        batches = []
        for v in vals:
            b = cb.CellBatchBuilder(T)
            b.add_cell(pk(1), ck(1), COL_REGULAR_BASE, v, 100)
            batches.append(cb.merge_sorted([b.seal()]))
        ref = cb.merge_sorted(batches)
        dev = assert_fast(batches)
        assert_equal_batches(ref, dev)
        outs.append(dev.cell_value(0))
    assert outs == [b"abcdZ", b"abcdZ"]


def test_unsorted_or_deleting_rounds_fall_back():
    b = cb.CellBatchBuilder(T)
    b.add_cell(pk(2), ck(1), COL_REGULAR_BASE, b"v", 5)
    b.add_cell(pk(1), ck(1), COL_REGULAR_BASE, b"v", 5)
    unsorted = b.seal()
    assert dmerge.submit_merge([unsorted]).mode != "fast"
    b2 = cb.CellBatchBuilder(T)
    b2.add_tombstone(pk(1), ck(1), COL_REGULAR_BASE, 10, 100)
    tomb = cb.merge_sorted([b2.seal()])
    assert dmerge.submit_merge([tomb]).mode != "fast"
    # both still produce correct results through their fallback paths
    for batches in ([unsorted], [tomb]):
        assert_equal_batches(cb.merge_sorted(batches),
                             dmerge.merge_sorted_device(batches))


def test_pipelined_task_matches_numpy(tmp_path):
    """CompactionTask engine=device (pipelined submit/collect) produces the
    same output sstable content as engine=numpy."""
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
    from cassandra_tpu.storage.table import ColumnFamilyStore

    rng = random.Random(99)
    results = {}
    for engine in ("numpy", "device"):
        base = tmp_path / engine
        base.mkdir()
        cfs = ColumnFamilyStore(T, str(base), commitlog=None)
        d = cfs.directory
        rng = random.Random(99)
        for gen in range(1, 4):
            b = cb.CellBatchBuilder(T)
            for _ in range(600):
                b.add_cell(pk(rng.randrange(40)), ck(rng.randrange(6)),
                           COL_REGULAR_BASE + rng.randrange(2),
                           bytes([65 + rng.randrange(26)]) * rng.randrange(1, 9),
                           (1 << 30) + rng.randrange(1 << 24))
            w = SSTableWriter(Descriptor(str(d), gen), T,
                              estimated_partitions=64)
            w.append(cb.merge_sorted([b.seal()]))
            w.finish()
        cfs.reload_sstables()
        task = CompactionTask(cfs, cfs.tracker.view(), engine=engine,
                              round_cells=1500)
        task.execute()
        [out] = cfs.live_sstables()
        scan = cb.CellBatch.concat(list(out.scanner()))
        results[engine] = scan
        cfs.close() if hasattr(cfs, "close") else None
    a, b = results["numpy"], results["device"]
    np.testing.assert_array_equal(a.lanes, b.lanes)
    np.testing.assert_array_equal(a.ts, b.ts)
    np.testing.assert_array_equal(a.payload, b.payload)
