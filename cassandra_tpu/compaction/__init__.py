from .executor import (ActiveCompactions, CompactionExecutor,  # noqa: F401
                       CompactionProgress)
from .manager import CompactionManager  # noqa: F401
from .strategies import get_strategy  # noqa: F401
