"""Process-global shard fan-out: the host-thread execution substrate of
the mesh data plane.

The mesh probe (parallel/mesh.py) established that per-shard work —
device programs AND host merge engines (the native FFI and numpy both
release the GIL in their hot paths) — overlaps only when each shard is
DRIVEN FROM ITS OWN HOST THREAD. This module owns the knob and the
read-side threads: `configure()` applies the hot-reloadable
`compaction_mesh_devices` setting exactly like the compressor pool's
(0 = off: every caller falls back to its serial path); batched mesh
reads and sharded range scans (storage/table.py) run on the shared
ShardFanout pool here, while mesh compaction (compaction/task.py)
reads only the WIDTH via mesh_devices() and drives its own
per-task lanes (a compaction shard can block on the throughput
limiter — parking a shared read lane behind the compaction throttle
would let one background task starve point-read batches).

map_shards(fn, n) preserves SHARD ORDER in its results — token-range
shard order is identity-lane order (the PR 4 memtable invariant), so
callers drain results 0..n-1 and get byte-identical output to their
serial paths. Completion order is free to be adversarial; the
`_TEST_SHARD_DELAY` hook lets tests force it.
"""
from __future__ import annotations

import queue
import threading
from ..utils import lockwitness

# test hook: {shard_index: seconds} delays applied before running the
# shard's closure — forces adversarial completion orders
_TEST_SHARD_DELAY: dict | None = None


class ShardFanout:
    """N hot-resizable worker threads executing per-shard closures.

    Same thread-lifecycle shape as compress_pool.CompressorPool:
    workers spawn lazily on first submit (a configured-but-unused
    fanout costs nothing), surplus workers retire after their current
    job when the target shrinks."""

    POLL_SECONDS = 0.2

    def __init__(self, workers: int = 1, name: str = "mesh-shard"):
        self.name = name
        self._q: queue.Queue = queue.Queue()
        self._lock = lockwitness.make_lock("mesh.fanout")
        self._threads: list[threading.Thread] = []
        self._target = max(int(workers), 1)
        self._shutdown = False
        self.jobs_completed = 0

    @property
    def workers(self) -> int:
        return self._target

    def set_workers(self, n: int) -> None:
        """Hot-resize; 0 idles the pool (every worker retires after its
        current job — no poll wakeups while the knob is off)."""
        with self._lock:
            if self._shutdown:
                return
            self._target = max(int(n), 0)
            if self._threads and self._target:
                self._spawn_locked()
        if self._target == 0:
            self._drain_queue()

    def _drain_queue(self) -> None:
        """Discard queued pull closures. Safe at any time: a pull only
        CLAIMS work from its map_shards call's claim queue, and the
        calling thread steals every unclaimed shard itself before
        waiting — so dropping queued pulls never strands a shard, it
        only releases the results/closure references they pin (with 0
        workers nobody would ever pop them)."""
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def _spawn_locked(self) -> None:
        while len(self._threads) < self._target:
            t = threading.Thread(target=self._work_loop,
                                 name=f"{self.name}-w", daemon=True)
            self._threads.append(t)
            t.start()

    def queue_depth(self) -> int:
        return self._q.qsize()

    def _work_loop(self) -> None:
        me = threading.current_thread()
        while True:
            with self._lock:
                if self._shutdown or len(self._threads) > self._target:
                    if me in self._threads:
                        self._threads.remove(me)
                    return
            try:
                job = self._q.get(timeout=self.POLL_SECONDS)
            except queue.Empty:
                continue
            try:
                job()
            except BaseException:
                # jobs own their error channel (map_shards collects per-
                # shard exceptions); a raise here is a job bug, and one
                # bad job must not retire a shared worker — the
                # CompressorPool contract
                pass
            finally:
                with self._lock:
                    self.jobs_completed += 1

    def map_shards(self, fn, n_shards: int) -> list:
        """Run fn(s) for s in 0..n_shards-1 across the workers; returns
        results IN SHARD ORDER. The caller's thread also works a share
        (shard 0 plus whatever it can steal) so a 1-worker fanout still
        overlaps caller-side draining with worker-side compute, and no
        configuration deadlocks. Exceptions propagate (first one wins)
        after every shard has settled."""
        results: list = [None] * n_shards
        errors: list[BaseException] = []
        done = threading.Event()
        remaining = [n_shards]
        lock = threading.Lock()
        claim_q: queue.Queue = queue.Queue()
        for s in range(n_shards):
            claim_q.put(s)

        def run_one(s: int) -> None:
            try:
                delay = _TEST_SHARD_DELAY
                if delay:
                    import time
                    time.sleep(delay.get(s, 0.0))
                results[s] = fn(s)
            except BaseException as e:
                errors.append(e)
            finally:
                with lock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        done.set()

        def pull() -> None:
            try:
                s = claim_q.get_nowait()
            except queue.Empty:
                return
            run_one(s)

        with self._lock:
            if self._shutdown:
                raise RuntimeError("shard fanout is shut down")
            self._spawn_locked()
        # hand every shard to the pool; the caller thread steals work
        # until all shards are claimed, then waits for stragglers
        for _ in range(n_shards):
            self._q.put(pull)
        while not claim_q.empty():
            pull()
        done.wait()
        if errors:
            raise errors[0]
        return results

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._shutdown = True
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=timeout)
        self._drain_queue()


# ---------------------------------------------------------- global state --

_LOCK = lockwitness.make_lock("mesh.fanout_registry")
_GLOBAL: ShardFanout | None = None
_DEVICES = 0
# per-owner width demands: the worker POOL is process-global (like the
# compressor pool) but each engine routes through its OWN knob, so one
# engine's compaction_mesh_devices=0 must not retire the lanes a
# co-hosted engine is using. The pool is sized to the max demand.
_DEMANDS: dict = {}


def configure(n: int, owner=None) -> None:
    """Apply the compaction_mesh_devices knob: 0 = mesh mode off
    (serial data plane), N = shard every eligible bulk operation N
    ways. Hot-reloadable; a live fanout resizes in place.

    owner: the demanding engine (or None for the anonymous process
    demand — scripts/tests). Each owner's latest value is its demand;
    the pool runs at the MAX across owners, so co-hosted engines with
    different knobs each get at least their width and an engine
    setting 0 only removes its own demand."""
    global _DEVICES, _GLOBAL
    n = max(int(n), 0)
    key = id(owner) if owner is not None else None
    with _LOCK:
        if n > 0:
            _DEMANDS[key] = n
        else:
            _DEMANDS.pop(key, None)
        eff = max(_DEMANDS.values(), default=0)
        _DEVICES = eff
        if eff > 0:
            if _GLOBAL is None:
                _GLOBAL = ShardFanout(eff)
                _register_gauges(_GLOBAL)
            else:
                _GLOBAL.set_workers(eff)
        elif _GLOBAL is not None:
            # every demand gone: retire the worker threads (they'd
            # otherwise poll the queue forever with no way to receive
            # work)
            _GLOBAL.set_workers(0)


def mesh_devices() -> int:
    """The effective mesh width (max demand across owners; 0 = off)."""
    return _DEVICES


def reset() -> None:
    """Drop every demand and idle the pool (test isolation)."""
    with _LOCK:
        _DEMANDS.clear()
    configure(0)


def get_fanout() -> ShardFanout | None:
    """The shared fanout, or None while mesh mode is off."""
    with _LOCK:
        return _GLOBAL if _DEVICES > 0 else None


def _register_gauges(f: ShardFanout) -> None:
    from ..service.metrics import GLOBAL

    GLOBAL.register_gauge("mesh.workers", lambda: float(f.workers))
    GLOBAL.register_gauge("mesh.queue_depth",
                          lambda: float(f.queue_depth()))
    GLOBAL.register_gauge("mesh.jobs_completed",
                          lambda: float(f.jobs_completed))
