"""Memtable: append-only columnar write buffer.

Reference counterpart: db/memtable/Memtable.java:55 (pluggable interface;
put:193, getFlushSet:299) and TrieMemtable. The reference maintains a
sorted structure per write; the TPU-native design appends O(1) to columnar
arrays and defers ALL ordering to the batch sort at read/flush time —
sorting is what the device does best, and flush-time batch sort replaces
per-write comparisons entirely.

A per-partition hash index (dict lane4 -> cell indices) gives point reads
their partition's cells without sorting the world; range scans and flush
sort the whole buffer once (cached until the next write).
"""
from __future__ import annotations

import threading

import numpy as np

from ..schema import TableMetadata
from .cellbatch import (CellBatch, CellBatchBuilder, merge_sorted,
                        pk_lane_key)
from .mutation import Mutation


class Memtable:
    def __init__(self, table: TableMetadata):
        self.table = table
        self._builder = CellBatchBuilder(table)
        self._partitions: dict[bytes, list[int]] = {}
        self._lock = threading.RLock()
        self._sorted_cache: CellBatch | None = None
        self.live_bytes = 0
        self.ops = 0

    def __len__(self):
        return len(self._builder)

    @property
    def is_empty(self) -> bool:
        return len(self._builder) == 0

    # ------------------------------------------------------------- write --

    def apply(self, mutation: Mutation) -> None:
        with self._lock:
            start = len(self._builder)
            mutation.apply_to(self._builder)
            end = len(self._builder)
            if end == start:
                return
            lane4 = self._builder._lanes[start][:4]
            key16 = b"".join(int(x).to_bytes(4, "big") for x in lane4)
            self._partitions.setdefault(key16, []).extend(range(start, end))
            # note: all ops of one mutation share the partition (one pk)
            self.live_bytes += mutation.size
            self.ops += len(mutation.ops)
            self._sorted_cache = None

    # -------------------------------------------------------------- read --

    def _subset(self, indices: list[int]) -> CellBatch:
        b = self._builder
        sub = CellBatchBuilder(self.table)
        for i in indices:
            lanes = b._lanes[i]
            frame = bytes(b._payload[b._value_off[i]:b._value_off[i + 1]])
            sub._lanes.append(lanes)
            sub._ts.append(b._ts[i])
            sub._ldt.append(b._ldt[i])
            sub._ttl.append(b._ttl[i])
            sub._flags.append(b._flags[i])
            sub._val_start.append(len(sub._payload)
                                  + (b._val_start[i] - b._value_off[i]))
            sub._payload += frame
            sub._value_off.append(len(sub._payload))
        sub.pk_map = self._builder.pk_map
        return sub.seal()

    def contains(self, pk: bytes) -> bool:
        """O(1) partition-presence check (compaction purge guard)."""
        with self._lock:
            return pk_lane_key(pk) in self._partitions

    def read_partition(self, pk: bytes) -> CellBatch | None:
        """The partition's cells, reconciled (newest versions only)."""
        key16 = pk_lane_key(pk)
        with self._lock:
            idx = self._partitions.get(key16)
            if not idx:
                return None
            return merge_sorted([self._subset(idx)])

    def scan(self) -> CellBatch:
        """Whole memtable, sorted + reconciled (cached until next write)."""
        with self._lock:
            if self._sorted_cache is None:
                self._sorted_cache = merge_sorted([self._builder.seal()])
            return self._sorted_cache

    def scan_window(self, lo: int, hi: int) -> CellBatch:
        """Cells of partitions with token in (lo, hi] (paging windows)."""
        from .cellbatch import filter_token_range
        return filter_token_range(self.scan(), lo + 1 if lo > -(1 << 63)
                                  else lo, hi)

    # ------------------------------------------------------------- flush --

    def flush_batch(self) -> CellBatch:
        """Sorted, deduplicated cells for the flush writer
        (Memtable.getFlushSet / Flushing.writeSortedContents role)."""
        return self.scan()
