"""Remote admin protocol (service/admin.py — the JMX/NodeProbe role) and
the round-3 nodetool command set, driven over a real TCP admin socket
against in-process nodes."""
import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.service.admin import AdminServer, admin_call
from cassandra_tpu.tools import nodetool


@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(2, str(tmp_path), rf=2)
    s = c.nodes[0].session()
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 2}")
    s.execute("CREATE TABLE ks.t (id int PRIMARY KEY, v text)")
    for i in range(20):
        s.execute(f"INSERT INTO ks.t (id, v) VALUES ({i}, 'v{i}')")
    c.nodes[0].engine.flush_all()
    try:
        yield c
    finally:
        c.shutdown()


@pytest.fixture
def admin(cluster):
    srv = AdminServer(cluster.nodes[0])
    try:
        yield ("127.0.0.1", srv.port)
    finally:
        srv.close()


def call(admin, cmd, **args):
    host, port = admin
    return admin_call(host, port, cmd, args)


def test_remote_status_and_info(admin):
    rows = call(admin, "status")
    assert len(rows) == 2 and all(r["status"] == "UN" for r in rows)
    info = call(admin, "info")
    assert "ks.t" in info["tables"]
    assert call(admin, "version")["release"].startswith("cassandra-tpu")


def test_remote_mutable_settings(cluster, admin):
    node = cluster.nodes[0]
    call(admin, "setcompactionthroughput", mib_s=17)
    assert node.engine.settings.get("compaction_throughput") == 17.0
    assert node.engine.compactions.limiter.rate == 17 * 2**20
    assert call(admin, "getcompactionthroughput") == {
        "compaction_throughput_mib": 17}
    call(admin, "settimeout", timeout_type="write", ms=1500)
    assert node.proxy.write_timeout == 1.5
    assert call(admin, "gettimeout", timeout_type="write") == {
        "write": 1500.0}
    call(admin, "settraceprobability", p=0.25)
    assert call(admin, "gettraceprobability") == {"trace_probability": 0.25}


def test_remote_handoff_and_autocompaction_toggles(cluster, admin):
    node = cluster.nodes[0]
    assert call(admin, "statushandoff") == {"handoff": "running"}
    call(admin, "disablehandoff")
    assert node.hints.enabled is False
    # a hint to a dead target is silently dropped while disabled
    from cassandra_tpu.storage.mutation import Mutation
    t = node.schema.get_table("ks", "t")
    m = Mutation(t.id, t.partition_key_columns[0].cql_type.serialize(1))
    m.add(b"", 6, b"", b"x", ts=1)
    node.hints.store(cluster.nodes[1].endpoint, m)
    assert call(admin, "listpendinghints") == []
    call(admin, "enablehandoff")
    assert node.hints.enabled is True

    call(admin, "disableautocompaction")
    assert node.engine.compactions.paused is True
    assert call(admin, "statusautocompaction") == {"running": False}
    call(admin, "enableautocompaction")
    assert node.engine.compactions.paused is False


def test_remote_ops_surface(admin):
    st = call(admin, "netstats")
    assert "messaging" in st and st["messaging"]["sent"] >= 0
    pools = {p["pool"] for p in call(admin, "tpstats")}
    assert "CompactionExecutor" in pools
    hist = call(admin, "proxyhistograms")
    assert "request" in hist
    ver = call(admin, "verify")
    assert ver and all(r["ok"] for r in ver)
    ssts = call(admin, "getsstables", keyspace="ks", table="t", key="3")
    assert isinstance(ssts, list)
    assert call(admin, "statusgossip")["gossip"] in ("running",
                                                     "not running")
    assert call(admin, "statusbinary") == {"native_transport":
                                           "not running"}
    call(admin, "invalidatechunkcache")
    call(admin, "invalidaterowcache")
    call(admin, "invalidatecountercache")
    # flush twice then major-compact so history has a real entry
    call(admin, "flush")
    call(admin, "compact")
    hist = call(admin, "compactionhistory")
    assert hist and all(h["table"] == "ks.t" for h in hist)
    assert hist[0]["cells_read"] >= 20


def test_remote_drain_and_refresh(cluster, admin):
    node = cluster.nodes[0]
    s = node.session()
    s.execute("INSERT INTO ks.t (id, v) VALUES (99, 'pre-drain')")
    assert call(admin, "drain") == {"drained": True}
    assert len(node.engine.store("ks", "t").memtable) == 0
    r = call(admin, "refresh", keyspace="ks", table="t")
    assert r["sstables_after"] >= 1


def test_unknown_command_and_bad_args(admin):
    with pytest.raises(RuntimeError, match="unknown command"):
        call(admin, "nosuchcmd")
    with pytest.raises(RuntimeError, match="unknown endpoint"):
        call(admin, "assassinate", endpoint="ghost")


def test_cli_offline_mode(tmp_path, capsys):
    """nodetool --data offline mode still works for engine commands."""
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine

    eng = StorageEngine(str(tmp_path / "d"), Schema())
    eng.close()
    nodetool.main(["info", "--data", str(tmp_path / "d")])
    out = capsys.readouterr().out
    assert '"tables"' in out
