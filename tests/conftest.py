"""Test configuration: force an 8-device virtual CPU mesh so sharding tests
run without TPU hardware (mirrors the reference's in-JVM dtest approach of
simulating a cluster in one process; see SURVEY.md section 4).

The image pins JAX_PLATFORMS=axon (the TPU plugin), so this must OVERRIDE,
not setdefault. Set CASSANDRA_TPU_TEST_BACKEND=axon to run the suite on
the real chip instead."""
import os

backend = os.environ.get("CASSANDRA_TPU_TEST_BACKEND", "cpu")
os.environ["JAX_PLATFORMS"] = backend
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# the axon sitecustomize registers the TPU plugin before this file runs;
# the env var alone is ignored once that happened — force via config
import jax  # noqa: E402

jax.config.update("jax_platforms", backend)
