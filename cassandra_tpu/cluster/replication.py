"""Replication strategies: token -> replica set.

Reference counterpart: locator/AbstractReplicationStrategy (SimpleStrategy,
NetworkTopologyStrategy with per-DC RF and rack spreading, LocalStrategy),
locator/ReplicaPlans (consistency-level math).
"""
from __future__ import annotations

from .ring import Endpoint, Ring


class ReplicationStrategy:
    def __init__(self, options: dict):
        self.options = options

    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        raise NotImplementedError

    def replication_factor(self) -> int:
        """The CONFIGURED total RF — consistency-level blockFor math uses
        this, never the materialized replica list, so a small ring does not
        silently weaken the guarantee (locator/ReplicationFactor.java,
        ConsistencyLevel.blockFor)."""
        raise NotImplementedError

    def dc_replication_factors(self) -> dict[str, int] | None:
        """Per-DC RF for NTS; None for non-topology-aware strategies."""
        return None

    @staticmethod
    def create(options: dict) -> "ReplicationStrategy":
        cls = str(options.get("class", "SimpleStrategy")).rsplit(".", 1)[-1]
        if cls == "SimpleStrategy":
            return SimpleStrategy(options)
        if cls == "NetworkTopologyStrategy":
            return NetworkTopologyStrategy(options)
        if cls == "LocalStrategy":
            return LocalStrategy(options)
        raise ValueError(f"unknown replication strategy {cls}")


class SimpleStrategy(ReplicationStrategy):
    def replication_factor(self) -> int:
        return int(self.options.get("replication_factor", 1))

    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        rf = self.replication_factor()
        out: list[Endpoint] = []
        for ep in ring.successors(token):
            if ep not in out:
                out.append(ep)
            if len(out) >= rf:
                break
        return out


class NetworkTopologyStrategy(ReplicationStrategy):
    """Per-DC replication factor, spreading across racks within a DC
    (locator/NetworkTopologyStrategy.calculateNaturalReplicas)."""

    def dc_replication_factors(self) -> dict[str, int]:
        return {k: int(v) for k, v in self.options.items() if k != "class"}

    def replication_factor(self) -> int:
        return sum(self.dc_replication_factors().values())

    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        rf_by_dc = self.dc_replication_factors()
        chosen: list[Endpoint] = []
        racks_seen: dict[str, set] = {}
        per_dc: dict[str, int] = {}
        skipped: dict[str, list[Endpoint]] = {}
        for ep in ring.successors(token):
            rf = rf_by_dc.get(ep.dc, 0)
            if per_dc.get(ep.dc, 0) >= rf or ep in chosen:
                continue
            racks = racks_seen.setdefault(ep.dc, set())
            if ep.rack in racks:
                skipped.setdefault(ep.dc, []).append(ep)
                continue
            chosen.append(ep)
            racks.add(ep.rack)
            per_dc[ep.dc] = per_dc.get(ep.dc, 0) + 1
            if all(per_dc.get(dc, 0) >= rf for dc, rf in rf_by_dc.items()):
                break
        # fill remaining slots from skipped same-rack nodes
        for dc, rf in rf_by_dc.items():
            for ep in skipped.get(dc, []):
                if per_dc.get(dc, 0) >= rf:
                    break
                if ep not in chosen:
                    chosen.append(ep)
                    per_dc[dc] = per_dc.get(dc, 0) + 1
        return chosen


class LocalStrategy(ReplicationStrategy):
    def replication_factor(self) -> int:
        return 1

    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        return []


# ------------------------------------------------------ consistency levels --

class ConsistencyLevel:
    ANY = "ANY"
    ONE = "ONE"
    TWO = "TWO"
    THREE = "THREE"
    QUORUM = "QUORUM"
    ALL = "ALL"
    LOCAL_QUORUM = "LOCAL_QUORUM"
    LOCAL_ONE = "LOCAL_ONE"
    EACH_QUORUM = "EACH_QUORUM"

    @staticmethod
    def block_for(cl: str, strategy: "ReplicationStrategy",
                  local_dc: str = "dc1") -> int:
        """How many acks the consistency level demands, from the CONFIGURED
        replication factor — not the materialized replica list. With RF=3
        on a 1-node ring, QUORUM must demand 2 and fail Unavailable, not
        quietly succeed with 1 (db/ConsistencyLevel.java blockFor)."""
        rf = strategy.replication_factor()
        if cl in ("ANY", "ONE", "LOCAL_ONE"):
            return 1 if rf else 0
        if cl == "TWO":
            return 2
        if cl == "THREE":
            return 3
        if cl == "QUORUM":
            return rf // 2 + 1
        if cl == "ALL":
            return rf
        if cl == "LOCAL_QUORUM":
            by_dc = strategy.dc_replication_factors()
            dc_rf = by_dc.get(local_dc, 0) if by_dc is not None else rf
            return dc_rf // 2 + 1
        if cl == "EACH_QUORUM":
            # total count only; the per-DC availability gate lives in
            # each_quorum_unavailable_dcs (ack counting stays global — a
            # DC whose quorum times out after the gate is approximated)
            by_dc = strategy.dc_replication_factors()
            if by_dc is not None:
                return sum(v // 2 + 1 for v in by_dc.values())
            return rf // 2 + 1
        raise ValueError(f"unknown consistency level {cl}")

    @staticmethod
    def each_quorum_unavailable_dcs(strategy: "ReplicationStrategy",
                                    live: list[Endpoint]) -> list[str]:
        """DCs whose quorum cannot be met from the live replicas —
        EACH_QUORUM must refuse if any (reference assureSufficient
        LiveReplicasForWrite per-DC path). Empty for non-NTS."""
        by_dc = strategy.dc_replication_factors()
        if by_dc is None:
            return []
        live_per_dc: dict[str, int] = {}
        for r in live:
            live_per_dc[r.dc] = live_per_dc.get(r.dc, 0) + 1
        return [dc for dc, rf in by_dc.items()
                if live_per_dc.get(dc, 0) < rf // 2 + 1]
