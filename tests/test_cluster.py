"""Multi-node tests over LocalCluster — the jvm-dtest analog (reference:
test/distributed/test/*; in-process nodes, droppable messages)."""
import time

import pytest

from cassandra_tpu.cluster.messaging import Verb
from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import (ConsistencyLevel,
                                               NetworkTopologyStrategy)
from cassandra_tpu.cluster.ring import Endpoint, Ring, even_tokens
from cassandra_tpu.cluster.coordinator import (TimeoutException,
                                               UnavailableException)


@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(3, str(tmp_path), rf=3)
    for n in c.nodes:
        n.proxy.timeout = 1.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 3}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    yield c
    c.shutdown()


def test_write_one_node_read_another(cluster):
    s1 = cluster.session(1)
    s1.keyspace = "ks"
    s1.execute("INSERT INTO kv (k, v) VALUES (1, 'hello')")
    s2 = cluster.session(2)
    s2.keyspace = "ks"
    assert s2.execute("SELECT v FROM kv WHERE k = 1").rows == [("hello",)]


def test_replicas_hold_data_locally(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    cluster.node(1).default_cl = ConsistencyLevel.ALL
    for i in range(20):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    # RF=3 on 3 nodes: every node holds every row locally
    t = cluster.schema.get_table("ks", "kv")
    pk = t.columns["k"].cql_type.serialize(7)
    for n in cluster.nodes:
        batch = n.engine.store("ks", "kv").read_partition(pk)
        assert len(batch) > 0, n.endpoint


def test_quorum_survives_one_dropped_replica(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    cluster.node(1).default_cl = ConsistencyLevel.QUORUM
    victim = cluster.nodes[2].endpoint
    cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim)
    s.execute("INSERT INTO kv (k, v) VALUES (5, 'q')")   # 2/3 acks: ok
    assert s.execute("SELECT v FROM kv WHERE k = 5").rows == [("q",)]
    cluster.filters.clear()


def test_all_fails_when_replica_dropped(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    cluster.node(1).default_cl = ConsistencyLevel.ALL
    cluster.filters.drop(verb=Verb.MUTATION_REQ,
                         to=cluster.nodes[2].endpoint)
    with pytest.raises(TimeoutException):
        s.execute("INSERT INTO kv (k, v) VALUES (6, 'x')")
    cluster.filters.clear()


def test_unavailable_when_nodes_down(cluster):
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.QUORUM
    # mark both peers dead in n1's view
    for other in (cluster.nodes[1], cluster.nodes[2]):
        n1.gossiper.states[other.endpoint].alive = False
    s = cluster.session(1)
    s.keyspace = "ks"
    with pytest.raises(UnavailableException):
        s.execute("INSERT INTO kv (k, v) VALUES (7, 'x')")
    for other in (cluster.nodes[1], cluster.nodes[2]):
        n1.gossiper.states[other.endpoint].alive = True


def test_hints_stored_and_replayed(cluster):
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ONE
    victim = cluster.nodes[2]
    # victim is seen dead -> writes hint instead of sending. Gossip
    # keeps running in this fixture, so mute it first: without the
    # drops an in-flight SYN/ACK about the victim can re-mark it alive
    # between the flag flip and the write (a real flake under full-run
    # load).
    cluster.filters.drop(verb=Verb.GOSSIP_SYN)
    cluster.filters.drop(verb=Verb.GOSSIP_ACK)
    n1.gossiper.states[victim.endpoint].alive = False
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("INSERT INTO kv (k, v) VALUES (9, 'hinted')")
    cluster.filters.clear()
    assert n1.hints.has_hints(victim.endpoint)
    # victim had no copy
    t = cluster.schema.get_table("ks", "kv")
    pk = t.columns["k"].cql_type.serialize(9)
    assert len(victim.engine.store("ks", "kv").read_partition(pk)) == 0
    # recovery: replay hints
    n1.gossiper.states[victim.endpoint].alive = True
    n1._on_peer_alive(victim.endpoint)
    deadline = time.time() + 3
    while time.time() < deadline:
        if len(victim.engine.store("ks", "kv").read_partition(pk)) > 0:
            break
        time.sleep(0.05)
    assert len(victim.engine.store("ks", "kv").read_partition(pk)) > 0
    assert not n1.hints.has_hints(victim.endpoint)


def test_read_repair(cluster):
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.QUORUM
    victim = cluster.nodes[2]
    cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim.endpoint)
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("INSERT INTO kv (k, v) VALUES (11, 'repair-me')")
    cluster.filters.clear()
    t = cluster.schema.get_table("ks", "kv")
    pk = t.columns["k"].cql_type.serialize(11)
    assert len(victim.engine.store("ks", "kv").read_partition(pk)) == 0
    # a CL=ALL read must detect the divergence and repair the victim
    n1.default_cl = ConsistencyLevel.ALL
    assert s.execute("SELECT v FROM kv WHERE k = 11").rows == [("repair-me",)]
    deadline = time.time() + 3
    while time.time() < deadline:
        if len(victim.engine.store("ks", "kv").read_partition(pk)) > 0:
            break
        time.sleep(0.05)
    assert len(victim.engine.store("ks", "kv").read_partition(pk)) > 0


def test_gossip_detects_death_and_recovery(tmp_path):
    c = LocalCluster(3, str(tmp_path), gossip_interval=0.05)
    try:
        # let a few rounds run
        time.sleep(0.5)
        n1 = c.node(1)
        assert all(n1.is_alive(n.endpoint) for n in c.nodes)
        c.stop_node(3)
        dead_ep = c.nodes[2].endpoint
        deadline = time.time() + 10
        while time.time() < deadline and n1.is_alive(dead_ep):
            time.sleep(0.1)
        assert not n1.is_alive(dead_ep), "phi detector never convicted"
    finally:
        c.shutdown()


def test_nts_placement():
    ring = Ring()
    toks = even_tokens(6, vnodes=1)
    for i in range(6):
        dc = "dc1" if i < 3 else "dc2"
        ring.add_node(Endpoint(f"n{i}", dc=dc, rack=f"r{i % 3}"), toks[i])
    strat = NetworkTopologyStrategy({"dc1": 2, "dc2": 2})
    reps = strat.replicas(ring, 0)
    assert len(reps) == 4
    assert sum(1 for r in reps if r.dc == "dc1") == 2
    assert sum(1 for r in reps if r.dc == "dc2") == 2


def test_scan_all_across_cluster(cluster):
    # write at ALL so every replica holds the rows before scanning: the
    # windowed range read serves each arc from blockFor replicas only
    # (real CL=ONE semantics), so ONE-written rows may lag replicas
    s1 = cluster.session(1)
    s1.keyspace = "ks"
    cluster.node(1).default_cl = ConsistencyLevel.ALL
    for i in range(30):
        s1.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    cluster.node(1).default_cl = ConsistencyLevel.ONE
    rows = cluster.session(2)
    rows.keyspace = "ks"
    got = rows.execute("SELECT count(*) FROM kv")
    assert got.rows == [(30,)]


def test_repair_reconciles_divergent_replicas(cluster):
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ONE
    victim = cluster.nodes[2]
    s = cluster.session(1)
    s.keyspace = "ks"
    # make node3 miss half the writes
    cluster.filters.drop(verb=Verb.MUTATION_REQ, to=victim.endpoint)
    for i in range(100, 110):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'r{i}')")
    cluster.filters.clear()
    # stop background hint redelivery from masking the divergence: purge
    import glob, os
    for n in cluster.nodes:
        for f in glob.glob(os.path.join(n.hints.directory, "*")):
            os.remove(f)
    t = cluster.schema.get_table("ks", "kv")
    missing = [i for i in range(100, 110)
               if len(victim.engine.store("ks", "kv").read_partition(
                   t.columns["k"].cql_type.serialize(i))) == 0]
    assert missing, "test setup: victim should have missed writes"
    stats = n1.repair.repair_table("ks", "kv")
    assert stats["ranges_synced"] > 0
    import time as _t
    deadline = _t.time() + 5
    def still_missing():
        return [i for i in missing
                if len(victim.engine.store("ks", "kv").read_partition(
                    t.columns["k"].cql_type.serialize(i))) == 0]
    while _t.time() < deadline and still_missing():
        _t.sleep(0.1)
    assert still_missing() == []


def test_merkle_tree_difference():
    from cassandra_tpu.utils.merkle import MerkleTree
    a, b = MerkleTree(8), MerkleTree(8)
    for t in range(-100, 100):
        tok = t * (1 << 55)
        a.add(tok, bytes([t & 0xFF]) * 16)
        b.add(tok, bytes([t & 0xFF]) * 16)
    b.add(42 * (1 << 55), b"\xff" * 16)  # diverge one leaf
    diffs = a.difference(b)
    assert len(diffs) == 1
    lo, hi = diffs[0]
    assert lo <= 42 * (1 << 55) <= hi
    assert a.difference(a) == []


def test_lwt_paxos_basic(cluster):
    s1 = cluster.session(1)
    s1.keyspace = "ks"
    rs = s1.execute("INSERT INTO kv (k, v) VALUES (50, 'first') "
                    "IF NOT EXISTS")
    assert rs.rows[0][0] is True
    # from ANOTHER node: must see the committed value and refuse
    s2 = cluster.session(2)
    s2.keyspace = "ks"
    rs = s2.execute("INSERT INTO kv (k, v) VALUES (50, 'second') "
                    "IF NOT EXISTS")
    assert rs.rows[0][0] is False
    assert "first" in rs.rows[0]  # prior row returned
    rs = s2.execute("UPDATE kv SET v = 'updated' WHERE k = 50 "
                    "IF v = 'first'")
    assert rs.rows[0][0] is True
    # the commit round acks at QUORUM (2/3): a CL.ONE read may hit the
    # straggler replica for a few ms — poll, don't race it
    deadline = time.time() + 10
    rows = None
    while time.time() < deadline:
        rows = s1.execute("SELECT v FROM kv WHERE k = 50").rows
        if rows == [("updated",)]:
            break
        time.sleep(0.05)
    assert rows == [("updated",)]
    rs = s1.execute("UPDATE kv SET v = 'nope' WHERE k = 50 IF v = 'wrong'")
    assert rs.rows[0][0] is False


def test_lwt_paxos_contention(cluster):
    import threading
    results = []
    lock = threading.Lock()

    def contend(i):
        s = cluster.session((i % 3) + 1)
        s.keyspace = "ks"
        try:
            rs = s.execute(
                f"INSERT INTO kv (k, v) VALUES (60, 'w{i}') IF NOT EXISTS")
            with lock:
                results.append(bool(rs.rows[0][0]))
        except Exception:
            with lock:
                results.append(None)   # contention timeout acceptable

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    wins = sum(1 for r in results if r is True)
    # at most one winner (a proposer whose in-flight round was finished by
    # a helper may report not-applied even though its value committed —
    # the reference has the same false-negative anomaly, CASSANDRA-12126)
    assert wins <= 1, results
    s = cluster.session(1)
    s.keyspace = "ks"
    rows = s.execute("SELECT v FROM kv WHERE k = 60").rows
    assert len(rows) == 1 and rows[0][0].startswith("w")


def test_logged_batch_atomic_replay(tmp_path):
    # batchlog: a crash after store but before apply replays at boot
    from cassandra_tpu.cql import Session
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.storage.mutation import Mutation
    d = str(tmp_path / "bl")
    eng = StorageEngine(d, Schema(), commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    t = eng.schema.get_table("ks", "kv")
    # simulate: batch persisted, crash before apply
    m1 = Mutation(t.id, t.columns["k"].cql_type.serialize(1))
    m1.add(b"", t.columns["v"].column_id, b"",
           t.columns["v"].cql_type.serialize("a"), 100)
    m2 = Mutation(t.id, t.columns["k"].cql_type.serialize(2))
    m2.add(b"", t.columns["v"].column_id, b"",
           t.columns["v"].cql_type.serialize("b"), 100)
    eng.batchlog.store([m1, m2])
    eng.close()
    eng2 = StorageEngine(d, Schema(), commitlog_sync="batch")
    s2 = Session(eng2)
    s2.keyspace = "ks"
    assert len(s2.execute("SELECT * FROM kv").rows) == 2
    assert list(eng2.batchlog.pending()) == []
    eng2.close()


def test_logged_batch_through_cql(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("""BEGIN BATCH
        INSERT INTO kv (k, v) VALUES (70, 'a');
        INSERT INTO kv (k, v) VALUES (71, 'b');
        APPLY BATCH""")
    assert len(s.execute("SELECT v FROM kv WHERE k IN (70, 71)").rows) == 2


def test_bootstrap_new_node(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    cluster.node(1).default_cl = ConsistencyLevel.ALL
    for i in range(200, 260):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'b{i}')")
    n4 = cluster.add_node()
    n4.proxy.timeout = 1.0
    # new node owns some ranges; its local store must hold the data for
    # partitions it now replicates (RF=3 over 4 nodes: NOT everything)
    t = cluster.schema.get_table("ks", "kv")
    from cassandra_tpu.cluster.replication import ReplicationStrategy
    strat = ReplicationStrategy.create(
        cluster.schema.keyspaces["ks"].params.replication)
    owned = missing = 0
    for i in range(200, 260):
        pk = t.columns["k"].cql_type.serialize(i)
        tok = cluster.ring.token_of(pk)
        if n4.endpoint in strat.replicas(cluster.ring, tok):
            owned += 1
            if len(n4.engine.store("ks", "kv").read_partition(pk)) == 0:
                missing += 1
    assert owned > 0, "new node owns nothing — token assignment broken"
    assert missing == 0, f"{missing}/{owned} owned partitions not streamed"
    # reads through the new node see everything
    s4 = n4.session()
    s4.keyspace = "ks"
    assert len(s4.execute(
        "SELECT k FROM kv WHERE k IN (200, 210, 259)").rows) == 3


def test_decommission_preserves_data(tmp_path):
    c = LocalCluster(3, str(tmp_path), gossip_interval=0.05)
    try:
        for n in c.nodes:
            n.proxy.timeout = 1.0
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        c.node(1).default_cl = ConsistencyLevel.ALL
        for i in range(40):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'd{i}')")
        c.nodes[2].decommission()
        import time as _t
        _t.sleep(0.5)   # one-way pushes drain
        s1 = c.session(1)
        s1.keyspace = "ks"
        assert len(s1.execute("SELECT k FROM kv").rows) == 40
    finally:
        c.shutdown()


def test_quorum_unavailable_on_undersized_ring(tmp_path):
    """blockFor comes from the CONFIGURED RF: QUORUM at RF=3 on a 1-node
    ring must refuse (blockFor=2), not silently accept with 1 replica
    (db/ConsistencyLevel.java blockFor)."""
    c = LocalCluster(1, str(tmp_path), rf=3)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE uks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE uks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        with pytest.raises(UnavailableException):
            s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
        c.node(1).default_cl = ConsistencyLevel.ONE
        s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        with pytest.raises(UnavailableException):
            s.execute("SELECT v FROM kv WHERE k = 1")
    finally:
        c.shutdown()


def test_range_delete_replicates(cluster):
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE rd (k int, c int, v text, PRIMARY KEY (k, c))")
    cluster.node(1).default_cl = ConsistencyLevel.ALL
    for c in range(6):
        s.execute(f"INSERT INTO rd (k, c, v) VALUES (1, {c}, 'x')")
    s.execute("DELETE FROM rd WHERE k = 1 AND c >= 3")
    # every replica applied the range; read from another coordinator
    s2 = cluster.session(2)
    s2.keyspace = "ks"
    got = sorted(r[0] for r in s2.execute("SELECT c FROM rd WHERE k = 1"))
    assert got == [0, 1, 2]


def test_paxos_state_survives_replica_restart(cluster):
    """A restarted replica must still know its promises and in-flight
    accepted values (system.paxos persistence): a prepare after the
    restart sees the accepted proposal and finishes it, and stale
    ballots stay rejected (service/paxos/PaxosState.java)."""
    from cassandra_tpu.cluster.paxos import Ballot, PaxosService
    from cassandra_tpu.cluster.messaging import Message
    from cassandra_tpu.storage.mutation import Mutation

    n2 = cluster.node(2)
    t = cluster.schema.get_table("ks", "kv")
    pk = t.columns["k"].cql_type.serialize(77)
    m = Mutation(t.id, pk)
    m.add(b"", 8, b"", t.columns["v"].cql_type.serialize("inflight"),
          1000, 0x7FFFFFFF, 0, 0)
    ballot = Ballot(500, "node1")

    def call(verb, payload):
        handler = {"PAXOS_PREPARE": n2.paxos._handle_prepare,
                   "PAXOS_PROPOSE": n2.paxos._handle_propose}[verb]
        return handler(Message(verb, payload, n2.endpoint, n2.endpoint))[1]

    assert call("PAXOS_PREPARE", (t.id, pk, ballot.pack()))["promised"]
    assert call("PAXOS_PROPOSE",
                (t.id, pk, ballot.pack(), m.serialize()))["accepted"]

    # crash-restart the replica's paxos service (state only on disk now)
    n2.paxos = PaxosService(n2)

    # a stale ballot must still be rejected after restart
    stale = call("PAXOS_PREPARE", (t.id, pk, Ballot(400, "nodeX").pack()))
    assert not stale["promised"]
    # a newer prepare must SURFACE the in-flight accepted value
    rsp = call("PAXOS_PREPARE", (t.id, pk, Ballot(600, "node3").pack()))
    assert rsp["promised"]
    assert Ballot.unpack(rsp["accepted_ballot"]) == ballot
    assert rsp["accepted_value"] == m.serialize()


def test_lwt_completes_across_replica_restarts(cluster):
    """End-to-end: an IF NOT EXISTS decided before a replica restart must
    keep excluding later contenders afterwards."""
    from cassandra_tpu.cluster.paxos import PaxosService
    s = cluster.session(1)
    s.keyspace = "ks"
    rs = s.execute("INSERT INTO kv (k, v) VALUES (88, 'first') "
                   "IF NOT EXISTS")
    assert rs.rows[0][0] is True
    for i in (1, 2):
        n = cluster.node(i + 1)
        n.paxos = PaxosService(n)     # restart 2 of 3 replicas
    rs = s.execute("INSERT INTO kv (k, v) VALUES (88, 'second') "
                   "IF NOT EXISTS")
    assert rs.rows[0][0] is False
    assert s.execute("SELECT v FROM kv WHERE k = 88").rows == [("first",)]


def test_pending_range_writes_during_bootstrap(tmp_path):
    """Writes landing while a node bootstraps must reach it for the
    ranges it is acquiring: at RF=1 ownership MOVES, so a write that only
    hit the old owner and never streamed would vanish at the flip
    (locator/ReplicaPlans pending replicas)."""
    c = LocalCluster(2, str(tmp_path), rf=1, gossip_interval=0.05)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        for i in range(30):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'pre{i}')")

        def mid_join():
            # the stream has completed; these writes arrive before the
            # ownership flip and must be duplicated to the pending node
            for i in range(30, 60):
                s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'mid{i}')")

        c.add_node(mid_join_hook=mid_join)
        # every row readable after the join, from any coordinator
        s3 = c.session(3)
        s3.keyspace = "ks"
        got = {r[0]: r[1] for r in s3.execute("SELECT k, v FROM kv").rows}
        assert set(got) == set(range(60)), \
            sorted(set(range(60)) - set(got))
        assert all(got[i] == f"pre{i}" for i in range(30))
        assert all(got[i] == f"mid{i}" for i in range(30, 60))
        # specifically: rows now owned by the NEW node exist locally there
        new = c.nodes[2]
        t = c.schema.get_table("ks", "kv")
        from cassandra_tpu.cluster.replication import ReplicationStrategy
        strat = ReplicationStrategy.create(
            c.schema.keyspaces["ks"].params.replication)
        owned_locally = 0
        for i in range(60):
            pk = t.columns["k"].cql_type.serialize(i)
            if strat.replicas(c.ring, c.ring.token_of(pk))[0] \
                    == new.endpoint:
                batch = new.engine.store("ks", "kv").read_partition(pk)
                assert len(batch) > 0, f"row {i} missing on joined node"
                owned_locally += 1
        assert owned_locally > 0   # the new node really owns some rows
    finally:
        c.shutdown()


def test_speculative_retry_rescues_slow_replica(cluster):
    """A digest replica that never answers must not stall the read until
    the full timeout: after the speculative delay a redundant request to
    a spare replica completes the quorum
    (service/reads/AbstractReadExecutor speculate)."""
    from cassandra_tpu.service.metrics import GLOBAL
    s = cluster.session(1)
    s.keyspace = "ks"
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    s.execute("INSERT INTO kv (k, v) VALUES (70, 'spec')")
    n1.default_cl = ConsistencyLevel.QUORUM
    # deterministic target choice: node2 looks fastest -> digest target;
    # node3 becomes the spare
    ep2, ep3 = cluster.nodes[1].endpoint, cluster.nodes[2].endpoint
    n1.proxy._latency = {ep2: 0.001, ep3: 0.5}
    cluster.filters.drop(verb=Verb.READ_REQ, to=ep2)
    n1.proxy.timeout = 5.0
    before = GLOBAL.counter("reads.speculative_retries")
    before_won = GLOBAL.counter("reads.speculative_retries_won")
    import time
    t0 = time.time()
    assert s.execute("SELECT v FROM kv WHERE k = 70").rows == [("spec",)]
    assert time.time() - t0 < 2.0, "speculation should beat the timeout"
    assert GLOBAL.counter("reads.speculative_retries") > before
    # the dropped digest never answers, so the spare's response is what
    # completed the round: the retry FIRED and WON
    assert GLOBAL.counter("reads.speculative_retries_won") > before_won
    cluster.filters.clear()


# ------------------------------------------------------ counter leader --

def test_counter_leader_shards(cluster):
    """Increments route through a leader replica and land as CUMULATIVE
    per-leader shard cells: every coordinator reads the same total
    (sum of shards), and replaying a shard mutation — the hint/retry
    case that double-counts naive deltas — changes nothing."""
    s1, s2 = cluster.session(1), cluster.session(2)
    for s in (s1, s2):
        s.keyspace = "ks"
    for n in cluster.nodes:      # leader waits full replication; reads
        n.default_cl = ConsistencyLevel.ALL   # then see every shard
    s1.execute("CREATE TABLE cnt (k int PRIMARY KEY, hits counter)")
    for _ in range(4):
        s1.execute("UPDATE cnt SET hits = hits + 3 WHERE k = 1")
    for _ in range(3):
        s2.execute("UPDATE cnt SET hits = hits - 2 WHERE k = 1")
    for s in (s1, s2):
        assert s.execute("SELECT hits FROM cnt WHERE k = 1").rows \
            == [(6,)]

    # shards are idempotent state: re-apply node1's current shard cell
    # verbatim (what a duplicated hint or a retried replication does)
    from cassandra_tpu.cluster.counters import CounterService
    from cassandra_tpu.storage.mutation import Mutation
    t = cluster.schema.get_table("ks", "cnt")
    pk = t.columns["k"].cql_type.serialize(1)
    col = t.columns["hits"].column_id
    n1 = cluster.node(1)
    batch = n1.engine.store("ks", "cnt").read_partition(pk)
    shard = n1.endpoint.name.encode()
    total, ts = CounterService._own_shard(batch, b"", col, shard)
    assert ts > 0       # node1 coordinated increments -> owns a shard
    replay = Mutation(t.id, pk)
    replay.add(b"", col, shard,
               total.to_bytes(8, "big", signed=True), ts)
    for n in cluster.nodes:
        n.engine.apply(replay)          # duplicated delivery
        n.engine.apply(replay)
    assert s2.execute("SELECT hits FROM cnt WHERE k = 1").rows == [(6,)]

    # flush + survive compaction: shards are plain LWW cells
    for n in cluster.nodes:
        n.engine.store("ks", "cnt").flush()
    assert s1.execute("SELECT hits FROM cnt WHERE k = 1").rows == [(6,)]


def test_counter_hinted_shard_converges(cluster):
    """A replica that missed shard replication converges through hints
    WITHOUT double counting — the hinted payload is cumulative shard
    state, not a delta."""
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ONE
    victim = cluster.nodes[2]
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("CREATE TABLE cnt2 (k int PRIMARY KEY, hits counter)")
    t = cluster.schema.get_table("ks", "cnt2")
    pk = t.columns["k"].cql_type.serialize(7)
    time.sleep(0.1)     # table reaches all stores
    # forced_down, not just alive=False: the victim IS gossiping, and a
    # heartbeat landing mid-test would resurrect a bare alive flip
    # (observed as flaky hint loss); only operator-asserted death
    # survives version churn
    n1.gossiper.states[victim.endpoint].alive = False
    n1.gossiper.states[victim.endpoint].forced_down = True
    for _ in range(5):
        s.execute("UPDATE cnt2 SET hits = hits + 2 WHERE k = 7")
    assert n1.hints.has_hints(victim.endpoint)
    assert len(victim.engine.store("ks", "cnt2").read_partition(pk)) == 0
    n1.gossiper.states[victim.endpoint].forced_down = False
    n1.gossiper.states[victim.endpoint].alive = True
    n1._on_peer_alive(victim.endpoint)
    # victim's LOCAL view alone converges to the full total: 5 hinted
    # cumulative shard mutations collapse to one shard worth +10 (a
    # delta scheme would replay to +30)
    from cassandra_tpu.storage.rows import row_to_dict, rows_from_batch
    store = victim.engine.store("ks", "cnt2")
    deadline = time.time() + 15
    got = None
    while time.time() < deadline:
        rows = list(rows_from_batch(t, store.read_partition(pk)))
        got = row_to_dict(t, rows[0])["hits"] if rows else None
        if got == 10 and not n1.hints.has_hints(victim.endpoint):
            break
        time.sleep(0.1)
    assert got == 10
    assert not n1.hints.has_hints(victim.endpoint)


def test_counter_cache_and_truncate(cluster):
    """The leader's counter cache makes repeat increments skip the
    partition read but must never survive TRUNCATE."""
    s = cluster.session(1)
    s.keyspace = "ks"
    for n in cluster.nodes:
        n.default_cl = ConsistencyLevel.ALL
    s.execute("CREATE TABLE cc (k int PRIMARY KEY, hits counter)")
    for _ in range(10):
        s.execute("UPDATE cc SET hits = hits + 1 WHERE k = 3")
    assert s.execute("SELECT hits FROM cc WHERE k = 3").rows == [(10,)]
    n1 = cluster.node(1)
    assert len(n1.counters._cache) > 0        # warmed
    s.execute("TRUNCATE cc")
    assert len(n1.counters._cache) == 0       # invalidated
    s.execute("UPDATE cc SET hits = hits + 5 WHERE k = 3")
    assert s.execute("SELECT hits FROM cc WHERE k = 3").rows == [(5,)]


def test_entire_sstable_streaming(cluster):
    """A whole in-range sstable ships as verbatim component files
    (CassandraEntireSSTableStreamWriter role): the receiver's Data.db
    bytes are identical to the source's, and straddling sstables fall
    back to batch re-serialization."""
    import os

    s = cluster.session(1)
    s.keyspace = "ks"
    cluster.node(1).default_cl = ConsistencyLevel.ALL
    for i in range(300, 340):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 's{i}')")
    n1 = cluster.node(1)
    src_cfs = n1.engine.store("ks", "kv")
    src_cfs.flush()
    src = src_cfs.live_sstables()[0]
    toks = src.partition_tokens
    lo, hi = int(toks[0]) - 1, int(toks[-1])

    n2 = cluster.node(2)
    files, leftover = n2.streams.fetch_range(
        n1.endpoint, "ks", "kv", lo, hi, 5.0)
    assert files, "whole in-range sstable should ship as files"
    comps = files[0]
    from cassandra_tpu.storage.sstable.format import Component
    assert Component.DATA in comps and Component.TOC in comps
    with open(os.path.join(
            src_cfs.directory,
            f"{src.desc.version}-{src.desc.generation}-"
            f"{Component.DATA}"), "rb") as f:
        assert comps[Component.DATA] == f.read()   # verbatim bytes

    # landing under a fresh generation serves reads
    dst_cfs = n2.engine.store("ks", "kv")
    before = len(dst_cfs.live_sstables())
    n2.streams.land_sstable(dst_cfs, comps)
    dst_cfs.reload_sstables()
    assert len(dst_cfs.live_sstables()) == before + 1

    # a narrower range makes the same sstable PARTIAL: batch fallback
    files2, leftover2 = n2.streams.fetch_range(
        n1.endpoint, "ks", "kv", lo, int(toks[len(toks) // 2]), 5.0)
    assert files2 == []
    assert 0 < len(leftover2) < src.n_cells


def test_paxos_log_compact_preserves_concurrent_append(tmp_path):
    """A promise fsynced while compaction is rewriting the log must
    survive the os.replace — otherwise a crash replays pre-promise state
    and the replica can re-promise a lower ballot (round-2 advisor
    finding on PaxosLog.compact)."""
    import threading
    import uuid

    from cassandra_tpu.cluster.paxos import Ballot, PaxosLog, PaxosState

    log = PaxosLog(str(tmp_path))
    tid = uuid.uuid4()
    st = PaxosState()
    st.promised = Ballot(5, "a")
    log.append(tid, b"k1", PaxosLog.K_PROMISE, Ballot(5, "a"), None)

    ready, proceed = threading.Event(), threading.Event()

    class Gate(dict):
        # compact() iterates items() after arming its pending buffer;
        # block there so the test can interleave an append
        def items(self):
            ready.set()
            proceed.wait(5)
            return super().items()

    t = threading.Thread(target=log.compact,
                         args=(Gate({(tid, b"k1"): st}),))
    t.start()
    assert ready.wait(5)
    log.append(tid, b"k2", PaxosLog.K_PROMISE, Ballot(9, "b"), None)
    proceed.set()
    t.join(5)
    assert not t.is_alive()

    recs = list(PaxosLog(str(tmp_path)).replay())
    by_pk = {pk: ballot for _, pk, _, ballot, _ in recs}
    assert by_pk.get(b"k1") == Ballot(5, "a")
    assert by_pk.get(b"k2") == Ballot(9, "b"), \
        "append during compaction was erased from the durable log"


def test_counter_leader_failure_classified_by_kind(cluster):
    """The origin classifies a remote counter-leader failure by the
    structured exception kind in FAILURE_RSP: a real Unavailable
    surfaces as Unavailable, while an unrelated error whose TEXT merely
    contains 'Unavailable' stays a maybe-applied timeout."""
    s = cluster.session(1)
    s.execute("CREATE KEYSPACE ks2 WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 2}")
    s.keyspace = "ks2"
    s.execute("CREATE TABLE cnt_err (k int PRIMARY KEY, hits counter)")
    time.sleep(0.1)
    n1 = cluster.node(1)
    t = cluster.schema.get_table("ks2", "cnt_err")
    key = None
    for k in range(200):
        pk = t.columns["k"].cql_type.serialize(k)
        reps, _, _ = n1.proxy._plan("ks2", pk)
        if n1.endpoint not in reps:
            key, leader_ep = k, reps[0]
            break
    assert key is not None, "no pk found with node1 as non-replica"
    leader = next(n for n in cluster.nodes if n.endpoint == leader_ep)

    def raise_unavailable(*a, **kw):
        raise UnavailableException("replication needs 2, 1 alive")

    orig = leader.counters.apply_as_leader
    leader.counters.apply_as_leader = raise_unavailable
    try:
        with pytest.raises(UnavailableException):
            s.execute(
                f"UPDATE cnt_err SET hits = hits + 1 WHERE k = {key}")

        def raise_other(*a, **kw):
            raise ValueError("text mentioning Unavailable is not a kind")

        leader.counters.apply_as_leader = raise_other
        with pytest.raises(TimeoutException):
            s.execute(
                f"UPDATE cnt_err SET hits = hits + 1 WHERE k = {key}")
    finally:
        leader.counters.apply_as_leader = orig


def test_range_read_repair_converges_replicas(tmp_path):
    """Range reads repair divergent replicas like single-partition
    reads do (DataResolver over RangeCommands): after a QUORUM scan,
    the replica that missed writes holds them locally."""
    import time

    from cassandra_tpu.cluster.messaging import Verb
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    c = LocalCluster(2, str(tmp_path), rf=2)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("USE ks")
        s.execute("CREATE TABLE rr (k int, c int, v text, "
                  "PRIMARY KEY (k, c))")
        n1 = c.node(1)
        n1.default_cl = ConsistencyLevel.ALL
        for k in range(10):
            s.execute(f"INSERT INTO rr (k, c, v) VALUES ({k}, 1, 'a')")
        # node2 misses a batch of updates
        rule = c.filters.drop(verb=Verb.MUTATION_REQ,
                              to=c.nodes[1].endpoint)
        n1.default_cl = ConsistencyLevel.ONE
        for k in range(5):
            s.execute(f"UPDATE rr SET v = 'NEW' WHERE k = {k} AND c = 1")
        rule["remaining"] = 0
        # QUORUM range scan sees the truth AND repairs node2
        n1.default_cl = ConsistencyLevel.QUORUM
        rows = dict((r[0], r[1]) for r in
                    s.execute("SELECT k, v FROM rr").rows)
        assert all(rows[k] == "NEW" for k in range(5))
        # give the one-way repairs a beat to apply, then check node2's
        # LOCAL data alone
        deadline = time.time() + 10
        ok = False
        while time.time() < deadline:
            local = c.node(2).engine.store("ks", "rr").scan_all()
            from cassandra_tpu.storage.rows import rows_from_batch
            t = c.nodes[1].schema.get_table("ks", "rr")
            vals = {}
            for r in rows_from_batch(t, local):
                from cassandra_tpu.storage.rows import row_to_dict
                d = row_to_dict(t, r)
                vals[d["k"]] = d["v"]
            if all(vals.get(k) == "NEW" for k in range(5)):
                ok = True
                break
            time.sleep(0.1)
        assert ok, vals
    finally:
        c.shutdown()


def test_conditional_batch_single_partition(tmp_path):
    """LWT batches (BatchStatement.executeWithConditions): conditions
    over multiple rows of ONE partition decide atomically through the
    partition's Paxos instance; cross-partition conditional batches are
    refused."""
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    c = LocalCluster(3, str(tmp_path), rf=3)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE acct (owner text, name text, bal int, "
                  "PRIMARY KEY (owner, name))")
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        s.execute("INSERT INTO acct (owner, name, bal) VALUES "
                  "('alice', 'checking', 100)")
        s.execute("INSERT INTO acct (owner, name, bal) VALUES "
                  "('alice', 'savings', 50)")
        # transfer iff the source still holds the expected balance
        rs = s.execute(
            "BEGIN BATCH "
            "UPDATE acct SET bal = 70 WHERE owner = 'alice' AND "
            "name = 'checking' IF bal = 100; "
            "UPDATE acct SET bal = 80 WHERE owner = 'alice' AND "
            "name = 'savings'; "
            "APPLY BATCH")
        assert rs.rows[0][0] is True
        got = dict(s.execute("SELECT name, bal FROM acct "
                             "WHERE owner = 'alice'").rows)
        assert got == {"checking": 70, "savings": 80}
        # failed condition: NOTHING applies
        rs = s.execute(
            "BEGIN BATCH "
            "UPDATE acct SET bal = 0 WHERE owner = 'alice' AND "
            "name = 'checking' IF bal = 999; "
            "UPDATE acct SET bal = 0 WHERE owner = 'alice' AND "
            "name = 'savings'; "
            "APPLY BATCH")
        assert rs.rows[0][0] is False
        got = dict(s.execute("SELECT name, bal FROM acct "
                             "WHERE owner = 'alice'").rows)
        assert got == {"checking": 70, "savings": 80}
        # IF NOT EXISTS in a batch
        rs = s.execute(
            "BEGIN BATCH "
            "INSERT INTO acct (owner, name, bal) VALUES "
            "('alice', 'broker', 5) IF NOT EXISTS; "
            "APPLY BATCH")
        assert rs.rows[0][0] is True
        rs = s.execute(
            "BEGIN BATCH "
            "INSERT INTO acct (owner, name, bal) VALUES "
            "('alice', 'broker', 9) IF NOT EXISTS; "
            "APPLY BATCH")
        assert rs.rows[0][0] is False
        # cross-partition refusal
        import pytest as _pytest
        with _pytest.raises(Exception, match="single partition"):
            s.execute(
                "BEGIN BATCH "
                "UPDATE acct SET bal = 1 WHERE owner = 'alice' AND "
                "name = 'checking' IF bal = 70; "
                "UPDATE acct SET bal = 1 WHERE owner = 'bob' AND "
                "name = 'checking'; "
                "APPLY BATCH")
    finally:
        c.shutdown()


def test_conditional_batch_json_and_shared_ast(tmp_path):
    """Regression pair: INSERT...JSON works inside conditional batches
    (key columns come from the document), and repeated execution of the
    SAME parsed batch keeps its conditions (no shared-AST stripping)."""
    from cassandra_tpu.cluster.node import LocalCluster
    c = LocalCluster(1, str(tmp_path), rf=1)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE j (k int, c int, v int, "
                  "PRIMARY KEY (k, c))")
        q = ("BEGIN BATCH "
             "INSERT INTO j JSON '{\"k\": 1, \"c\": 2, \"v\": 9}' "
             "IF NOT EXISTS; APPLY BATCH")
        assert s.execute(q).rows[0][0] is True
        # second run of the same statement text (same prepared-cache
        # entry underneath): the IF must still be there and fail
        assert s.execute(q).rows[0][0] is False
        assert s.execute("SELECT v FROM j WHERE k = 1 AND c = 2"
                         ).rows == [(9,)]
        # unconditional partition delete rides in a conditional batch
        rs = s.execute(
            "BEGIN BATCH "
            "UPDATE j SET v = 10 WHERE k = 1 AND c = 2 IF v = 9; "
            "DELETE FROM j WHERE k = 1; "
            "APPLY BATCH")
        assert rs.rows[0][0] is True
    finally:
        c.shutdown()


def test_dispatch_worker_death_blast_radius(cluster):
    """Worker-death blast radius for the verb-dispatch pool: a handler
    escalating past Exception kills exactly one pool worker — the
    death is counted, the worker replaced (the pool never shrinks
    behind the operator's back), only that message is lost, and the
    node keeps serving replica traffic. A merely-raising handler costs
    its message (process_failures) and nothing else."""
    import threading

    s = cluster.session(1)
    s.keyspace = "ks"
    target = cluster.nodes[1]
    ms = target.messaging
    ms.set_dispatch_workers(2)
    # real replica load so the pool is live before the kill
    for i in range(10):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and ms.pool_width() < 2:
        time.sleep(0.01)
    assert ms.pool_width() == 2

    class _Kill(BaseException):
        pass

    ran = threading.Event()

    def boom(msg):
        ran.set()
        raise _Kill()

    ms.register_handler("TEST_BOOM", boom)
    deaths0 = ms.metrics["dispatch_worker_deaths"]
    fails0 = ms.metrics["process_failures"]
    cluster.nodes[0].messaging.send_one_way("TEST_BOOM", {},
                                            target.endpoint)
    assert ran.wait(5.0)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and (
            ms.metrics["dispatch_worker_deaths"] == deaths0
            or ms.pool_width() < 2):
        time.sleep(0.01)
    assert ms.metrics["dispatch_worker_deaths"] == deaths0 + 1
    assert ms.metrics["process_failures"] == fails0 + 1
    assert ms.pool_width() == 2      # respawned, not silently narrower

    def soft(msg):
        raise RuntimeError("handler bug")

    ms.register_handler("TEST_SOFT", soft)
    failed = threading.Event()
    cluster.nodes[0].messaging.send_with_callback(
        "TEST_SOFT", {}, target.endpoint,
        on_response=lambda m: None, on_failure=lambda m: failed.set(),
        timeout=5.0)
    # a merely-raising handler becomes a FAILURE_RSP to the sender —
    # no worker dies, the pool stays at width
    assert failed.wait(5.0)
    assert ms.metrics["dispatch_worker_deaths"] == deaths0 + 1
    # the node still serves QUORUM traffic after the kill
    for i in range(10, 30):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    assert s.execute("SELECT v FROM kv WHERE k = 15").rows == [("v15",)]
