"""Selector-based event-loop CQL native-protocol server.

Reference counterpart: transport/Server.java (Netty boss/worker event
loops), Dispatcher.java:104 (the request executor decoupling protocol
I/O from query execution) and CQLMessageHandler.java (framing state
machine). Replaces the original thread-per-connection transport_server:
a FIXED set of threads now serves any number of connections —

  N event-loop threads   (`native_transport_event_loops`) multiplex all
                         sockets through `selectors`: accept, TLS
                         handshakes, framing reassembly, response
                         writes. Connections are assigned round-robin
                         at accept time and owned by one loop for life.
  M dispatch workers     (`native_transport_max_threads`) execute
                         QUERY/PREPARE/EXECUTE bodies pulled from a
                         bounded hand-off queue — protocol parsing never
                         blocks on storage, and a slow query never
                         stalls unrelated connections on the same loop.

Admission control (transport/admission.py) runs on the event loop
BEFORE a request reaches the workers: per-client ops rate limiting,
data-plane overload signals (storage.write_stall / commitlog sync
backlog) and the `native_transport_max_concurrent_requests` permit gate
each answer with a v5 OVERLOADED error instead of queueing forever.

Wire behavior (STARTUP/AUTH/OPTIONS/QUERY/PREPARE/EXECUTE/REGISTER,
v4 envelopes + v5 CRC segment framing, paging, events) is byte-
compatible with the original server — the codec lives in frame.py and
every pre-existing protocol test runs unchanged against this server.

Writes are never performed off-loop: responses and event pushes append
to a per-connection outgoing buffer and the owning loop flushes when
the socket is writable. A client that stops reading (slow consumer) is
disconnected and counted (`clients.slow_consumer_disconnects`) once its
buffer exceeds the cap, rather than wedging a loop or an emitter.
"""
from __future__ import annotations

import collections
import queue as queue_mod
import selectors
import socket
import ssl
import struct
import threading
import time

from ..cql.processor import QueryProcessor
from ..service.metrics import GLOBAL as METRICS
from ..utils.ratelimit import RateLimiter
from ..utils import lockwitness
from .admission import OverloadSignals, PermitGate
from .frame import (CONSISTENCY_NAMES, ERR_BAD_CREDENTIALS, ERR_INVALID,
                    ERR_OVERLOADED, ERR_PROTOCOL, ERR_SERVER, EVENT_TYPES,
                    MAX_ENVELOPE_BODY, OP_AUTH_RESPONSE, OP_AUTH_SUCCESS,
                    OP_AUTHENTICATE, OP_ERROR, OP_EVENT, OP_EXECUTE,
                    OP_OPTIONS, OP_PREPARE,
                    OP_QUERY, OP_READY, OP_REGISTER, OP_RESULT, OP_STARTUP,
                    OP_SUPPORTED, RESULT_PREPARED, RESULT_SET_KEYSPACE,
                    RESULT_VOID, SUPPORTED_VERSIONS, WireValue, _bytes,
                    _crc32_v5, _encode_rows, _inet, _read_bytes,
                    _read_long_string, _read_string, _string,
                    decode_segment_header, encode_envelope, error_body,
                    frame_envelope, unprepared_body)

# opcodes that run on the dispatch executor; everything else (handshake,
# registration) is cheap enough to handle inline on the event loop
DISPATCH_OPCODES = frozenset((OP_QUERY, OP_PREPARE, OP_EXECUTE))

# a connection whose unsent response bytes exceed this is a slow
# consumer and gets disconnected rather than growing without bound
OUT_BUFFER_CAP = 32 << 20
# server-push events are fire-and-forget: a much smaller backlog of
# unread pushes already proves the client stopped reading
EVENT_BACKLOG_CAP = 256 << 10


def server_thread_count(port: int) -> int:
    """Live threads belonging to the CQLServer on `port` (event loops +
    dispatch workers) — the measuring stick for the fixed-thread-set
    contract, shared by the stress smoke drill, the bench sampler and
    the tests so they can never drift from the naming scheme."""
    pfx = (f"cql-loop-{port}-", f"cql-exec-{port}-")
    return len([t for t in threading.enumerate()
                if t.name.startswith(pfx) and t.is_alive()])


def _error_response(e: Exception) -> tuple[int, bytes]:
    """Uncaught execution error -> wire ERROR (InvalidRequest subclasses
    ValueError, so CQL-level rejections map to 0x2200; everything else
    is a server bug, 0x0000)."""
    code = ERR_INVALID if isinstance(e, ValueError) else ERR_SERVER
    return OP_ERROR, error_body(code, f"{type(e).__name__}: {e}")


def _cert_identity(sock) -> str | None:
    """The VERIFIED client certificate's identity: SAN URI (SPIFFE
    style) preferred, else subject CN (MutualTlsAuthenticator's
    identity extraction). None for plaintext / cert-less TLS."""
    if not isinstance(sock, ssl.SSLSocket):
        return None
    try:
        cert = sock.getpeercert()
    except ssl.SSLError:
        return None
    if not cert:
        return None
    for typ, val in cert.get("subjectAltName", ()):
        if typ == "URI":
            return val
    for rdn in cert.get("subject", ()):
        for k, v in rdn:
            if k == "commonName":
                return v
    return None


class Connection:
    """Per-connection state, owned by exactly one event loop (the
    ServerConnection + CQLMessageHandler roles). Reads, framing and
    socket writes happen only on the owning loop thread; dispatch
    workers and event emitters hand bytes over via `enqueue`."""

    def __init__(self, server: "CQLServer", loop: "_EventLoop", sock,
                 cid: int, peer: str, peer_ip: str | None,
                 handshaking: bool):
        self.server = server
        self.loop = loop
        self.sock = sock
        self.cid = cid
        self.peer = peer
        self.peer_ip = peer_ip
        self.version: int | None = None
        self.modern = False            # v5 segment framing active
        self.keyspace: str | None = None
        self.user: str | None = None
        self.authed = False
        self.tls_identity: str | None = None
        self.registrations: set[str] = set()
        self.handshaking = handshaking  # TLS handshake still pending
        self.closing = False
        self.close_when_drained = False  # flush the error, then close
        self.rbuf = bytearray()        # raw (decrypted) socket bytes
        self.ebuf = bytearray()        # reassembled envelope bytes (v5)
        self.out = bytearray()         # encoded, not-yet-sent bytes
        self._wchunk: bytes | None = None   # chunk mid-send
        self._write_armed = False
        self._event_backlog = 0        # event bytes since the last drain
        self.paused_reads = False      # response backpressure engaged
        self.wlock = lockwitness.make_lock("transport.conn.wlock")
        self.in_flight = 0             # admitted, response not yet queued
        self.rate_limited = 0          # requests shed by the ops limiter
        self.limiter = RateLimiter(server.rate_limit_ops, unit=1.0)

    # ------------------------------------------------------ write path --

    def send_envelope(self, ver_rsp: int, stream: int, op: int,
                      body: bytes, legacy: bool = False) -> None:
        env = encode_envelope(ver_rsp, stream, op, body)
        self.enqueue(frame_envelope(env, self.modern and not legacy))

    def send_error(self, stream: int, code: int, msg: str) -> None:
        self.send_envelope(0x80 | (self.version or 0x04), stream,
                           OP_ERROR, error_body(code, msg))

    def enqueue(self, data: bytes, event: bool = False) -> bool:
        """Append encoded bytes for the loop to flush. Never blocks the
        caller. Two distinct protections:

        - RESPONSE backlog past OUT_BUFFER_CAP engages BACKPRESSURE:
          the loop stops reading this connection (no new requests get
          parsed or admitted) until the buffer drains — the event-loop
          analog of the old server blocking in sendall. Memory stays
          bounded (already-admitted responses only), the client keeps
          its data, nobody is disconnected for being slower than
          in-process response production.
        - EVENT pushes are fire-and-forget with no request to pace
          them, so a push backlog (own accumulated bytes since the
          last full drain — a draining response must not count) past
          EVENT_BACKLOG_CAP marks a true slow consumer: disconnected
          and counted rather than growing without bound."""
        wake = slow = pause = dropped = False
        with self.wlock:
            if self.closing:
                return False
            if event:
                if len(self.out) + len(data) > OUT_BUFFER_CAP:
                    # fire-and-forget: a client this far behind does
                    # not need more events QUEUED — drop the push,
                    # keep the connection (the old server dropped the
                    # oldest event when its queue filled)
                    dropped = True
                else:
                    self._event_backlog += len(data)
                    if self._event_backlog > EVENT_BACKLOG_CAP:
                        slow = True
                        self.closing = True
            if not slow and not dropped:
                self.out += data
                if not event and len(self.out) > OUT_BUFFER_CAP \
                        and not self.paused_reads:
                    self.paused_reads = True
                    pause = True
                if not self._write_armed:
                    self._write_armed = True
                    wake = True
        if dropped:
            METRICS.incr("clients.events_dropped")
            return False
        if slow:
            METRICS.incr("clients.slow_consumer_disconnects")
            from ..service import diagnostics
            diagnostics.publish("transport.slow_consumer",
                                address=self.peer,
                                backlog=self._event_backlog)
            self.loop.call(lambda: self.loop.close_conn(self))
            return False
        if pause:
            self.loop.call(lambda: self.loop.pause_reads(self))
        if wake:
            self.loop.call(lambda: self.loop.arm_write(self))
        return True

    def take_chunk(self):
        """What to send next (loop thread only). Swaps the WHOLE
        accumulated buffer out in one move and walks it with a
        memoryview cursor — a del-from-front drain would memmove the
        remaining buffer per send call, quadratic for multi-MiB
        responses, stalling every connection sharing the loop. The view
        stays stable across partial sends (the OpenSSL retry rule)."""
        if self._wchunk is None:
            with self.wlock:
                if not self.out:
                    return None
                self._wchunk = memoryview(bytes(self.out))
                self.out = bytearray()
        return self._wchunk

    def chunk_sent(self, n: int) -> None:
        assert self._wchunk is not None
        self._wchunk = self._wchunk[n:] if n < len(self._wchunk) else None
        if n > 0:
            # forward progress proves the client is reading: reset the
            # event-backlog accounting, so a steadily-draining (however
            # slow) consumer of a large response is never killed by an
            # unlucky event. A truly stalled client makes no progress,
            # accumulates, and still gets disconnected; memory for a
            # trickling one stays bounded by the event-drop rule above.
            self._event_backlog = 0

    def drained(self) -> bool:
        """True (and disarms the write interest) iff nothing is pending;
        called by the loop after a flush pass. A full drain also resets
        the event-backlog accounting: this client is provably reading."""
        if self._wchunk is not None:
            return False
        with self.wlock:
            if self.out:
                return False
            self._write_armed = False
            self._event_backlog = 0
            return True


class _EventLoop(threading.Thread):
    """One selector thread serving many connections. Work from other
    threads (response enqueues, close requests, new connections) arrives
    through `call`, which wakes the selector via a socketpair."""

    def __init__(self, server: "CQLServer", idx: int):
        super().__init__(daemon=True,
                         name=f"cql-loop-{server.port}-{idx}")
        self.server = server
        self.sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.sel.register(self._wake_r, selectors.EVENT_READ,
                          ("wake", None))
        self._jobs: collections.deque = collections.deque()
        self.conns: set[Connection] = set()

    def call(self, fn) -> None:
        """Run fn on the loop thread. Calls made FROM the loop thread
        (inline responses, event pushes fanned out by a handler) run
        immediately — no queue round trip, no self-wake."""
        if threading.current_thread() is self:
            try:
                fn()
            except Exception:
                pass
            return
        self._jobs.append(fn)
        self.wake()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (OSError, BlockingIOError):
            pass   # full pipe still wakes the selector

    # --------------------------------------------------- loop lifecycle --

    def run(self) -> None:
        while not self.server._closed:
            try:
                events = self.sel.select(timeout=0.5)
            except OSError:
                break
            while self._jobs:
                fn = self._jobs.popleft()
                try:
                    fn()
                except Exception:
                    pass
            for key, mask in events:
                kind, obj = key.data
                try:
                    if kind == "wake":
                        self._drain_wake()
                    elif kind == "accept":
                        self.server._on_accept()
                    elif kind == "conn" and obj in self.conns:
                        self._on_ready(obj, mask)
                except Exception:
                    # a bug in one connection's handling costs THAT
                    # connection at most — never the loop, which owns
                    # every other connection assigned to it (ctpulint
                    # worker-loops; the close path below is defensive
                    # against double-close). Counted: a recurring loop
                    # error must show in clientstats, not vanish.
                    METRICS.incr("clients.loop_errors")
                    if kind == "conn":
                        try:
                            self.close_conn(obj)
                        except Exception:
                            pass
        for conn in list(self.conns):
            self.close_conn(conn)
        try:
            self.sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    # ------------------------------------------------ connection events --

    def add_conn(self, conn: Connection) -> None:
        conn.sock.setblocking(False)
        self.conns.add(conn)
        try:
            self.sel.register(conn.sock, selectors.EVENT_READ,
                              ("conn", conn))
        except (OSError, ValueError):
            self.close_conn(conn)
            return
        if conn.handshaking:
            self._continue_handshake(conn)

    def _interest(self, conn: Connection, mask: int) -> None:
        try:
            self.sel.modify(conn.sock, mask, ("conn", conn))
        except (KeyError, OSError, ValueError):
            pass

    def arm_write(self, conn: Connection) -> None:
        if conn.closing or conn not in self.conns:
            return
        if conn.handshaking:
            return   # handshake owns the interest set until done
        # opportunistic immediate flush: the socket is almost always
        # writable, so most responses go out right here instead of
        # paying another select round; _flush arms EVENT_WRITE interest
        # only for the leftover-bytes case
        self._flush(conn)

    def pause_reads(self, conn: Connection) -> None:
        """Response backpressure: stop reading (and so admitting) from
        this connection until its outgoing buffer drains."""
        if conn.closing or conn not in self.conns or conn.handshaking:
            return
        if conn.paused_reads:
            self._interest(conn, selectors.EVENT_WRITE)

    def close_conn(self, conn: Connection) -> None:
        if conn not in self.conns:
            return
        with conn.wlock:
            conn.closing = True
        self.conns.discard(conn)
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, OSError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self.server._forget(conn)

    def _continue_handshake(self, conn: Connection) -> None:
        try:
            conn.sock.do_handshake()
        except ssl.SSLWantReadError:
            self._interest(conn, selectors.EVENT_READ)
            return
        except ssl.SSLWantWriteError:
            self._interest(conn, selectors.EVENT_WRITE)
            return
        except (ssl.SSLError, OSError):
            self.close_conn(conn)
            return
        conn.handshaking = False
        conn.tls_identity = _cert_identity(conn.sock)
        self._interest(conn, selectors.EVENT_READ)
        if conn._write_armed:
            self.arm_write(conn)
        # a client may pipeline its first envelope into the final
        # handshake flight: OpenSSL has already pulled those bytes off
        # the kernel socket, so the selector will never fire for them —
        # drain the SSL layer's buffer now
        if conn in self.conns:
            self._read_ready(conn)

    def _on_ready(self, conn: Connection, mask: int) -> None:
        if conn.handshaking:
            self._continue_handshake(conn)
            return
        if mask & selectors.EVENT_WRITE:
            self._flush(conn)
        if conn.closing or conn not in self.conns:
            return
        if mask & selectors.EVENT_READ:
            self._read_ready(conn)

    def _flush(self, conn: Connection) -> None:
        while True:
            chunk = conn.take_chunk()
            if chunk is None:
                # out looked empty — but a worker may have appended
                # between take_chunk's lock release and here, with
                # _write_armed still set (so it sent no wake). Only
                # drained() — which clears _write_armed under the same
                # lock — decides the buffer is truly dry; if it says
                # no, loop and pick the new bytes up NOW, or the
                # connection would stall forever with read-only
                # interest and no future wake.
                if conn.drained():
                    if conn.close_when_drained:
                        self.close_conn(conn)
                        return
                    resume = False
                    with conn.wlock:
                        if conn.paused_reads:
                            conn.paused_reads = False
                            resume = True
                    self._interest(conn, selectors.EVENT_READ)
                    if resume:
                        # bytes may have piled up in the kernel while
                        # reads were paused — pick them up now
                        self._read_ready(conn)
                    return
                continue
            try:
                sent = conn.sock.send(chunk)
            except (BlockingIOError, ssl.SSLWantWriteError,
                    ssl.SSLWantReadError):
                # kernel buffer full: let the selector call us back
                # (write-only while response backpressure is engaged)
                self._interest(conn, selectors.EVENT_WRITE if
                               conn.paused_reads else
                               selectors.EVENT_READ
                               | selectors.EVENT_WRITE)
                return
            except OSError:
                self.close_conn(conn)
                return
            conn.chunk_sent(sent)

    def _read_ready(self, conn: Connection) -> None:
        while True:
            try:
                chunk = conn.sock.recv(1 << 16)
            except (BlockingIOError, ssl.SSLWantReadError,
                    ssl.SSLWantWriteError):
                break
            except (OSError, ssl.SSLError):
                self.close_conn(conn)
                return
            if not chunk:
                self.close_conn(conn)
                return
            if conn.close_when_drained or conn.closing:
                # dying connection: keep recv'ing only to notice EOF —
                # buffering a stream we will never parse would let a
                # client that ignores its error grow rbuf without bound
                continue
            conn.rbuf += chunk
        if not conn.close_when_drained and not conn.closing:
            self.server._parse(conn)


class _Dispatcher:
    """Bounded request executor (Dispatcher.java role): admitted
    requests are handed from the event loops to `n_threads` workers.
    The queue never grows past the permit cap — admission happens
    before submit — so there is no unbounded queueing anywhere on the
    request path."""

    def __init__(self, server: "CQLServer", n_threads: int):
        self.server = server
        self.queue: queue_mod.Queue = queue_mod.Queue()
        # unified pipeline ledger stage (utils/pipeline_ledger.py):
        # busy = request execution, idle = workers parked on an empty
        # queue, queue_hwm = dispatch backlog high-water — the
        # front-door leg of the where-did-the-wall-go table
        from ..utils import pipeline_ledger
        self._stage = pipeline_ledger.ledger("transport") \
            .stage("dispatch")
        self.threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"cql-exec-{server.port}-{i}")
            for i in range(max(1, n_threads))]
        for t in self.threads:
            t.start()

    def submit(self, conn: Connection, stream: int, opcode: int,
               body: bytes) -> None:
        self.queue.put((conn, stream, opcode, body))
        self._stage.note_queue(self.queue.qsize())

    def shutdown(self) -> None:
        for _ in self.threads:
            self.queue.put(None)

    def _work(self) -> None:
        srv = self.server
        while True:
            t_idle = time.monotonic()
            item = self.queue.get()
            t0 = time.monotonic()
            self._stage.add_idle(t0 - t_idle)
            if item is None:
                return
            conn, stream, opcode, body = item
            billed = False
            try:
                try:
                    op, rsp = srv._dispatch(srv.processor, conn,
                                            srv._need_auth, srv._auth,
                                            opcode, body)
                except Exception as e:
                    op, rsp = _error_response(e)
                # bill the ledger BEFORE the response leaves: a client
                # that has already READ its response must be able to
                # observe this request's dispatch busy/items — billing
                # after send_envelope raced exactly that observation
                # (the send only enqueues to the out buffer anyway;
                # the socket write is the loop thread's work)
                self._stage.add_busy(time.monotonic() - t0)
                self._stage.add_items(1, len(body))
                billed = True
                try:
                    conn.send_envelope(0x80 | (conn.version or 0x04),
                                       stream, op, rsp)
                except Exception:
                    # an encode/enqueue failure (e.g. a response body
                    # overflowing the envelope length field) must cost
                    # THAT connection, never this shared worker — a
                    # dead worker would strand queued requests holding
                    # permits until the whole front door wedges
                    conn.loop.call(
                        lambda c=conn: c.loop.close_conn(c))
            finally:
                if not billed:   # _error_response itself raised
                    self._stage.add_busy(time.monotonic() - t0)
                    self._stage.add_items(1, len(body))
                with conn.wlock:
                    conn.in_flight -= 1
                srv.permits.release()


class CQLServer:
    """Event-loop native-protocol endpoint over a backend (StorageEngine
    or cluster Node) — transport/Server.java role. The public surface
    (port, paused, min_version, clients, processor, close) matches the
    original thread-per-connection server."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 tls=None):
        """tls: a cluster.tls.TLSConfig — client_encryption_options
        role: connections are TLS, with client certs demanded only when
        the config sets require_client_auth."""
        self.backend = backend
        self._tls_ctx = tls.server_context() if tls else None
        # ONE processor for the whole server: prepared-statement ids are
        # server-global like the reference's (drivers prepare on one
        # connection and execute on another); keyspace/user stay
        # per-connection
        self.processor = QueryProcessor(backend)
        self._auth = getattr(backend, "auth", None)
        self._need_auth = self._auth is not None and self._auth.enabled
        settings = getattr(backend, "settings", None)
        if settings is None:
            from ..config import Settings
            settings = Settings()
        self._settings = settings
        self.permits = PermitGate(
            self._setting("native_transport_max_concurrent_requests", 256))
        self.rate_limit_ops = float(
            self._setting("native_transport_rate_limit_ops", 0))
        self.overload = OverloadSignals(backend)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(256)
        self._listen.setblocking(False)
        self.port = self._listen.getsockname()[1]
        self._closed = False
        self._close_lock = threading.Lock()
        # nodetool disablebinary: new connections are refused while
        # paused (existing ones keep serving)
        self.paused = False
        # nodetool disableoldprotocolversions
        self.min_version = min(SUPPORTED_VERSIONS)
        self._event_conns: set[Connection] = set()
        self._conn_lock = threading.Lock()
        # live connection registry (system_views.clients / `nodetool
        # clientstats`; transport/ConnectedClient role)
        self.clients: dict[int, dict] = {}
        self._client_ids = 0
        self._next_loop = 0
        try:
            if not hasattr(backend, "cql_servers"):
                backend.cql_servers = []
            backend.cql_servers.append(self)
        except Exception:
            pass
        # settings listeners: both admission knobs hot-reload like
        # compaction_throughput_mib_per_sec
        self._knob_listeners = []
        for knob, cb in (
                ("native_transport_max_concurrent_requests",
                 self.permits.set_cap),
                ("native_transport_rate_limit_ops",
                 self._set_rate_limit)):
            try:
                settings.on_change(knob, cb)
                self._knob_listeners.append((knob, cb))
            except Exception:
                pass
        n_loops = max(1, int(self._setting(
            "native_transport_event_loops", 2)))
        self.event_loops = [_EventLoop(self, i) for i in range(n_loops)]
        self.event_loops[0].sel.register(self._listen,
                                         selectors.EVENT_READ,
                                         ("accept", None))
        self.dispatcher = _Dispatcher(
            self, int(self._setting("native_transport_max_threads", 4)))
        for lp in self.event_loops:
            lp.start()
        # server-push events: a cluster Node surfaces liveness/topology/
        # schema transitions through add_event_listener. Pushes are
        # non-blocking appends to each registered connection's outgoing
        # buffer — the emitting thread (gossiper, DDL executor) never
        # touches a socket, and a client that stops reading is dropped
        # by the buffer cap rather than wedging fan-out.
        if hasattr(backend, "add_event_listener"):
            backend.add_event_listener(self._on_node_event)

    def _setting(self, name: str, default):
        try:
            return self._settings.get(name)
        except Exception:
            return default

    def _set_rate_limit(self, ops: float) -> None:
        self.rate_limit_ops = float(ops)
        for info in list(self.clients.values()):
            info["conn"].limiter.set_rate(ops)

    # -------------------------------------------------------- event push --

    def _on_node_event(self, kind: str, info: dict) -> None:
        """Translate a node event into a wire EVENT envelope and append
        it to every registered connection's outgoing buffer
        (EventMessage + Server.EventNotifier roles). Never blocks the
        emitter; a slow consumer is disconnected by the buffer cap."""
        body = _string(kind)
        if kind in ("STATUS_CHANGE", "TOPOLOGY_CHANGE"):
            body += _string(info["change"])
            body += _inet(info.get("host", "127.0.0.1"),
                          int(info.get("port", 0)))
        elif kind == "SCHEMA_CHANGE":
            body += _string(info["change"])       # CREATED/UPDATED/DROPPED
            body += _string(info["target"])       # KEYSPACE/TABLE/...
            body += _string(info.get("keyspace") or "")
            if info["target"] != "KEYSPACE":
                body += _string(info.get("name") or "")
        else:
            return
        with self._conn_lock:
            conns = [c for c in self._event_conns
                     if kind in c.registrations]
        for c in conns:
            env = encode_envelope(0x80 | (c.version or 0x04), -1,
                                  OP_EVENT, body)
            c.enqueue(frame_envelope(env, c.modern), event=True)

    # ------------------------------------------------------------ accept --

    def _on_accept(self) -> None:
        """Runs on event loop 0 when the listen socket is readable."""
        while True:
            try:
                sock, _ = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            if self.paused or self._closed:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            try:
                # response envelopes are small and latency-bound: Nagle
                # + delayed ACK would add ~40ms to every round trip
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            handshaking = False
            if self._tls_ctx is not None:
                try:
                    sock = self._tls_ctx.wrap_socket(
                        sock, server_side=True,
                        do_handshake_on_connect=False)
                    handshaking = True
                except (ssl.SSLError, OSError):
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
            try:
                try:
                    peername = sock.getpeername()[:2]
                    peer = "%s:%d" % peername
                    peer_ip = peername[0]
                except OSError:
                    peer, peer_ip = "?", None
                with self._conn_lock:
                    self._client_ids += 1
                    cid = self._client_ids
                    loop = self.event_loops[self._next_loop]
                    self._next_loop = (self._next_loop + 1) \
                        % len(self.event_loops)
                conn = Connection(self, loop, sock, cid, peer, peer_ip,
                                  handshaking)
                self.clients[cid] = {"id": cid, "address": peer,
                                     "requests": 0, "conn": conn}
                if loop is self.event_loops[0]:
                    loop.add_conn(conn)
                else:
                    loop.call(lambda lp=loop, c=conn: lp.add_conn(c))
            except Exception:
                # a bug in per-connection setup must not leak the
                # accepted fd (the client would hang to timeout) or
                # kill the accept pass for later connections
                METRICS.incr("clients.loop_errors")
                try:
                    sock.close()
                except OSError:
                    pass

    def _forget(self, conn: Connection) -> None:
        self.clients.pop(conn.cid, None)
        with self._conn_lock:
            self._event_conns.discard(conn)

    # ------------------------------------------------------------- close --

    def close(self) -> None:
        """Idempotent shutdown: stop accepting, close every connection,
        then JOIN the event loops and dispatch workers under a deadline
        so callers never race a half-dead server."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        servers = getattr(self.backend, "cql_servers", None)
        if servers is not None and self in servers:
            servers.remove(self)
        remove = getattr(self.backend, "remove_event_listener", None)
        if remove is not None:
            remove(self._on_node_event)
        for knob, cb in self._knob_listeners:
            try:
                self._settings.remove_listener(knob, cb)
            except Exception:
                pass
        try:
            self._listen.close()
        except OSError:
            pass
        self.dispatcher.shutdown()
        for lp in self.event_loops:
            lp.wake()
        import time as _time
        deadline = _time.monotonic() + 5.0
        for t in self.event_loops + self.dispatcher.threads:
            t.join(max(0.0, deadline - _time.monotonic()))

    # ------------------------------------------------------------ framing --

    def _parse(self, conn: Connection) -> None:
        """Drain as many complete envelopes as conn's buffers hold.
        Runs on the owning loop; a framing error answers a PROTOCOL
        error and closes (never a silent hang). Both layers walk a
        cursor and compact ONCE per pass — a del-from-front per
        envelope/segment would memmove the remaining buffer each time,
        quadratic for a client pipelining many small envelopes (the
        same defect class take_chunk's memoryview cursor fixes on the
        write side), and it runs on the shared loop thread."""
        while not conn.closing and not conn.close_when_drained:
            if conn.modern:
                # segment layer: rbuf -> ebuf (envelope bytes)
                rbuf = conn.rbuf
                pos = 0
                err = None
                while len(rbuf) - pos >= 6:
                    try:
                        plen, _sc = decode_segment_header(
                            bytes(rbuf[pos:pos + 6]))
                    except ValueError as e:
                        err = str(e)
                        break
                    if len(rbuf) - pos < 6 + plen + 4:
                        break
                    payload = bytes(rbuf[pos + 6:pos + 6 + plen])
                    crc = rbuf[pos + 6 + plen:pos + 6 + plen + 4]
                    if int.from_bytes(crc, "little") != _crc32_v5(payload):
                        err = "segment payload CRC mismatch"
                        break
                    conn.ebuf += payload
                    pos += 6 + plen + 4
                if pos:
                    del rbuf[:pos]
                if err is not None:
                    self._protocol_error(conn, err)
                    return
                buf = conn.ebuf
            else:
                buf = conn.rbuf
            pos = 0
            progressed = False
            while len(buf) - pos >= 9:
                (length,) = struct.unpack_from(">I", buf, pos + 5)
                if length > MAX_ENVELOPE_BODY:
                    del buf[:pos]
                    self._protocol_error(conn, "envelope too large")
                    return
                if len(buf) - pos < 9 + length:
                    break
                ver_raw, flags, stream, opcode = struct.unpack_from(
                    ">BBhB", buf, pos)
                body = bytes(buf[pos + 9:pos + 9 + length])
                pos += 9 + length
                progressed = True
                self._handle_envelope(conn, ver_raw & 0x7F, flags,
                                      stream, opcode, body)
                if conn.closing or conn.close_when_drained:
                    break
                if conn.modern and buf is conn.rbuf:
                    # STARTUP just switched framing: the rest of rbuf
                    # is segment-framed — stop consuming it as bare
                    # envelopes and let the outer loop re-read it
                    break
            if pos:
                del buf[:pos]
            if not progressed:
                return

    def _protocol_error(self, conn: Connection, msg: str) -> None:
        """A framing-level error: answer PROTOCOL (so the client learns
        WHY, instead of hanging on a dead socket) and close once the
        error has flushed. The stream id is 0 — a corrupt frame has no
        trustworthy stream to echo. The flag goes up BEFORE the enqueue:
        the loop may flush (and must then close) within the send."""
        conn.close_when_drained = True
        # already-buffered input will never be parsed — release it
        conn.rbuf.clear()
        conn.ebuf.clear()
        conn.send_error(0, ERR_PROTOCOL, msg)

    def _handle_envelope(self, conn: Connection, ver: int, flags: int,
                         stream: int, opcode: int, body: bytes) -> None:
        info = self.clients.get(conn.cid)
        if info is not None:
            info["requests"] += 1
        if ver not in SUPPORTED_VERSIONS or ver < self.min_version:
            # reject cleanly (spec: respond with a PROTOCOL error naming
            # the supported versions) and close
            env = encode_envelope(
                0x80 | max(SUPPORTED_VERSIONS), stream, OP_ERROR,
                error_body(ERR_PROTOCOL,
                           f"Invalid or unsupported protocol version "
                           f"({ver}); supported versions are "
                           f"(4/v4, 5/v5)"))
            conn.close_when_drained = True
            conn.enqueue(env)            # always legacy-framed
            return
        if conn.version is None:
            conn.version = ver
        elif ver != conn.version:
            conn.close_when_drained = True
            conn.send_error(stream, ERR_PROTOCOL,
                            "protocol version changed mid-stream")
            return
        if flags & 0x01:
            conn.close_when_drained = True
            conn.send_error(stream, ERR_PROTOCOL,
                            "compression is not supported")
            return
        if opcode in DISPATCH_OPCODES:
            self._admit(conn, stream, opcode, body)
            return
        # handshake / registration: cheap, handled inline on the loop
        try:
            op, rsp = self._dispatch(self.processor, conn,
                                     self._need_auth, self._auth,
                                     opcode, body)
        except Exception as e:
            op, rsp = _error_response(e)
        conn.send_envelope(0x80 | conn.version, stream, op, rsp)
        if opcode == OP_STARTUP and conn.version >= 0x05:
            # STARTUP processed: v5 switches to segment framing (the
            # STARTUP response itself goes out legacy; any auth
            # exchange continues framed)
            conn.modern = True

    # --------------------------------------------------------- admission --

    def _admit(self, conn: Connection, stream: int, opcode: int,
               body: bytes) -> None:
        """All three admission gates, on the event loop. A request that
        cannot be admitted is answered OVERLOADED right now — bounded
        buffers all the way down, no unbounded queueing."""
        from ..service import diagnostics
        if self.rate_limit_ops > 0 and not conn.limiter.try_acquire(1):
            conn.rate_limited += 1
            METRICS.incr("clients.rate_limited_requests")
            diagnostics.publish("transport.overload_shed",
                                reason="rate_limited",
                                address=conn.peer)
            conn.send_error(stream, ERR_OVERLOADED,
                            "Request rate limited "
                            "(native_transport_rate_limit_ops)")
            return
        reason = self.overload.reason()
        if reason is not None:
            METRICS.incr("clients.overload_shed")
            diagnostics.publish("transport.overload_shed",
                                reason=reason[:120],
                                address=conn.peer)
            conn.send_error(stream, ERR_OVERLOADED, reason)
            return
        if not self.permits.try_acquire():
            METRICS.incr("clients.overload_shed")
            diagnostics.publish("transport.overload_shed",
                                reason="permit_cap",
                                address=conn.peer)
            conn.send_error(
                stream, ERR_OVERLOADED,
                f"Maximum concurrent requests "
                f"({self.permits.cap}) reached "
                f"(native_transport_max_concurrent_requests)")
            return
        with conn.wlock:
            conn.in_flight += 1
        self.dispatcher.submit(conn, stream, opcode, body)

    # ------------------------------------------------------------- opcodes

    def _post_auth_checks(self, auth, conn: Connection, user: str) -> None:
        """CIDR + network (datacenter) authorization at connect time
        (auth/CIDRPermissionsManager, CassandraNetworkAuthorizer)."""
        if conn.peer_ip:
            auth.check_cidr(user, conn.peer_ip)
        ep = getattr(self.backend, "endpoint", None)
        if ep is not None:
            auth.check_datacenter(user, ep.dc)

    def _dispatch(self, processor, conn: Connection, need_auth, auth,
                  opcode, body):
        if opcode == OP_OPTIONS:
            return OP_SUPPORTED, struct.pack(">H", 2) + \
                _string("CQL_VERSION") + struct.pack(">H", 1) + \
                _string("3.4.5") + \
                _string("PROTOCOL_VERSIONS") + struct.pack(">H", 2) + \
                _string("4/v4") + _string("5/v5")
        if opcode == OP_STARTUP:
            if need_auth:
                # mutual-TLS path (MutualTlsAuthenticator): a VERIFIED
                # client certificate authenticates by identity mapping
                # without a password exchange
                ident = conn.tls_identity
                if ident is not None and ident in auth.identities:
                    # mapped identity: cert authenticates; an UNMAPPED
                    # cert falls through to the password exchange
                    # (optional-mTLS upgrade path)
                    try:
                        user = auth.authenticate_identity(ident)
                        self._post_auth_checks(auth, conn, user)
                    except Exception as e:
                        return OP_ERROR, error_body(ERR_BAD_CREDENTIALS,
                                                    str(e))
                    conn.user = user
                    conn.authed = True
                    return OP_READY, b""
                return OP_AUTHENTICATE, _string(
                    "org.apache.cassandra.auth.PasswordAuthenticator")
            conn.authed = True
            return OP_READY, b""
        if opcode == OP_AUTH_RESPONSE:
            token, _ = _read_bytes(body, 0)
            parts = (token or b"").split(b"\x00")
            if len(parts) >= 3:
                user, pw = parts[1].decode(), parts[2].decode()
                try:
                    auth.authenticate(user, pw)
                    self._post_auth_checks(auth, conn, user)
                except Exception:
                    return OP_ERROR, error_body(ERR_BAD_CREDENTIALS,
                                                "bad credentials")
                conn.user = user
                conn.authed = True
                return OP_AUTH_SUCCESS, _bytes(None)
            return OP_ERROR, error_body(ERR_BAD_CREDENTIALS,
                                        "malformed SASL token")
        if not conn.authed:
            return OP_ERROR, error_body(ERR_PROTOCOL, "STARTUP required")
        if opcode == OP_REGISTER:
            (n,) = struct.unpack_from(">H", body, 0)
            pos = 2
            for _ in range(n):
                etype, pos = _read_string(body, pos)
                if etype not in EVENT_TYPES:
                    return OP_ERROR, error_body(
                        ERR_PROTOCOL, f"unknown event type {etype!r}")
                conn.registrations.add(etype)
            with self._conn_lock:
                self._event_conns.add(conn)
            return OP_READY, b""
        if opcode == OP_QUERY:
            query, pos = _read_long_string(body, 0)
            return self._run(processor, conn, query, body, pos)
        if opcode == OP_PREPARE:
            query, pos = _read_long_string(body, 0)
            if conn.version >= 0x05 and pos < len(body):
                (_pflags,) = struct.unpack_from(">I", body, pos)  # keyspace
            qid, prep = processor.prepare_full(query)
            n_binds = getattr(prep.statement, "n_markers", 0)
            rsp = bytearray()
            rsp += struct.pack(">i", RESULT_PREPARED)
            rsp += struct.pack(">H", len(qid)) + qid
            if conn.version >= 0x05:
                # result_metadata_id (short bytes): stable per statement
                rsp += struct.pack(">H", len(qid)) + qid
            # bind metadata: declared as BLOB — the server deserializes
            # wire bytes against the real column type at bind time, so
            # clients pass pre-serialized values (documented subset)
            rsp += struct.pack(">Ii", 0x0001, n_binds)   # flags, count
            rsp += struct.pack(">i", 0)                   # pk_count
            rsp += _string("") + _string("")              # global spec
            for i in range(n_binds):
                rsp += _string(f"p{i}") + struct.pack(">H", 0x03)
            # result metadata: clients re-read it from each RESULT
            rsp += struct.pack(">Ii", 0, 0)
            return OP_RESULT, bytes(rsp)
        if opcode == OP_EXECUTE:
            (n,) = struct.unpack_from(">H", body, 0)
            qid = bytes(body[2:2 + n])
            pos = 2 + n
            if conn.version >= 0x05:
                # v5 EXECUTE carries the result_metadata_id
                (mn,) = struct.unpack_from(">H", body, pos)
                pos += 2 + mn
            prep = processor.get_prepared(qid)
            if prep is None:
                # evicted or never prepared: the UNPREPARED error tells
                # drivers to re-PREPARE and retry (spec §9 / 0x2500)
                return OP_ERROR, unprepared_body(qid)
            return self._run(processor, conn, None, body, pos, prep=prep)
        return OP_ERROR, error_body(ERR_PROTOCOL,
                                    f"unsupported opcode {opcode}")

    def _run(self, processor, conn: Connection, query, body: bytes,
             pos: int, prep=None):
        import time as time_mod
        consistency, = struct.unpack_from(">H", body, pos)
        pos += 2
        if conn.version >= 0x05:          # v5 widened flags to [int]
            (flags,) = struct.unpack_from(">I", body, pos)
            pos += 4
        else:
            flags = body[pos]
            pos += 1
        params: tuple = ()
        page_size = None
        paging_state = None
        if flags & 0x01:                 # values
            (nv,) = struct.unpack_from(">H", body, pos)
            pos += 2
            vals = []
            for _ in range(nv):
                b, pos = _read_bytes(body, pos)
                vals.append(None if b is None else WireValue(b))
            params = tuple(vals)
        if flags & 0x04:                 # page_size
            (page_size,) = struct.unpack_from(">i", body, pos)
            pos += 4
        if flags & 0x08:                 # paging_state
            paging_state, pos = _read_bytes(body, pos)
        # per-verb client-request latency (ClientRequestMetrics role):
        # SELECTs are reads, everything else mutates
        if prep is not None:
            is_read = type(prep.statement).__name__ == "SelectStatement"
        else:
            is_read = query.lstrip()[:6].upper() == "SELECT"
        t0 = time_mod.perf_counter()
        if prep is not None:   # EXECUTE: resolved statement, no re-parse
            rs = processor.execute_statement(
                prep, params, conn.keyspace, user=conn.user,
                page_size=page_size, paging_state=paging_state)
        else:
            rs = processor.process(query, params, conn.keyspace,
                                   user=conn.user,
                                   page_size=page_size,
                                   paging_state=paging_state)
        us = (time_mod.perf_counter() - t0) * 1e6
        verb = "read" if is_read else "write"
        # the per-CL tag uses the level the client DECLARED, so a
        # saturation-matrix breach attributes to ONE vs QUORUM instead
        # of blending them; a code outside the spec table lands in an
        # explicit "unknown" bucket, never mis-attributed to a real CL
        cl = CONSISTENCY_NAMES.get(consistency, "unknown")
        # blended hist (the historical surface + default SLO objective)
        # AND the per-CL family the matrix attributes breaches through
        METRICS.hist(f"client_requests.{verb}").update_us(us)
        METRICS.hist(f"client_requests.{verb}.{cl}").update_us(us)
        new_ks = getattr(rs, "keyspace", None)
        if new_ks is not None:
            conn.keyspace = new_ks
            return OP_RESULT, struct.pack(">i", RESULT_SET_KEYSPACE) \
                + _string(new_ks)
        if not rs.column_names:
            return OP_RESULT, struct.pack(">i", RESULT_VOID)
        return OP_RESULT, _encode_rows(rs)
