"""Chunk cache: decoded-segment LRU shared by every reader.

Reference counterpart: cache/ChunkCache.java:46 (the off-heap cache in
front of chunk decompression). Here the cached unit is a DECODED segment
CellBatch — caching after decompression+decode saves both the pread and
the codec pass, which profiling showed dominate point-read latency.

Entries key on (sstable path, generation, segment). Cached batches are
treated as immutable by every consumer (merge paths concat/permute into
fresh arrays before any mutation); `flags.setflags(write=False)` guards
the contract in debug use.

Capacity is bytes-bounded with LRU eviction; a table-dropping truncate or
compaction leaves stale entries that simply age out (keys are
generation-scoped so they can never be served for new data).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

DEFAULT_CAPACITY = 128 << 20    # 128 MiB, cassandra.yaml file_cache_size


class ChunkCache:
    def __init__(self, capacity_bytes: int = DEFAULT_CAPACITY):
        self.capacity = capacity_bytes
        self._lru: OrderedDict = OrderedDict()
        self._sizes: dict = {}
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _size_of(batch) -> int:
        return int(batch.lanes.nbytes + batch.ts.nbytes + batch.ldt.nbytes
                   + batch.ttl.nbytes + batch.flags.nbytes
                   + batch.off.nbytes + batch.val_start.nbytes
                   + batch.payload.nbytes)

    def get(self, key):
        with self._lock:
            batch = self._lru.get(key)
            if batch is None:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return batch

    def put(self, key, batch) -> None:
        size = self._size_of(batch)
        if size > self.capacity:
            return
        with self._lock:
            if key in self._lru:
                # replace: an existing entry may be getting swapped for
                # a repaired copy (reader ck_comp fix-up) — the atomic
                # reference swap is safe for concurrent readers holding
                # the old object
                self._bytes -= self._sizes[key]
            self._lru[key] = batch
            self._lru.move_to_end(key)
            self._sizes[key] = size
            self._bytes += size
            while self._bytes > self.capacity and self._lru:
                k, _ = self._lru.popitem(last=False)
                self._bytes -= self._sizes.pop(k)

    def invalidate_generation(self, directory: str, generation: int):
        """Drop a dead sstable's entries eagerly (truncate path)."""
        with self._lock:
            dead = [k for k in self._lru
                    if k[0] == directory and k[1] == generation]
            for k in dead:
                del self._lru[k]
                self._bytes -= self._sizes.pop(k)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._lru), "bytes": self._bytes,
                    "capacity": self.capacity, "hits": self.hits,
                    "misses": self.misses}

    def clear(self) -> None:
        """nodetool invalidatechunkcache."""
        with self._lock:
            self._lru.clear()
            self._sizes.clear()
            self._bytes = 0


GLOBAL = ChunkCache()
