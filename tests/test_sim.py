"""Deterministic simulation (cassandra_tpu/sim): virtual time + a
seeded event queue own every message delivery, timeout, retry sleep and
background tick — so a distributed scenario REPLAYS byte-for-byte from
its seed, and interleaving space is explored by sweeping seeds.

Reference role: test/simulator (InterceptClasses.java achieves this via
bytecode interception; here it falls out of construction — one pumping
thread, scheduler-owned nondeterminism).

The scenario under test is round 4's shipped failure: a CMS metadata
commit racing a partition heal (VERDICT r4 Weak #1) — the exact class
of timing seam a deterministic scheduler exists to pin down.
"""
import pytest

from cassandra_tpu.cluster.cms import MetadataUnavailable
from cassandra_tpu.sim import SimCluster, simulated


def _cms_heal_scenario(tmp_path, seed, tag):
    """Partition the lexically-first CMS member mid-stream, commit DDL
    on the majority DURING the partition, heal, and let anti-entropy
    converge the straggler. Returns (trace, epochs, logs)."""
    with simulated(seed) as sched:
        c = SimCluster(sched, str(tmp_path / f"{tag}"), n=3)
        try:
            s1 = c.session(1)
            s1.execute("CREATE KEYSPACE ks WITH replication = "
                       "{'class': 'SimpleStrategy', "
                       "'replication_factor': 3}")
            sched.run(1.0)
            # cut node1 (a CMS member) off
            rules = c.partition(c.eps[0])
            sched.run(2.0)     # let conviction land
            # the majority commits DURING the partition
            s2 = c.session(2)
            s2.execute("CREATE TABLE ks.during (k int PRIMARY KEY)")
            # the minority must refuse (no quorum)
            with pytest.raises(MetadataUnavailable):
                c.session(1).execute(
                    "CREATE TABLE ks.minority (k int PRIMARY KEY)")
            # heal: the races between liveness restoration, the healed
            # node's pull retries, gossip epoch anti-entropy and fresh
            # commits are exactly what the seed explores
            for r in rules:
                r["remaining"] = 0
            s2.execute("CREATE TABLE ks.racing (k int PRIMARY KEY)")
            sched.run(8.0)
            epochs = [n.schema_sync.epoch for n in c.nodes]
            logs = [n.schema_sync.entries_after(0) for n in c.nodes]
            return list(sched.trace), epochs, logs
        finally:
            c.shutdown()


def test_replay_is_byte_for_byte(tmp_path):
    """Same seed, same scenario, twice: the event traces — every
    delivery, timeout and tick, with virtual timestamps — must be
    IDENTICAL. This is the property that makes a seed a reproducer."""
    t1, e1, _ = _cms_heal_scenario(tmp_path, seed=1234, tag="a")
    t2, e2, _ = _cms_heal_scenario(tmp_path, seed=1234, tag="b")
    assert e1 == e2
    assert len(t1) == len(t2)
    assert t1 == t2, next(
        (i, a, b) for i, (a, b) in enumerate(zip(t1, t2)) if a != b)


def test_seeds_change_interleavings(tmp_path):
    """Different seeds must actually explore different delivery orders
    (otherwise the sweep below proves nothing)."""
    t1, _, _ = _cms_heal_scenario(tmp_path, seed=1, tag="s1")
    t2, _, _ = _cms_heal_scenario(tmp_path, seed=2, tag="s2")
    assert t1 != t2


@pytest.mark.parametrize("seed", [7, 77, 777, 7777, 77777])
def test_cms_heal_race_invariants_across_seeds(tmp_path, seed):
    """Sweep interleavings of the CMS-vs-heal race: under EVERY seed the
    cluster converges to ONE log — same epochs, identical entry
    sequences, client-acked DDL present everywhere, no fork."""
    _, epochs, logs = _cms_heal_scenario(tmp_path, seed, tag=f"s{seed}")
    assert len(set(epochs)) == 1, f"seed {seed}: epochs diverged {epochs}"
    assert all(lg == logs[0] for lg in logs[1:]), \
        f"seed {seed}: log fork"
    committed = {q for _, q, *_ in logs[0]} if logs[0] and \
        len(logs[0][0]) > 2 else set()
    texts = " ".join(str(e) for e in logs[0])
    assert "during" in texts and "racing" in texts, \
        f"seed {seed}: client-acked DDL missing from the log"


def _executor_harry_state(tmp_path, seed, tag):
    """A seeded harry op stream where flush-triggered compactions run
    through the engine's CompactionManager -> CompactionExecutor in
    SYNCHRONOUS inline mode (run_pending). Returns a fingerprint of the
    quiescent storage state: per-sstable (cells, digest) plus row count.
    """
    import os

    from cassandra_tpu.tools.harry import OpGenerator

    with simulated(seed) as sched:
        c = SimCluster(sched, str(tmp_path / tag), n=3)
        try:
            s = c.session(1)
            node = c.node(1)
            s.execute("CREATE KEYSPACE ex WITH replication = "
                      "{'class': 'SimpleStrategy', "
                      "'replication_factor': 3}")
            s.execute("USE ex")
            s.execute("CREATE TABLE t (k int, c int, v text, w int, "
                      "st text static, m map<text,int>, "
                      "PRIMARY KEY (k, c))")
            sched.run(1.0)
            gen = OpGenerator(seed)
            eng = node.engine
            cfs = eng.store("ex", "t")
            for op in gen:
                if op.index >= 250:
                    break
                if op.kind == "advance":
                    sched.run(op.seconds)
                elif op.kind == "flush":
                    cfs.flush()
                elif op.kind == "compact":
                    # the executor's synchronous mode: deterministic,
                    # runs on this (pumping) thread
                    eng.compactions.run_pending()
                else:
                    s.execute(op.cql("t"))
            cfs.flush()
            eng.compactions.major_compaction(cfs)
            state = []
            for sst in sorted(cfs.live_sstables(),
                              key=lambda r: r.n_cells):
                with open(sst.desc.path("Digest.crc32")) as f:
                    state.append((sst.n_cells, f.read().strip()))
            nrows = len(cfs.scan_all())
            assert eng.compactions.compacting_generations(cfs) == set()
            return state, nrows
        finally:
            c.shutdown()


def test_executor_sync_mode_keeps_sim_deterministic(tmp_path):
    """Same seed, same harry stream, compactions routed through the
    CompactionExecutor's synchronous mode: the resulting storage state
    (sstable digests + logical rows) must be IDENTICAL across runs —
    the property that keeps executor-era compaction simulable."""
    s1, n1 = _executor_harry_state(tmp_path, 31337, "a")
    s2, n2 = _executor_harry_state(tmp_path, 31337, "b")
    assert s1 == s2
    assert n1 == n2
    assert s1, "no sstables produced — scenario under-exercised storage"


def test_harry_stream_under_simulation(tmp_path):
    """A seeded harry op stream against a simulated 3-node cluster with
    periodic MUTATION drops: hints replay on virtual time, and the
    quiescent state must match the model — the harry-under-simulator
    role, now with a deterministic schedule."""
    from cassandra_tpu.cluster.messaging import Verb
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    from cassandra_tpu.tools.harry import Model, OpGenerator, \
        check_partition
    from cassandra_tpu.utils import timeutil

    with simulated(424242) as sched:
        c = SimCluster(sched, str(tmp_path), n=3)
        try:
            s = c.session(1)
            node = c.node(1)
            node.default_cl = ConsistencyLevel.QUORUM
            s.execute("CREATE KEYSPACE fz WITH replication = "
                      "{'class': 'SimpleStrategy', "
                      "'replication_factor': 3}")
            s.execute("USE fz")
            s.execute("CREATE TABLE t (k int, c int, v text, w int, "
                      "st text static, m map<text,int>, "
                      "PRIMARY KEY (k, c))")
            sched.run(1.0)
            gen = OpGenerator(424242)
            model = Model()
            dropping = None
            for op in gen:
                if op.index >= 400:
                    break
                if op.index % 100 == 40:
                    victim = c.nodes[1 + (op.index // 100) % 2]
                    dropping = c.filters.drop(verb=Verb.MUTATION_REQ,
                                              to=victim.endpoint)
                if op.index % 100 == 90 and dropping is not None:
                    dropping["remaining"] = 0
                    dropping = None
                if op.kind == "advance":
                    sched.run(op.seconds)
                elif op.kind == "flush":
                    node.engine.store("fz", "t").flush()
                elif op.kind == "compact":
                    from cassandra_tpu.compaction.task import \
                        CompactionTask
                    cfs = node.engine.store("fz", "t")
                    inputs = list(cfs.live_sstables())
                    if len(inputs) >= 2:
                        CompactionTask(cfs, inputs,
                                       engine="numpy").execute()
                else:
                    s.execute(op.cql("t"))
                model.apply(op, now_s=timeutil.now_seconds())
            if dropping is not None:
                dropping["remaining"] = 0
            sched.run(10.0)     # hints replay on virtual time
            node.default_cl = ConsistencyLevel.ALL
            for pk in range(gen.n_pks):
                check_partition(s, model, "t", pk, 424242, 400,
                                now=timeutil.now_seconds())
        finally:
            c.shutdown()
