// Host merge engine: k-way streaming merge + inline reconcile of sorted
// CellBatch runs — the CompactionIterator formulation
// (db/compaction/CompactionIterator.java:90, utils/MergeIterator.java:23)
// compiled to native code for the host execution path. The numpy
// implementation (storage/cellbatch.py reconcile) is the executable spec;
// randomized tests require bit-identical outputs from numpy, this engine,
// and the TPU kernel.
//
// Inputs: the CONCATENATED batch arrays plus run boundaries. Every run
// must already be sorted by identity lanes asc then ts desc (flush output
// and sstable segments are). Within a cell run (equal identity), the
// winner is selected by the full Cells.resolveRegular comparator, so the
// runs' internal ordering beyond (identity, ts) does not matter. Counter
// batches are handled by the caller (python falls back to the numpy
// path; counters are rare).
//
// Output: indices (into the concatenated arrays) of KEPT cells in merged
// order, plus a per-kept flag marking expired-TTL cells the caller must
// convert to tombstones (AbstractCell.purge path).

#include <cstdint>
#include <cstring>

extern "C" {

// must match storage/cellbatch.py / schema.py
static const uint8_t F_TOMBSTONE = 1;
static const uint8_t F_EXPIRING = 2;
static const uint8_t F_PARTITION_DEL = 4;
static const uint8_t F_ROW_DEL = 8;
static const uint8_t F_COMPLEX_DEL = 32;
static const uint8_t F_DEATH =
    F_TOMBSTONE | F_PARTITION_DEL | F_ROW_DEL | F_COMPLEX_DEL;
static const uint32_t COL_PARTITION_DEL_ID = 0;
static const uint32_t COL_ROW_DEL_ID = 1;
static const int64_t TS_NEG_INF = INT64_MIN;

struct View {
    const uint32_t* lanes;    // [n, K] native-endian
    const int64_t* ts;
    const int32_t* ldt;
    const uint8_t* flags;
    const int64_t* off;       // [n+1]
    const int64_t* val_start; // [n]
    const uint8_t* payload;
    int64_t K;
};

static inline int cmp_lanes(const View& v, int64_t a, int64_t b) {
    const uint32_t* pa = v.lanes + a * v.K;
    const uint32_t* pb = v.lanes + b * v.K;
    for (int64_t k = 0; k < v.K; k++) {
        if (pa[k] != pb[k]) return pa[k] < pb[k] ? -1 : 1;
    }
    return 0;
}

// merge-order comparator between runs: identity lanes asc, ts desc.
// (equality in both -> caller keeps lower run index: stability)
static inline bool stream_less(const View& v, int64_t a, int64_t b) {
    int c = cmp_lanes(v, a, b);
    if (c) return c < 0;
    return v.ts[a] > v.ts[b];
}

// winner ranking within a cell run — Cells.resolveRegular
// (db/rows/Cells.java:79, CASSANDRA-14592): newest ts, then
// expiring-or-tombstone over live, pure tombstone over expiring, larger
// localDeletionTime, larger value bytes, then first-seen. "Pure
// tombstone" is the STATIC isTombstone property (death flag, NO ttl):
// an expired cell converted to a tombstone keeps F_EXPIRING, so its
// rank is identical before and after conversion — clock-independent.
static inline bool beats(const View& v, int64_t a, int64_t b) {
    if (v.ts[a] != v.ts[b]) return v.ts[a] > v.ts[b];
    uint8_t fa = v.flags[a], fb = v.flags[b];
    bool ea = (fa & (F_DEATH | F_EXPIRING)) != 0;
    bool eb = (fb & (F_DEATH | F_EXPIRING)) != 0;
    if (ea != eb) return ea;
    bool da = (fa & F_DEATH) != 0 && (fa & F_EXPIRING) == 0;
    bool db = (fb & F_DEATH) != 0 && (fb & F_EXPIRING) == 0;
    if (da != db) return da;
    if (v.ldt[a] != v.ldt[b]) return v.ldt[a] > v.ldt[b];
    int64_t la = v.off[a + 1] - v.val_start[a];
    int64_t lb = v.off[b + 1] - v.val_start[b];
    int64_t m = la < lb ? la : lb;
    int r = m ? memcmp(v.payload + v.val_start[a],
                       v.payload + v.val_start[b], (size_t)m) : 0;
    if (r) return r > 0;
    if (la != lb) return la > lb;
    return false;                      // full tie: first-seen stays
}

// merge_reconcile: returns number of kept cells (indices written to
// out_idx in merged order; out_expired[i]=1 marks a kept expired-TTL
// cell). run_starts has n_runs+1 entries delimiting the concatenated
// arrays. pts: per-cell max-purgeable timestamp (NULL = +inf), indexed
// like the concatenated arrays. Returns -1 on invalid input.
int64_t merge_reconcile(
    const uint32_t* lanes, const int64_t* ts, const int32_t* ldt,
    const uint8_t* flags, const int64_t* off, const int64_t* val_start,
    const uint8_t* payload, int64_t K, const int64_t* run_starts,
    int64_t n_runs, const int64_t* pts, int64_t gc_before, int64_t now,
    int64_t* out_idx, uint8_t* out_expired) {
    View v{lanes, ts, ldt, flags, off, val_start, payload, K};
    int64_t head[64];
    if (n_runs > 64 || n_runs < 1 || K < 9) return -1;
    for (int64_t r = 0; r < n_runs; r++) head[r] = run_starts[r];

    // reconcile state, carried across the single merged stream. The
    // invariants mirror the numpy scan: rd_ts = max(row deletion, pd),
    // cd_ts = max(complex deletion of this column, rd_ts).
    int64_t pd_ts = TS_NEG_INF;
    int64_t rd_ts = TS_NEG_INF;
    int64_t cd_ts = TS_NEG_INF;
    int64_t cand = -1;                 // current cell run's winner so far
    int64_t n_out = 0;

    const int64_t C = K - 9;
    const int64_t ROW_LANES = 4 + C + 2;  // partition + ck prefix + ckh
    const int64_t COL_LANE = 6 + C;

    // emit the completed cell run's winner: evaluate shadowing/purge with
    // the state of its scopes, then fold deletion markers (winner-only
    // folds — a losing duplicate marker must not shadow anything, exactly
    // like the numpy pd_lead/rd_lead/cd_lead winner masks)
    auto emit = [&](int64_t c) {
        uint32_t col = lanes[c * K + COL_LANE];
        uint8_t fl = flags[c];
        int64_t t = ts[c];
        bool shadowed;
        if (col == COL_PARTITION_DEL_ID) {
            shadowed = false;          // nothing outranks it in-partition
            if (t > pd_ts) {
                pd_ts = t;
                if (rd_ts < t) rd_ts = t;
                if (cd_ts < t) cd_ts = t;
            }
        } else if (col == COL_ROW_DEL_ID) {
            shadowed = t <= pd_ts;
            if (t > rd_ts) {
                rd_ts = t;
                if (cd_ts < t) cd_ts = t;
            }
        } else if (fl & F_COMPLEX_DEL) {
            shadowed = t <= rd_ts;
            if (t > cd_ts) cd_ts = t;
        } else {
            shadowed = t <= cd_ts;
        }
        bool expired = (fl & F_EXPIRING) && ldt[c] <= now;
        bool death = (fl & F_DEATH) != 0 || expired;
        bool purgeable = pts == NULL || t < pts[c];
        bool purged = death && ldt[c] < gc_before && purgeable;
        if (!shadowed && !purged) {
            out_idx[n_out] = c;
            out_expired[n_out] = expired ? 1 : 0;
            n_out++;
        }
    };

    for (;;) {
        int64_t best_run = -1, best = -1;
        for (int64_t r = 0; r < n_runs; r++) {
            if (head[r] >= run_starts[r + 1]) continue;
            if (best_run < 0 || stream_less(v, head[r], best)) {
                best_run = r;
                best = head[r];
            }
        }
        if (best_run < 0) break;
        head[best_run]++;
        int64_t i = best;

        if (cand < 0) {                // very first cell
            cand = i;
            continue;
        }
        const uint32_t* pi = lanes + i * K;
        const uint32_t* pc = lanes + cand * K;
        bool part_new = memcmp(pi, pc, 4 * sizeof(uint32_t)) != 0;
        bool row_new = part_new ||
            memcmp(pi + 4, pc + 4,
                   (size_t)(ROW_LANES - 4) * sizeof(uint32_t)) != 0;
        bool col_new = row_new || pi[COL_LANE] != pc[COL_LANE];
        bool cell_new = col_new ||
            memcmp(pi + COL_LANE + 1, pc + COL_LANE + 1,
                   (size_t)(K - COL_LANE - 1) * sizeof(uint32_t)) != 0;

        if (!cell_new) {               // same cell: compete for winner
            if (beats(v, i, cand)) cand = i;
            continue;
        }
        emit(cand);
        if (part_new) {
            pd_ts = TS_NEG_INF; rd_ts = TS_NEG_INF; cd_ts = TS_NEG_INF;
        } else if (row_new) {
            rd_ts = pd_ts; cd_ts = pd_ts;
        } else if (col_new) {
            cd_ts = rd_ts;
        }
        cand = i;
    }
    if (cand >= 0) emit(cand);
    return n_out;
}

}  // extern "C"
