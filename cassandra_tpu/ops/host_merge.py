"""Host merge engine binding: C++ k-way streaming merge + inline
reconcile (ops/native/merge.cpp) for sorted CellBatch runs.

This is the host-side counterpart of the TPU kernel (ops/merge.py) —
the CompactionIterator formulation (db/compaction/CompactionIterator.java
:90) in native code. The compaction task picks an engine per the measured
environment: the TPU kernel when the device link sustains it, this engine
when the link is latency/bandwidth-bound (e.g. a tunneled chip), numpy as
the always-available executable spec.

Falls back to the numpy merge when a batch is unsorted, contains counter
cells (commutative-sum reconcile lives in numpy), or the native library
is unavailable.
"""
from __future__ import annotations

import ctypes

import numpy as np

from ..storage import cellbatch as cb
from ..storage.cellbatch import (FLAG_COUNTER, FLAG_RANGE_BOUND,
                                 FLAG_TOMBSTONE, CellBatch)


_lib = None
_lib_checked = False


def available() -> bool:
    global _lib, _lib_checked
    if not _lib_checked:
        _lib_checked = True
        try:
            from .native import build as native_build
            _lib = native_build.load()
        except Exception:
            _lib = None
    return _lib is not None


class LazyMergedBatch:
    """A native merge result whose GATHER (permutation materialization
    — the biggest single producer-thread cost after decode) has not run
    yet. The compaction write loop materializes it on the WRITER
    thread, so round k's gather overlaps round k+1's decode + merge —
    a pipeline rebalance, not a semantic change: the wq drains FIFO on
    one thread, so materialization order equals merge order and output
    bytes are untouched."""

    __slots__ = ("cat", "out_idx", "out_exp", "n_out", "prof")

    def __init__(self, cat, out_idx, out_exp, n_out, prof):
        self.cat = cat
        self.out_idx = out_idx
        self.out_exp = out_exp
        self.n_out = n_out
        self.prof = prof

    def __len__(self) -> int:
        return self.n_out

    def materialize(self) -> CellBatch:
        import time as _time
        t0 = _time.perf_counter()
        out = self.cat.apply_permutation(self.out_idx[:self.n_out])
        out.sorted = True
        converted = self.out_exp[:self.n_out].astype(bool)
        if converted.any():
            out.flags[converted] |= FLAG_TOMBSTONE
            out = out.drop_values(converted)
        if self.prof is not None:
            # single-writer key: only the materializing thread bills
            # 'gather' once deferral is on
            self.prof["gather"] = self.prof.get("gather", 0.0) \
                + (_time.perf_counter() - t0)
        self.cat = None   # drop the concat refs as soon as gathered
        return out


def merge_sorted_native(batches: list[CellBatch], gc_before: int = 0,
                        now: int = 0, purgeable_ts_fn=None,
                        prof: dict | None = None,
                        defer_gather: bool = False) -> CellBatch:
    """Drop-in equivalent of storage.cellbatch.merge_sorted running the
    merge/reconcile in C++. Requires every batch sorted; counter tables
    fall back to numpy. defer_gather=True returns a LazyMergedBatch
    (same length) whose materialize() runs the output gather — the
    compaction pipeline calls it from the writer thread."""
    import time as _time

    batches = [b for b in batches if len(b)]
    if not batches:
        return CellBatch.empty()
    if not available() or len(batches) > 64 \
            or not all(b.sorted for b in batches) \
            or any((b.flags & (FLAG_COUNTER | FLAG_RANGE_BOUND)).any()
                   for b in batches):
        return cb.merge_sorted(batches, gc_before=gc_before, now=now,
                               purgeable_ts_fn=purgeable_ts_fn)

    t0 = _time.perf_counter()
    cat = CellBatch.concat(batches)
    n = len(cat)
    run_starts = np.zeros(len(batches) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in batches], out=run_starts[1:])

    pts = None
    t1 = _time.perf_counter()
    if purgeable_ts_fn is not None:
        pts = np.ascontiguousarray(purgeable_ts_fn(cat), dtype=np.int64)
    t2 = _time.perf_counter()

    lanes = np.ascontiguousarray(cat.lanes, dtype=np.uint32)
    ts = np.ascontiguousarray(cat.ts, dtype=np.int64)
    ldt = np.ascontiguousarray(cat.ldt, dtype=np.int32)
    flags = np.ascontiguousarray(cat.flags, dtype=np.uint8)
    off = np.ascontiguousarray(cat.off, dtype=np.int64)
    val_start = np.ascontiguousarray(cat.val_start, dtype=np.int64)
    payload = np.ascontiguousarray(cat.payload, dtype=np.uint8)

    out_idx = np.empty(n, dtype=np.int64)
    out_exp = np.empty(n, dtype=np.uint8)

    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    n_out = _lib.merge_reconcile(
        lanes.ctypes.data_as(u32p), ts.ctypes.data_as(i64p),
        ldt.ctypes.data_as(i32p), flags.ctypes.data_as(u8p),
        off.ctypes.data_as(i64p), val_start.ctypes.data_as(i64p),
        payload.ctypes.data_as(u8p), cat.n_lanes,
        run_starts.ctypes.data_as(i64p), len(batches),
        pts.ctypes.data_as(i64p) if pts is not None else None,
        gc_before, now, out_idx.ctypes.data_as(i64p),
        out_exp.ctypes.data_as(u8p))
    if n_out < 0:
        raise RuntimeError("native merge_reconcile failed")
    t3 = _time.perf_counter()
    if prof is not None:
        prof["purge_fn"] = prof.get("purge_fn", 0.0) + (t2 - t1)
        prof["pack"] = prof.get("pack", 0.0) + (t1 - t0)
        prof["native_merge"] = prof.get("native_merge", 0.0) + (t3 - t2)

    lazy = LazyMergedBatch(cat, out_idx, out_exp, int(n_out), prof)
    if defer_gather:
        return lazy
    return lazy.materialize()
