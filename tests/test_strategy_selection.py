"""Strategy-selection pins: LeveledCompactionStrategy level overflow,
TimeWindowCompactionStrategy window grouping + fully-expired drop —
the `next_background_task` behaviors ROADMAP item 3's adaptive layer
will build on (reference models: LeveledCompactionStrategyTest,
TimeWindowCompactionStrategyTest.testDropExpiredSSTables)."""
import time

import pytest

from cassandra_tpu.compaction.strategies import (
    LeveledCompactionStrategy, TimeWindowCompactionStrategy,
    UnifiedCompactionStrategy, get_strategy)
from cassandra_tpu.schema import (COL_ROW_LIVENESS, Schema, TableParams,
                                  make_table)
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.mutation import Mutation
from cassandra_tpu.utils import timeutil


def new_engine(tmp_path, compaction=None, gc_grace=864000):
    schema = Schema()
    schema.create_keyspace("ks")
    params = TableParams(gc_grace_seconds=gc_grace)
    if compaction:
        params.compaction = compaction
    t = make_table("ks", "t", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "text"},
                   params=params)
    schema.add_table(t)
    eng = StorageEngine(str(tmp_path / "data"), schema,
                        commitlog_sync="batch")
    return eng, t, eng.store("ks", "t")


def put(eng, t, p, c, v, ts=None):
    m = Mutation(t.id, t.columns["id"].cql_type.serialize(p))
    ck = t.serialize_clustering([c])
    ts = ts or timeutil.now_micros()
    m.add(ck, COL_ROW_LIVENESS, b"", b"", ts)
    m.add(ck, t.columns["v"].column_id, b"",
          t.columns["v"].cql_type.serialize(v), ts)
    eng.apply(m)


def put_dead(eng, t, p, c, ts, ldt):
    """A cell tombstone (the shape a TTL'd cell takes once a merge past
    its expiry converted it)."""
    from cassandra_tpu.storage.cellbatch import FLAG_TOMBSTONE
    m = Mutation(t.id, t.columns["id"].cql_type.serialize(p))
    ck = t.serialize_clustering([c])
    m.add(ck, t.columns["v"].column_id, b"", b"", ts, ldt=ldt,
          flags=FLAG_TOMBSTONE)
    eng.apply(m)


def test_lcs_level_overflow_promotes_one_victim(tmp_path):
    """A level above its byte target pushes its LARGEST sstable into
    the next level, merged with every overlapping run there — the
    LeveledManifest overflow rule (no L0 backlog involved)."""
    eng, t, cfs = new_engine(
        tmp_path,
        compaction={"class": "LeveledCompactionStrategy",
                    # tiny level target so two small flushes overflow L1
                    "sstable_size_in_mb": 0.001, "fanout_size": 2,
                    "l0_threshold": 4})
    for gen in range(3):
        for p in range(40):
            put(eng, t, p + gen * 40, 0, "x" * 120)
        cfs.flush()
    # pin the flushed sstables to L1/L2 by rewriting their level stats
    ssts = sorted(cfs.live_sstables(), key=lambda s: s.desc.generation)
    for s, lvl in zip(ssts, (1, 1, 2)):
        s.stats["level"] = lvl
    strat = LeveledCompactionStrategy(
        cfs, {"sstable_size_in_mb": 0.001, "fanout_size": 2,
              "l0_threshold": 4})
    task = strat.next_background_task()
    assert task is not None
    # the victim is the LARGEST L1 sstable; every overlapping L2 run
    # rides along; the output lands one level down
    victim = max((s for s in ssts if s.level == 1),
                 key=lambda s: s.data_size)
    assert victim in task.inputs
    assert task.level == 2
    assert all(s.level in (1, 2) for s in task.inputs)
    l2 = [s for s in ssts if s.level == 2]
    overlapping = [s for s in l2
                   if s.min_token() <= victim.max_token()
                   and victim.min_token() <= s.max_token()]
    assert set(overlapping) <= set(task.inputs)
    # output-size cap carries the strategy's shard target
    assert task.max_output_bytes == strat.max_sstable_bytes
    eng.close()


def test_lcs_no_task_when_levels_fit(tmp_path):
    eng, t, cfs = new_engine(
        tmp_path,
        compaction={"class": "LeveledCompactionStrategy",
                    "sstable_size_in_mb": 160, "l0_threshold": 4})
    for p in range(20):
        put(eng, t, p, 0, "v")
    cfs.flush()
    assert get_strategy(cfs).next_background_task() is None
    eng.close()


def test_twcs_window_grouping_current_vs_old(tmp_path):
    """One sstable per OLD window is the goal: any old window holding
    more than one sstable is compacted first; the CURRENT window runs
    STCS and only compacts at min_threshold."""
    eng, t, cfs = new_engine(
        tmp_path,
        compaction={"class": "TimeWindowCompactionStrategy",
                    "compaction_window_unit": "HOURS",
                    "compaction_window_size": 1})
    now_us = timeutil.now_micros()
    hour = 3600 * 1_000_000
    # current window: 3 sstables (below min_threshold=4 -> untouched)
    for i in range(3):
        put(eng, t, i, 0, f"cur{i}", ts=now_us + i)
        cfs.flush()
    strat = get_strategy(cfs)
    assert strat.next_background_task() is None
    # an old window accumulates 2 sstables -> grouped into one task
    for i in range(2):
        put(eng, t, 10 + i, 0, f"old{i}", ts=now_us - 7 * hour + i)
        cfs.flush()
    task = get_strategy(cfs).next_background_task()
    assert task is not None and len(task.inputs) == 2
    wins = {strat._window_of(s) for s in task.inputs}
    assert len(wins) == 1
    assert wins.pop() != strat._window_of(
        max(cfs.live_sstables(), key=lambda s: s.max_ts or 0))
    # current window reaches min_threshold -> STCS inside the window
    task.execute()
    put(eng, t, 3, 0, "cur3", ts=now_us + 3)
    cfs.flush()
    task2 = get_strategy(cfs).next_background_task()
    assert task2 is not None
    assert {strat._window_of(s) for s in task2.inputs} == {
        strat._window_of(max(cfs.live_sstables(),
                             key=lambda s: s.max_ts or 0))}
    assert len(task2.inputs) >= 4
    eng.close()


def test_twcs_fully_expired_drop(tmp_path):
    """SSTables whose every cell is an expired tombstone past gc grace,
    with no overlapping older data and an empty memtable, are selected
    for a rewrite-free DROP — before any window compaction
    (TimeWindowCompactionStrategy.java:128)."""
    eng, t, cfs = new_engine(
        tmp_path,
        compaction={"class": "TimeWindowCompactionStrategy",
                    "compaction_window_unit": "HOURS",
                    "compaction_window_size": 1},
        gc_grace=0)
    now = int(time.time())
    # disjoint partition ranges so the expired sstable has no
    # overlapping-older-data concern with the live one; every cell is
    # a tombstone whose ldt is long past (gc_grace=0)
    for p in range(5):
        put_dead(eng, t, p, 0, ts=1_000_000 + p, ldt=now - 7200)
    cfs.flush()
    for p in range(100, 105):
        put(eng, t, p, 0, "live", ts=2_000_000 + p)
    cfs.flush()
    strat = get_strategy(cfs)
    expired = strat._fully_expired()
    assert len(expired) == 1
    task = strat.next_background_task()
    assert task is not None and list(task.inputs) == expired
    before = len(cfs.live_sstables())
    stats = task.execute()
    # everything purged: the expired sstable vanishes, no output lands
    assert stats["outputs"] == 0
    assert len(cfs.live_sstables()) == before - 1
    # a hot memtable blocks the drop (purge guard consults it)
    put_dead(eng, t, 200, 0, ts=1, ldt=now - 7200)
    cfs.flush()
    put(eng, t, 3, 0, "resurrect", ts=1)
    assert strat._fully_expired() == []
    eng.close()


def _component_hashes(cfs, gens):
    """{(generation, component): sha256} for the given generations —
    the check_compaction_ab.py byte-identity contract."""
    import hashlib
    import os
    out = {}
    for s in cfs.live_sstables():
        if s.desc.generation not in gens:
            continue
        d = os.path.dirname(s.desc.path("Data.db"))
        prefix = os.path.basename(s.desc.path("Data.db"))[:-len("Data.db")]
        for fn in sorted(os.listdir(d)):
            if not fn.startswith(prefix):
                continue
            with open(os.path.join(d, fn), "rb") as f:
                out[(s.desc.generation, fn[len(prefix):])] = \
                    hashlib.sha256(f.read()).hexdigest()
    return out


def _burst_fixture(tmp_path, table):
    """Four identical fixed-timestamp flushes — an STCS bucket one
    selection away from compacting. Takes a SHARED TableMetadata so
    the two legs' sstables are byte-comparable (Statistics.db embeds
    the table id, which make_table mints randomly)."""
    schema = Schema()
    schema.create_keyspace("ks")
    schema.add_table(table)
    eng = StorageEngine(str(tmp_path / "data"), schema,
                        commitlog_sync="batch")
    cfs = eng.store("ks", "t")
    ts = 1_000_000
    for gen in range(4):
        for p in range(32):
            put(eng, table, p + gen * 32, 0, "v" * 64, ts=ts)
            ts += 1
        cfs.flush()
    return eng, cfs


def test_mid_flight_strategy_flip_no_orphan_bytes_identical(tmp_path):
    """A hot STCS->LCS flip while a compaction task is in flight (the
    adaptive controller's actuation seam,
    ColumnFamilyStore.set_compaction_params) must never orphan or
    re-select the task's inputs: the manager's claim registry refuses
    the new strategy's overlapping selection, the in-flight task
    finishes under its OLD plan, and the resulting sstables are
    byte-identical to a no-flip run."""
    stcs = {"class": "SizeTieredCompactionStrategy", "min_threshold": 4}
    table = make_table("ks", "t", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "text"},
                       params=TableParams(compaction=dict(stcs)))

    # --- leg B FIRST: identical fixture, no flip (the flip leg
    # mutates the SHARED table params, so it must run second)
    eng_b, cfs_b = _burst_fixture(tmp_path / "b", table)
    task_b = get_strategy(cfs_b).next_background_task()
    assert task_b is not None
    assert eng_b.compactions._claim(cfs_b, task_b.inputs)
    task_b.execute()
    eng_b.compactions._release(cfs_b, task_b.inputs)
    live_b = {s.desc.generation for s in cfs_b.live_sstables()}
    hashes_b = _component_hashes(cfs_b, live_b)
    eng_b.close()

    # --- leg A: flip mid-flight
    eng_a, cfs_a = _burst_fixture(tmp_path / "a", table)
    mgr = eng_a.compactions
    task = get_strategy(cfs_a).next_background_task()
    assert task is not None and len(task.inputs) == 4
    assert mgr._claim(cfs_a, task.inputs)   # in flight now
    inputs_a = {s.desc.generation for s in task.inputs}
    old = cfs_a.set_compaction_params(
        {"class": "LeveledCompactionStrategy",
         "sstable_size_in_mb": 160, "l0_threshold": 4})
    assert old["class"] == "SizeTieredCompactionStrategy"
    # the NEW strategy sees the same four L0 sstables and wants them —
    # but the claim registry holds: the manager would DROP this
    # selection (_execute_task returns None), never double-compact
    resel = get_strategy(cfs_a).next_background_task()
    assert resel is not None
    assert not mgr._claim(cfs_a, resel.inputs)
    # the in-flight task completes under the OLD (STCS) plan
    stats = task.execute()
    mgr._release(cfs_a, task.inputs)
    assert stats["inputs"] == 4
    live_a = {s.desc.generation for s in cfs_a.live_sstables()}
    assert not (inputs_a & live_a)   # inputs replaced, none orphaned
    out_gens_a = live_a - inputs_a
    hashes_a = _component_hashes(cfs_a, out_gens_a)
    eng_a.close()

    assert out_gens_a == live_b
    assert hashes_a == hashes_b
    assert len(hashes_a) > 0


def test_strategy_registry_covers_all_four(tmp_path):
    """get_strategy resolves every shipped class (the ROADMAP item 3
    note that 'only STCS exists' is stale — pin the roster)."""
    for cls_name, cls in (
            ("LeveledCompactionStrategy", LeveledCompactionStrategy),
            ("TimeWindowCompactionStrategy",
             TimeWindowCompactionStrategy),
            ("UnifiedCompactionStrategy", UnifiedCompactionStrategy)):
        eng, t, cfs = new_engine(tmp_path / cls_name,
                                 compaction={"class": cls_name})
        assert isinstance(get_strategy(cfs).unrepaired, cls)
        eng.close()
