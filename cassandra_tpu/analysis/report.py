"""Violation reporting + `# ctpulint: allow(...)` suppression policy.

A violation pins one defect to one `file:line`. Suppressions are inline
comments on the violating line (or the line directly above it):

    # ctpulint: allow(<check>, reason=<why this is safe>)

The reason is MANDATORY — an allow without one is itself reported (the
allowlist is documentation, not an off switch), and `check_static.py
--explain` prints every active suppression with its reason so the
allowlist stays auditable.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field

# the closing paren is anchored at end-of-line so a reason may itself
# contain parentheses
_ALLOW_RE = re.compile(
    r"#\s*ctpulint:\s*allow\(\s*(?P<check>[a-z][a-z0-9-]*)\s*"
    r"(?:,\s*reason\s*=\s*(?P<reason>.*\S))?\s*\)\s*$")


@dataclass
class Violation:
    check: str
    path: str          # repo-relative
    line: int
    message: str
    suppressed_by: "Suppression | None" = None

    def __str__(self) -> str:
        return f"{self.path}:{self.line}  [{self.check}]  {self.message}"


@dataclass
class Suppression:
    check: str
    path: str
    line: int          # line the comment sits on
    reason: str | None
    used: bool = field(default=False, compare=False)

    def __str__(self) -> str:
        why = self.reason if self.reason else "<NO REASON GIVEN>"
        return f"{self.path}:{self.line}  allow({self.check}): {why}"


def parse_suppressions(path: str, text: str) -> list[Suppression]:
    out = []
    for i, line in enumerate(text.splitlines(), start=1):
        m = _ALLOW_RE.search(line)
        if m:
            out.append(Suppression(m.group("check"), path, i,
                                   m.group("reason")))
    return out


def apply_suppressions(violations: list[Violation],
                       supps: list[Suppression]) -> list[Violation]:
    """Mark violations covered by an allow comment on the same line or
    the line directly above; returns the UNSUPPRESSED remainder. A
    reasonless allow never suppresses (it is reported separately by
    reasonless())."""
    by_site = {}
    for s in supps:
        if s.reason:
            by_site[(s.path, s.check, s.line)] = s
    remaining = []
    for v in violations:
        s = by_site.get((v.path, v.check, v.line)) \
            or by_site.get((v.path, v.check, v.line - 1))
        if s is not None:
            v.suppressed_by = s
            s.used = True
        else:
            remaining.append(v)
    return remaining


def reasonless(supps: list[Suppression]) -> list[Violation]:
    """Every allow() missing its reason, as violations of the
    `suppression` meta-check."""
    return [Violation("suppression", s.path, s.line,
                      f"allow({s.check}) carries no reason= — the "
                      "allowlist is documentation, write down why this "
                      "site is safe")
            for s in supps if not s.reason]
