"""Repaired/unrepaired split + incremental repair + anticompaction
(reference CompactionStrategyManager.java:107, CompactionManager.java:838
doAntiCompaction, repair/consistent/)."""
import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.compaction.strategies import get_strategy
from cassandra_tpu.compaction.task import CompactionTask
from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine


@pytest.fixture
def engine(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch")
    yield eng
    eng.close()


@pytest.fixture
def session(engine):
    s = Session(engine)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    return s


def _mark_repaired(sst, at=12345):
    """Simulate a prior repair by rewriting the stats metadata."""
    import json
    from cassandra_tpu.storage.sstable.format import Component
    p = sst.desc.path(Component.STATS)
    stats = json.load(open(p))
    stats["repaired_at"] = at
    json.dump(stats, open(p, "w"))
    sst.stats["repaired_at"] = at


def test_compaction_never_crosses_repaired_boundary(session, engine):
    session.execute("CREATE TABLE t (k int PRIMARY KEY, v text)")
    cfs = engine.store("ks", "t")
    for gen in range(8):
        for k in range(20):
            session.execute(f"INSERT INTO t (k, v) VALUES ({k}, 'g{gen}')")
        cfs.flush()
    live = cfs.live_sstables()
    for sst in live[:4]:
        _mark_repaired(sst)
    mgr = get_strategy(cfs)
    # drain background selections: every task stays on one side
    for _ in range(10):
        task = mgr.next_background_task()
        if task is None:
            break
        sides = {s.is_repaired for s in task.inputs}
        assert len(sides) == 1, "compaction crossed the repaired boundary"
        task.execute()
    # major compaction produces one output per side
    task = mgr.major_task()
    if task is not None:
        task.execute()
    repaired = [s for s in cfs.live_sstables() if s.is_repaired]
    unrepaired = [s for s in cfs.live_sstables() if not s.is_repaired]
    assert repaired and unrepaired
    # outputs carry min repairedAt: repaired side kept its stamp
    assert all(s.repaired_at > 0 for s in repaired)


def test_anticompaction_splits_by_range(session, engine):
    from cassandra_tpu.storage.cellbatch import batch_tokens
    from cassandra_tpu.utils import murmur3
    session.execute("CREATE TABLE a (k int PRIMARY KEY, v text)")
    cfs = engine.store("ks", "a")
    t = engine.schema.get_table("ks", "a")
    toks = {}
    for k in range(40):
        session.execute(f"INSERT INTO a (k, v) VALUES ({k}, 'x')")
        toks[k] = murmur3.token_of(t.columns["k"].cql_type.serialize(k))
    cfs.flush()
    median = sorted(toks.values())[20]

    class _FakeNode:
        pass

    # drive anticompact_local directly through a repair service facade
    svc = type("S", (), {"node": type("N", (), {"engine": engine})()})()
    from cassandra_tpu.cluster.repair import RepairService
    n = RepairService.anticompact_local(
        svc, "ks", "a", [(-(1 << 63), median)], repaired_at=777)
    assert n == 1
    live = cfs.live_sstables()
    rep = [s for s in live if s.is_repaired]
    unrep = [s for s in live if not s.is_repaired]
    assert len(rep) == 1 and len(unrep) == 1
    assert rep[0].repaired_at == 777
    # token split is exact
    assert rep[0].max_token() <= median
    assert unrep[0].min_token() > median
    total = sum(s.n_cells for s in live)
    assert total == 40 * 2  # 40 rows x (liveness + value cell)


def test_incremental_repair_end_to_end(tmp_path):
    c = LocalCluster(3, str(tmp_path), rf=3)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        n1 = c.node(1)
        n1.default_cl = ConsistencyLevel.ALL
        for k in range(30):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({k}, 'v{k}')")
        for node in c.nodes:
            node.engine.store("ks", "kv").flush()
        stats = n1.repair.repair_table("ks", "kv", incremental=True,
                                       timeout=15.0)
        assert stats["anticompacted"] >= 3   # every replica anticompacted
        for node in c.nodes:
            cfs = node.engine.store("ks", "kv")
            assert all(sst.is_repaired for sst in cfs.live_sstables())
        # a second incremental repair has nothing unrepaired to validate
        stats2 = n1.repair.repair_table("ks", "kv", incremental=True,
                                        timeout=15.0)
        assert stats2["ranges_synced"] == 0
        # reads still correct afterwards
        assert s.execute("SELECT v FROM kv WHERE k = 7").rows == [("v7",)]
    finally:
        c.shutdown()


def test_incremental_repair_refuses_down_replica(tmp_path):
    import time
    c = LocalCluster(3, str(tmp_path), rf=3, gossip_interval=0.05)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        victim = c.nodes[2]
        victim.messaging.close()
        victim.gossiper.stop()
        deadline = time.time() + 10
        while time.time() < deadline and \
                c.node(1).is_alive(victim.endpoint):
            time.sleep(0.1)
        assert not c.node(1).is_alive(victim.endpoint)
        with pytest.raises(RuntimeError, match="all replicas up"):
            c.node(1).repair.repair_table("ks", "kv", incremental=True,
                                          timeout=5.0)
    finally:
        c.shutdown()


def test_preview_repair_reports_without_streaming(tmp_path):
    """repair --preview (PreviewKind role): diverged replicas are
    REPORTED but nothing streams and nothing is stamped; a followup
    real repair fixes what preview saw."""
    from cassandra_tpu.cluster.messaging import Verb
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    c = LocalCluster(2, str(tmp_path), rf=2)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("CREATE TABLE ks.t (k int PRIMARY KEY, v int)")
        n1 = c.node(1)
        n1.default_cl = ConsistencyLevel.ALL
        for i in range(10):
            s.execute(f"INSERT INTO ks.t (k, v) VALUES ({i}, {i})")
        rule = c.filters.drop(verb=Verb.MUTATION_REQ,
                              to=c.nodes[1].endpoint)
        n1.default_cl = ConsistencyLevel.ONE
        s.execute("INSERT INTO ks.t (k, v) VALUES (99, 99)")
        rule["remaining"] = 0
        before2 = len(c.node(2).engine.store("ks", "t").scan_all())
        stats = n1.repair.repair_table("ks", "t", preview=True)
        assert stats["preview"] and stats["ranges_mismatched"] > 0
        assert stats["cells_streamed"] == 0
        # nothing moved
        assert len(c.node(2).engine.store("ks", "t").scan_all()) == before2
        # the session journal recorded it durably
        sessions = n1.repair.sessions.sessions()
        assert sessions and sessions[-1]["state"] == "COMPLETED"
        assert sessions[-1]["preview"] is True
        # a real repair then converges the replicas
        stats2 = n1.repair.repair_table("ks", "t")
        assert stats2["cells_streamed"] > 0
    finally:
        c.shutdown()


def test_repair_sessions_survive_restart(tmp_path):
    """An IN_PROGRESS record with no FINALIZED pair survives a
    coordinator restart and shows in repair_admin (LocalSessions
    persistence role)."""
    from cassandra_tpu.cluster.repair import RepairSessionStore
    store = RepairSessionStore(str(tmp_path))
    store.begin("s1", keyspace="ks", table="t", incremental=True,
                preview=False, coordinator="node1")
    store.begin("s2", keyspace="ks", table="u", incremental=False,
                preview=False, coordinator="node1")
    store.finish("s2", "COMPLETED")
    # "restart": a fresh store over the same directory
    store2 = RepairSessionStore(str(tmp_path))
    inflight = store2.in_flight()
    assert [s["id"] for s in inflight] == ["s1"]
    states = {s["id"]: s["state"] for s in store2.sessions()}
    assert states == {"s1": "IN_PROGRESS", "s2": "COMPLETED"}
