"""Fused device scan: predicate masks and aggregate folds over value lanes.

Reference counterpart: the SAI query path (index/sai/plan) fused with
LUDA's thesis (PAPERS.md, arxiv 2004.03054) — when the host would touch
every byte anyway, move the per-cell work onto the accelerator. The
columnar "ce" segment layout already carries each column's cells as
(value offset, length) runs over one payload blob, so predicate
evaluation vectorizes without row assembly.

The trick that keeps ONE kernel per predicate shape instead of one per
CQL type: every supported column type maps monotonically into a single
u64 *scan key* space (`keys_from_values`), so comparison predicates on
values become unsigned comparisons on keys:

  i64     tinyint/smallint/int/bigint — sign-bias to u64 (exact)
  f64     float/double — widen to f64, IEEE total-order bits (exact;
          -0.0 normalized to 0.0 so key equality == value equality)
  bool    the serialized byte (exact)
  prefix  text/ascii/blob — first 8 bytes, zero-padded (monotone but
          NOT injective: masks are a SUPERSET and every candidate is
          re-verified by the executor's exact `_match`)

The same keys feed the flush-time zone maps (index/sstable_index.py):
a segment's (min key, max key) bounds every live cell, so
`prune_keep_mask` can drop whole segments without decoding them.

Determinism contract (the device_compress.py pattern): the jitted
kernels and the numpy references below compute identical results for
any input, so the `scan_device_filter` gate — explicit pin > table fn >
config knob, re-read per segment — only moves work between device and
host, never changes results. The device lane stays inside jax's default
32-bit dtypes: u64 keys travel as (hi32, lo32) lane pairs and compare
lexicographically; COUNT/MIN/MAX fold on device over the key lanes
(min/max keys invert exactly back to values for the exact kinds), while
SUM folds host-side in vectorized numpy (a 32-bit device lane cannot
carry an exact 64-bit accumulator) — still zero rows materialized.
"""
from __future__ import annotations

import struct

import numpy as np

from ..schema import ColumnKind, TableMetadata

_BIAS = 1 << 63
_U64_MAX = (1 << 64) - 1
_SIGN64 = np.uint64(_BIAS)

#: kinds whose key space is order-isomorphic AND injective to the value
#: space — key comparisons reproduce `_match` exactly (modulo the NaN
#: fixup `nan_fix` applies); prefix keys are conservative supersets.
EXACT_KINDS = frozenset({"i64", "f64", "bool"})


# ------------------------------------------------------------------ kinds --

def zone_kind(cql_type):
    """(kind, width) for a column type the scan lane understands, else
    None. Deliberately narrow: counters reconcile by shard-summing,
    collections compare whole reassembled containers, and the
    object-valued types (timestamp/date/uuid/...) deserialize to Python
    objects whose ordering the key space does not model."""
    from ..types import marshal as m
    t = cql_type
    if getattr(t, "is_counter", False) or getattr(t, "is_collection", False) \
            or getattr(t, "is_multicell", False):
        return None
    cls = type(t)   # exact class: TimestampType subclasses the int kinds
    if cls in (m.TinyIntType, m.SmallIntType, m.Int32Type, m.LongType):
        return ("i64", t.width)
    if cls is m.FloatType:
        return ("f64", 4)
    if cls is m.DoubleType:
        return ("f64", 8)
    if cls is m.BooleanType:
        return ("bool", 1)
    if cls in (m.TextType, m.AsciiType, m.BlobType):
        return ("prefix", 0)
    return None


def zonemap_columns(table: TableMetadata) -> list[tuple[int, str, int]]:
    """[(column_id, kind, width)] for every regular/static column the
    zone maps cover, ascending column id (the on-disk order)."""
    out = []
    for col in table.static_columns + table.regular_columns:
        kw = zone_kind(col.cql_type)
        if kw is not None:
            out.append((col.column_id, kw[0], kw[1]))
    out.sort()
    return out


# ---------------------------------------------------------------- scan keys --

def _fold_be(b: np.ndarray) -> np.ndarray:
    """Big-endian fold of a [n, w] uint8 byte matrix into u64."""
    k = np.zeros(len(b), dtype=np.uint64)
    for j in range(b.shape[1]):
        k = (k << np.uint64(8)) | b[:, j].astype(np.uint64)
    return k


def _f64_order(vals: np.ndarray) -> np.ndarray:
    """IEEE-754 total-order transform: monotone f64 -> u64 (after
    normalizing -0.0 to 0.0 so key equality equals value equality)."""
    vals = vals + 0.0           # -0.0 + 0.0 == +0.0
    bits = np.ascontiguousarray(vals, dtype=np.float64).view(np.uint64)
    neg = (bits >> np.uint64(63)) != 0
    return np.where(neg, ~bits, bits | _SIGN64)


def keys_from_values(kind: str, width: int, payload: np.ndarray,
                     vs: np.ndarray, ve: np.ndarray
                     ) -> tuple[np.ndarray, np.ndarray]:
    """u64 scan keys for value byte-ranges [vs, ve) of `payload`.
    Returns (keys, valid): a cell whose stored length does not fit the
    kind gets valid=False (callers widen it to "matches anything" —
    conservative, and such cells cannot appear through the write path).
    """
    n = len(vs)
    keys = np.zeros(n, dtype=np.uint64)
    ln = ve - vs
    if n == 0:
        return keys, np.ones(0, dtype=bool)
    if len(payload) == 0:       # all-empty frames: nothing to gather
        payload = np.zeros(1, dtype=np.uint8)
    if kind == "prefix":
        take = np.minimum(ln, 8)
        idx = vs[:, None] + np.arange(8, dtype=vs.dtype)[None, :]
        have = np.arange(8)[None, :] < take[:, None]
        b = np.where(have,
                     payload[np.minimum(idx, len(payload) - 1)],
                     np.uint8(0))
        return _fold_be(b), np.ones(n, dtype=bool)
    valid = ln == width
    safe_vs = np.where(valid, vs, 0)
    idx = safe_vs[:, None] + np.arange(width, dtype=vs.dtype)[None, :]
    b = payload[np.minimum(idx, len(payload) - 1)].reshape(n, width)
    raw = _fold_be(b)
    if kind == "bool":
        return raw, valid
    if kind == "i64":
        sign = np.uint64(1 << (8 * width - 1))
        keys = (raw ^ sign) + np.uint64(_BIAS - (1 << (8 * width - 1)))
        return keys, valid
    # f64: widen the stored IEEE float to f64, then total-order
    if width == 4:
        vals = raw.astype(np.uint32).view(np.float32).astype(np.float64)
    else:
        vals = raw.view(np.float64)
    return _f64_order(vals), valid


def key_of_value(kind: str, value) -> int | None:
    """Scan key of a BOUND Python value (the post-bind literal), or None
    when the value cannot be keyed exactly — the caller falls back.
    Bound keys are computed from the Python value directly, never
    through a serialize round-trip: FloatType.serialize would truncate
    an f8 bound to f4 and diverge from `_match`'s f8 comparison."""
    if kind == "bool":
        return int(value) if isinstance(value, bool) else None
    if kind == "i64":
        if isinstance(value, bool) or not isinstance(value, int):
            return None
        if not (-_BIAS <= value < _BIAS):
            return None
        return value + _BIAS
    if kind == "f64":
        if isinstance(value, bool):
            return None
        if isinstance(value, int):
            if float(value) != value:
                return None     # not exactly representable: key order
            value = float(value)  # could disagree with int comparison
        if not isinstance(value, float) or value != value:
            return None         # NaN bound: _match is all-False anyway
        return int(_f64_order(np.array([value]))[0])
    if kind == "prefix":
        if isinstance(value, str):
            try:
                value = value.encode("utf-8")
            except UnicodeEncodeError:
                return None
        if not isinstance(value, (bytes, bytearray)):
            return None
        b = bytes(value)[:8]
        return int.from_bytes(b + b"\x00" * (8 - len(b)), "big")
    return None


def value_of_key(kind: str, key: int):
    """Inverse of the key map for the exact kinds (min/max fold results
    come back from the device as keys)."""
    if kind == "i64":
        return key - _BIAS
    if kind == "bool":
        return bool(key)
    if kind == "f64":
        bits = key ^ _BIAS if key >= _BIAS else ~key & _U64_MAX
        return struct.unpack(">d", bits.to_bytes(8, "big"))[0]
    raise ValueError(f"kind {kind!r} has no exact inverse")


# ------------------------------------------------------------- predicates --

#: executor op -> (kernel op, still-exact) per kind family. Prefix keys
#: truncate, so strict ops widen to their inclusive forms and '!='
#: degenerates to "every live cell" — all supersets the executor's
#: exact `_match` re-verification shrinks back.
_EXACT_KOPS = {"=": "eq", "!=": "ne", "<": "lt", "<=": "le",
               ">": "gt", ">=": "ge", "IN": "in"}
_PREFIX_KOPS = {"=": "eq", "!=": "all", "<": "le", "<=": "le",
                ">": "ge", ">=": "ge", "IN": "in"}


class CompiledPredicate:
    """One pushdown-supported column filter, compiled to key space."""

    __slots__ = ("col_id", "col_name", "kind", "width", "op", "kop",
                 "qkeys", "exact", "is_static")

    def __init__(self, col_id, col_name, kind, width, op, kop, qkeys,
                 exact, is_static):
        self.col_id = col_id
        self.col_name = col_name
        self.kind = kind
        self.width = width
        self.op = op
        self.kop = kop
        self.qkeys = qkeys          # np.uint64[m]
        self.exact = exact
        self.is_static = is_static


def compile_predicate(table: TableMetadata, filters) -> CompiledPredicate | None:
    """Compile the FIRST pushdown-supported filter as the driving
    predicate (the remaining filters stay host-checked by the executor,
    which re-applies ALL of them to every candidate row). None when no
    filter is supported — the caller keeps the Python path."""
    for col, op, v in filters:
        kw = zone_kind(col.cql_type)
        if kw is None or col.kind not in (ColumnKind.REGULAR,
                                          ColumnKind.STATIC):
            continue
        kind, width = kw
        kops = _EXACT_KOPS if kind in EXACT_KINDS else _PREFIX_KOPS
        kop = kops.get(op)
        if kop is None:
            continue
        if op == "IN":
            if not isinstance(v, (list, tuple)):
                continue
            qk = [key_of_value(kind, x) for x in v]
            if any(k is None for k in qk):
                continue
        else:
            k = key_of_value(kind, v)
            if k is None:
                continue
            qk = [k]
        return CompiledPredicate(
            col.column_id, col.name, kind, width, op, kop,
            np.asarray(qk, dtype=np.uint64),
            kind in EXACT_KINDS,
            col.kind == ColumnKind.STATIC)
    return None


# ------------------------------------------------------- zone-map pruning --

def segment_zone_entries(zone_cols, col_lane, flags, vs, ve, payload):
    """Per-column (min_key, max_key, live, dead) rows for ONE segment —
    shared by the writer tail (flush/compaction) and the rebuild path.
    `dead` counts death-flagged cells of the column (tombstones at any
    scope); empty-range sentinels are (U64_MAX, 0). A cell the kind
    cannot key widens the column to the full key range (never prunes)."""
    from ..storage.cellbatch import DEATH_FLAGS
    col_lane = np.asarray(col_lane)
    flags = np.asarray(flags)
    out = []
    for cid, kind, width in zone_cols:
        sel = col_lane == cid
        n_col = int(sel.sum())
        if n_col == 0:
            out.append((_U64_MAX, 0, 0, 0))
            continue
        alive = sel & ((flags & DEATH_FLAGS) == 0)
        live = int(alive.sum())
        dead = n_col - live
        if live == 0:
            out.append((_U64_MAX, 0, 0, dead))
            continue
        idx = np.flatnonzero(alive)
        keys, valid = keys_from_values(kind, width, payload,
                                       vs[idx], ve[idx])
        if not valid.all():
            out.append((0, _U64_MAX, live, dead))
            continue
        out.append((int(keys.min()), int(keys.max()), live, dead))
    return out


def prune_keep_mask(kmin, kmax, live, pred: CompiledPredicate) -> np.ndarray:
    """bool[n_segments] — True where the segment MAY hold a live cell
    matching pred and must be decoded. Conservative by construction:
    keys are monotone, so value a <= b implies key(a) <= key(b), and a
    matching cell's key always lands inside [kmin, kmax]."""
    keep = live > 0
    kop = pred.kop
    if kop == "all":
        return keep
    q = pred.qkeys
    if kop == "eq":
        return keep & (kmin <= q[0]) & (q[0] <= kmax)
    if kop == "in":
        any_in = np.zeros(len(kmin), dtype=bool)
        for qk in q:
            any_in |= (kmin <= qk) & (qk <= kmax)
        return keep & any_in
    if kop in ("lt", "le"):
        return keep & (kmin <= q[0]) if kop == "le" \
            else keep & (kmin < q[0])
    if kop in ("gt", "ge"):
        return keep & (kmax >= q[0]) if kop == "ge" \
            else keep & (kmax > q[0])
    if kop == "ne":
        # exact kinds only: a segment where every live cell IS the
        # bound can never match !=
        return keep & ~((kmin == q[0]) & (kmax == q[0]))
    raise ValueError(f"unknown kernel op {kop!r}")


# ------------------------------------------------------------ mask kernels --
# u64 keys travel as (hi32, lo32) pairs: jax defaults to 32-bit dtypes
# repo-wide and the unsigned lexicographic compare is exact.

def _define_kernels():
    import jax
    import jax.numpy as jnp
    from ..service.profiling import GLOBAL as _kprof

    def _lt(hi, lo, qhi, qlo):
        return (hi < qhi) | ((hi == qhi) & (lo < qlo))

    def _eqk(hi, lo, qhi, qlo):
        return (hi == qhi) & (lo == qlo)

    kernels = {
        "eq": lambda hi, lo, qh, ql: _eqk(hi, lo, qh[0], ql[0]),
        "ne": lambda hi, lo, qh, ql: ~_eqk(hi, lo, qh[0], ql[0]),
        "lt": lambda hi, lo, qh, ql: _lt(hi, lo, qh[0], ql[0]),
        "ge": lambda hi, lo, qh, ql: ~_lt(hi, lo, qh[0], ql[0]),
        "gt": lambda hi, lo, qh, ql: _lt(qh[0], ql[0], hi, lo),
        "le": lambda hi, lo, qh, ql: ~_lt(qh[0], ql[0], hi, lo),
        "in": lambda hi, lo, qh, ql: (
            (hi[:, None] == qh[None, :]) & (lo[:, None] == ql[None, :])
        ).any(axis=1),
        "all": lambda hi, lo, qh, ql: jnp.ones(hi.shape, dtype=bool),
    }
    out = {}
    for name, fn in kernels.items():
        out[name] = _kprof.wrap(f"scan.mask_{name}", jax.jit(fn))

    def _fold(hi, lo, mask):
        cnt = jnp.sum(mask.astype(jnp.int32))
        u32max = jnp.uint32(0xFFFFFFFF)
        hi_f = jnp.where(mask, hi, u32max)
        lo_f = jnp.where(mask, lo, u32max)
        min_hi = jnp.min(hi_f) if hi.shape[0] else jnp.uint32(0)
        min_lo = jnp.min(jnp.where(hi_f == min_hi, lo_f, u32max))
        hi_c = jnp.where(mask, hi, jnp.uint32(0))
        lo_c = jnp.where(mask, lo, jnp.uint32(0))
        max_hi = jnp.max(hi_c)
        max_lo = jnp.max(jnp.where(hi_c == max_hi, lo_c, jnp.uint32(0)))
        return cnt, min_hi, min_lo, max_hi, max_lo

    fold = _kprof.wrap("scan.fold", jax.jit(_fold))
    return out, fold


_KERNELS = None
_FOLD = None


def _kernels():
    global _KERNELS, _FOLD
    if _KERNELS is None:
        _KERNELS, _FOLD = _define_kernels()
    return _KERNELS, _FOLD


def _split(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    return ((keys >> np.uint64(32)).astype(np.uint32),
            (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def mask_device(keys: np.ndarray, pred: CompiledPredicate) -> np.ndarray:
    """Predicate mask evaluated by the jitted kernel. Bit-identical to
    mask_host for any input (the AB check pins it)."""
    kernels, _ = _kernels()
    hi, lo = _split(keys)
    qhi, qlo = _split(pred.qkeys if len(pred.qkeys)
                      else np.zeros(1, dtype=np.uint64))
    if pred.kop == "in" and len(pred.qkeys) == 0:
        return np.zeros(len(keys), dtype=bool)
    out = kernels[pred.kop](hi, lo, qhi, qlo)
    return np.asarray(out, dtype=bool)


def mask_host(keys: np.ndarray, pred: CompiledPredicate) -> np.ndarray:
    """Numpy reference for mask_device — the per-segment fallback."""
    kop, q = pred.kop, pred.qkeys
    if kop == "all":
        return np.ones(len(keys), dtype=bool)
    if kop == "in":
        out = np.zeros(len(keys), dtype=bool)
        for qk in q:
            out |= keys == qk
        return out
    ops = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
           "le": np.less_equal, "gt": np.greater,
           "ge": np.greater_equal}
    return ops[kop](keys, q[0])


def nan_fix(mask: np.ndarray, keys: np.ndarray,
            pred: CompiledPredicate) -> np.ndarray:
    """Align key-space masks with Python NaN semantics: `_match` is
    False for every comparison against a NaN cell EXCEPT '!=' (which is
    True). NaN keys sit outside the finite total-order run, so patch
    them explicitly; other kinds pass through untouched."""
    if pred.kind != "f64" or not len(mask):
        return mask
    kinf = _f64_order(np.array([np.inf, -np.inf]))
    is_nan = (keys > kinf[0]) | (keys < kinf[1])
    if not is_nan.any():
        return mask
    mask = mask.copy()
    mask[is_nan] = pred.op == "!="
    return mask


def segment_mask(keys: np.ndarray, pred: CompiledPredicate,
                 use_device: bool) -> tuple[np.ndarray, bool]:
    """(mask, ran_on_device). The device leg falls back PER SEGMENT on
    any kernel failure — counted by the caller, results identical."""
    if use_device:
        try:
            return nan_fix(mask_device(keys, pred), keys, pred), True
        except Exception:
            pass
    return nan_fix(mask_host(keys, pred), keys, pred), False


# ----------------------------------------------------------- batch helpers --

def batch_predicate_cells(batch, pred: CompiledPredicate,
                          reconciled: bool
                          ) -> tuple[np.ndarray, np.ndarray]:
    """(cell indices, u64 keys) of the predicate column's live cells in
    a CellBatch. reconciled=False (write-order segments / memtable):
    live means no death flag — a superset is fine, the executor
    re-verifies. reconciled=True (merge_sorted output): live means
    exactly what rows_from_batch would surface as a non-null value.
    A cell the kind cannot key keeps key 0 with its index returned in
    the caller-visible `keys` as-is only when valid — invalid cells
    raise, matching the naive path's deserialize failure."""
    from ..storage.cellbatch import (DEATH_FLAGS, FLAG_COMPLEX_DEL,
                                     FLAG_TOMBSTONE)
    n = len(batch)
    if n == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.uint64)
    C = batch.n_lanes - 9
    cols = np.asarray(batch.lanes[:, 6 + C])
    flags = np.asarray(batch.flags)
    deadbits = (FLAG_TOMBSTONE | FLAG_COMPLEX_DEL) if reconciled \
        else DEATH_FLAGS
    sel = np.flatnonzero((cols == pred.col_id) & ((flags & deadbits) == 0))
    if not len(sel):
        return sel, np.zeros(0, dtype=np.uint64)
    off = np.asarray(batch.off)
    vs = np.asarray(batch.val_start)[sel]
    ve = off[sel + 1]
    payload = np.asarray(batch.payload)
    keys, valid = keys_from_values(pred.kind, pred.width, payload, vs, ve)
    if not valid.all():
        raise ValueError(
            f"column {pred.col_name}: stored cell width does not fit "
            f"kind {pred.kind}")
    return sel, keys


def fold_batch(batch, pred: CompiledPredicate, use_device: bool
               ) -> tuple[int, int | None, int | None, int, bool]:
    """Exact aggregate partials over a RECONCILED batch: (count,
    min_key, max_key, int_sum, ran_on_device). Only called for exact
    predicate kinds, so the mask equals `_match` row for row; the i64
    sum is exact because the executor only pushes SUM/AVG for integer
    widths <= 4 bytes (no 64-bit overflow for any realistic row count).
    """
    sel, keys = batch_predicate_cells(batch, pred, reconciled=True)
    if not len(sel):
        return 0, None, None, 0, use_device
    on_device = False
    if use_device:
        try:
            _, fold = _kernels()
            hi, lo = _split(keys)
            mask = nan_fix(mask_device(keys, pred), keys, pred)
            cnt, mnh, mnl, mxh, mxl = fold(hi, lo, mask)
            cnt = int(cnt)
            if cnt == 0:
                return 0, None, None, 0, True
            kmin = (int(mnh) << 32) | int(mnl)
            kmax = (int(mxh) << 32) | int(mxl)
            on_device = True
        except Exception:
            on_device = False
    if not on_device:
        mask = nan_fix(mask_host(keys, pred), keys, pred)
        cnt = int(mask.sum())
        if cnt == 0:
            return 0, None, None, 0, False
        mk = keys[mask]
        kmin, kmax = int(mk.min()), int(mk.max())
        sel_keys = mk
    else:
        sel_keys = keys[np.asarray(mask, dtype=bool)]
    total = 0
    if pred.kind == "i64":
        vals = (sel_keys ^ _SIGN64).view(np.int64)
        total = int(vals.sum())
    elif pred.kind == "bool":
        total = int(sel_keys.sum())
    return cnt, kmin, kmax, total, on_device
