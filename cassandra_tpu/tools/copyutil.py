"""cqlsh COPY TO / COPY FROM — CSV import/export.

Reference counterpart: pylib/cqlshlib/copyutil.py (cqlsh's COPY command).
This is the supported migration path from a reference cluster: export
there with its own cqlsh (`COPY ks.t TO 'x.csv'`), import here with
`COPY ks.t FROM 'x.csv'` — data-level interop that works against every
reference version, independent of sstable format internals (see
SURVEY.md "SSTable format scope").

Syntax: COPY <table> [(col, ...)] TO|FROM '<file>' [WITH HEADER = true]
Export pages through the normal query pager (bounded memory).
"""
from __future__ import annotations

import csv
import datetime
import re
import uuid

_COPY_RE = re.compile(
    r"^\s*copy\s+(?P<table>[\w.]+)\s*(?:\((?P<cols>[^)]*)\))?\s*"
    r"(?P<dir>to|from)\s+'(?P<path>[^']+)'\s*"
    r"(?:with\s+(?P<opts>.*?))?\s*;?\s*$", re.I | re.S)


def parse_copy(stmt: str):
    m = _COPY_RE.match(stmt)
    if not m:
        return None
    cols = [c.strip() for c in (m.group("cols") or "").split(",")
            if c.strip()]
    opts = {}
    for part in re.split(r"\s+and\s+", m.group("opts") or "", flags=re.I):
        if "=" in part:
            k, v = part.split("=", 1)
            opts[k.strip().lower()] = v.strip().strip("'\"").lower()
    return {"table": m.group("table"), "columns": cols,
            "direction": m.group("dir").lower(), "path": m.group("path"),
            "header": opts.get("header", "true") in ("true", "1", "yes")}


def _cql_literal(v) -> str:
    """A value as a CQL literal (quoted strings) — collection exports
    must re-parse through the CQL grammar on import."""
    if v is None:
        return "null"
    if isinstance(v, bytes):
        return "0x" + v.hex()
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, uuid.UUID):
        return str(v)
    if isinstance(v, (datetime.datetime, datetime.date, datetime.time)):
        return "'" + v.isoformat() + "'"
    if isinstance(v, (set, frozenset)):
        return "{" + ", ".join(sorted(_cql_literal(x) for x in v)) + "}"
    if isinstance(v, tuple):
        return "(" + ", ".join(_cql_literal(x) for x in v) + ")"
    if isinstance(v, list):
        return "[" + ", ".join(_cql_literal(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ", ".join(
            f"{_cql_literal(k)}: {_cql_literal(x)}"
            for k, x in sorted(v.items(), key=lambda kv: str(kv[0]))) + "}"
    return str(v)


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bytes):
        return "0x" + v.hex()
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (set, frozenset, list, tuple, dict)):
        return _cql_literal(v)   # CQL literal: round-trips via the parser
    return str(v)


from ..types.textval import parse_text_value as _parse_value  # noqa: E402


def copy_to(session, table_name: str, columns: list[str],
            path: str, header: bool = True, fetch_size: int = 5000) -> int:
    """Paged export; returns rows written."""
    cols = ", ".join(columns) if columns else "*"
    n = 0
    state = None
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        first = True
        while True:
            rs = session.execute(f"SELECT {cols} FROM {table_name}",
                                 fetch_size=fetch_size,
                                 paging_state=state)
            if first and header:
                w.writerow(rs.column_names)
            first = False
            for row in rs.rows:
                w.writerow([_fmt(v) for v in row])
                n += 1
            state = rs.paging_state
            if state is None:
                return n


def copy_from(session, schema, keyspace: str, table_name: str,
              columns: list[str], path: str, header: bool = True) -> int:
    """CSV import, streaming (never materializes the file). Scalar-only
    tables go through ONE prepared statement; tables with collection/
    tuple/UDT/vector columns splice those values as CQL literals (the
    export wrote them in literal syntax) and parse per row. Returns rows
    read."""
    import itertools

    if "." in table_name:
        keyspace, table_name = table_name.split(".", 1)
    t = schema.get_table(keyspace, table_name)
    with open(path, newline="") as f:
        r = csv.reader(f)
        rows = iter(r)
        first = next(rows, None)
        if first is None:
            return 0
        if not columns:
            columns = list(first) if header else \
                [c.name for c in (t.partition_key_columns
                                  + t.clustering_columns
                                  + t.static_columns + t.regular_columns)]
        if not header:
            rows = itertools.chain([first], rows)
        types = [t.columns[c].cql_type for c in columns]
        complex_cols = [getattr(ty, "is_multicell", False)
                        or type(ty).__name__ in ("TupleType", "UserType",
                                                 "VectorType")
                        for ty in types]
        col_list = ", ".join(columns)
        n = 0
        if not any(complex_cols):
            placeholders = ", ".join("?" for _ in columns)
            qid = session.processor.prepare(
                f"INSERT INTO {keyspace}.{table_name} "
                f"({col_list}) VALUES ({placeholders})")
            for row in rows:
                params = tuple(_parse_value(v, ty)
                               for v, ty in zip(row, types))
                session.processor.execute_prepared(
                    qid, params, keyspace, user=session.user)
                n += 1
            return n
        for row in rows:
            vals = []
            for v, ty, cx in zip(row, types, complex_cols):
                if cx:
                    vals.append(v if v else "null")
                else:
                    vals.append(_cql_literal(_parse_value(v, ty)))
            session.execute(
                f"INSERT INTO {keyspace}.{table_name} "
                f"({col_list}) VALUES ({', '.join(vals)})")
            n += 1
        return n
