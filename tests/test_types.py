"""Type system tests: round-trips, byte-comparable order properties,
type-string parsing (reference spec: db/marshal/* comparison semantics)."""
import random
import uuid
from datetime import date, datetime, timezone
from decimal import Decimal

import pytest

from cassandra_tpu.types import (
    parse_type, ListType, SetType, MapType, TupleType, VectorType,
    TextType, Int32Type, LongType, DoubleType, DecimalType, IntegerType,
    UUIDType, TimeUUIDType, BooleanType, InetAddressType, DurationType,
    TimestampType, SimpleDateType, TimeType,
)


def order_check(t, values):
    """byte-comparable order must match python value order."""
    ser = [(v, t.serialize(v)) for v in values]
    by_val = [v for v, _ in sorted(ser, key=lambda p: p[0])]
    by_bc = [v for v, s in sorted(ser, key=lambda p: t.to_bytecomp(p[1]))]
    assert by_val == by_bc


def roundtrip(t, values):
    for v in values:
        assert t.deserialize(t.serialize(v)) == v, (t, v)


def test_int_types():
    rng = random.Random(1)
    for t, lo, hi in [(Int32Type(), -2**31, 2**31 - 1),
                      (LongType(), -2**63, 2**63 - 1)]:
        vals = sorted({rng.randrange(lo, hi + 1) for _ in range(100)} | {lo, hi, 0, -1, 1})
        roundtrip(t, vals)
        order_check(t, vals)


def test_text_blob():
    t = TextType()
    vals = ["", "a", "abc", "ü", "z" * 100, "é中"]
    roundtrip(t, vals)
    # utf-8 byte order
    ser = sorted(vals, key=lambda v: t.serialize(v))
    bc = sorted(vals, key=lambda v: t.to_bytecomp(t.serialize(v)))
    assert ser == bc


def test_double_order():
    rng = random.Random(2)
    vals = sorted({rng.uniform(-1e6, 1e6) for _ in range(100)} | {0.0, 1.5, -2.25, float("inf"), float("-inf")})
    t = DoubleType()
    roundtrip(t, vals)
    order_check(t, vals)


def test_decimal():
    t = DecimalType()
    vals = [Decimal("0"), Decimal("1.5"), Decimal("-1.5"), Decimal("100"),
            Decimal("0.001"), Decimal("-0.001"), Decimal("123456.789"),
            Decimal("-123456.789"), Decimal("1E+10"), Decimal("-1E+10"),
            Decimal("9.99"), Decimal("10.01")]
    roundtrip(t, vals)
    order_check(t, sorted(set(vals)))


def test_varint_type():
    t = IntegerType()
    vals = [0, 1, -1, 127, 128, -128, -129, 2**70, -2**70, 255, 256]
    roundtrip(t, vals)
    order_check(t, sorted(set(vals)))


def test_timestamp_date_time():
    ts = TimestampType()
    d = datetime(2024, 5, 1, 12, 30, tzinfo=timezone.utc)
    assert ts.deserialize(ts.serialize(d)) == d
    sd = SimpleDateType()
    assert sd.deserialize(sd.serialize(date(2024, 5, 1))) == date(2024, 5, 1)
    order_check(sd, [date(1969, 1, 1), date(1970, 1, 1), date(2024, 5, 1)])
    tt = TimeType()
    roundtrip(tt, [0, 1, 86399999999999])
    order_check(tt, [0, 1, 86399999999999])


def test_uuid_types():
    t = UUIDType()
    u = uuid.uuid4()
    assert t.deserialize(t.serialize(u)) == u
    # v1 ordering by timestamp
    tu = TimeUUIDType()
    a = uuid.uuid1(clock_seq=5)
    b = uuid.uuid1(clock_seq=3)
    assert tu.to_bytecomp(tu.serialize(a)) < tu.to_bytecomp(tu.serialize(b)) or a.time <= b.time
    with pytest.raises(ValueError):
        tu.validate(uuid.uuid4().bytes)


def test_inet_duration_boolean():
    t = InetAddressType()
    for addr in ["127.0.0.1", "10.0.0.1", "::1", "2001:db8::1"]:
        assert t.deserialize(t.serialize(addr)) == addr
    d = DurationType()
    assert d.deserialize(d.serialize((1, 2, 3))) == (1, 2, 3)
    assert d.deserialize(d.serialize((-1, -2, -3))) == (-1, -2, -3)
    b = BooleanType()
    assert b.deserialize(b.serialize(True)) is True
    assert b.deserialize(b.serialize(False)) is False


def test_collections():
    lt = parse_type("list<int>")
    assert lt.deserialize(lt.serialize([1, 2, 3])) == [1, 2, 3]
    st = parse_type("set<text>")
    assert st.deserialize(st.serialize({"b", "a"})) == {"a", "b"}
    mt = parse_type("map<text, int>")
    assert mt.deserialize(mt.serialize({"x": 1, "y": 2})) == {"x": 1, "y": 2}
    # frozen list ordering: prefix rule
    fl = parse_type("frozen<list<int>>")
    a = fl.to_bytecomp(fl.serialize([1, 2]))
    b = fl.to_bytecomp(fl.serialize([1, 2, 3]))
    c = fl.to_bytecomp(fl.serialize([2]))
    assert a < b < c


def test_tuple_and_vector():
    tt = parse_type("tuple<int, text>")
    assert tt.deserialize(tt.serialize((1, "a"))) == (1, "a")
    assert tt.deserialize(tt.serialize((None, "a"))) == (None, "a")
    vt = parse_type("vector<float, 3>")
    out = vt.deserialize(vt.serialize([1.0, 2.0, 3.0]))
    assert out == [1.0, 2.0, 3.0]
    with pytest.raises(ValueError):
        vt.serialize([1.0])


def test_parse_nested():
    t = parse_type("map<text, frozen<list<int>>>")
    v = {"a": [1, 2], "b": []}
    assert t.deserialize(t.serialize(v)) == v
    assert t.is_multicell
    assert not parse_type("frozen<map<text, int>>").is_multicell
    with pytest.raises(ValueError):
        parse_type("wat")
