"""Filesystem helpers for the durable write paths.

Block preallocation: on this environment's ext4 mount, writes that extend
a file (delayed allocation) run at ~16-24 MiB/s while writes into
preallocated ranges run at ~1.8 GiB/s — allocation, not data movement, is
the cost. The reference leans on the JVM's buffered writers + the kernel;
here the sstable writer and commitlog preallocate explicitly (the
reference's commitlog does the same thing for its own reasons:
CommitLogSegment pre-creates fixed 32MiB segments).
"""
from __future__ import annotations

import ctypes
import ctypes.util
import os

_FALLOC_FL_KEEP_SIZE = 0x01

_libc = None
_has_fallocate = None


def _load():
    global _libc, _has_fallocate
    if _has_fallocate is None:
        try:
            _libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                                use_errno=True)
            _libc.fallocate.restype = ctypes.c_int
            _libc.fallocate.argtypes = [ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int64, ctypes.c_int64]
            _has_fallocate = True
        except (OSError, AttributeError):
            _has_fallocate = False
    return _has_fallocate


def preallocate_keep_size(fd: int, offset: int, length: int) -> bool:
    """fallocate(FALLOC_FL_KEEP_SIZE): reserve blocks without changing
    st_size, so append-mode writers and EOF-terminated readers (commitlog
    replay) are unaffected. Returns False if unsupported (caller falls
    back to plain extending writes)."""
    if length <= 0 or not _load():
        return False
    r = _libc.fallocate(fd, _FALLOC_FL_KEEP_SIZE, offset, length)
    return r == 0
