"""Internode messaging: verb-dispatched request/response with timeouts and
test-controllable fault injection.

Reference counterpart: net/MessagingService.java:208 (send/sendWithCallback),
net/Verb.java:127 (verb registry with handlers + timeouts), and the in-JVM
dtest MessageFilters (test/distributed/impl/AbstractCluster.java:796) that
drop/intercept messages between in-process nodes.

Transport is pluggable: LocalTransport routes in-process (the jvm-dtest
model — our multi-node tests run N nodes in one process); a socket
transport slots in behind the same send() seam for real deployments.
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field

from ..service import tracing
from ..service.metrics import GLOBAL as METRICS
from ..utils import pipeline_ledger
from .ring import Endpoint


def auto_dispatch_workers() -> int:
    """0 = auto resolution for internode_dispatch_threads: replica-side
    verb handlers are GIL-bound python plus engine calls that release it
    (storage reads, commitlog appends with fsync), so a small multiple
    of cores pays for itself by keeping acks flowing while one handler
    blocks on fsync — but every in-process node spawns its own pool, so
    the cap stays low (the 3-node dtest cluster runs 3 pools on one
    box)."""
    return max(1, min(os.cpu_count() or 2, 4))


# metric-name cache for the per-verb received counters (one entry per
# verb string, built lazily)
_VERB_RECEIVED: dict = {}

# replica-shipped trace events per response are CAPPED: a chatty
# handler (or a pathological loop inside one) must not bloat every RSP
# payload on the wire. The chronological HEAD is kept — the re-base
# math on the coordinator (tracing.merge_remote) anchors on the last
# shipped offset, so a truncated tail just shortens the merged
# timeline. Drops count under `verb.<rsp-verb>.trace_dropped`.
TRACE_EVENTS_CAP = 64
_VERB_TRACE_DROPPED: dict = {}


class Verb:
    MUTATION_REQ = "MUTATION_REQ"
    MUTATION_RSP = "MUTATION_RSP"
    COUNTER_REQ = "COUNTER_REQ"
    COUNTER_RSP = "COUNTER_RSP"
    READ_REQ = "READ_REQ"
    READ_RSP = "READ_RSP"
    RANGE_REQ = "RANGE_REQ"
    RANGE_RSP = "RANGE_RSP"
    HINT_REQ = "HINT_REQ"
    ECHO_REQ = "ECHO_REQ"
    ECHO_RSP = "ECHO_RSP"
    GOSSIP_SYN = "GOSSIP_SYN"
    GOSSIP_ACK = "GOSSIP_ACK"
    SCHEMA_PUSH = "SCHEMA_PUSH"
    SCHEMA_PULL = "SCHEMA_PULL"
    SCHEMA_FORWARD = "SCHEMA_FORWARD"
    STREAM_REQ = "STREAM_REQ"
    STREAM_DATA = "STREAM_DATA"
    # sessioned streaming (cluster/stream_session.py): manifest-planned
    # chunked transfer with acks, retransmit and resume
    STREAM_SESSION_REQ = "STREAM_SESSION_REQ"
    STREAM_MANIFEST = "STREAM_MANIFEST"
    STREAM_CHUNK = "STREAM_CHUNK"
    STREAM_ACK = "STREAM_ACK"
    STREAM_SESSION_DONE = "STREAM_SESSION_DONE"
    STREAM_PULL_REQ = "STREAM_PULL_REQ"
    STREAM_PULL_RSP = "STREAM_PULL_RSP"
    REPAIR_VALIDATION_REQ = "REPAIR_VALIDATION_REQ"
    REPAIR_VALIDATION_RSP = "REPAIR_VALIDATION_RSP"
    REPAIR_SYNC_REQ = "REPAIR_SYNC_REQ"
    REPAIR_ANTICOMPACT_REQ = "REPAIR_ANTICOMPACT_REQ"
    REPAIR_ANTICOMPACT_RSP = "REPAIR_ANTICOMPACT_RSP"
    BOOTSTRAP_PULL_REQ = "BOOTSTRAP_PULL_REQ"
    FAILURE_RSP = "FAILURE_RSP"
    TRUNCATE_REQ = "TRUNCATE_REQ"
    TRUNCATE_RSP = "TRUNCATE_RSP"
    INDEX_REQ = "INDEX_REQ"
    INDEX_RSP = "INDEX_RSP"
    # cluster-wide telemetry pull (the observatory): any node asks a
    # peer for its engine-scoped metric/tpstats/SLO snapshot
    METRICS_SNAPSHOT_REQ = "METRICS_SNAPSHOT_REQ"
    METRICS_SNAPSHOT_RSP = "METRICS_SNAPSHOT_RSP"


@dataclass
class Message:
    verb: str
    payload: object
    sender: Endpoint
    to: Endpoint
    id: int = 0
    reply_to: int = 0
    # distributed tracing headers (tracing/Tracing.java message params):
    # requests carry the coordinator's session id; responses echo it back
    # along with the replica-side (elapsed_us, source, activity) events
    trace_session: str | None = None
    trace_events: list | None = None


class MessageFilters:
    """Test hook: drop or intercept messages (jvm-dtest MessageFilters)."""

    def __init__(self):
        self._drop_rules: list = []
        self._intercepts: list = []
        self._lock = threading.Lock()

    def drop(self, verb: str | None = None, frm: Endpoint | None = None,
             to: Endpoint | None = None, count: int | None = None):
        rule = {"verb": verb, "from": frm, "to": to,
                "remaining": count if count is not None else float("inf")}
        with self._lock:
            self._drop_rules.append(rule)
        return rule

    def clear(self):
        with self._lock:
            self._drop_rules.clear()
            self._intercepts.clear()

    def intercept(self, fn):
        with self._lock:
            self._intercepts.append(fn)

    def should_drop(self, msg: Message) -> bool:
        with self._lock:
            for fn in self._intercepts:
                fn(msg)
            for r in self._drop_rules:
                if ((r["verb"] is None or r["verb"] == msg.verb)
                        and (r["from"] is None or r["from"] == msg.sender)
                        and (r["to"] is None or r["to"] == msg.to)
                        and r["remaining"] > 0):
                    r["remaining"] -= 1
                    return True
        return False


class LocalTransport:
    """In-process message routing between registered nodes; each node gets
    a delivery thread (the reference's per-connection Netty event loop)."""

    def __init__(self):
        self.filters = MessageFilters()
        self._nodes: dict[Endpoint, "MessagingService"] = {}
        self._lock = threading.Lock()

    def register(self, ep: Endpoint, svc: "MessagingService") -> None:
        with self._lock:
            self._nodes[ep] = svc

    def unregister(self, ep: Endpoint) -> None:
        with self._lock:
            self._nodes.pop(ep, None)

    def deliver(self, msg: Message) -> None:
        if self.filters.should_drop(msg):
            return
        with self._lock:
            target = self._nodes.get(msg.to)
        if target is not None and not target.closed:
            target.inbound(msg)


class MessagingService:
    """Per-node messaging endpoint: verb handlers + response callbacks with
    timeouts (net/RequestCallbacks)."""

    # how long a surplus/shut-down dispatch worker can linger blocked on
    # an empty queue before noticing it should exit (CompressorPool's
    # POLL_SECONDS role)
    POLL_SECONDS = 0.2

    def __init__(self, ep: Endpoint, transport: LocalTransport,
                 dispatch_workers: int = 0):
        self.ep = ep
        self.transport = transport
        self.handlers: dict[str, callable] = {}
        self._callbacks: dict[int, tuple] = {}
        self._ids = itertools.count(1)
        self._cb_lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue()
        self.closed = False
        self.metrics = {"sent": 0, "received": 0, "dropped_timeout": 0,
                        "process_failures": 0, "dispatch_worker_deaths": 0}
        # verb-dispatch pool (the reference's per-Verb handler stages,
        # net/: inbound requests execute on Stage executors, not the
        # deserialization thread): the distributor thread routes
        # response callbacks inline — per-callback-id ordering is the
        # single-thread total order — and hands verb-handler messages
        # to `_pool_target` workers over `_dispatch_q`, so replica-side
        # verbs scale with cores instead of serializing behind one
        # fsync-bound handler. 0 = auto; hot-resized by the
        # internode_dispatch_threads knob via set_dispatch_workers().
        self._dispatch_q: queue.Queue = queue.Queue()
        self._pool_lock = threading.Lock()
        self._pool: list[threading.Thread] = []
        self._pool_target = int(dispatch_workers) if dispatch_workers > 0 \
            else auto_dispatch_workers()
        # ledger stage (utils/pipeline_ledger.py): busy = handler
        # execution, idle = workers parked on an empty dispatch queue,
        # queue_hwm = verb backlog high-water behind the distributor
        self._stage = pipeline_ledger.ledger("messaging").stage("dispatch")
        self._verb_stages: dict[str, object] = {}
        # deterministic-simulation mode: a SimTransport (sim/scheduler.py)
        # carries a scheduler; deliveries and callback timeouts become
        # virtual-time events processed inline on the pumping thread, so
        # NO worker/reaper/pool threads exist and every interleaving
        # replays from the scheduler's seed
        self._sim = getattr(transport, "scheduler", None)
        transport.register(ep, self)
        if self._sim is None:
            self._worker = threading.Thread(target=self._run, daemon=True,
                                            name=f"msg-{ep.name}")
            self._worker.start()
            self._reaper = threading.Thread(target=self._reap, daemon=True)
            self._reaper.start()

    # ------------------------------------------------------ dispatch pool

    @property
    def dispatch_workers(self) -> int:
        return self._pool_target

    def set_dispatch_workers(self, n: int) -> None:
        """Hot-resize (internode_dispatch_threads; 0 = auto). Growing
        spawns immediately when the pool is live; shrinking retires
        surplus workers after their current message."""
        n = int(n)
        n = n if n > 0 else auto_dispatch_workers()
        with self._pool_lock:
            self._pool_target = n
            if self._pool and not self.closed:
                self._spawn_locked()

    def _spawn_locked(self) -> None:
        while len(self._pool) < self._pool_target:
            w = threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name=f"msg-dispatch-{self.ep.name}")
            self._pool.append(w)
            w.start()

    def pool_width(self) -> int:
        """Live worker count (test/telemetry surface — the worker-death
        blast-radius pin asserts this never shrinks silently)."""
        with self._pool_lock:
            return len(self._pool)

    # ------------------------------------------------------------- sending

    def register_handler(self, verb: str, fn) -> None:
        """fn(message) -> response payload | None (one-way)."""
        self.handlers[verb] = fn

    def send_one_way(self, verb: str, payload, to: Endpoint) -> None:
        msg = Message(verb, payload, self.ep, to, next(self._ids))
        st = tracing.active()
        if st is not None:
            msg.trace_session = st.session_id
            st.add(f"Sending {verb} to {to.name}")
        self.metrics["sent"] += 1
        self.transport.deliver(msg)

    def send_with_callback(self, verb: str, payload, to: Endpoint,
                           on_response, on_failure=None,
                           timeout: float = 5.0) -> int:
        msg = Message(verb, payload, self.ep, to, next(self._ids))
        st = tracing.active()
        if st is not None:
            # tracing header: the session id rides the message; the
            # failure wrapper records by id because expirations fire on
            # the reaper thread, outside this contextvar
            msg.trace_session = st.session_id
            st.add(f"Sending {verb} to {to.name}")
            sid, orig_fail = st.session_id, on_failure

            def on_failure(arg, _of=orig_fail, _sid=sid, _to=to, _v=verb):
                tracing.record(
                    _sid, f"Failure/timeout waiting for {_v} "
                          f"response from {_to.name}",
                    source=self.ep.name)
                if _of is not None:
                    _of(arg)
        with self._cb_lock:
            self._callbacks[msg.id] = (on_response, on_failure,
                                       time.monotonic() + timeout)
        self.metrics["sent"] += 1
        if self._sim is not None:
            self._sim.after(timeout, lambda: self._expire_one(msg.id),
                            f"timeout {self.ep.name}#{msg.id}")
        self.transport.deliver(msg)
        return msg.id

    def respond(self, original: Message, verb: str, payload,
                trace_events: list | None = None) -> None:
        if trace_events is not None \
                and len(trace_events) > TRACE_EVENTS_CAP:
            dropped = len(trace_events) - TRACE_EVENTS_CAP
            trace_events = trace_events[:TRACE_EVENTS_CAP]
            name = _VERB_TRACE_DROPPED.get(verb)
            if name is None:
                name = _VERB_TRACE_DROPPED[verb] = \
                    f"verb.{verb}.trace_dropped"
            METRICS.incr(name, dropped)
        msg = Message(verb, payload, self.ep, original.sender,
                      next(self._ids), reply_to=original.id,
                      trace_session=original.trace_session,
                      trace_events=trace_events)
        self.transport.deliver(msg)

    def respond_failure(self, original: Message, exc: Exception,
                        trace_events: list | None = None) -> None:
        """The one definition of the FAILURE_RSP wire shape; classify
        remote errors with failure_kind(), never by parsing repr text."""
        self.respond(original, Verb.FAILURE_RSP,
                     {"kind": type(exc).__name__, "error": repr(exc)},
                     trace_events=trace_events)

    @staticmethod
    def failure_kind(payload) -> str | None:
        """Exception class name from a FAILURE_RSP payload (None for
        reap-timeout bare ids or legacy shapes)."""
        return payload.get("kind") if isinstance(payload, dict) else None

    # ------------------------------------------------------------ receiving

    def inbound(self, msg: Message) -> None:
        self._queue.put(msg)

    def _run(self) -> None:
        """Distributor: pulls the inbound queue, routes response
        callbacks INLINE (this thread is the per-callback-id total
        order — acks for one request can never reorder), and hands
        verb-handler messages to the dispatch pool."""
        while not self.closed:
            try:
                msg = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._account(msg)
                if msg.reply_to:
                    self._process_response(msg)
                else:
                    self._dispatch_q.put(msg)
                    self._stage.note_queue(self._dispatch_q.qsize())
                    with self._pool_lock:
                        self._spawn_locked()
            except Exception:
                # a raising response callback must cost that MESSAGE,
                # never this node's single distributor thread — a dead
                # distributor leaves the node deaf with no trace (the
                # PR 4/PR 6 silent-daemon-death class, ctpulint
                # worker-loops)
                self.metrics["process_failures"] += 1

    def _dispatch_loop(self) -> None:
        """Pool worker: verb handlers only. A raising handler costs
        that MESSAGE (process_failures) and nothing else; a handler
        that escalates past Exception kills this thread, but the death
        is counted and the worker replaced (_respawn) — the pool never
        shrinks silently."""
        me = threading.current_thread()
        try:
            while True:
                with self._pool_lock:
                    if self.closed or len(self._pool) > self._pool_target:
                        if me in self._pool:
                            self._pool.remove(me)
                        return
                t_idle = time.monotonic()
                try:
                    msg = self._dispatch_q.get(timeout=self.POLL_SECONDS)
                except queue.Empty:
                    continue
                t0 = time.monotonic()
                self._stage.add_idle(t0 - t_idle)
                done = False
                try:
                    self._process_handler(msg)
                    done = True
                except Exception:
                    self.metrics["process_failures"] += 1
                    done = True
                finally:
                    # BaseException escaping a handler (the kill seam):
                    # still cost the message before the thread dies
                    if not done:
                        self.metrics["process_failures"] += 1
                    self._stage.add_busy(time.monotonic() - t0)
                    self._stage.add_items(1)
        finally:
            self._respawn(me)

    def _respawn(self, me: threading.Thread) -> None:
        """Replace a worker that died mid-message. Normal retirement
        (shutdown / surplus under a shrink) already removed `me` from
        the pool; a thread still listed here died abnormally, and the
        pool width must not degrade behind the operator's back."""
        with self._pool_lock:
            if me not in self._pool:
                return
            self._pool.remove(me)
            if self.closed:
                return
            self.metrics["dispatch_worker_deaths"] += 1
            self._spawn_locked()

    def _account(self, msg: Message) -> None:
        self.metrics["received"] += 1
        # per-verb group (InternodeInboundTable / per-verb Dropwizard
        # meters): verb.<verb>.received counters in the global registry;
        # names cached per verb so the hot path skips the f-string build
        name = _VERB_RECEIVED.get(msg.verb)
        if name is None:
            name = _VERB_RECEIVED[msg.verb] = \
                f"verb.{msg.verb.lower()}.received"
        METRICS.incr(name)

    def _process(self, msg: Message) -> None:
        """Handle one inbound message inline: response-callback dispatch
        or verb-handler execution (the deterministic simulator calls
        this directly as a scheduled event, so sim runs keep the exact
        pre-pool single-threaded interleaving)."""
        self._account(msg)
        if msg.reply_to:
            self._process_response(msg)
        else:
            self._process_handler(msg)

    def _process_response(self, msg: Message) -> None:
        """Response-callback dispatch: distributor-thread (or sim) only,
        so callbacks for one request id observe a total order."""
        if msg.trace_session and msg.trace_events:
            # replica events merge BEFORE the callback acks — the
            # waiting coordinator may finish (and persist) the
            # session the instant the callback fires
            tracing.record_remote(msg.trace_session, msg.trace_events,
                                  source=msg.sender.name)
        with self._cb_lock:
            cb = self._callbacks.pop(msg.reply_to, None)
        if cb is not None:
            on_response, on_failure, _ = cb
            # a FAILURE_RSP (remote handler raised) is a failure,
            # never an ack (write/hint acks must mean applied)
            fn = on_failure if msg.verb == Verb.FAILURE_RSP \
                else on_response
            if fn is not None:
                try:
                    # both callbacks receive the Message, so a
                    # failure handler can inspect the remote
                    # error payload (callbacks reaped on timeout
                    # get the bare id instead — see _reap)
                    fn(msg)
                except Exception:
                    pass

    def _process_handler(self, msg: Message) -> None:
        """Verb-handler execution (pool workers; inline in sim mode).
        Bills the per-verb ledger stage so the where-did-the-wall-go
        table can attribute replica-side time by verb."""
        handler = self.handlers.get(msg.verb)
        if handler is None:
            return
        # per-verb ledger stage (pipeline.messaging.<verb>.*), created
        # lazily for verbs this node actually handles
        vstage = self._verb_stages.get(msg.verb)
        if vstage is None:
            vstage = self._verb_stages[msg.verb] = \
                pipeline_ledger.ledger("messaging").stage(msg.verb.lower())
        rst = token = None
        if msg.trace_session:
            # replica-side session: record handler events under the
            # propagated id; they ship back on the response and merge
            # into the coordinator's timeline
            rst = tracing.TraceState(session_id=msg.trace_session,
                                     source=self.ep.name)
            rst.add(f"{msg.verb} received from {msg.sender.name}")
            token = tracing.activate(rst)
        t0 = time.monotonic()
        try:
            result = handler(msg)
        except Exception as e:
            if rst is not None:
                rst.add(f"{msg.verb} failed: {type(e).__name__}")
            self.respond_failure(msg, e,
                                 trace_events=rst.events if rst else None)
            return
        finally:
            vstage.add_busy(time.monotonic() - t0)
            vstage.add_items(1)
            if token is not None:
                tracing.deactivate(token)
        if result is not None:
            rsp_verb, payload = result
            if rst is not None:
                rst.add(f"Enqueuing {rsp_verb} to {msg.sender.name}")
            self.respond(msg, rsp_verb, payload,
                         trace_events=rst.events if rst else None)

    def _reap(self) -> None:
        """Expire callbacks whose responses never arrived."""
        while not self.closed:
            time.sleep(0.1)
            now = time.monotonic()
            expired = []
            with self._cb_lock:
                for mid, (ok, fail, deadline) in list(self._callbacks.items()):
                    if now > deadline:
                        expired.append((mid, fail))
                        del self._callbacks[mid]
            for mid, fail in expired:
                self.metrics["dropped_timeout"] += 1
                if fail is not None:
                    try:
                        fail(mid)
                    except Exception:
                        pass

    def _expire_one(self, mid: int) -> None:
        """Sim-mode callback expiry (the _reap role as a scheduled
        event): same contract — the failure callback gets the bare id."""
        with self._cb_lock:
            cb = self._callbacks.pop(mid, None)
        if cb is None:
            return
        _ok, fail, _deadline = cb
        self.metrics["dropped_timeout"] += 1
        if fail is not None:
            try:
                fail(mid)
            except Exception:
                pass

    def close(self) -> None:
        self.closed = True
        self.transport.unregister(self.ep)
