"""Seeded fuzzing against the model checker (the harry role —
test/harry/.../QuiescentChecker.java). Any failure prints the seed and
op index that reproduce it; set CTPU_FUZZ_SEED to replay.

TTL expiry runs against a VIRTUAL clock (utils/timeutil.CLOCK) moved by
the generator's `advance` ops, so expiring cells die mid-stream at
deterministic points and every run is replayable from its seed —
including the interaction of expiry with flush/compaction timing, which
is exactly where the three merge engines could silently diverge
(CASSANDRA-14592 ranking)."""
import os
import time

import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.tools.harry import Model, OpGenerator, check_partition
from cassandra_tpu.utils import timeutil

SEED = int(os.environ.get("CTPU_FUZZ_SEED", "20260729"))
N_OPS = int(os.environ.get("CTPU_FUZZ_OPS", "10000"))

DDL = ("CREATE TABLE t (k int, c int, v text, w int, st text static, "
       "m map<text,int>, PRIMARY KEY (k, c))")


@pytest.fixture
def vclock(monkeypatch):
    """Deterministic virtual clock for TTL expiry: the engine reads it
    through timeutil.CLOCK, the model gets it passed explicitly."""
    state = {"now": int(time.time())}
    monkeypatch.setattr(timeutil, "CLOCK", lambda: state["now"])
    return state


def _compact(node, engine=None):
    from cassandra_tpu.compaction.task import CompactionTask
    cfs = node.engine.store("fz", "t")
    inputs = list(cfs.live_sstables())
    if len(inputs) >= 2:
        if engine is None:
            CompactionTask(cfs, inputs).execute()
        else:
            CompactionTask(cfs, inputs, engine=engine).execute()


def _mk_cluster(tmp_path, n, rf):
    c = LocalCluster(n, str(tmp_path), rf=rf)
    for nd in c.nodes:
        nd.proxy.timeout = 2.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE fz WITH replication = "
              f"{{'class': 'SimpleStrategy', 'replication_factor': {rf}}}")
    s.execute("USE fz")
    s.execute(DDL)
    return c, s


def _drive(op, s, node, vclock, model, engine=None):
    """Apply one op to the engine and the model under the shared clock."""
    if op.kind == "advance":
        vclock["now"] += op.seconds
    elif op.kind == "flush":
        node.engine.store("fz", "t").flush()
    elif op.kind == "compact":
        _compact(node, engine)
    else:
        s.execute(op.cql("t"))
    model.apply(op, now_s=vclock["now"])


def test_fuzz_single_node(tmp_path, vclock):
    """10k seeded ops — TTLs, collections, statics, tombstone algebra —
    on one node with interleaved flush/compaction and virtual-clock
    advances; every partition checked against the model every 500 ops
    and at the end. This certifies the write path + merge/reconcile +
    expiry + deletion algebra end-to-end through CQL."""
    cluster, s = _mk_cluster(tmp_path, 1, 1)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.ONE
    gen = OpGenerator(SEED)
    model = Model()
    try:
        for op in gen:
            if op.index >= N_OPS:
                break
            _drive(op, s, node, vclock, model)
            if (op.index + 1) % 500 == 0:
                for pk in range(gen.n_pks):
                    check_partition(s, model, "t", pk, SEED, op.index,
                                    now=vclock["now"])
        node.engine.store("fz", "t").flush()
        _compact(node)
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED, N_OPS,
                            now=vclock["now"])
        # fast-forward past every short TTL: survivors must be exactly
        # the non-expiring + capped-overflow cells
        vclock["now"] += 200_000
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED, N_OPS,
                            now=vclock["now"])
    finally:
        cluster.shutdown()


def test_fuzz_cluster_with_drops(tmp_path, vclock):
    """Seeded ops against a 3-node RF=3 cluster while one replica's
    MUTATION stream is periodically dropped; after hints replay, every
    replica-quorum read must match the model (quiescent checking with
    faults — the harry-under-simulator role)."""
    from cassandra_tpu.cluster.messaging import Verb
    cluster, s = _mk_cluster(tmp_path, 3, 3)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.QUORUM
    gen = OpGenerator(SEED + 1)
    model = Model()
    n_ops = min(N_OPS, 2000)
    dropping = None
    try:
        for op in gen:
            if op.index >= n_ops:
                break
            if op.index % 400 == 200:       # start dropping a victim
                victim = cluster.nodes[1 + (op.index // 400) % 2]
                dropping = cluster.filters.drop(
                    verb=Verb.MUTATION_REQ, to=victim.endpoint)
            if op.index % 400 == 399 and dropping is not None:
                dropping["remaining"] = 0
                dropping = None
            _drive(op, s, node, vclock, model)
        if dropping is not None:
            dropping["remaining"] = 0
        # quiesce: hints must drain to every node
        deadline = time.time() + 30
        while time.time() < deadline:
            if not any(n.hints.has_hints(ep)
                       for n in cluster.nodes
                       for ep in cluster.ring.endpoints):
                break
            time.sleep(0.2)
        node.default_cl = ConsistencyLevel.ALL
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED + 1, n_ops,
                            now=vclock["now"])
        # and each node's LOCAL data alone serves the model: ONE with a
        # self-first replica ordering reads node i's own copy, so a
        # replica that hint-replay failed to converge is caught here
        for i in (1, 2, 3):
            si = cluster.session(i)
            si.keyspace = "fz"
            cluster.node(i).default_cl = ConsistencyLevel.ONE
            for pk in range(0, gen.n_pks, 3):
                check_partition(si, model, "t", pk, SEED + 1, n_ops,
                                now=vclock["now"])
    finally:
        cluster.shutdown()


def test_fuzz_engines_agree_with_ttls(tmp_path, vclock):
    """The same seeded TTL+collection stream compacted with the numpy
    spec engine must serve identical reads — AND the numpy/native
    engines must produce bit-identical sstable content on the final
    fuzz-shaped state (expiry conversions included). The bit-identity
    micro tests in test_merge_device.py do the exhaustive version."""
    cluster, s = _mk_cluster(tmp_path, 1, 1)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.ONE
    gen = OpGenerator(SEED + 2)
    model = Model()
    try:
        for op in gen:
            if op.index >= 1500:
                break
            _drive(op, s, node, vclock, model, engine="numpy")
        node.engine.store("fz", "t").flush()
        for pk in range(gen.n_pks):
            check_partition(s, model, "t", pk, SEED + 2, 1500,
                            now=vclock["now"])
        # cross-engine bit-identity on the accumulated fuzz state
        from cassandra_tpu.storage import cellbatch as cb
        cfs = node.engine.store("fz", "t")
        sources = []
        for sst in cfs.tracker.view():
            segs = list(sst.scanner())
            if segs:
                cat = cb.CellBatch.concat(segs)
                cat.sorted = True
                sources.append(cat)
        if len(sources) >= 2:
            a = cb.merge_sorted(sources, now=vclock["now"])
            from cassandra_tpu.ops.host_merge import merge_sorted_native
            b = merge_sorted_native(sources, now=vclock["now"])
            assert cb.content_digest(a) == cb.content_digest(b), (
                f"numpy vs native merge diverged on fuzz state "
                f"(seed {SEED + 2})")
    finally:
        cluster.shutdown()


def test_expiration_overflow_boundary(tmp_path, vclock):
    """TTL at MAX_TTL pushes now+ttl past the int32 horizon: the expiry
    must CAP (cell stays live), not wrap into the past and vanish
    (db/ExpirationDateOverflowHandling.java policy CAP); TTLs beyond
    MAX_TTL are rejected at validation."""
    from cassandra_tpu.cql.execution import InvalidRequest
    from cassandra_tpu.utils.timeutil import MAX_TTL, NO_DELETION_TIME
    cluster, s = _mk_cluster(tmp_path, 1, 1)
    node = cluster.node(1)
    node.default_cl = ConsistencyLevel.ONE
    try:
        s.execute(f"INSERT INTO t (k, c, v) VALUES (1, 1, 'cap') "
                  f"USING TTL {MAX_TTL}")
        rows = s.execute("SELECT c, v FROM t WHERE k = 1").rows
        assert rows == [(1, "cap")]
        batch = node.engine.store("fz", "t").read_partition(
            node.schema.get_table("fz", "t").partition_key_columns[0]
            .cql_type.serialize(1))
        assert int(batch.ldt.max()) == NO_DELETION_TIME - 1, (
            "expiry must cap at the int32 horizon, not overflow")
        with pytest.raises(InvalidRequest, match="too large"):
            s.execute(f"INSERT INTO t (k, c, v) VALUES (1, 2, 'x') "
                      f"USING TTL {MAX_TTL + 1}")
    finally:
        cluster.shutdown()


def test_expiry_rank_is_clock_independent(tmp_path):
    """CASSANDRA-14592 core property: two expiring writes to the same
    cell at the SAME timestamp with different expiries must reconcile
    identically whether the shorter-lived one was compacted (and so
    converted to a tombstone) before the merge or not."""
    import numpy as np

    from cassandra_tpu.schema import COL_REGULAR_BASE, make_table
    from cassandra_tpu.storage import cellbatch as cb
    t = make_table("ks", "t", pk=["k"], ck=["c"],
                   cols={"k": "int", "c": "int", "v": "text"})
    pk = t.columns["k"].cql_type.serialize(1)
    ck = t.serialize_clustering([1])

    def expiring(value, ldt):
        b = cb.CellBatchBuilder(t)
        b.append_raw(pk, ck, COL_REGULAR_BASE, b"", value, 5,
                     ldt=ldt, ttl=ldt - 1, flags=cb.FLAG_EXPIRING)
        return b.seal()

    x, z = expiring(b"short", 10), expiring(b"long", 30)
    now = 20   # x expired, z still alive
    # path A: merged together at now
    a = cb.merge_sorted([x, z], now=now)
    # path B: x compacted ALONE first (expired -> tombstone conversion
    # persists), then merged with z
    x_conv = cb.merge_sorted([expiring(b"short", 10)], now=now)
    assert bool(x_conv.flags[0] & cb.FLAG_TOMBSTONE)
    b_ = cb.merge_sorted([x_conv, z], now=now)
    assert cb.content_digest(a) == cb.content_digest(b_), (
        "merge outcome depends on when compaction ran relative to "
        "expiry — the equal-ts rank must be clock-independent")
    # and the long-lived value is the winner in both
    assert (a.flags[0] & cb.FLAG_TOMBSTONE) == 0
    assert a.cell_value(0) == b"long"
