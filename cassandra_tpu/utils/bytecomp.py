"""Byte-comparable encodings: map typed values to byte strings whose
unsigned lexicographic order equals the type's comparison order.

This is the substrate that lets the device merge kernel compare clustering
keys as fixed-width integer lanes (reference semantics:
src/java/org/apache/cassandra/utils/bytecomparable/ByteComparable.md and
ByteSourceInverse.java; our encodings are our own design, not the OSS41
format — we never need to interoperate with reference files).

Composite encoding: each component is escaped so that 0x00 never appears
raw (0x00 -> 0x00 0x01), then terminated with 0x00 0x00. A shorter
composite that is a prefix of a longer one therefore sorts first, and
component boundaries cannot bleed into each other. For DESC (reversed)
clustering columns the escaped component bytes are complemented and the
escape/terminator pair flips to 0xFF-based, preserving order reversal.
"""
from __future__ import annotations

import struct

SEP = b"\x00\x00"           # ascending terminator
SEP_DESC = b"\xff\xff"      # descending terminator

# ---------------------------------------------------------------- scalars --


def encode_int(v: int, width: int) -> bytes:
    """Signed big-endian with flipped sign bit: orders as signed compare."""
    bias = 1 << (width * 8 - 1)
    return (v + bias).to_bytes(width, "big")


def decode_int(b: bytes, width: int) -> int:
    bias = 1 << (width * 8 - 1)
    return int.from_bytes(b, "big") - bias


def encode_float(v: float, double: bool = True) -> bytes:
    """IEEE754 with the standard order-preserving transform:
    positive: flip sign bit; negative: flip all bits. NaNs sort last."""
    raw = struct.pack(">d", v) if double else struct.pack(">f", v)
    n = int.from_bytes(raw, "big")
    bits = 64 if double else 32
    if n >> (bits - 1):  # negative
        n = (~n) & ((1 << bits) - 1)
    else:
        n |= 1 << (bits - 1)
    return n.to_bytes(bits // 8, "big")


def decode_float(b: bytes, double: bool = True) -> float:
    bits = 64 if double else 32
    n = int.from_bytes(b, "big")
    if n >> (bits - 1):
        n &= (1 << (bits - 1)) - 1
    else:
        n = (~n) & ((1 << bits) - 1)
    raw = n.to_bytes(bits // 8, "big")
    return struct.unpack(">d" if double else ">f", raw)[0]


def encode_varint(v: int) -> bytes:
    """Arbitrary-precision integer, order-preserving.

    Layout: 1 length-class byte then magnitude. Positive: 0x80+len then BE
    magnitude; negative: 0x7F-len then complemented BE magnitude; zero: 0x80.
    Correct for |magnitude| < 2^(8*127)."""
    if v == 0:
        return b"\x80"
    if v > 0:
        mag = v.to_bytes((v.bit_length() + 7) // 8, "big")
        if len(mag) > 0x7F:
            raise ValueError("varint too large")
        return bytes([0x80 + len(mag)]) + mag
    m = -v
    mag = m.to_bytes((m.bit_length() + 7) // 8, "big")
    if len(mag) > 0x7E:
        raise ValueError("varint too large")
    comp = bytes(0xFF - b for b in mag)
    return bytes([0x7F - len(mag)]) + comp


def decode_varint(b: bytes) -> int:
    cls = b[0]
    if cls == 0x80:
        return 0
    if cls > 0x80:
        return int.from_bytes(b[1:1 + (cls - 0x80)], "big")
    n = 0x7F - cls
    mag = bytes(0xFF - x for x in b[1:1 + n])
    return -int.from_bytes(mag, "big")


# -------------------------------------------------------------- composite --


def escape_component(data: bytes, desc: bool = False) -> bytes:
    """Escape a component so the terminator can't be confused with data."""
    if not desc:
        return data.replace(b"\x00", b"\x00\x01")
    inv = bytes(0xFF - b for b in data)
    return inv.replace(b"\xff", b"\xff\xfe")


def unescape_component(data: bytes, desc: bool = False) -> bytes:
    if not desc:
        return data.replace(b"\x00\x01", b"\x00")
    raw = data.replace(b"\xff\xfe", b"\xff")
    return bytes(0xFF - b for b in raw)


def encode_composite(components: list[bytes], descending: list[bool] | None = None) -> bytes:
    """Concatenate escaped components with terminators. The result's
    lexicographic order equals tuple-wise order of the components (with
    per-component ASC/DESC)."""
    out = bytearray()
    for i, c in enumerate(components):
        desc = bool(descending[i]) if descending else False
        out += escape_component(c, desc)
        out += SEP_DESC if desc else SEP
    return bytes(out)


def decode_composite(data: bytes, n: int, descending: list[bool] | None = None) -> list[bytes]:
    """Split a composite back into n raw components."""
    comps = []
    pos = 0
    for i in range(n):
        desc = bool(descending[i]) if descending else False
        term = SEP_DESC if desc else SEP
        esc = b"\xff\xfe" if desc else b"\x00\x01"
        # scan for terminator not part of an escape
        j = pos
        while True:
            j = data.index(term[0:1], j)
            if data[j: j + 2] == esc:
                j += 2
                continue
            if data[j: j + 2] == term:
                break
            j += 1
        comps.append(unescape_component(data[pos:j], desc))
        pos = j + 2
    return comps
