"""cassandra_tpu — a TPU-native distributed database framework with the
capabilities of Apache Cassandra (reference: /root/reference, 5.1-dev).

Architecture (not a port):
  - Host runtime (Python + C++) owns files, networking, cluster state.
  - TPU (JAX/XLA/Pallas) is a batch coprocessor for the LSM data plane:
    segmented k-way sort-merge with timestamp reconciliation and tombstone
    purge, chunk codecs and checksums, bloom/hash batches, ANN search.
  - SSTables are *columnar*: fixed-width byte-comparable key lanes +
    metadata lanes + a variable-length payload blob, so device kernels
    operate on sorted fixed-shape arrays instead of row iterators
    (contrast: reference db/rows/* pull-based iterators).

Layer map (mirrors SURVEY.md section 1):
  cql/        CQL language layer         (ref: cql3/)
  cluster/    coordination + placement   (ref: service/, locator/, dht/, gms/)
  storage/    local storage engine       (ref: db/)
  compaction/ compaction + lifecycle     (ref: db/compaction/, db/lifecycle/)
  ops/        device kernels + codecs    (ref: utils/MergeIterator, io/compress/)
  parallel/   mesh sharding of kernels   (ref: db/compaction/ShardManager)
  types/      CQL type system            (ref: db/marshal/)
  utils/      substrate                  (ref: utils/)
"""

__version__ = "0.1.0"
