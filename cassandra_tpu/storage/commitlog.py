"""Commitlog: segmented durable WAL with CRC-framed records and replay.

Reference counterpart: db/commitlog/CommitLog.java:300 (add),
CommitLogSegment, AbstractCommitLogSegmentManager (segment rotation,
per-table dirty tracking), CommitLogReplayer (boot replay). Sync
strategies (AbstractCommitLogService subclasses, conf/cassandra.yaml
commitlog_sync options):
  'periodic'  buffered appends, background fsync every sync_period_ms;
              acked writes may be lost on crash inside the window.
  'batch'     durable before ack. Fast lane (CTPU_WRITE_FASTPATH=1):
              writers append to the buffered segment and PARK on a sync
              barrier; a SYNC LEADER elected among the parked writers
              runs one flush+fsync that acks every writer it covers, so
              N concurrent writers pay ~1 fsync instead of N
              (BatchCommitLogService with a zero window).
              Fast lane off: fsync inline under the segment lock.
  'group'     durable before ack like batch, but the syncer thread
              paces fsyncs commitlog_sync_group_window apart, trading
              ack latency for larger coalesced groups
              (GroupCommitLogService). Fast lane off: degrades to the
              inline-fsync batch behavior (same durability, no grouping).

Rotation is double-buffered on the fast lane: the outgoing segment is
handed to the syncer to flush+fsync+close+archive off the write path,
so appends to segment k+1 proceed while k syncs. Segments are block-
preallocated at open (fsutil.preallocate_keep_size).

Record frame: [u32 length][u32 crc32-of-payload][payload]. A zero length
or short read terminates replay of a segment (torn tail after crash).
"""
from __future__ import annotations

import logging
import os
import re
import struct
import threading
from ..utils import lockwitness
import time
import zlib

from ..utils import fsutil
from .mutation import Mutation

_SEG_RE = re.compile(r"^commitlog-(\d+)\.log$")

_log = logging.getLogger(__name__)


def write_fastpath_enabled() -> bool:
    """CTPU_WRITE_FASTPATH=0 disables the write-path fast lane (commitlog
    group commit, sharded memtable ingest, pipelined flush) for A/B runs
    (bench.py write_path section, scripts/check_writepath_ab.py). Read
    per call so a toggle mid-process takes effect immediately."""
    return os.environ.get("CTPU_WRITE_FASTPATH", "1") != "0"


class CommitLogPosition(tuple):
    """(segment_id, offset) — totally ordered."""
    def __new__(cls, segment_id: int, offset: int):
        return super().__new__(cls, (segment_id, offset))

    @property
    def segment_id(self):
        return self[0]

    @property
    def offset(self):
        return self[1]


_ENC_MAGIC = b"CTPUCLE1"   # encrypted segment: magic + u32 key id + nonce16
_ENC_HDR = len(_ENC_MAGIC) + 4 + 16
# compressed segment (db/commitlog/CompressedSegment.java role): magic +
# u8 codec-name length + codec name. Records in such a segment use the
# 12-byte frame [u32 stored_len][u32 crc][u32 raw_len]; raw_len ==
# stored_len marks an incompressible record stored raw. Composes with
# encryption as compress-then-encrypt (the reference's EncryptedSegment
# also compresses before encrypting); the CRC covers the stored bytes.
_COMP_MAGIC = b"CTPUCLC1"


class CommitLog:
    def __init__(self, directory: str, segment_size: int = 32 * 1024 * 1024,
                 sync_mode: str = "periodic", sync_period_ms: int = 1000,
                 archive_dir: str | None = None, encrypt: bool = False,
                 compression: str | None = None,
                 group_window_ms: float = 10.0,
                 failure_handler=None):
        """archive_dir: finished segments are copied there on rotation
        and at close (CommitLogArchiver role — the restore half is
        replay_archived / StorageEngine.restore_point_in_time).
        encrypt: segments carry an AES-CTR header and record payloads
        are keystream-XORed at their file offset
        (db/commitlog/EncryptedSegment.java role; CRCs cover ciphertext).
        group_window_ms: minimum spacing between fsyncs under
        sync_mode='group' (commitlog_sync_group_window).
        failure_handler: a storage.failures.FailureHandler — every sync
        failure funnels into its commit_failure_policy (stop_commit
        halts new writes while reads continue; ignore keeps the
        count-and-propagate behavior)."""
        self.directory = directory
        self._failure_handler = failure_handler
        self.segment_size = segment_size
        self.sync_mode = sync_mode
        self.sync_period_ms = sync_period_ms
        self.group_window_ms = group_window_ms
        self.archive_dir = archive_dir
        self.encrypt = encrypt
        self.compression = compression or None
        self._compressor = None
        if self.compression:
            from ..ops.codec import get_compressor
            self._compressor = get_compressor(self.compression)
        if archive_dir:
            os.makedirs(archive_dir, exist_ok=True)
        os.makedirs(directory, exist_ok=True)
        self._lock = lockwitness.make_lock("commitlog.append")
        existing = self.segment_ids()
        self._seg_id = (existing[-1] + 1) if existing else 1
        self._file = None
        self._seg_enc = None      # (key_id, nonce) of the open segment
        # archiver worker: rotation must not stall writers on a 32MB
        # copy+fsync (the reference archives asynchronously too); a
        # segment awaiting archive is protected from deletion
        self._archive_q: list[int] = []
        self._archiving: set[int] = set()
        self._archive_ev = threading.Event()
        self._archive_thread = None
        if archive_dir:
            # crash recovery: segments already on disk were finished by
            # the crash and were never archived (there was no clean
            # close) — archive them NOW, before boot replay flushes and
            # deletes them, or PITR silently loses the tail
            for seg in existing:
                self._archive(seg)
            self._archive_thread = threading.Thread(
                target=self._archive_loop, daemon=True,
                name="commitlog-archiver")
            self._archive_thread.start()
        # ---- group-commit sync barrier (AbstractCommitLogService role):
        # writers park in _await_sync until _synced covers their frame;
        # the syncer thread coalesces all parked writers into one fsync.
        self._sync_cond = lockwitness.make_condition("commitlog.sync_barrier")
        self._synced = CommitLogPosition(0, 0)
        self._sync_req = threading.Event()   # "waiters (or dirty retired
        #                                       segments) need a sync"
        self._waiting = 0                    # writers parked on the barrier
        self._leader_active = False          # a writer is running the sync
        self._sync_error: BaseException | None = None
        # serializes sync CYCLES (leader writer vs syncer thread): two
        # concurrent _do_sync calls could otherwise race a rotation —
        # one closing a just-retired file the other captured for fsync
        self._sync_mutex = lockwitness.make_lock("commitlog.sync_cycle")
        self._sync_failures = 0
        self._failure_logged = False
        self._last_sync = 0.0
        # rotated-but-unsynced segments: (seg_id, file) pairs the syncer
        # flushes, fsyncs, closes and hands to the archiver — the double
        # buffer that keeps rotation off the write path
        self._retiring: list[tuple[int, object]] = []
        from ..service.metrics import GLOBAL as _METRICS
        self._wait_hist = _METRICS.hist("commitlog.waiting_on_commit")
        self._sync_hist = _METRICS.hist("commitlog.sync_latency")
        self._metrics = _METRICS
        self._open_segment()
        # dirty tracking: segment -> set of table ids with unflushed writes
        self._dirty: dict[int, set] = {}
        self._stop = threading.Event()
        # ONE syncer for every mode: periodic ticks on sync_period_ms;
        # batch/group wake on _sync_req (fast lane) and stay idle when
        # writers fsync inline (fast lane off)
        self._syncer = threading.Thread(target=self._sync_loop,
                                        daemon=True,
                                        name="commitlog-syncer")
        self._syncer.start()

    # ------------------------------------------------------------ segments

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.directory, f"commitlog-{seg_id}.log")

    def segment_ids(self) -> list[int]:
        out = []
        for fn in os.listdir(self.directory):
            m = _SEG_RE.match(fn)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _open_segment(self) -> None:
        """Open the segment for _seg_id, retiring the previous file to
        the syncer (flush to the OS now so a concurrent _do_sync never
        misses buffered bytes; fsync+close+archive happen off the write
        path — the rotation half of the double buffer). Callers hold
        _lock except the __init__ call, which races nothing."""
        if self._file:
            prev = self._seg_id - 1
            self._file.flush()
            if self.archive_dir:
                # deletion must wait for the PITR copy; claim BEFORE the
                # retire becomes visible to discard_completed
                self._archiving.add(prev)
            self._retiring.append((prev, self._file))
            self._sync_req.set()
        self._file = open(self._seg_path(self._seg_id), "ab")
        self._seg_comp = None
        if self.encrypt:
            from . import encryption as enc_mod
            ctx = enc_mod.get_context()
            if ctx is None:
                raise enc_mod.EncryptionError(
                    "commitlog encryption requires an EncryptionContext")
            if self._file.tell() == 0:
                kid = ctx.current_key_id
                nonce = ctx.new_nonce()
                self._file.write(_ENC_MAGIC + kid.to_bytes(4, "little")
                                 + nonce)
                self._file.flush()
                self._seg_enc = (kid, nonce)
            else:   # restart onto a partially-written encrypted segment
                with open(self._seg_path(self._seg_id), "rb") as f:
                    hdr = f.read(_ENC_HDR)
                if not hdr.startswith(_ENC_MAGIC):
                    raise enc_mod.EncryptionError(
                        "existing active segment is not encrypted; "
                        "rotate before enabling encryption")
                self._seg_enc = (int.from_bytes(hdr[8:12], "little"),
                                 hdr[12:28])
        if self._compressor is not None:
            if self._file.tell() == 0 or (
                    self.encrypt and self._file.tell() == _ENC_HDR):
                name = self.compression.encode()
                self._file.write(_COMP_MAGIC + bytes([len(name)]) + name)
                self._file.flush()
            self._seg_comp = self._compressor
        # reserve the whole segment's blocks up front (KEEP_SIZE: st_size
        # stays at the append point so replay's EOF/torn-tail detection is
        # unaffected). The reference pre-creates fixed-size segments for
        # the same reason (CommitLogSegment); on this box extending
        # writes are ~75x slower than writes into reserved blocks.
        fsutil.preallocate_keep_size(
            self._file.fileno(), self._file.tell(),
            max(0, self.segment_size - self._file.tell()))

    # ----------------------------------------------------------------- add

    def _append_locked(self, mutation: Mutation,
                       payload: bytes) -> CommitLogPosition:
        """Frame + write one serialized mutation; caller holds _lock."""
        if self._file.tell() + len(payload) + 12 > self.segment_size:
            self._seg_id += 1
            self._open_segment()
        pos = CommitLogPosition(self._seg_id, self._file.tell())
        raw_len = len(payload)
        if self._seg_comp is not None:
            c = self._seg_comp.compress(payload)
            if len(c) < raw_len:
                payload = c
        if self._seg_enc is not None:
            from . import encryption as enc_mod
            kid, nonce = self._seg_enc
            hdr = 12 if self._seg_comp is not None else 8
            payload = enc_mod.get_context().xor_at(
                kid, nonce, pos.offset + hdr, payload)
        if self._seg_comp is not None:
            frame = struct.pack("<III", len(payload),
                                zlib.crc32(payload), raw_len) + payload
        else:
            frame = struct.pack("<II", len(payload),
                                zlib.crc32(payload)) + payload
        self._file.write(frame)
        self._dirty.setdefault(self._seg_id, set()).add(mutation.table_id)
        return pos

    def append(self, mutation: Mutation
               ) -> tuple[CommitLogPosition, CommitLogPosition | None]:
        """Append WITHOUT waiting for durability: returns (position,
        barrier) where barrier is the position await_durable must reach
        before the write may be acked (None when the mode needs no wait
        — periodic, or the record was inline-fsynced). Callers that
        hold a coarser lock (the ColumnFamilyStore write barrier) use
        this so the durability wait happens OUTSIDE that lock — parked
        writers must not serialize the writers behind them, or group
        commit coalesces nothing."""
        poss, barrier = self.append_batch([mutation])
        return poss[0], barrier

    def append_batch(self, mutations: list[Mutation]
                     ) -> tuple[list[CommitLogPosition],
                                CommitLogPosition | None]:
        """Batch form of append(): the whole batch lands under ONE lock
        acquisition and shares one durability barrier (the commitlog
        half of the batched write fast lane)."""
        if not mutations:
            return [], None
        payloads = [m.serialize() for m in mutations]
        fast = self.sync_mode in ("batch", "group") \
            and write_fastpath_enabled()
        barrier = None
        with self._lock:
            out = [self._append_locked(m, p)
                   for m, p in zip(mutations, payloads)]
            if self.sync_mode in ("batch", "group"):
                end = CommitLogPosition(self._seg_id, self._file.tell())
                if fast:
                    barrier = end
                else:
                    self._file.flush()
                    os.fsync(self._file.fileno())
        if not fast and self.sync_mode in ("batch", "group"):
            self._advance_synced(end)
        return out, barrier

    def await_durable(self, barrier: CommitLogPosition | None) -> None:
        """Block until a barrier returned by append/append_batch is
        durable (no-op for None)."""
        if barrier is not None:
            self._await_sync(barrier)

    def add(self, mutation: Mutation) -> CommitLogPosition:
        """Append a mutation; returns its position. With
        sync_mode='batch'/'group' the record is durable when this
        returns (CommitLog.add:300). Fast lane: the writer appends
        buffered and parks on the sync barrier; one syncer fsync acks
        every parked writer at once."""
        pos, barrier = self.append(mutation)
        self.await_durable(barrier)
        return pos

    def add_batch(self, mutations: list[Mutation]) -> list[CommitLogPosition]:
        """add() for a batch: one lock acquisition, one sync barrier."""
        out, barrier = self.append_batch(mutations)
        self.await_durable(barrier)
        return out

    # ------------------------------------------------------ sync barrier --

    def _advance_synced(self, pos: CommitLogPosition) -> None:
        with self._sync_cond:
            if pos > self._synced:
                self._synced = pos
            self._sync_error = None
            self._sync_cond.notify_all()

    def _await_sync(self, pos: CommitLogPosition) -> None:
        """Park until `pos` is durable (the reference's WaitQueue in
        AbstractCommitLogService.finishWriteFor).

        batch mode elects a SYNC LEADER among the parked writers: the
        first unsynced writer runs flush+fsync itself and releases
        everyone its sync covered; writers arriving during that fsync
        park, and one of them leads the next cycle back-to-back. A
        dedicated syncer thread would add a thread handoff (and, in a
        GIL runtime, a scheduling gap that measured LARGER than the
        fsync itself) to every cycle. group mode keeps the syncer
        thread: the window is a pacing decision, not a handoff."""
        t0 = time.perf_counter()
        lead_mode = self.sync_mode == "batch"
        with self._sync_cond:
            self._waiting += 1
            fail0 = self._sync_failures
        try:
            while True:
                lead = False
                with self._sync_cond:
                    if self._synced >= pos:
                        return
                    if self._sync_failures > fail0 \
                            and self._sync_error is not None:
                        # a sync attempted AFTER this append failed:
                        # durability cannot be confirmed — fail the
                        # write like the inline fsync would have.
                        # (An error predating this writer is not fatal
                        # on sight: this writer triggers a fresh sync,
                        # which either succeeds — releasing it — or
                        # fails anew and raises here.)
                        raise self._sync_error
                    if lead_mode and not self._leader_active:
                        self._leader_active = True
                        lead = True
                    else:
                        if not lead_mode:
                            # group mode: the syncer paces the cycles.
                            # (batch mode must NOT wake it — a leader
                            # exists or will be elected next iteration,
                            # and a parallel syncer cycle would double
                            # the fsyncs group commit just coalesced.)
                            self._sync_req.set()
                        self._sync_cond.wait(0.05)
                        if not lead_mode:
                            # re-arm against a lost wakeup (the syncer
                            # may have cleared the request while this
                            # writer was between append and park)
                            self._sync_req.set()
                if lead:
                    try:
                        self._do_sync()
                    except (OSError, ValueError) as e:
                        self._record_sync_failure(e)
                    finally:
                        with self._sync_cond:
                            self._leader_active = False
                            self._sync_cond.notify_all()
        finally:
            with self._sync_cond:
                self._waiting -= 1
            self._wait_hist.update_us((time.perf_counter() - t0) * 1e6)

    def sync(self) -> None:
        """Flush + fsync everything appended so far (public surface for
        close/tests; the syncer thread uses the same primitive)."""
        self._do_sync()

    def _do_sync(self) -> None:
        """One coalesced sync cycle: flush the active segment's python
        buffer under the lock, then fsync OUTSIDE it (appends to the
        same — or the next — segment proceed during the device flush),
        then release every writer parked at or before the synced
        position. Retired segments sync first so the position order
        (segment, offset) stays truthful. Cycles are serialized by
        _sync_mutex: a concurrent cycle could close a just-retired file
        this one captured for fsync."""
        t0 = time.perf_counter()
        with self._sync_mutex:
            with self._lock:
                from ..utils import faultfs
                # commitlog.fsync fault checkpoint: an injected EIO here
                # takes the same path a dying device would — caught by
                # the sync loop / leader, counted, routed to the commit
                # failure policy, propagated to parked writers
                faultfs.check("commitlog.fsync", self.directory)
                retiring = self._retiring
                self._retiring = []
                f = self._file
                target = None
                if f is not None and not f.closed:
                    f.flush()
                    target = CommitLogPosition(self._seg_id, f.tell())
            try:
                while retiring:
                    seg, rf = retiring[0]
                    rf.flush()
                    os.fsync(rf.fileno())
                    # durable: safe to drop from the re-queue window
                    # even if close/archive below has trouble
                    retiring.pop(0)
                    rf.close()
                    if self.archive_dir:
                        with self._lock:
                            self._archive_q.append(seg)
                        self._archive_ev.set()
                if target is not None:
                    os.fsync(f.fileno())
            except BaseException:
                # un-synced retired segments go BACK on the queue: a
                # later successful cycle advancing _synced past their
                # positions must not ack writers whose bytes were never
                # fsynced (and the archiver claim must stay honorable)
                with self._lock:
                    self._retiring[:0] = retiring
                raise
        self._sync_hist.update_us((time.perf_counter() - t0) * 1e6)
        self._last_sync = time.perf_counter()
        if target is not None:
            self._advance_synced(target)

    def _record_sync_failure(self, exc: BaseException) -> None:
        """Satellite fix: a failing sync used to kill the loop silently.
        Count it (commitlog.sync_failures), log ONCE, propagate to
        parked writers (their ack must not lie), keep the loop alive —
        a transient EIO/ENOSPC must not permanently disable syncing."""
        self._metrics.incr("commitlog.sync_failures")
        if not self._failure_logged:
            self._failure_logged = True
            _log.warning("commitlog sync failed (%s); further failures "
                         "are counted in commitlog.sync_failures", exc)
        with self._sync_cond:
            self._sync_failures += 1
            self._sync_error = exc
            self._sync_cond.notify_all()
        if self._failure_handler is not None:
            # commit_failure_policy decision (stop_commit halts future
            # writes at the engine gate; die/stop take the node out) —
            # AFTER the parked writers were released with the error
            self._failure_handler.handle_commit(exc)

    def _sync_loop(self) -> None:
        period = self.sync_period_ms / 1000.0
        while True:
            if self.sync_mode == "periodic":
                if self._stop.wait(period):
                    return
            else:
                self._sync_req.wait(timeout=0.2)
                if self._stop.is_set():
                    return
                if not self._sync_req.is_set():
                    continue
                self._sync_req.clear()
                if self.sync_mode == "group":
                    # spacing, not latency-from-request: coalesce every
                    # writer arriving inside the window since last sync
                    rem = self.group_window_ms / 1000.0 \
                        - (time.perf_counter() - self._last_sync)
                    if rem > 0 and self._stop.wait(rem):
                        return
            try:
                self._do_sync()
            except Exception as e:
                # EVERY sync failure — EIO, a closed fd (ValueError),
                # or an outright bug — routes through the
                # commit_failure_policy funnel; the syncer thread
                # itself must survive, or parked writers wait forever
                # on a durability that will never come (ctpulint
                # worker-loops; the PR 4 _sync_loop bug class)
                self._record_sync_failure(e)

    # -------------------------------------------------------------- replay

    def replay(self):
        """Yield (position, Mutation) for every intact record on disk
        (CommitLogReplayer semantics: stop a segment at the first torn
        record)."""
        for seg_id in self.segment_ids():
            yield from self._replay_file(self._seg_path(seg_id), seg_id)

    @staticmethod
    def _replay_file(path: str, seg_id: int):
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        enc = None
        comp = None
        if data.startswith(_ENC_MAGIC):
            from . import encryption as enc_mod
            ctx = enc_mod.get_context()
            if ctx is None:
                raise enc_mod.EncryptionError(
                    f"{path} is encrypted but no EncryptionContext is "
                    f"installed")
            enc = (ctx, int.from_bytes(data[8:12], "little"),
                   data[12:_ENC_HDR])
            pos = _ENC_HDR
        if data[pos:pos + len(_COMP_MAGIC)] == _COMP_MAGIC:
            from ..ops.codec import get_compressor
            nlen = data[pos + len(_COMP_MAGIC)]
            name = data[pos + len(_COMP_MAGIC) + 1:
                        pos + len(_COMP_MAGIC) + 1 + nlen].decode()
            comp = get_compressor(name)
            pos += len(_COMP_MAGIC) + 1 + nlen
        hdr = 12 if comp is not None else 8
        while pos + hdr <= len(data):
            if comp is not None:
                length, crc, raw_len = struct.unpack_from("<III", data,
                                                          pos)
            else:
                length, crc = struct.unpack_from("<II", data, pos)
                raw_len = length
            if length == 0 or pos + hdr + length > len(data):
                break  # torn tail
            payload = data[pos + hdr: pos + hdr + length]
            if zlib.crc32(payload) != crc:
                break  # corrupt tail
            if enc is not None:
                ctx, kid, nonce = enc
                payload = ctx.xor_at(kid, nonce, pos + hdr, payload)
            if comp is not None and length < raw_len:
                payload = comp.uncompress(bytes(payload), raw_len)
            yield CommitLogPosition(seg_id, pos), \
                Mutation.deserialize(bytes(payload))
            pos += hdr + length

    # ------------------------------------------------------------ archive

    def _archive(self, seg_id: int) -> None:
        """Copy a FINISHED (rotated/closed) segment to the archive
        (CommitLogArchiver.java:54 role; a directory copy stands in for
        the archive_command hook)."""
        if not self.archive_dir:
            return
        src = self._seg_path(seg_id)
        if not os.path.exists(src):
            return
        dst = os.path.join(self.archive_dir, os.path.basename(src))
        import shutil
        tmp = dst + ".tmp"
        shutil.copy2(src, tmp)
        with open(tmp, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def _archive_loop(self) -> None:
        while True:
            self._archive_ev.wait()
            self._archive_ev.clear()
            while True:
                with self._lock:
                    if not self._archive_q:
                        break
                    seg = self._archive_q.pop(0)
                try:
                    self._archive(seg)
                except Exception:
                    # archiving is best-effort PITR copy; any failure
                    # (I/O or bug) skips this segment but must not end
                    # the archiver thread (ctpulint worker-loops)
                    pass
                with self._lock:
                    self._archiving.discard(seg)

    def _deletable(self, seg_id: int) -> bool:
        """A segment pending archive must not be deleted: its PITR copy
        hasn't landed yet."""
        return seg_id not in self._archiving

    @classmethod
    def replay_archived(cls, archive_dir: str):
        """Yield (position, Mutation) from archived segments in order —
        the restore half of PITR (CommitLogArchiver restore_directories
        + restore_point_in_time)."""
        segs = []
        for fn in os.listdir(archive_dir):
            m = _SEG_RE.match(fn)
            if m:
                segs.append((int(m.group(1)), fn))
        for seg_id, fn in sorted(segs):
            yield from cls._replay_file(os.path.join(archive_dir, fn),
                                        seg_id)

    # ----------------------------------------------------- flush lifecycle

    def discard_completed(self, table_id, upto: CommitLogPosition) -> None:
        """Mark a table's writes flushed up to `upto`; delete segments no
        table dirties anymore (CommitLog.discardCompletedSegments)."""
        with self._lock:
            # a segment at/after the flush point may hold post-switch writes
            # for this table, so only older segments become clean
            for seg_id in list(self._dirty):
                if seg_id < upto.segment_id:
                    self._dirty[seg_id].discard(table_id)
                    if not self._dirty[seg_id] and seg_id != self._seg_id \
                            and self._deletable(seg_id):
                        try:
                            os.remove(self._seg_path(seg_id))
                        except FileNotFoundError:
                            pass
                        del self._dirty[seg_id]

    def forget_table(self, table_id) -> None:
        """A dropped table's writes no longer pin segments."""
        with self._lock:
            for seg_id in list(self._dirty):
                self._dirty[seg_id].discard(table_id)
                if not self._dirty[seg_id] and seg_id != self._seg_id \
                        and self._deletable(seg_id):
                    try:
                        os.remove(self._seg_path(seg_id))
                    except FileNotFoundError:
                        pass
                    del self._dirty[seg_id]

    def current_position(self) -> CommitLogPosition:
        with self._lock:
            return CommitLogPosition(self._seg_id, self._file.tell())

    def delete_segments_before(self, seg_id: int) -> None:
        for s in self.segment_ids():
            if s < seg_id and self._deletable(s):
                try:
                    os.remove(self._seg_path(s))
                except FileNotFoundError:
                    pass
                self._dirty.pop(s, None)

    def stats(self) -> dict:
        """One consistent operator view (nodetool commitlogstats + the
        system_views.commitlog status row)."""
        with self._lock:
            dirty = sorted(self._dirty)
            retiring = len(self._retiring)
            pos = CommitLogPosition(
                self._seg_id,
                self._file.tell() if self._file and not self._file.closed
                else 0)
        with self._sync_cond:
            waiting = self._waiting
            synced = self._synced
        files = []
        for seg in self.segment_ids():
            p = self._seg_path(seg)
            try:
                files.append((os.path.basename(p), os.path.getsize(p)))
            except OSError:
                continue
        return {
            "sync_mode": self.sync_mode,
            "segments": len(files),
            "total_bytes": sum(sz for _fn, sz in files),
            "files": files,
            "active_segment": pos.segment_id,
            "active_offset": pos.offset,
            "oldest_dirty": dirty[0] if dirty else None,
            "pending_syncs": waiting + retiring,
            "synced_segment": synced.segment_id,
            "synced_offset": synced.offset,
            "sync_failures": self._sync_failures,
        }

    def close(self) -> None:
        self._stop.set()
        self._sync_req.set()
        if self._syncer:
            self._syncer.join(timeout=2)
        # retired segments the syncer didn't get to: finish them inline
        # (flush+fsync+close, then the PITR copy)
        with self._lock:
            retiring = self._retiring
            self._retiring = []
        for seg, rf in retiring:
            try:
                rf.flush()
                os.fsync(rf.fileno())
                rf.close()
            except (OSError, ValueError):
                pass
            self._archive(seg)
            with self._lock:
                self._archiving.discard(seg)
        # drain pending async archives BEFORE the final archive so the
        # directory copy is complete when close() returns
        deadline = 50
        while deadline and self._archiving:
            import time as _t
            _t.sleep(0.1)
            deadline -= 1
        with self._lock:
            if self._file and not self._file.closed:
                self._file.flush()
                os.fsync(self._file.fileno())
                final = CommitLogPosition(self._seg_id, self._file.tell())
                self._file.close()
                # a cleanly-closed active segment is archivable too
                self._archive(self._seg_id)
            else:
                final = None
        if final is not None:
            # everything is durable: release any writer still parked
            self._advance_synced(final)
