"""Paxos-backed lightweight transactions (compare-and-set).

Reference counterpart: service/paxos/ (Paxos.java / Paxos.md — v2 rounds:
begin(prepare) -> read -> condition -> propose(accept) -> commit;
PaxosState per partition; in-flight proposals from a previous coordinator
are finished by the next prepare). Entry: StorageProxy.cas:305.

Single-decree per (table, partition, ballot): ballots are monotonic
(timestamp, endpoint) pairs; a quorum of promises is required to read the
linearization point, a quorum of accepts to decide, and commit applies the
mutation through the normal write path on all replicas.

PaxosState here is in-memory per process (the reference persists it in the
system.paxos table; crash-restart of a replica forgets promises, which can
only cause a retried round, not a lost committed write — commits go
through the durable write path).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..storage.mutation import Mutation
from .messaging import Verb
from .replication import ConsistencyLevel, ReplicationStrategy


class CasTimeout(Exception):
    pass


class CasContention(Exception):
    pass


@dataclass(order=True, frozen=True)
class Ballot:
    ts: int
    endpoint: str

    def pack(self):
        return (self.ts, self.endpoint)

    @staticmethod
    def unpack(t):
        return Ballot(t[0], t[1]) if t else None


ZERO = Ballot(0, "")


@dataclass
class PaxosState:
    promised: Ballot = ZERO
    accepted_ballot: Ballot | None = None
    accepted_value: bytes | None = None
    committed: Ballot = ZERO
    lock: threading.Lock = field(default_factory=threading.Lock)


class PaxosService:
    def __init__(self, node):
        self.node = node
        self._states: dict[tuple, PaxosState] = {}
        self._lock = threading.Lock()
        ms = node.messaging
        ms.register_handler("PAXOS_PREPARE", self._handle_prepare)
        ms.register_handler("PAXOS_PROPOSE", self._handle_propose)
        ms.register_handler("PAXOS_COMMIT", self._handle_commit)

    def _state(self, table_id, pk: bytes) -> PaxosState:
        key = (table_id, pk)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = PaxosState()
            return st

    # ------------------------------------------------------------ replicas

    def _handle_prepare(self, msg):
        table_id, pk, ballot_t = msg.payload
        ballot = Ballot.unpack(ballot_t)
        st = self._state(table_id, pk)
        with st.lock:
            if ballot > st.promised:
                st.promised = ballot
                return "PAXOS_PROMISE", {
                    "promised": True,
                    "accepted_ballot": st.accepted_ballot.pack()
                    if st.accepted_ballot else None,
                    "accepted_value": st.accepted_value,
                    "committed": st.committed.pack(),
                }
            return "PAXOS_PROMISE", {"promised": False,
                                     "promised_ballot": st.promised.pack()}

    def _handle_propose(self, msg):
        table_id, pk, ballot_t, value = msg.payload
        ballot = Ballot.unpack(ballot_t)
        st = self._state(table_id, pk)
        with st.lock:
            if ballot >= st.promised:
                st.promised = ballot
                st.accepted_ballot = ballot
                st.accepted_value = value
                return "PAXOS_ACCEPTED", {"accepted": True}
            return "PAXOS_ACCEPTED", {"accepted": False}

    def _handle_commit(self, msg):
        table_id, pk, ballot_t, value = msg.payload
        ballot = Ballot.unpack(ballot_t)
        st = self._state(table_id, pk)
        with st.lock:
            if ballot > st.committed:
                st.committed = ballot
                if st.accepted_ballot == ballot:
                    st.accepted_ballot = None
                    st.accepted_value = None
        if value:
            self.node.engine.apply(Mutation.deserialize(value))
        return "PAXOS_COMMITTED", {}

    # ---------------------------------------------------------- coordinator

    def _quorum_round(self, verb, payload, replicas, timeout, need):
        """Send a round to all live replicas (self included), wait for
        `need` responses (majority of the FULL replica set — partitions
        must not let both sides decide)."""
        node = self.node
        results = []
        lock = threading.Lock()
        ev = threading.Event()

        def collect(res):
            with lock:
                results.append(res)
                if len(results) >= need:
                    ev.set()

        handler = {"PAXOS_PREPARE": self._handle_prepare,
                   "PAXOS_PROPOSE": self._handle_propose,
                   "PAXOS_COMMIT": self._handle_commit}[verb]
        for ep in replicas:
            if ep == node.endpoint:
                from .messaging import Message
                m = Message(verb, payload, ep, ep)
                collect(handler(m)[1])
            else:
                node.messaging.send_with_callback(
                    verb, payload, ep,
                    on_response=lambda m: collect(m.payload),
                    timeout=timeout)
        if not ev.wait(timeout):
            raise CasTimeout(f"{verb}: {len(results)}/{need} responses")
        with lock:
            return list(results)

    def cas(self, keyspace: str, table, pk: bytes, ck: bytes, check_fn,
            mutation_fn, timeout: float = 5.0, attempts: int = 10):
        """Linearizable compare-and-set: check_fn(current_row_dict|None) ->
        bool; mutation_fn() -> Mutation applied iff the check passed.
        Returns (applied, current_row)."""
        node = self.node
        ks = node.schema.keyspaces[keyspace]
        strat = ReplicationStrategy.create(ks.params.replication)
        token = node.ring.token_of(pk)
        all_replicas = strat.replicas(node.ring, token) or [node.endpoint]
        # quorum from the CONFIGURED RF: SERIAL on an undersized ring must
        # refuse like QUORUM does, not decide with fewer promises than a
        # real majority of the replication factor (Paxos.java blockFor)
        need = strat.replication_factor() // 2 + 1
        live = [r for r in all_replicas if node.is_alive(r)]
        if len(live) < need:
            from .coordinator import UnavailableException
            raise UnavailableException(
                f"SERIAL requires {need}/{len(all_replicas)} replicas, "
                f"{len(live)} alive")

        last_contention = None
        for attempt in range(attempts):
            ballot = self._next_ballot()
            promises = self._quorum_round(
                "PAXOS_PREPARE", (table.id, pk, ballot.pack()),
                live, timeout, need)
            if not all(p.get("promised") for p in promises):
                last_contention = CasContention("prepare rejected")
                time.sleep(0.01 * (attempt + 1))
                continue
            # finish an in-flight accepted-but-uncommitted proposal first
            inflight = [(Ballot.unpack(p["accepted_ballot"]),
                         p["accepted_value"]) for p in promises
                        if p.get("accepted_ballot") is not None]
            if inflight:
                ib, iv = max(inflight, key=lambda x: x[0])
                acc = self._quorum_round(
                    "PAXOS_PROPOSE", (table.id, pk, ballot.pack(), iv),
                    live, timeout, need)
                if all(a.get("accepted") for a in acc):
                    self._quorum_round(
                        "PAXOS_COMMIT", (table.id, pk, ballot.pack(), iv),
                        live, timeout, need)
                # either way: retry our own round on fresh state
                continue

            # linearization-point read (QUORUM)
            current = self._read_row(keyspace, table, pk, ck)
            if not check_fn(current):
                return False, current

            mutation = mutation_fn()
            value = mutation.serialize()
            accepts = self._quorum_round(
                "PAXOS_PROPOSE", (table.id, pk, ballot.pack(), value),
                live, timeout, need)
            if not all(a.get("accepted") for a in accepts):
                last_contention = CasContention("propose rejected")
                time.sleep(0.01 * (attempt + 1))
                continue
            self._quorum_round("PAXOS_COMMIT",
                               (table.id, pk, ballot.pack(), value),
                               live, timeout, need)
            return True, current
        raise last_contention or CasContention("cas retries exhausted")

    _last_ballot_ts = 0
    _ballot_lock = threading.Lock()

    def _next_ballot(self) -> Ballot:
        """Wall-clock-derived monotonic ballots: comparable ACROSS
        processes (the reference uses UUID-v1 ballots for the same
        reason; monotonic_ns has a per-process epoch and must not be
        used)."""
        with self._ballot_lock:
            ts = max(time.time_ns(), PaxosService._last_ballot_ts + 1)
            PaxosService._last_ballot_ts = ts
        return Ballot(ts, self.node.endpoint.name)

    def _read_row(self, keyspace, table, pk, ck):
        from ..storage.rows import row_to_dict, rows_from_batch
        batch = self.node.proxy.read_partition(
            keyspace, table.name, pk, ConsistencyLevel.QUORUM)
        for r in rows_from_batch(table, batch):
            if not r.is_static and r.ck_frame == ck:
                return row_to_dict(table, r)
        return None
