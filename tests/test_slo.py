"""SLO layer (service/slo.py) + saturation-matrix plumbing.

Covers the ISSUE 11 acceptance surface unit-by-unit: error-budget math
under an injectable clock (burn, replenish, the exhaustion edge), the
`slo.breach` -> flight-recorder-dump path with dump dedup pinned, the
hot-reloadable `slo_targets` knob (retarget + per-CL registration), the
`system_views.slos` vtable and `nodetool slostats`, the per-CL tagging
of the front-door latency hists, and the stress driver's deterministic
seeded key streams with disjoint sequential partitioning.
"""
import json
import os
import sys

import pytest

from cassandra_tpu.schema import Schema
from cassandra_tpu.service import diagnostics
from cassandra_tpu.service.diagnostics import FlightRecorder
from cassandra_tpu.service.metrics import GLOBAL as METRICS
from cassandra_tpu.service.slo import SLObjective, SLOService
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.tools import nodetool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def eng(tmp_path):
    from cassandra_tpu.config import Config, Settings
    settings = Settings(Config.load({"diagnostic_events_enabled": True}))
    e = StorageEngine(str(tmp_path / "d"), Schema(),
                      commitlog_sync="periodic", settings=settings)
    yield e
    e.close()
    diagnostics.GLOBAL.reset()


def _svc(clock, target_ms=10.0, budget_s=3.0, window_s=30.0):
    """Engine-less service with one source-injected objective."""
    svc = SLOService(engine=None, clock=clock)
    p99 = {"v": 0.0}
    obj = svc.register(SLObjective(
        "t", hist="client_requests.read", target_ms=target_ms,
        budget_s=budget_s, window_s=window_s,
        source=lambda: p99["v"]))
    return svc, obj, p99


# ------------------------------------------------------- budget math --


def test_budget_burns_only_observed_breach_seconds():
    clock = Clock()
    svc, obj, p99 = _svc(clock)
    svc.check()                       # healthy baseline
    p99["v"] = 50_000.0
    clock.t += 5.0
    svc.check()                       # transition check: no burn yet
    assert obj.breaching and obj.breaches == 1
    assert obj.budget_remaining_s == 3.0
    clock.t += 1.25
    svc.check()                       # 1.25s observed in breach
    assert obj.budget_remaining_s == pytest.approx(1.75)


def test_budget_replenishes_at_fraction_and_caps():
    clock = Clock()
    svc, obj, p99 = _svc(clock, budget_s=3.0, window_s=30.0)
    svc.check()
    p99["v"] = 50_000.0
    clock.t += 1.0
    svc.check()
    clock.t += 2.0
    svc.check()                       # burned 2.0 -> 1.0 left
    assert obj.budget_remaining_s == pytest.approx(1.0)
    p99["v"] = 1_000.0
    clock.t += 0.5
    svc.check()                       # recover interval BEGAN in
    assert not obj.breaching          # breach: it still burns
    assert obj.budget_remaining_s == pytest.approx(0.5)
    clock.t += 10.0
    svc.check()                       # 10s * (3/30) = 1.0 replenished
    assert obj.budget_remaining_s == pytest.approx(1.5)
    clock.t += 1000.0
    svc.check()                       # capped at budget_s
    assert obj.budget_remaining_s == pytest.approx(3.0)


def test_flapping_objective_burns_its_breach_share():
    """p99 oscillating around the target every check must still burn
    roughly half the elapsed time — an interval is billed to the state
    it BEGAN in, so alternating breach/compliant cannot dodge the
    budget forever."""
    clock = Clock()
    svc, obj, p99 = _svc(clock, budget_s=2.0, window_s=1e9)
    svc.check()
    for i in range(8):                # breach, recover, breach, ...
        p99["v"] = 50_000.0 if i % 2 == 0 else 1_000.0
        clock.t += 0.25
        svc.check()
    # 4 of the 8 quarter-second intervals began in breach
    assert obj.budget_remaining_s == pytest.approx(2.0 - 4 * 0.25)


def test_exhaustion_edge_latches_and_unlatches():
    clock = Clock()
    svc, obj, p99 = _svc(clock, budget_s=1.0, window_s=10.0)
    svc.check()
    p99["v"] = 50_000.0
    clock.t += 1.0
    svc.check()                       # breach observed
    clock.t += 1.0
    svc.check()                       # burns exactly to 0.0
    assert obj.budget_remaining_s == 0.0
    assert obj.exhausted and obj.exhaustions == 1
    clock.t += 1.0
    svc.check()                       # still breaching: latched, once
    assert obj.exhaustions == 1
    assert len(diagnostics.GLOBAL.events("slo.budget_exhausted")) <= 1
    p99["v"] = 1_000.0
    clock.t += 1.0
    svc.check()                       # recover (no credit yet)
    clock.t += 1.0
    svc.check()                       # replenish > 0 unlatches
    assert not obj.exhausted and obj.budget_remaining_s > 0.0
    p99["v"] = 50_000.0
    clock.t += 0.1
    svc.check()
    clock.t += 5.0
    svc.check()                       # re-exhaust counts again
    assert obj.exhausted and obj.exhaustions == 2


def test_burn_to_zero_in_interval_ending_compliant_still_exhausts():
    """The zero-crossing is detected AT the burn: a breach interval
    that ends with a recovered p99 still exhausted the budget it spent
    breaching — the event must not be skipped just because the check
    lands after recovery."""
    diagnostics.GLOBAL.set_enabled(True)
    try:
        clock = Clock()
        svc, obj, p99 = _svc(clock, budget_s=1.0, window_s=10.0)
        svc.check()
        p99["v"] = 50_000.0
        clock.t += 1.0
        svc.check()                   # breach observed
        p99["v"] = 1_000.0            # recovered by the next check...
        clock.t += 2.0
        svc.check()                   # ...but the 2s began in breach
        assert not obj.breaching
        assert obj.budget_remaining_s == 0.0
        assert obj.exhausted and obj.exhaustions == 1
        assert len(
            diagnostics.GLOBAL.events("slo.budget_exhausted")) == 1
    finally:
        diagnostics.GLOBAL.reset()


def test_reset_rebaselines_state_but_keeps_tallies():
    clock = Clock()
    svc, obj, p99 = _svc(clock, budget_s=1.0, window_s=10.0)
    svc.check()
    p99["v"] = 50_000.0
    clock.t += 1.0
    svc.check()
    clock.t += 2.0
    svc.check()
    assert obj.breaching and obj.exhausted
    svc.reset()
    assert not obj.breaching and not obj.exhausted
    assert obj.budget_remaining_s == obj.budget_s
    assert obj.breaches == 1 and obj.exhaustions == 1   # lifetime kept
    # still-elevated p99 is a FRESH transition after reset (the matrix
    # leg-boundary contract: the new leg's scenario id gets stamped)
    clock.t += 0.1
    svc.check()
    assert obj.breaching and obj.breaches == 2


def test_breach_bundle_selfcontained_with_bus_disabled(eng):
    # the engine fixture enables the bus; withdraw every demand so this
    # runs under the DEFAULT disabled bus
    diagnostics.GLOBAL.reset()
    assert not diagnostics.GLOBAL.enabled
    clock = Clock()
    svc = SLOService(engine=eng, clock=clock)
    svc.recorder = FlightRecorder(engine=eng, clock=clock)
    svc.register(SLObjective("dark", hist="client_requests.read",
                             target_ms=10.0,
                             source=lambda: 99_000.0))
    try:
        clock.t += 1.0
        svc.check()
        assert not diagnostics.GLOBAL.events("slo.breach")  # bus: no-op
        assert len(svc.recorder.dumps) == 1
        with open(svc.recorder.dumps[0]) as f:
            bundle = json.load(f)
        # the black box still carries its own breach event (folded
        # directly, seq 0 marking the bus bypass)
        evs = [e for e in bundle["events"] if e["type"] == "slo.breach"]
        assert evs and evs[0]["seq"] == 0
    finally:
        svc.recorder.close()


def test_no_samples_is_not_a_breach():
    clock = Clock()
    svc = SLOService(clock=clock)
    obj = svc.register(SLObjective("empty", hist="slo_test.nothing",
                                   target_ms=0.001))
    clock.t += 1.0
    svc.check()
    assert not obj.breaching   # p99 of an empty window is 0 -> compliant


# --------------------------------------- breach -> bundle, dedup pinned --


def test_breach_publishes_event_and_dumps_deduplicated(eng):
    clock = Clock()
    svc = SLOService(engine=eng, clock=clock)
    svc.recorder = FlightRecorder(engine=eng, clock=clock)
    p99 = {"v": 99_000.0}
    svc.register(SLObjective("b", hist="client_requests.read",
                             target_ms=10.0, budget_s=5.0,
                             source=lambda: p99["v"]))
    svc.set_context(scenario="matrix:leg-x")
    try:
        clock.t += 1.0
        svc.check()
        evs = diagnostics.GLOBAL.events("slo.breach")
        assert len(evs) == 1
        assert evs[0].fields["objective"] == "b"
        assert evs[0].fields["scenario"] == "matrix:leg-x"
        assert len(svc.recorder.dumps) == 1
        with open(svc.recorder.dumps[0]) as f:
            bundle = json.load(f)
        assert bundle["reason"] == "slo_breach_b"
        assert bundle["trigger"]["scenario"] == "matrix:leg-x"
        types = [e["type"] for e in bundle["events"]]
        assert "slo.breach" in types   # event published BEFORE the dump
        # recover + re-breach inside the 5s dedup window: second event,
        # same single bundle
        p99["v"] = 1_000.0
        clock.t += 0.5
        svc.check()
        p99["v"] = 99_000.0
        clock.t += 0.5
        svc.check()
        assert len(diagnostics.GLOBAL.events("slo.breach")) == 2
        assert len(svc.recorder.dumps) == 1
        # past the window: a fresh transition dumps again (the long
        # breach interval also burns the budget out — that exhaustion
        # artifact rides under its own reason, counted separately)
        clock.t += FlightRecorder.DEDUP_WINDOW_S + 0.1
        p99["v"] = 1_000.0
        svc.check()
        p99["v"] = 99_000.0
        clock.t += 0.1
        svc.check()
        assert len([p for p in svc.recorder.dumps
                    if "slo_breach_" in p]) == 2
    finally:
        svc.recorder.close()


# ------------------------------------------------- knob + surfaces --


def test_slo_targets_knob_retargets_and_registers(eng):
    ro = eng.slo.objective("client_requests.read")
    assert ro is not None and ro.target_us == 250_000.0
    eng.settings.set("slo_targets",
                     {"client_requests.read": 5,
                      "client_requests.write.quorum": 12.5})
    assert ro.target_us == 5_000.0
    per_cl = eng.slo.objective("client_requests.write.quorum")
    assert per_cl is not None
    assert per_cl.hist == "client_requests.write.quorum"
    assert per_cl.target_us == 12_500.0


def test_slos_vtable_is_pure_and_slostats_checks(eng):
    checks0 = eng.slo.checks
    vt = eng.virtual_tables.get("system_views", "slos")
    rows = {r["objective"]: r for r in vt.rows()}
    assert {"client_requests.read",
            "client_requests.write"} <= set(rows)
    assert eng.slo.checks == checks0        # vtable read = no check
    st = nodetool.slostats(eng)
    assert eng.slo.checks == checks0 + 1    # slostats = one live check
    assert {v["objective"] for v in st["objectives"]} >= set(rows)
    for v in st["objectives"]:
        assert {"p99_us", "target_us", "breaching",
                "budget_remaining_s"} <= set(v)


def test_nodetool_info_reports_speculative_pair(eng):
    info = nodetool.info(eng)
    assert set(info["requests"]) == {"speculative_retries",
                                     "speculative_retries_won"}


# ------------------------------------- per-CL front-door tagging --


def test_client_requests_tagged_by_declared_cl(eng, tmp_path):
    from cassandra_tpu.client import Cluster
    from cassandra_tpu.transport import CQLServer
    srv = CQLServer(eng)
    before_one = METRICS.hist("client_requests.write.one").count
    before_q = METRICS.hist("client_requests.write.quorum").count
    before_blend = METRICS.hist("client_requests.write").count
    try:
        s = Cluster("127.0.0.1", srv.port).connect()
        s.execute("CREATE KEYSPACE cltag WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("CREATE TABLE cltag.t (k int PRIMARY KEY, v text)")
        s.execute("INSERT INTO cltag.t (k, v) VALUES (1, 'a')")
        s.execute("INSERT INTO cltag.t (k, v) VALUES (2, 'b')",
                  consistency="QUORUM")
        s.close()
    finally:
        srv.close()
    assert METRICS.hist("client_requests.write.one").count \
        >= before_one + 1
    assert METRICS.hist("client_requests.write.quorum").count \
        == before_q + 1
    # the blended hist still sees every request
    assert METRICS.hist("client_requests.write").count \
        >= before_blend + 2


# --------------------------------------------- stress determinism --


def _stress_mod():
    path = os.path.join(REPO, "scripts")
    if path not in sys.path:
        sys.path.insert(0, path)
    import stress
    return stress


def test_sequential_keys_partition_key_space_disjointly():
    st = _stress_mod()
    workers, key_space, n = 8, 320, 40
    slices = [st._keys("sequential", n, key_space, None, w, workers)
              for w in range(workers)]
    seen = set()
    for sl in slices:
        assert set(sl).isdisjoint(seen)   # no overlapping walkers
        seen.update(int(k) for k in sl)
    assert seen == set(range(key_space))  # exact coverage at ops==space
    # wrap within the slice when ops exceed the share — still disjoint
    long = st._keys("sequential", n * 3, key_space, None, 2, workers)
    assert set(long) == set(slices[2])
    # non-divisible key_space: balanced slices stay disjoint and the
    # union still covers every key (no lost tail)
    seen = set()
    for w in range(6):
        sl = set(int(k) for k in
                 st._keys("sequential", 512, 512, None, w, 6))
        assert sl.isdisjoint(seen)
        seen |= sl
    assert seen == set(range(512))


def test_key_streams_deterministic_under_seed():
    import numpy as np
    st = _stress_mod()
    for dist in ("uniform", "zipf", "sequential"):
        a = st._keys(dist, 64, 512,
                     np.random.default_rng(7 * 100_000 + 3), 3, 8)
        b = st._keys(dist, 64, 512,
                     np.random.default_rng(7 * 100_000 + 3), 3, 8)
        assert (a == b).all(), dist


def test_matrix_scenario_registry_covers_workload_classes():
    st = _stress_mod()
    assert {"kv", "wide", "timeseries", "counter", "lwt", "batch",
            "rmw"} <= set(st.SCENARIOS)
    legs = set(st.DEFAULT_LEGS)
    assert {s for s, _ in legs} == set(st.SCENARIOS)
    assert {d for _, d in legs} == {"zipf", "uniform", "sequential"}
