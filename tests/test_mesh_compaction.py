"""Mesh execution mode of the data plane (docs/multichip.md): mesh
compaction byte-identity vs the serial path, adversarial shard
completion orders, corrupt-input quarantine under mesh mode,
boundary-planning balance on skewed inputs, mesh batched reads /
range scans, knob hot-reload, and sim determinism."""
import importlib.util
import os

import numpy as np
import pytest

from cassandra_tpu.compaction.task import CompactionTask
from cassandra_tpu.parallel import fanout
from cassandra_tpu.parallel.mesh import (boundaries_from_indexes,
                                         boundaries_to_ranges,
                                         distinct_token_weights,
                                         plan_token_boundaries,
                                         shard_imbalance)
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.cellbatch import content_digest
from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
from cassandra_tpu.storage.table import ColumnFamilyStore
from cassandra_tpu.utils import faultfs

_AB = None


def _ab():
    """scripts/check_compaction_ab.py loaded once: the mesh tests reuse
    its fixture builder and component-hash machinery so the identity
    argument tested here is the same one CI pins."""
    global _AB
    if _AB is None:
        spec = importlib.util.spec_from_file_location(
            "check_compaction_ab",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "check_compaction_ab.py"))
        _AB = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_AB)
    return _AB


@pytest.fixture(autouse=True)
def _mesh_off_after():
    yield
    fanout.reset()   # drops engine-owned demands too, not just ours
    fanout._TEST_SHARD_DELAY = None
    faultfs.disarm()


def _seed_sstables(cfs, table, n=40_000, gens=(1, 2, 3)):
    for gen in gens:
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=256)
        w.append(_ab()._mixed_batch(table, seed=gen, n=n))
        w.finish()
    cfs.reload_sstables()


# ------------------------------------------------- boundary planning --

def test_plan_boundaries_balances_skewed_weights():
    """A hot token carrying 30% of the weight must not starve its
    neighbours: remaining shards re-balance around it and max/mean
    stays bounded by the hot token itself."""
    rng = np.random.default_rng(5)
    toks = np.sort(rng.choice(np.arange(10_000, dtype=np.uint64) * 7919,
                              4_000, replace=False))
    w = np.ones(len(toks), dtype=np.int64)
    w[123] = int(0.3 / 0.7 * len(toks))   # one token = 30% of total
    bounds = plan_token_boundaries(toks, w, 8)
    assert len(bounds) == 7
    sizes = np.zeros(8, dtype=np.int64)
    shard = np.searchsorted(bounds, toks, side="left")
    np.add.at(sizes, shard, w)
    # the hot token is unsplittable: its shard IS the max; everyone
    # else balances
    others = np.delete(sizes, int(shard[123]))
    assert shard_imbalance(others) <= 1.2, sizes.tolist()
    assert sizes.min() > 0


def test_distinct_weights_collapse_duplicates():
    """Weighting by raw cells overweights duplicate-heavy partitions;
    the planner weight source must count distinct identities (what
    survives the merge)."""
    table = _ab()._mk_table("w")
    b1 = _ab()._mixed_batch(table, seed=1, n=20_000)
    # duplicate the whole batch: raw cells double, distinct must not
    cat = cb.CellBatch.concat([b1, b1])
    uniq, w = distinct_token_weights(cat)
    assert int(w.sum()) == len(np.unique(
        np.ascontiguousarray(b1.lanes.astype(">u4"))
        .view(f"S{4 * b1.n_lanes}").ravel()))


def test_boundaries_from_indexes_skewed_fixture(tmp_path):
    """Planning from the input sstables' partition directories must hold
    the skewed fixture's per-shard INPUT spread at max/mean <= 1.2 —
    the MULTICHIP_r05 skew (21x kept-cell spread) this PR fixes."""
    table = _ab()._mk_table("skew")
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    rng = np.random.default_rng(3)
    from cassandra_tpu.tools import bulk
    for gen in (1, 2):
        n = 60_000
        hot = rng.random(n) < 0.4
        pk = np.where(hot, rng.integers(0, 2, n),
                      rng.integers(2, 2048, n))
        batch = cb.merge_sorted([bulk.build_int_batch(
            table, pk, rng.integers(1, 10_000, n),
            rng.integers(97, 122, (n, 16), dtype=np.uint8),
            rng.integers(1, 1 << 40, n).astype(np.int64))])
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=2048)
        w.append(batch)
        w.finish()
    cfs.reload_sstables()
    readers = cfs.tracker.view()
    bounds = boundaries_from_indexes(readers, 8)
    assert bounds is not None and len(bounds) == 7
    ranges = boundaries_to_ranges(bounds, 8)
    sizes = []       # post-merge (kept) cells per shard — the spread
    total_in = 0     # the planner's distinct weighting balances
    for lo, hi in ranges:
        slices = [w for r in readers
                  if (w := r.scan_tokens(lo, hi)) is not None and len(w)]
        total_in += sum(len(w) for w in slices)
        sizes.append(len(cb.merge_sorted(slices)) if slices else 0)
    assert total_in == sum(r.n_cells for r in readers)
    # index counts can't see CROSS-input duplicate collapse (they
    # max-combine per-sstable distinct counts), so the kept-cell spread
    # floor on this adversarial fixture is ~1.35 — still 15x better
    # than the 21x the single-batch sample produced (MULTICHIP_r05).
    # The exact-weight planner path is pinned at <= 1.2 by the
    # multichip entry sweep (__graft_entry__._dryrun_inner).
    assert shard_imbalance(sizes) <= 1.5, sizes


# ------------------------------------------------ compaction identity --

def test_mesh_compaction_byte_identity(tmp_path):
    """serial vs mesh-1 vs mesh-4: sha256-identical components and
    equal merged-view digests — the mesh drains shard results in token
    order through the same writer, so bytes cannot depend on the lane
    count."""
    ab = _ab()
    table = ab._mk_table("meshid")
    pristine = os.path.join(str(tmp_path), "pristine")
    cfs = ColumnFamilyStore(table, pristine, commitlog=None)
    for gen in (1, 2, 3):
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=256)
        w.append(ab._mixed_batch(table, seed=gen, n=60_000))
        w.finish()
    legs = {
        "serial": dict(mesh_devices=0),
        "mesh1": dict(mesh_devices=1),
        "mesh4": dict(mesh_devices=4),
    }
    results = {tag: ab._compaction_leg(str(tmp_path), pristine, table,
                                       tag, **kw)
               for tag, kw in legs.items()}
    ref_hashes, ref_digest = results["serial"]
    assert ref_hashes
    for tag, (hashes, digest) in results.items():
        assert hashes == ref_hashes, (tag, sorted(
            k for k in hashes if hashes[k] != ref_hashes.get(k)))
        assert digest == ref_digest, tag


def test_mesh_adversarial_completion_order(tmp_path):
    """Shards finishing in REVERSE order must not reorder output bytes:
    the drain walks shard 0..n-1 regardless of completion order."""
    ab = _ab()
    table = ab._mk_table("meshadv")
    pristine = os.path.join(str(tmp_path), "pristine")
    cfs = ColumnFamilyStore(table, pristine, commitlog=None)
    for gen in (1, 2):
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=256)
        w.append(ab._mixed_batch(table, seed=gen, n=40_000))
        w.finish()
    ref_hashes, ref_digest = ab._compaction_leg(
        str(tmp_path), pristine, table, "ref", mesh_devices=0)

    # make later shards finish FIRST (reverse completion)
    fanout._TEST_SHARD_DELAY = {0: 0.3, 1: 0.2, 2: 0.1, 3: 0.0}
    leg = os.path.join(str(tmp_path), "adv")
    import shutil
    shutil.copytree(pristine, leg)
    cfs2 = ColumnFamilyStore(table, leg, commitlog=None)
    cfs2.reload_sstables()
    task = CompactionTask(cfs2, cfs2.tracker.view(), mesh_devices=4)
    task.execute()
    fanout._TEST_SHARD_DELAY = None
    order = task._mesh_completion_order
    assert order != sorted(order), order   # the delays really inverted it
    assert ab._component_hashes(cfs2.directory) == ref_hashes
    assert ab._scan_digest(cfs2) == ref_digest
    for r in cfs2.live_sstables():
        r.close()


def test_mesh_compaction_purge_identity(tmp_path):
    """Tombstone/TTL purging interacts with sharding through gc_before
    and the purge gate: a mesh compaction that PURGES (deletions at
    every scope past gc_grace, expired TTLs) must still produce
    sha256-identical components to serial."""
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    from cassandra_tpu.storage.cellbatch import (FLAG_ROW_LIVENESS,
                                                 CellBatchBuilder)

    ab = _ab()
    table = ab._mk_table("meshpurge")
    table.params.gc_grace_seconds = 0   # everything purgeable at once
    pristine = os.path.join(str(tmp_path), "pristine")
    cfs = ColumnFamilyStore(table, pristine, commitlog=None)
    vcol = table.columns["v"].column_id
    rng = np.random.default_rng(4)
    old = 1_600_000_000
    for gen in (1, 2, 3):
        b = CellBatchBuilder(table)
        ts0 = gen * 1_000_000
        for p in range(192):
            pk = table.serialize_partition_key([p])
            if p % 9 == 0 and gen == 2:
                b.add_partition_deletion(pk, ts0 + 900_000, ldt=old)
            for c in range(40):
                ck = table.serialize_clustering([c])
                if p % 4 == 0 and c % 5 == 0 and gen == 3:
                    b.add_row_deletion(pk, ck, ts0 + c + 50, ldt=old)
                elif p % 6 == 0 and gen == 1:
                    b.add_tombstone(pk, ck, vcol, ts0 + c, ldt=old)
                else:
                    b.add_row_liveness(pk, ck, ts0 + c)
                    b.add_cell(pk, ck, vcol,
                               rng.integers(0, 256, 32,
                                            dtype=np.uint8).tobytes(),
                               ts0 + c,
                               ttl=(60 if p % 10 == 0 else 0))
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=192)
        w.append(cb.merge_sorted([b.seal()]))
        w.finish()
    ref_hashes, ref_digest = ab._compaction_leg(
        str(tmp_path), pristine, table, "serial", mesh_devices=0)
    mesh_hashes, mesh_digest = ab._compaction_leg(
        str(tmp_path), pristine, table, "mesh", mesh_devices=4)
    assert ref_hashes and mesh_hashes == ref_hashes
    assert mesh_digest == ref_digest


def test_mesh_corrupt_input_quarantine(tmp_path):
    """PR 5 semantics survive mesh mode: a corrupt input aborts ONLY
    the task, the bad sstable is quarantined, and the manager re-plans
    without it in the same submission."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_fault_tolerance import new_engine, pk_of, seeded

    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t, rounds=5)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    bad = gens[1]
    fanout.configure(4)
    faultfs.arm("sstable.read", "bitflip", path_substr=f"-{bad}-Data.db")
    eng.compactions.submit_background(cfs)
    n = eng.compactions.run_pending()
    faultfs.disarm()
    assert [q["generation"] for q in cfs.quarantined] == [bad]
    assert bad not in [s.desc.generation for s in cfs.live_sstables()]
    assert n >= 1
    assert len(cfs.read_partition(pk_of(t, 3))) > 0
    eng.close()


def test_mesh_deterministic_under_sim(tmp_path):
    """Same seed, mesh-4 compaction under the sim scheduler: identical
    sstable digests across runs — lane scheduling cannot leak into
    bytes (keeps the mesh leg simulable)."""
    from cassandra_tpu.sim.scheduler import simulated

    ab = _ab()
    table = ab._mk_table("meshsim")

    def run(tag):
        with simulated(99):
            cfs = ColumnFamilyStore(table, str(tmp_path / tag),
                                    commitlog=None)
            for gen in (1, 2):
                w = SSTableWriter(Descriptor(cfs.directory, gen), table)
                w.append(ab._mixed_batch(table, seed=gen, n=30_000))
                w.finish()
            cfs.reload_sstables()
            CompactionTask(cfs, cfs.tracker.view(), mesh_devices=3,
                           round_cells=8192).execute()
            [s] = cfs.live_sstables()
            with open(s.desc.path("Digest.crc32")) as f:
                return f.read().strip()

    assert run("a") == run("b")


# -------------------------------------------------------- read routes --

def _read_fixture(tmp_path, n=30_000):
    table = _ab()._mk_table("meshread")
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    _seed_sstables(cfs, table, n=n)
    return cfs, table


NOW = 1_700_000_000


def test_mesh_batched_reads_identical(tmp_path):
    cfs, table = _read_fixture(tmp_path)
    pks = [table.serialize_partition_key([k]) for k in range(0, 256, 2)]
    fanout.configure(0)
    ref = cfs.read_partitions(pks, now=NOW)
    fanout.configure(4)
    got = cfs.read_partitions(pks, now=NOW)
    assert len(ref) == len(got)
    for (pa, a), (pb, b) in zip(ref, got):
        assert pa == pb
        assert content_digest(a) == content_digest(b)


def test_mesh_batched_reads_small_batch_stays_serial(tmp_path):
    """Batches under MESH_READ_MIN_KEYS must not pay fan-out overhead:
    the mesh counters stay untouched."""
    from cassandra_tpu.service.metrics import GLOBAL
    cfs, table = _read_fixture(tmp_path, n=10_000)
    fanout.configure(4)
    before = GLOBAL.counter("mesh.batch_reads")
    pks = [table.serialize_partition_key([k]) for k in range(8)]
    cfs.read_partitions(pks, now=NOW)
    assert GLOBAL.counter("mesh.batch_reads") == before


def test_mesh_scan_all_identical(tmp_path):
    cfs, table = _read_fixture(tmp_path)
    fanout.configure(0)
    ref = cfs.scan_all(now=NOW)
    fanout.configure(4)
    got = cfs.scan_all(now=NOW)
    assert len(ref) == len(got)
    np.testing.assert_array_equal(ref.lanes, got.lanes)
    np.testing.assert_array_equal(ref.ts, got.ts)
    np.testing.assert_array_equal(ref.payload, got.payload)


def test_mesh_batched_reads_deletion_heavy_identity(tmp_path):
    """The shard-merge formulation (_shard_merge_slices: one merge per
    shard, sliced per partition) must survive deletions at every scope
    — partition deletions, row deletions, cell tombstones, TTL — with
    results identical to the per-key serial merges, including keys the
    merge fully purges and keys that don't exist."""
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    from cassandra_tpu.storage.cellbatch import (FLAG_ROW_LIVENESS,
                                                 CellBatchBuilder)

    table = _ab()._mk_table("meshdel")
    cfs = ColumnFamilyStore(table, str(tmp_path), commitlog=None)
    vcol = table.columns["v"].column_id
    rng = np.random.default_rng(9)
    for gen in (1, 2, 3):
        b = CellBatchBuilder(table)
        ts0 = gen * 1_000_000
        for p in range(256):
            pk = table.serialize_partition_key([p])
            if p % 7 == 0 and gen == 2:
                b.add_partition_deletion(pk, ts0 + 500_000, ldt=NOW - 10)
            for c in range(12):
                ck = table.serialize_clustering([c])
                ts = ts0 + c
                if p % 5 == 0 and c % 3 == 0 and gen == 3:
                    b.add_row_deletion(pk, ck, ts + 10, ldt=NOW - 10)
                elif p % 11 == 0 and gen == 1:
                    b.add_tombstone(pk, ck, vcol, ts + 5, ldt=NOW - 10)
                else:
                    b.add_row_liveness(pk, ck, ts)
                    b.add_cell(pk, ck, vcol,
                               rng.integers(0, 256, 24,
                                            dtype=np.uint8).tobytes(),
                               ts, ttl=(600 if p % 13 == 0 else 0))
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=256)
        w.append(cb.merge_sorted([b.seal()], now=NOW))
        w.finish()
    cfs.reload_sstables()
    # include keys that don't exist (negative lookups must stay empty)
    pks = [table.serialize_partition_key([p]) for p in range(300)]
    fanout.configure(0)
    ref = cfs.read_partitions(pks, now=NOW)
    fanout.configure(4)
    got = cfs.read_partitions(pks, now=NOW)
    for (pa, a), (pb, b_) in zip(ref, got):
        assert pa == pb
        assert len(a) == len(b_), pa
        assert content_digest(a) == content_digest(b_), pa


def test_mesh_reads_cover_memtable(tmp_path):
    """The mesh scan/read routes go through scan_window/_batched_merge,
    both of which consult the memtable — unflushed writes must appear."""
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    from cassandra_tpu.storage.cellbatch import FLAG_ROW_LIVENESS
    from cassandra_tpu.storage.mutation import Mutation

    cfs, table = _read_fixture(tmp_path, n=10_000)
    pk = table.serialize_partition_key([7])
    m = Mutation(table.id, pk)
    m.add(table.serialize_clustering([999_999]), COL_ROW_LIVENESS,
          b"", b"", 1 << 50, flags=FLAG_ROW_LIVENESS)
    cfs.apply(m)
    fanout.configure(0)
    ref = cfs.read_partitions([pk] * 1 + [
        table.serialize_partition_key([k]) for k in range(32)], now=NOW)
    ref_scan = cfs.scan_all(now=NOW)
    fanout.configure(4)
    got = cfs.read_partitions([pk] * 1 + [
        table.serialize_partition_key([k]) for k in range(32)], now=NOW)
    got_scan = cfs.scan_all(now=NOW)
    assert content_digest(ref[0][1]) == content_digest(got[0][1])
    assert content_digest(ref_scan) == content_digest(got_scan)
    assert len(got_scan) == len(ref_scan)


# ----------------------------------------------------- fanout + knob --

def test_fanout_preserves_shard_order_under_delay():
    fanout.configure(3)
    fan = fanout.get_fanout()
    fanout._TEST_SHARD_DELAY = {0: 0.2, 1: 0.1}
    out = fan.map_shards(lambda s: s * 10, 6)
    fanout._TEST_SHARD_DELAY = None
    assert out == [0, 10, 20, 30, 40, 50]


def test_fanout_propagates_errors():
    fanout.configure(2)
    fan = fanout.get_fanout()

    def boom(s):
        if s == 3:
            raise ValueError("shard 3 failed")
        return s

    with pytest.raises(ValueError, match="shard 3"):
        fan.map_shards(boom, 5)
    # the fanout survives for the next caller
    assert fan.map_shards(lambda s: s, 4) == [0, 1, 2, 3]


def test_fanout_knob_off_releases_queued_closures():
    """set_workers(0) drains the job queue: the last map_shards call's
    pull closures (which pin every shard result) must not stay
    referenced for the life of the process once the knob turns off."""
    fanout.configure(1)
    fan = fanout.get_fanout()
    assert fan.map_shards(lambda s: s, 8) == list(range(8))
    fanout.configure(0)
    assert fan.queue_depth() == 0


def test_mesh_knob_hot_reload(tmp_path):
    """compaction_mesh_devices wires through engine settings to the
    process-global fanout like compaction_compressor_threads does."""
    from cassandra_tpu.config import Config, Settings
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine

    eng = StorageEngine(str(tmp_path), Schema(),
                        settings=Settings(Config.load({})))
    try:
        assert fanout.mesh_devices() == 0
        assert fanout.get_fanout() is None
        eng.settings.set("compaction_mesh_devices", 4)
        assert fanout.mesh_devices() == 4
        fan = fanout.get_fanout()
        assert fan is not None and fan.workers == 4
        eng.settings.set("compaction_mesh_devices", 2)
        assert fanout.get_fanout().workers == 2
        eng.settings.set("compaction_mesh_devices", 0)
        assert fanout.get_fanout() is None
    finally:
        eng.close()


def test_mesh_knob_engine_scoped(tmp_path):
    """Co-hosted engines (LocalCluster shape) each route by their OWN
    knob: the shared pool sizes to the max demand, and one engine
    setting 0 neither disables the other's mesh mode nor shrinks its
    lanes. Closing an engine retires its demand."""
    from cassandra_tpu.config import Config, Settings
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine

    a = StorageEngine(str(tmp_path / "a"), Schema(),
                      settings=Settings(Config.load({})))
    b = StorageEngine(str(tmp_path / "b"), Schema(),
                      settings=Settings(Config.load({})))
    try:
        a.settings.set("compaction_mesh_devices", 4)
        assert fanout.mesh_devices() == 4
        assert a.compactions.mesh_devices_fn() == 4
        assert b.compactions.mesh_devices_fn() == 0
        # B's knob writes must not flip A's routing or shrink the pool
        b.settings.set("compaction_mesh_devices", 0)
        assert fanout.mesh_devices() == 4
        b.settings.set("compaction_mesh_devices", 2)
        assert fanout.mesh_devices() == 4
        assert b.compactions.mesh_devices_fn() == 2
        a.close()
        assert fanout.mesh_devices() == 2   # A's demand retired
    finally:
        b.close()
    assert fanout.mesh_devices() == 0


def test_task_inherits_knob(tmp_path):
    """mesh_devices=None inherits the knob; an explicit value wins."""
    cfs, table = _read_fixture(tmp_path, n=5_000)
    fanout.configure(3)
    t = CompactionTask(cfs, cfs.tracker.view())
    assert t._effective_mesh_devices() == 3
    t2 = CompactionTask(cfs, cfs.tracker.view(), mesh_devices=5)
    assert t2._effective_mesh_devices() == 5
    t3 = CompactionTask(cfs, cfs.tracker.view(), mesh_devices=0)
    assert t3._effective_mesh_devices() == 0
