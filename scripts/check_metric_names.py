#!/usr/bin/env python
"""CI check: every metric name registered in the codebase follows the
documented scheme (docs/observability.md):

    group(.sub)*.name — dot-separated, >= 2 components, each component
    lowercase [a-z0-9_]+ (the first starting with a letter).

Scanned call sites: .incr("...") / .hist("...") / .timer("...") /
.counter("...") / .register_gauge("...") / .group("...") string literals
(plain and f-strings) under cassandra_tpu/, scripts/ and bench.py.
f-string placeholders ({...}) count as one valid component — dynamic
parts like `table.{ks}.{name}.writes` pass structurally; their runtime
values are the caller's contract.

Names passed to a *group* facade (cfs.latency.hist("read_latency")) are
single components: the group prefix supplies the rest.

Beyond structure, every dotted name's TOP-LEVEL group must be one of
the documented groups (KNOWN_GROUPS — the "Established groups" list in
docs/observability.md plus the mesh.* data-plane group from
docs/multichip.md): a typo'd or undocumented group fails the check, so
new groups land in the docs the same commit they land in code.

Beyond the static scan, `main()` DIFFS THE DOCS AGAINST REALITY: a
deterministic engine-level smoke run (writes, flush, mesh compaction,
batched reads, slow query, audit, a fault) collects every metric name
actually emitted and compares it — both directions — against the
"Metric catalog" table in docs/observability.md:

  - emitted but undocumented        -> FAIL (document it)
  - documented but never emitted    -> FAIL (dead entry; delete it or
                                      mark it `(conditional)` if the
                                      smoke cannot deterministically
                                      reach it)

Catalog entries whose notes contain `(conditional)` or whose scope
column says `cluster`/`transport` are exempt from the dead-entry
direction (the engine smoke has no peers or wire clients) but still
participate in the undocumented direction.

Exit 0 = clean; exit 1 prints each violation.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# whole-file scan (\s* spans newlines): a literal on the line AFTER the
# open paren is still validated
CALL_RE = re.compile(
    r"\.(incr|hist|timer|counter|register_gauge|group)\(\s*f?([\"'])"
    r"(?P<name>[^\"']+)\2")

COMPONENT = r"[a-z][a-z0-9_]*"
ANY_COMPONENT = r"(?:[a-z0-9_]+|X)"      # X = collapsed f-placeholder
FULL_RE = re.compile(rf"^{COMPONENT}(\.{ANY_COMPONENT})+$")
PREFIX_RE = re.compile(rf"^{COMPONENT}(\.{ANY_COMPONENT})*$")
SINGLE_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# the documented top-level groups (docs/observability.md "Established
# groups" + the mesh.* group from docs/multichip.md)
KNOWN_GROUPS = {
    "audit", "client_requests", "clients", "commitlog", "compaction",
    "compress_pool", "controller", "cql", "flush", "hints", "history",
    "index", "mesh",
    "pipeline", "prepared_statements", "profile", "reads", "request",
    "scan", "slo", "storage", "streaming", "system", "table", "verb",
}


def _collapse_placeholders(name: str) -> str:
    return re.sub(r"\{[^{}]*\}", "X", name)


def check_name(method: str, raw: str) -> bool:
    name = _collapse_placeholders(raw)
    if method == "group":
        # dotless prefixes are indistinguishable from re.Match.group()
        # captures — only dotted prefixes get the group check
        return (PREFIX_RE.match(name) is not None
                and ("." not in name or _known_group(name)))
    if "." in name:
        return (FULL_RE.match(name) is not None
                and _known_group(name))
    # dotless: a group-member name (one component) — the group facade
    # supplied (and already validated) the prefix
    return SINGLE_RE.match(name) is not None


def _known_group(name: str) -> bool:
    top = name.split(".", 1)[0]
    # an f-placeholder top group is the caller's contract, not ours
    return top == "X" or top in KNOWN_GROUPS


def scan(paths=None) -> list[tuple[str, int, str, str]]:
    """[(relpath, lineno, method, name)] violations."""
    if paths is None:
        # module discovery is the shared ctpulint walker's
        # (cassandra_tpu/analysis/walker.py): both tools answer "what
        # are the project's modules" identically, so a file one scans
        # and the other misses cannot exist
        sys.path.insert(0, REPO)
        from cassandra_tpu.analysis.walker import project_files
        self_rel = os.path.relpath(os.path.abspath(__file__), REPO)
        paths = project_files(REPO, tops=("cassandra_tpu", "scripts"),
                              extras=("bench.py",),
                              exclude=(self_rel,))
    bad = []
    for p in sorted(paths):
        with open(p, encoding="utf-8") as f:
            text = f.read()
        for m in CALL_RE.finditer(text):
            method, name = m.group(1), m.group("name")
            if not check_name(method, name):
                lineno = text.count("\n", 0, m.start()) + 1
                bad.append((os.path.relpath(p, REPO), lineno,
                            method, name))
    return bad


# ------------------------------------------------------- docs <-> smoke --

# histogram snapshot suffixes collapse onto the base hist name
_HIST_SUFFIXES = (".count", ".mean_us", ".p50_us", ".p95_us",
                  ".p99_us", ".max_us")
# components replaced by X during normalization: the smoke run's
# keyspace/table names and any `<placeholder>` from the docs
_SMOKE_DYNAMIC = {"smoke", "t", "sc"}


def normalize_name(name: str) -> str:
    """Collapse an EMITTED metric name to its documented pattern:
    hist-snapshot suffixes stripped, dynamic components (the smoke
    fixture's keyspace/table, per-statement cql kinds, per-verb names,
    pipeline/stage names) replaced by X."""
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf):
            name = name[: -len(suf)]
            break
    parts = [("X" if p in _SMOKE_DYNAMIC else p)
             for p in name.split(".")]
    # per-statement counters (`cql.{kind}`) and per-verb counters
    # (`verb.{verb}.received`) are open-ended families: one catalog row
    if parts[0] == "cql" and len(parts) == 2 \
            and parts[1] not in ("request", "slow_queries"):
        parts[1] = "X"
    if parts[0] == "verb" and len(parts) == 3:
        parts[1] = "X"
    # pipeline stats: `pipeline.<pipeline>.<stage>.<stat>` — the
    # pipeline/stage catalog lives in the ledger doc section; the
    # metric catalog carries one row per STAT
    if parts[0] == "pipeline" and len(parts) == 4:
        parts[1] = parts[2] = "X"
    # per-consistency-level client-request hists
    # (`client_requests.<verb>.<cl>`) are an open-ended family: one
    # catalog row per verb
    if parts[0] == "client_requests" and len(parts) == 3:
        parts[2] = "X"
    return ".".join(parts)


def normalize_doc(name: str) -> str:
    """Collapse a DOCUMENTED metric name: `<ks>`-style placeholders
    become X."""
    return re.sub(r"<[^>]+>", "X", name)


def documented_catalog() -> dict[str, dict]:
    """Parse the docs/observability.md Metric catalog table:
    {normalized name: {raw, scope, notes}}. The table rows look like
    `| `storage.writes` | engine | counter; ... |`."""
    path = os.path.join(REPO, "docs", "observability.md")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"## Metric catalog\n(.*?)(?:\n## |\Z)", text, re.S)
    if not m:
        return {}
    out: dict[str, dict] = {}
    for row in re.finditer(
            r"^\|\s*`([^`]+)`\s*\|\s*([a-z]+)\s*\|\s*(.*?)\s*\|\s*$",
            m.group(1), re.M):
        raw, scope, notes = row.group(1), row.group(2), row.group(3)
        out[normalize_doc(raw)] = {"raw": raw, "scope": scope,
                                   "notes": notes}
    return out


def smoke_emitted() -> set[str]:
    """Run the deterministic engine-level smoke workload and return the
    NORMALIZED set of metric names it emitted (registry snapshot +
    engine-scoped gauges + per-table counter dict)."""
    import tempfile

    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from cassandra_tpu.config import Config, Settings
    from cassandra_tpu.cql import Session
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.service import diagnostics
    from cassandra_tpu.service.metrics import GLOBAL
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.utils import pipeline_ledger

    with tempfile.TemporaryDirectory() as base:
        settings = Settings(Config.load({
            "diagnostic_events_enabled": True,
            "compaction_mesh_devices": 2,
            "disk_failure_policy": "best_effort",
            "row_cache_size_mib": 4}))
        eng = StorageEngine(
            base, Schema(), commitlog_sync="batch",
            settings=settings,
            audit_log_path=os.path.join(base, "audit.jsonl"))
        try:
            s = Session(eng)
            s.execute("CREATE KEYSPACE smoke WITH replication = "
                      "{'class': 'SimpleStrategy', "
                      "'replication_factor': 1}")
            s.execute("USE smoke")
            s.execute("CREATE TABLE t (k int PRIMARY KEY, v text) "
                      "WITH caching = "
                      "{'rows_per_partition': 'ALL'}")
            cfs = eng.store("smoke", "t")
            # two generations so the major compaction + the batched
            # mesh read both have real work
            for gen in range(2):
                for i in range(64):
                    s.execute(f"INSERT INTO t (k, v) VALUES "
                              f"({i}, 'v{gen}-{i}')")
                cfs.flush()
            eng.compactions.major_compaction(cfs)
            # point + batched (mesh-fanned, >= 16 keys) + cached reads
            s.execute("SELECT v FROM t WHERE k = 1")
            s.execute("SELECT v FROM t WHERE k = 1")   # row-cache hit
            keys = ", ".join(str(i) for i in range(32))
            s.execute(f"SELECT v FROM t WHERE k IN ({keys})")
            # slow-query path (threshold 0: everything is slow)
            eng.monitor.threshold_ms = 0.0
            s.execute("SELECT v FROM t WHERE k = 2")
            # audit drop path: a wedged (closed) log file must count,
            # not raise
            eng.audit_log.close()
            s.execute("SELECT v FROM t WHERE k = 3")
            # one counted disk failure through the policy funnel
            # (best_effort: nothing stops)
            eng.failures.handle_disk(OSError(5, "smoke"), "smoke-path")
            # observatory: one on-demand history sample (history.samples
            # counter) — the retained-series layer must stay catalogued
            eng.metrics_history.sample()
            # control plane: one on-demand decision tick
            # (controller.ticks counter)
            eng.controller.tick()
            # continuous profiler: one on-demand wall-clock capture
            # (profile.samples counter) — layer 6 must stay catalogued
            from cassandra_tpu.service.sampler import GLOBAL as _sp
            _sp.sample_once()
            # analytical scan lane (ops/device_scan.py + the ZMP1 zone
            # maps): eager index build at flush, pushdown row +
            # aggregate queries, a provably-empty predicate (segment
            # AND sstable prune), a host-pinned reference leg, a torn
            # zone map (rebuild path) and an unsupported-kind fallback
            s.execute("CREATE TABLE sc (k int PRIMARY KEY, "
                      "v int, w varint)")
            s.execute("CREATE INDEX ON sc (v)")
            scs = eng.store("smoke", "sc")
            for i in range(64):
                s.execute(f"INSERT INTO sc (k, v, w) VALUES "
                          f"({i}, {i % 8}, {i})")
            scs.flush()                          # -> index.builds
            from cassandra_tpu.index import sstable_index as _ssi
            for r in scs.live_sstables():        # torn component ->
                os.remove(_ssi.zonemap_path(r.desc))   # ..rebuilds
            s.execute("SELECT k FROM sc WHERE v = 3 ALLOW FILTERING")
            s.execute("SELECT count(*) FROM sc WHERE v = 1000 "
                      "ALLOW FILTERING")          # every segment pruned
            s.execute("SELECT k FROM sc WHERE w = 5 "
                      "ALLOW FILTERING")          # varint: fallback
            from cassandra_tpu.ops import device_scan as _ds
            scs.scan_filtered(_ds.compile_predicate(  # host leg
                scs.table, [(scs.table.columns["v"], "=", 1)]),
                use_device=False)
            s.execute("CREATE INDEX ON sc (w)")  # post-flush index:
            s.execute("SELECT k FROM sc WHERE w = 5")  # lazy build
            emitted = set(GLOBAL.snapshot())
            emitted |= set(eng.compactions.gauges())
            for st in eng.stores.values():
                basek = f"table.{st.table.keyspace}.{st.table.name}"
                emitted |= {f"{basek}.{k}" for k in st.metrics}
                # derived per-table amplification gauges (served by the
                # metrics vtable beside the counter dict)
                emitted |= {f"{basek}.{k}"
                            for k in st.amplification()}
        finally:
            eng.close()
            diagnostics.GLOBAL.reset()
            pipeline_ledger.reset_all()
    return {normalize_name(n) for n in emitted}


def diff_docs(emitted: set[str] | None = None) -> list[str]:
    """Both-direction diff of the docs catalog vs the smoke run;
    returns violation strings (empty = clean)."""
    catalog = documented_catalog()
    if not catalog:
        return ["docs/observability.md has no Metric catalog section"]
    if emitted is None:
        emitted = smoke_emitted()
    problems = []
    for name in sorted(emitted - set(catalog)):
        problems.append(f"emitted but not in the docs catalog: {name}")
    for name, meta in sorted(catalog.items()):
        if name in emitted:
            continue
        if "(conditional)" in meta["notes"] \
                or meta["scope"] in ("cluster", "transport"):
            continue   # unreachable from an engine-only smoke run
        problems.append(
            f"documented but never emitted (dead entry?): "
            f"{meta['raw']}")
    return problems


def main() -> int:
    bad = scan()
    if bad:
        print("metric names outside the documented group.sub.name "
              "scheme (docs/observability.md):", file=sys.stderr)
        for path, lineno, method, name in bad:
            print(f"  {path}:{lineno}  .{method}({name!r})",
                  file=sys.stderr)
        return 1
    if "--scan-only" not in sys.argv:
        problems = diff_docs()
        if problems:
            print("docs/observability.md Metric catalog out of sync "
                  "with the smoke run:", file=sys.stderr)
            for p in problems:
                print(f"  - {p}", file=sys.stderr)
            return 1
        print("metric names OK; docs catalog matches the smoke run")
        return 0
    print("metric names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
