#!/usr/bin/env python
"""CI check: parallel-compression byte-identity A/B.

The parallel compress leg (storage/sstable/compress_pool.py + the
writer's ordered completion queue) promises BYTE-identical sstables for
any compressor pool size — including the serial path. That promise has
two load-bearing parts:

  - the ordered completion queue re-sequences out-of-order worker
    results before any sequential writer state (file offsets, index
    entries, digest folds) sees them;
  - the adaptive-compression-skip machine decides attempt flags from a
    FIXED-lag outcome stream (SSTableWriter.SKIP_DECISION_LAG), so the
    decision sequence cannot depend on completion timing or pool size.

This check exercises both with a workload built to CROSS skip-machine
transitions (alternating compressible text and incompressible random
partitions — the payload stream enters and leaves skip mode):

  1. the same input sstables major-compacted with the serial compress
     thread, a 1-worker pool and a 4-worker pool (+ decode-ahead),
     under the mesh execution mode (2 lanes, and 4 lanes combined with
     a 2-worker pool — docs/multichip.md: token-range shards drained in
     token order), under the DEVICE engine (device-resident rounds,
     ops/device_write.py — fused sort/reconcile/purge/serialize on the
     jax device incl. its per-round host fallbacks, plus the
     device+mesh-2 cross), and with DEVICE-SIDE BLOCK COMPRESSION
     (ops/device_compress.py — the policy-scan kernel compresses META +
     lanes on-device; alone, feeding a 2-worker pool's ordered
     completion queue, and crossed with mesh-2) must produce
     sha256-identical components AND equal merged-view content_digests;
  2. the same mutation set flushed with CTPU_WRITE_FASTPATH=0 (serial
     sort-and-write) and =1 over 1- and 4-worker shared pools must
     produce identical sstable bytes and read-back digests.

Run as a script (exit 1 on divergence) or through pytest
(tests/test_parallel_compress.py imports run_check).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FIXED_NOW = 1_700_000_000
HASHED_COMPONENTS = ("Data.db", "Index.db", "Partitions.db",
                     "Filter.db", "Statistics.db", "Digest.crc32")


def _mk_table(name: str):
    from cassandra_tpu.ops.codec import CompressionParams
    from cassandra_tpu.schema import TableParams, make_table

    return make_table(
        "abks", name, pk=["id"], ck=["c"],
        cols={"id": "int", "c": "int", "v": "blob"},
        params=TableParams(compression=CompressionParams(
            "LZ4Compressor", chunk_length=16 * 1024)))


def _mixed_batch(table, seed: int, n: int):
    """Sorted batch whose payload compressibility ALTERNATES by
    partition: even partitions carry lowercase text (compresses well),
    odd ones uniform random bytes (stores raw) — segments flip between
    the two, driving the skip machine through engage/probe/disengage."""
    import numpy as np

    from cassandra_tpu.storage import cellbatch as cb
    from cassandra_tpu.tools import bulk

    rng = np.random.default_rng(seed)
    pk = rng.integers(0, 256, n)
    ck = rng.integers(0, 100_000, n)
    text = rng.integers(97, 122, (n, 48), dtype=np.uint8)
    blob = rng.integers(0, 256, (n, 48), dtype=np.uint8)
    vals = np.where((pk % 2 == 0)[:, None], text, blob)
    ts = rng.integers(1, 1 << 40, n).astype(np.int64)
    return cb.merge_sorted([bulk.build_int_batch(table, pk, ck, vals, ts)])


def _component_hashes(directory: str) -> dict:
    out = {}
    for fn in sorted(os.listdir(directory)):
        p = os.path.join(directory, fn)
        if not os.path.isfile(p):
            continue
        if not any(fn.endswith(c) for c in HASHED_COMPONENTS):
            continue
        with open(p, "rb") as f:
            out[fn] = hashlib.sha256(f.read()).hexdigest()
    return out


def _scan_digest(cfs) -> bytes:
    from cassandra_tpu.storage.cellbatch import content_digest

    return content_digest(cfs.scan_all(now=FIXED_NOW))


# ------------------------------------------------------------ compaction --

def _compaction_leg(base: str, pristine: str, table, tag: str,
                    **task_kw) -> tuple[dict, bytes]:
    from cassandra_tpu.compaction.task import CompactionTask
    from cassandra_tpu.storage.table import ColumnFamilyStore

    leg = os.path.join(base, tag)
    shutil.copytree(pristine, leg)
    cfs = ColumnFamilyStore(table, leg, commitlog=None)
    cfs.reload_sstables()
    task = CompactionTask(cfs, cfs.tracker.view(), **task_kw)
    task.execute()
    hashes = _component_hashes(cfs.directory)
    digest = _scan_digest(cfs)
    for r in cfs.live_sstables():
        r.close()
    return hashes, digest


def check_compaction(base: str) -> list[str]:
    from cassandra_tpu.storage.sstable import Descriptor, SSTableWriter
    from cassandra_tpu.storage.sstable.compress_pool import CompressorPool
    from cassandra_tpu.storage.table import ColumnFamilyStore

    table = _mk_table("compact")
    pristine = os.path.join(base, "pristine")
    cfs = ColumnFamilyStore(table, pristine, commitlog=None)
    for gen in range(1, 4):
        w = SSTableWriter(Descriptor(cfs.directory, gen), table,
                          estimated_partitions=256)
        w.append(_mixed_batch(table, seed=gen, n=200_000))
        w.finish()

    legs = {
        "serial": dict(pipelined_io=False, compress_pool=0,
                       decode_ahead=False),
        "threaded": dict(pipelined_io=True, compress_pool=0,
                         decode_ahead=False),
        "pool1": dict(pipelined_io=True, compress_pool=CompressorPool(1),
                      decode_ahead=True),
        "pool4": dict(pipelined_io=True, compress_pool=CompressorPool(4),
                      decode_ahead=True),
        # mesh execution mode (docs/multichip.md): token-range-sharded
        # decode->merge fanned across mesh lanes, drained in token
        # order — bytes must match serial for any lane count, including
        # combined with the parallel compress pool
        "mesh2": dict(pipelined_io=True, compress_pool=0,
                      decode_ahead=False, mesh_devices=2),
        "mesh4_pool2": dict(pipelined_io=True,
                            compress_pool=CompressorPool(2),
                            decode_ahead=False, mesh_devices=4),
        # device engine, device-resident rounds (ops/device_write.py):
        # merge + purge + segment-cut + META serialize run on the jax
        # device; the mixed fixture's equal-ts duplicates also push
        # rounds through the per-round host fallback — both sides of
        # the residency decision must land the same bytes
        "device": dict(pipelined_io=True, compress_pool=0,
                       decode_ahead=False, engine="device",
                       use_device=True, device_compress=False),
        # device engine crossed with the mesh execution mode: shards
        # fan across jax devices and drain host-side in token order
        "device_mesh2": dict(pipelined_io=True, compress_pool=0,
                             decode_ahead=False, engine="device",
                             use_device=True, mesh_devices=2),
        # device-side block compression (ops/device_compress.py): full
        # segments arrive at the writer ALREADY LZ4-compressed by the
        # fused policy-scan kernel; the mixed fixture crosses skip-
        # machine transitions, so attempted/raw decisions and the
        # compress-vs-raw boundary must land identically to the native
        # packer on every stream
        "device_compress": dict(pipelined_io=True, compress_pool=0,
                                decode_ahead=False, engine="device",
                                use_device=True, device_compress=True),
        # device compression feeding the ordered completion queue of a
        # live compressor pool: device-born jobs (ready pre-set) and
        # pool jobs (partial final segment, per-segment fallbacks)
        # interleave in submit order
        "device_compress_pool2": dict(pipelined_io=True,
                                      compress_pool=CompressorPool(2),
                                      decode_ahead=False,
                                      engine="device", use_device=True,
                                      device_compress=True),
        # the mesh cross: shards drain through the host writer (the
        # device-resident lane is a serial-round mode), so this pins
        # that device_compress=True stays inert — and byte-identical —
        # under the mesh execution mode
        "device_compress_mesh2": dict(pipelined_io=True, compress_pool=0,
                                      decode_ahead=False,
                                      engine="device", use_device=True,
                                      mesh_devices=2,
                                      device_compress=True),
    }
    results = {tag: _compaction_leg(base, pristine, table, tag, **kw)
               for tag, kw in legs.items()}
    for kw in legs.values():
        pool = kw["compress_pool"]
        if pool:
            pool.shutdown(timeout=5.0)

    diverged = []
    ref_tag = "serial"
    ref_hashes, ref_digest = results[ref_tag]
    if not ref_hashes:
        diverged.append("compaction produced no components to compare")
    for tag, (hashes, digest) in results.items():
        if tag == ref_tag:
            continue
        if hashes != ref_hashes:
            bad = sorted(set(hashes) ^ set(ref_hashes)) or sorted(
                k for k in hashes if hashes[k] != ref_hashes.get(k))
            diverged.append(
                f"compaction {tag} vs {ref_tag}: component bytes "
                f"differ: {bad}")
        if digest != ref_digest:
            diverged.append(
                f"compaction {tag} vs {ref_tag}: merged-view "
                f"content_digest differs")
    return diverged


# ----------------------------------------------------------------- flush --

def _flush_mutations(table):
    """Deterministic mutation set, compressibility alternating by
    partition like the compaction fixture; fixed timestamps so every
    leg writes identical cells."""
    from cassandra_tpu.schema import COL_ROW_LIVENESS
    from cassandra_tpu.storage.cellbatch import FLAG_ROW_LIVENESS
    from cassandra_tpu.storage.mutation import Mutation

    vcol = table.columns["v"].column_id
    muts = []
    text = b"abcdefghijklmnopqrstuvwx" * 2
    for k in range(160):
        pkb = table.serialize_partition_key([k])
        for c in range(450):
            m = Mutation(table.id, pkb)
            ck = table.serialize_clustering([c])
            ts = 1_000_000 + k * 1000 + c
            if k % 2 == 0:
                val = text
            else:   # deterministic pseudo-random bytes
                val = hashlib.sha256(b"%d-%d" % (k, c)).digest() + \
                    hashlib.sha256(b"x%d-%d" % (k, c)).digest()[:16]
            m.add(ck, COL_ROW_LIVENESS, b"", b"", ts,
                  flags=FLAG_ROW_LIVENESS)
            m.add(ck, vcol, b"", val, ts)
            muts.append(m)
    return muts


def _flush_leg(base: str, table, tag: str, fast: bool,
               pool_workers: int) -> tuple[dict, bytes]:
    from cassandra_tpu.storage.sstable import compress_pool
    from cassandra_tpu.storage.table import ColumnFamilyStore

    os.environ["CTPU_WRITE_FASTPATH"] = "1" if fast else "0"
    compress_pool.configure(pool_workers)
    try:
        cfs = ColumnFamilyStore(table, os.path.join(base, tag),
                                commitlog=None)
        muts = _flush_mutations(table)
        for i in range(0, len(muts), 512):
            cfs.apply_batch(muts[i:i + 512])
        cfs.flush()
        hashes = _component_hashes(cfs.directory)
        digest = _scan_digest(cfs)
        for r in cfs.live_sstables():
            r.close()
        return hashes, digest
    finally:
        os.environ.pop("CTPU_WRITE_FASTPATH", None)
        compress_pool.configure(0)   # back to auto


def check_flush(base: str) -> list[str]:
    table = _mk_table("flush")
    legs = {
        "serial": (False, 1),
        "fast_pool1": (True, 1),
        "fast_pool4": (True, 4),
    }
    results = {tag: _flush_leg(base, table, tag, fast, w)
               for tag, (fast, w) in legs.items()}
    diverged = []
    ref_hashes, ref_digest = results["serial"]
    if not ref_hashes:
        diverged.append("flush produced no components to compare")
    for tag, (hashes, digest) in results.items():
        if tag == "serial":
            continue
        if hashes != ref_hashes:
            bad = sorted(set(hashes) ^ set(ref_hashes)) or sorted(
                k for k in hashes if hashes[k] != ref_hashes.get(k))
            diverged.append(
                f"flush {tag} vs serial: component bytes differ: {bad}")
        if digest != ref_digest:
            diverged.append(
                f"flush {tag} vs serial: content_digest differs")
    return diverged


# ------------------------------------------------------------------ main --

def run_check(base_dir: str | None = None) -> list[str]:
    own = base_dir is None
    base = base_dir or tempfile.mkdtemp(prefix="ctpu-compab-")
    try:
        diverged = check_compaction(os.path.join(base, "compaction"))
        diverged += check_flush(os.path.join(base, "flush"))
        return diverged
    finally:
        if own:
            shutil.rmtree(base, ignore_errors=True)


def main() -> int:
    diverged = run_check()
    if diverged:
        print("parallel-compression A/B DIVERGED:", file=sys.stderr)
        for d in diverged:
            print(f"  {d}", file=sys.stderr)
        return 1
    print("compaction/flush parallel-compression A/B: zero divergence "
          "(serial vs threaded vs pool-1 vs pool-4 vs mesh-2 vs "
          "mesh-4+pool-2 vs device-resident vs device+mesh-2 vs "
          "device-compress vs device-compress+pool-2 vs "
          "device-compress+mesh-2)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
