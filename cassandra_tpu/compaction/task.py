"""CompactionTask: the streaming device-merge rewrite of N sstables.

Reference counterpart: db/compaction/CompactionTask.java:114 (runMayThrow;
the hot loop :207-225 `while (ci.hasNext()) writer.append(ci.next())`),
CompactionIterator.java:90 (merge + purge pipeline) and
CompactionController.java:55 (purgeability from overlapping sources).

TPU formulation: instead of a row-at-a-time heap, each round buffers one
batch per input run, finds the safe merge boundary (min of the runs'
buffered maxima), merges everything below it in ONE device kernel call
(ops/merge.py), and appends the result to the output writer. Disk I/O
(segment decode) and device merge alternate per round; batches are large
(64K cells) so the device amortises.
"""
from __future__ import annotations

import time

import numpy as np

from ..ops import merge as dmerge
from ..storage import cellbatch as cb
from ..storage.lifecycle import LifecycleTransaction
from ..storage.sstable import Descriptor, SSTableReader, SSTableWriter
from ..utils import timeutil


def _lane_keys(batch: cb.CellBatch) -> np.ndarray:
    """Rows as fixed-width byte strings (lexicographic == lane order)."""
    K = batch.n_lanes
    return np.ascontiguousarray(batch.lanes.astype(">u4")).view(
        f"S{4 * K}").ravel()


def _full_key(batch: cb.CellBatch, i: int) -> bytes:
    """Row i's lane key as exactly 4*K bytes. numpy S-dtype strips trailing
    NUL bytes; comparisons re-pad, but PREFIX SLICING must not see a
    shortened string — always pad before [:16]."""
    K = batch.n_lanes
    return bytes(_lane_keys(batch)[i]).ljust(4 * K, b"\x00")


class _Cursor:
    """Buffered scanner over one input sstable.

    Merge rounds are PARTITION-ALIGNED: deletion markers sort at the start
    of their partition/row, so reconcile is only correct when a round sees
    whole partitions (the reference's CompactionIterator merges per
    partition for the same reason). A partition larger than one segment is
    buffered whole — acceptable for round 1; the reference streams within
    partitions via its row index."""

    def __init__(self, reader: SSTableReader):
        self._it = reader.scanner()
        self.bufs: list[cb.CellBatch] = []
        self.exhausted = False
        self._fetch()

    def _fetch(self) -> bool:
        try:
            self.bufs.append(next(self._it))
            return True
        except StopIteration:
            self.exhausted = True
            return False

    @property
    def has_data(self) -> bool:
        return bool(self.bufs)

    def last_key(self) -> bytes:
        return _full_key(self.bufs[-1], -1)

    def extend_past_partition(self, prefix16: bytes) -> None:
        """Buffer more segments until the buffered data no longer ENDS
        inside the given partition (or the input is exhausted). Segments
        accumulate in a list — concat happens once, at slice time."""
        while self.bufs and self.last_key()[:16] == prefix16:
            if not self._fetch():
                return

    def split_at(self, boundary: bytes) -> cb.CellBatch | None:
        """Take cells with key <= boundary from the buffer; refill when the
        whole buffer is consumed."""
        if not self.bufs:
            return None
        buf = self.bufs[0] if len(self.bufs) == 1 \
            else cb.CellBatch.concat(self.bufs)
        buf.sorted = True
        keys = _lane_keys(buf)
        idx = int(np.searchsorted(keys, np.bytes_(boundary), side="right"))
        if idx == 0:
            self.bufs = [buf]
            return None
        if idx >= len(buf):
            self.bufs = []
            self._fetch()
            return buf
        head = buf.slice_range(0, idx)
        tail = buf.slice_range(idx, len(buf))
        self.bufs = [tail]
        return head


class CompactionController:
    """Purge decisions: a tombstone may only be dropped if no source
    OUTSIDE the compaction could still hold older shadowed data for its
    partition (CompactionController.java:61-121 maxPurgeableTimestamp).

    The overlap set is re-read per batch — a flush landing mid-compaction
    produces a new sstable (and the construction-time memtable is checked
    too), so concurrently-written older-timestamp data can never be purged
    against (the reference refreshes overlaps once a minute for the same
    reason)."""

    def __init__(self, cfs, compacting: list[SSTableReader]):
        self.cfs = cfs
        self.compacting_gens = {r.desc.generation for r in compacting}
        self.memtable_at_start = cfs.memtable

    def _overlapping(self) -> list[SSTableReader]:
        return [s for s in self.cfs.live_sstables()
                if s.desc.generation not in self.compacting_gens]

    def purgeable_ts_fn(self, batch: cb.CellBatch) -> np.ndarray:
        n = len(batch)
        out = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
        overlapping = self._overlapping()
        mems = {id(m): m for m in (self.memtable_at_start,
                                   self.cfs.memtable)}.values()
        mems = [m for m in mems if not m.is_empty]
        if not overlapping and not mems:
            return out
        lane4 = batch.lanes[:, :4]
        part_new = np.ones(n, dtype=bool)
        part_new[1:] = (lane4[1:] != lane4[:-1]).any(axis=1)
        part_id = np.cumsum(part_new) - 1
        starts = np.flatnonzero(part_new)
        per_part = np.full(len(starts), np.iinfo(np.int64).max,
                           dtype=np.int64)
        for j, s in enumerate(starts):
            pk = batch.partition_key(int(s))
            lo = np.iinfo(np.int64).max
            for src in overlapping:
                if src.might_contain(pk) and src.min_ts is not None:
                    lo = min(lo, src.min_ts)
            if any(m.contains(pk) for m in mems):
                lo = min(lo, 0)  # memtable data is never purged against
            per_part[j] = lo
        return per_part[part_id]


class CompactionTask:
    def __init__(self, cfs, inputs: list[SSTableReader],
                 max_output_bytes: int | None = None,
                 level: int = 0, use_device: bool = True):
        self.cfs = cfs
        self.inputs = inputs
        self.max_output_bytes = max_output_bytes
        self.level = level
        self.use_device = use_device

    def execute(self) -> dict:
        """Run the compaction; returns stats (reference logs these at
        CompactionTask.java:252-266)."""
        cfs = self.cfs
        table = cfs.table
        t0 = time.time()
        gc_before = timeutil.now_seconds() - table.params.gc_grace_seconds
        now = timeutil.now_seconds()
        controller = CompactionController(cfs, self.inputs)
        merge_fn = dmerge.merge_sorted_device if self.use_device \
            else cb.merge_sorted

        txn = LifecycleTransaction(cfs.directory)
        writers: list[SSTableWriter] = []
        new_readers: list[SSTableReader] = []
        bytes_read = sum(r.data_size for r in self.inputs)
        cells_read = sum(r.n_cells for r in self.inputs)
        cells_written = 0

        def new_writer() -> SSTableWriter:
            gen = cfs.next_generation()
            desc = Descriptor(cfs.directory, gen)
            txn.track_new(gen)
            w = SSTableWriter(desc, table,
                              estimated_partitions=max(
                                  sum(r.n_partitions for r in self.inputs), 16))
            w.level = self.level
            writers.append(w)
            return w

        try:
            writer = new_writer()
            cursors = [_Cursor(r) for r in self.inputs]
            while True:
                active = [c for c in cursors if c.has_data]
                if not active:
                    break
                # partition-aligned round: find the minimal buffered-through
                # key, then make sure no cursor's buffer ends INSIDE that
                # key's partition, and merge everything up to the partition
                # end (full key width padded with 0xFF)
                prefix16 = min(c.last_key() for c in active)[:16]
                for c in cursors:
                    c.extend_past_partition(prefix16)
                K = self.inputs[0].K
                boundary = prefix16 + b"\xff" * (4 * K - 16)
                slices = []
                for c in cursors:
                    s = c.split_at(boundary)
                    if s is not None and len(s):
                        slices.append(s)
                if not slices:
                    continue
                merged = merge_fn(slices, gc_before=gc_before, now=now,
                                  purgeable_ts_fn=controller.purgeable_ts_fn)
                if len(merged):
                    writer.append(merged)
                    cells_written += len(merged)
                if self.max_output_bytes and \
                        writer._data_off >= self.max_output_bytes:
                    # roll the output (MaxSSTableSizeWriter role)
                    writer.finish()
                    new_readers.append(SSTableReader(writer.desc))
                    writer = new_writer()
            writer.finish()
            new_readers.append(SSTableReader(writer.desc))
            for r in self.inputs:
                txn.track_obsolete(r.desc.generation)
            # empty outputs (everything purged) die in the same txn
            live_new = []
            for r in new_readers:
                if r.n_cells > 0:
                    live_new.append(r)
                else:
                    r.close()
                    txn.track_obsolete(r.desc.generation)
            # COMMIT first (a failure here must roll back cleanly while the
            # tracker still serves the inputs), then swap the live view;
            # input files may already be unlinked but their open fds keep
            # serving in-flight reads. Inputs are RELEASED, not closed
            # (reference SSTableReader ref-counting, utils/concurrent/Ref).
            txn.commit()
            cfs.tracker.replace(self.inputs, live_new)
            for r in self.inputs:
                r.release()
        except BaseException:
            for w in writers:
                try:
                    w.abort()
                except Exception:
                    pass
            for r in new_readers:
                r.close()
            txn.abort()   # no-op if the COMMIT record already landed
            raise

        dt = time.time() - t0
        bytes_written = sum(r.data_size for r in new_readers)
        stats = {
            "inputs": len(self.inputs),
            "outputs": len([r for r in new_readers if r.n_cells > 0]),
            "bytes_read": bytes_read,
            "bytes_written": bytes_written,
            "cells_read": cells_read,
            "cells_written": cells_written,
            "seconds": dt,
            "read_mib_s": bytes_read / dt / 2**20 if dt > 0 else 0,
            "write_mib_s": bytes_written / dt / 2**20 if dt > 0 else 0,
        }
        if cfs.compaction_history is not None:
            cfs.compaction_history.append(stats)
        return stats
