"""Key cache: partition-key -> partition location, shared by readers.

Reference counterpart: cache/KeyCacheKey.java + the key cache in
CacheService.java:108 — avoids the partition-index walk on repeat point
reads. Matters most for summary-mode sstables (large partition
directories kept downsampled in memory, storage/sstable/reader.py):
a hit skips the on-disk directory bracket scan entirely.

Entries key on (directory, generation, pk) — generation-scoped like the
chunk cache, so stale entries can never serve a new sstable. Persisted
across restarts by storage/saved_caches.py (AutoSavingCache role).
"""
from __future__ import annotations

import threading
from collections import OrderedDict


class KeyCache:
    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple):
        with self._lock:
            v = self._lru.get(key)
            if v is None:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key: tuple, value: tuple) -> None:
        with self._lock:
            self._lru[key] = value
            self._lru.move_to_end(key)
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()

    def invalidate_generation(self, directory: str, generation: int):
        """Drop a dead sstable's entries eagerly (truncate path — the
        generation number can be REUSED by a store recreated over the
        same directory)."""
        with self._lock:
            dead = [k for k in self._lru
                    if k[0] == directory and k[1] == generation]
            for k in dead:
                del self._lru[k]

    def keys(self) -> list[tuple]:
        with self._lock:
            return list(self._lru)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._lru), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses}


GLOBAL = KeyCache()
