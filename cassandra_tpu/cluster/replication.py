"""Replication strategies: token -> replica set.

Reference counterpart: locator/AbstractReplicationStrategy (SimpleStrategy,
NetworkTopologyStrategy with per-DC RF and rack spreading, LocalStrategy),
locator/ReplicaPlans (consistency-level math).
"""
from __future__ import annotations

from .ring import Endpoint, Ring


class ReplicationStrategy:
    def __init__(self, options: dict):
        self.options = options

    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        raise NotImplementedError

    @staticmethod
    def create(options: dict) -> "ReplicationStrategy":
        cls = str(options.get("class", "SimpleStrategy")).rsplit(".", 1)[-1]
        if cls == "SimpleStrategy":
            return SimpleStrategy(options)
        if cls == "NetworkTopologyStrategy":
            return NetworkTopologyStrategy(options)
        if cls == "LocalStrategy":
            return LocalStrategy(options)
        raise ValueError(f"unknown replication strategy {cls}")


class SimpleStrategy(ReplicationStrategy):
    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        rf = int(self.options.get("replication_factor", 1))
        out: list[Endpoint] = []
        for ep in ring.successors(token):
            if ep not in out:
                out.append(ep)
            if len(out) >= rf:
                break
        return out


class NetworkTopologyStrategy(ReplicationStrategy):
    """Per-DC replication factor, spreading across racks within a DC
    (locator/NetworkTopologyStrategy.calculateNaturalReplicas)."""

    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        rf_by_dc = {k: int(v) for k, v in self.options.items()
                    if k != "class"}
        chosen: list[Endpoint] = []
        racks_seen: dict[str, set] = {}
        per_dc: dict[str, int] = {}
        skipped: dict[str, list[Endpoint]] = {}
        for ep in ring.successors(token):
            rf = rf_by_dc.get(ep.dc, 0)
            if per_dc.get(ep.dc, 0) >= rf or ep in chosen:
                continue
            racks = racks_seen.setdefault(ep.dc, set())
            if ep.rack in racks:
                skipped.setdefault(ep.dc, []).append(ep)
                continue
            chosen.append(ep)
            racks.add(ep.rack)
            per_dc[ep.dc] = per_dc.get(ep.dc, 0) + 1
            if all(per_dc.get(dc, 0) >= rf for dc, rf in rf_by_dc.items()):
                break
        # fill remaining slots from skipped same-rack nodes
        for dc, rf in rf_by_dc.items():
            for ep in skipped.get(dc, []):
                if per_dc.get(dc, 0) >= rf:
                    break
                if ep not in chosen:
                    chosen.append(ep)
                    per_dc[dc] = per_dc.get(dc, 0) + 1
        return chosen


class LocalStrategy(ReplicationStrategy):
    def replicas(self, ring: Ring, token: int) -> list[Endpoint]:
        return []


# ------------------------------------------------------ consistency levels --

class ConsistencyLevel:
    ANY = "ANY"
    ONE = "ONE"
    TWO = "TWO"
    THREE = "THREE"
    QUORUM = "QUORUM"
    ALL = "ALL"
    LOCAL_QUORUM = "LOCAL_QUORUM"
    LOCAL_ONE = "LOCAL_ONE"
    EACH_QUORUM = "EACH_QUORUM"

    @staticmethod
    def required(cl: str, replicas: list[Endpoint],
                 local_dc: str = "dc1") -> int:
        n = len(replicas)
        if cl in ("ANY", "ONE", "LOCAL_ONE"):
            return 1 if n else 0
        if cl == "TWO":
            return min(2, n)
        if cl == "THREE":
            return min(3, n)
        if cl == "QUORUM":
            return n // 2 + 1
        if cl == "ALL":
            return n
        if cl == "LOCAL_QUORUM":
            local = [r for r in replicas if r.dc == local_dc]
            return len(local) // 2 + 1
        if cl == "EACH_QUORUM":
            # approximated as global quorum for the blocking count
            return n // 2 + 1
        raise ValueError(f"unknown consistency level {cl}")
