"""Chunk compressor framework — the ICompressor seam.

Reference semantics: io/compress/ICompressor.java:27 (compress/uncompress,
recommendedUses), schema/CompressionParams.java:45 (per-table configuration,
16KiB default chunks, min_compress_ratio / maxCompressedLength fallback).

Five codecs, matching the reference set:
  LZ4Compressor      C++ (ops/native/codec.cpp), LZ4 block format
  SnappyCompressor   C++ (ops/native/codec.cpp), snappy raw format
  ZstdCompressor     system libzstd dlopen'd by the C++ layer (the
                     reference's zstd-jni role); python `zstandard`
                     fallback when the library is absent
  DeflateCompressor  zlib stdlib
  NoopCompressor     identity

Batch-first API: `compress_batch`/`decompress_batch` move a whole flush or
compaction write's chunks across the FFI in one call.
"""
from __future__ import annotations

import ctypes
import threading
import zlib

import numpy as np

from .native import build as native_build

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover - baked into this image
    _zstd = None


class Compressor:
    name = "?"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def uncompress(self, data: bytes, uncompressed_length: int) -> bytes:
        raise NotImplementedError

    def compress_batch(self, chunks: list[bytes]) -> list[bytes]:
        return [self.compress(c) for c in chunks]

    def decompress_batch(self, chunks: list[bytes],
                         lengths: list[int]) -> list[bytes]:
        return [self.uncompress(c, n) for c, n in zip(chunks, lengths)]

    @staticmethod
    def _frame_view(f):
        """Zero-copy read view of a buffer-protocol frame. bytes pass
        through; numpy arrays / memoryviews become flat byte views —
        no staging copy unless the frame is non-contiguous."""
        if isinstance(f, (bytes, bytearray)):
            return f
        if isinstance(f, np.ndarray):
            return memoryview(np.ascontiguousarray(f)).cast("B")
        return memoryview(f).cast("B")

    def compress_iov(self, frames: list) -> tuple:
        """Compress buffer-protocol frames (numpy arrays / memoryviews)
        without staging copies. Returns (dst_uint8_array, offsets, sizes):
        frame i's compressed bytes are dst[offsets[i]:offsets[i]+sizes[i]].
        Generic fallback; the native codecs override with a zero-copy
        FFI path. The pure-Python codecs (zlib, zstandard) accept any
        buffer object, so frames go in as views — the per-frame
        bytes(f) copy this used to make was a measured cost on the
        encrypted-table write path (bench.py codec section)."""
        outs = [self.compress(self._frame_view(f)) for f in frames]
        offs = np.zeros(len(outs) + 1, dtype=np.int64)
        np.cumsum([len(o) for o in outs], out=offs[1:])
        # b"".join is the single unavoidable gather of the compressed
        # output; frombuffer wraps it without another copy
        dst = np.frombuffer(b"".join(outs), dtype=np.uint8)
        return dst, offs[:-1], np.diff(offs)

    def decompress_iov(self, src: np.ndarray, src_offs, src_lens,
                       dsts: list) -> None:
        """Decompress chunk i (src[src_offs[i] : +src_lens[i]]) directly
        into the writable buffer dsts[i] (numpy uint8 views — the arrays
        the decoded CellBatch will own). Generic fallback."""
        for i, d in enumerate(dsts):
            o, l = int(src_offs[i]), int(src_lens[i])
            raw = self.uncompress(src[o:o + l].tobytes(), d.nbytes)
            d.reshape(-1).view(np.uint8)[:] = np.frombuffer(raw,
                                                            dtype=np.uint8)


class _NativeCompressor(Compressor):
    """ctypes front-end over the C++ batch codecs."""
    _prefix = "?"

    def _prepare(self) -> None:
        """Hook run (on the calling thread) before each FFI entry —
        codecs with per-instance state (zstd level) sync it here."""

    def __init__(self):
        self._lib = native_build.load()
        self._compress = getattr(self._lib, f"{self._prefix}_compress")
        self._decompress = getattr(self._lib, f"{self._prefix}_decompress")
        self._compress_b = getattr(self._lib, f"{self._prefix}_compress_batch")
        self._decompress_b = getattr(self._lib, f"{self._prefix}_decompress_batch")
        self._compress_iov = getattr(self._lib, f"{self._prefix}_compress_iov")
        self._decompress_iov = getattr(self._lib,
                                       f"{self._prefix}_decompress_iov")
        self._max = getattr(self._lib, f"{self._prefix}_max_compressed")

    def compress(self, data: bytes) -> bytes:
        self._prepare()
        cap = self._max(len(data))
        dst = ctypes.create_string_buffer(cap)
        src = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        n = self._compress(src, len(data),
                           ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)), cap)
        if n < 0:
            raise RuntimeError(f"{self.name}: compression failed")
        return dst.raw[:n]

    def uncompress(self, data: bytes, uncompressed_length: int) -> bytes:
        self._prepare()
        dst = ctypes.create_string_buffer(uncompressed_length or 1)
        src = (ctypes.c_uint8 * max(len(data), 1)).from_buffer_copy(data or b"\x00")
        n = self._decompress(src, len(data),
                             ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)),
                             uncompressed_length)
        if n < 0 or n != uncompressed_length:
            raise ValueError(f"{self.name}: corrupt chunk")
        return dst.raw[:n]

    def compress_batch(self, chunks: list[bytes]) -> list[bytes]:
        if not chunks:
            return []
        self._prepare()
        src = b"".join(chunks)
        src_offs = np.zeros(len(chunks) + 1, dtype=np.int64)
        np.cumsum([len(c) for c in chunks], out=src_offs[1:])
        dst_offs = np.zeros(len(chunks) + 1, dtype=np.int64)
        np.cumsum([self._max(len(c)) for c in chunks], out=dst_offs[1:])
        dst = ctypes.create_string_buffer(int(dst_offs[-1]))
        sizes = np.zeros(len(chunks), dtype=np.int64)
        sbuf = (ctypes.c_uint8 * len(src)).from_buffer_copy(src)
        r = self._compress_b(
            sbuf, src_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)),
            dst_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(chunks))
        if r < 0:
            raise RuntimeError(f"{self.name}: batch compression failed")
        raw = dst.raw
        return [raw[int(dst_offs[i]):int(dst_offs[i]) + int(sizes[i])]
                for i in range(len(chunks))]

    def decompress_batch(self, chunks: list[bytes],
                         lengths: list[int]) -> list[bytes]:
        if not chunks:
            return []
        self._prepare()
        src = b"".join(chunks)
        src_offs = np.zeros(len(chunks) + 1, dtype=np.int64)
        np.cumsum([len(c) for c in chunks], out=src_offs[1:])
        dst_offs = np.zeros(len(chunks) + 1, dtype=np.int64)
        np.cumsum(lengths, out=dst_offs[1:])
        dst = ctypes.create_string_buffer(max(int(dst_offs[-1]), 1))
        sizes = np.zeros(len(chunks), dtype=np.int64)
        sbuf = (ctypes.c_uint8 * max(len(src), 1)).from_buffer_copy(src or b"\x00")
        r = self._decompress_b(
            sbuf, src_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.cast(dst, ctypes.POINTER(ctypes.c_uint8)),
            dst_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(chunks))
        if r < 0 or not (sizes == np.asarray(lengths, dtype=np.int64)).all():
            raise ValueError(f"{self.name}: corrupt chunk in batch")
        raw = dst.raw
        return [raw[int(dst_offs[i]):int(dst_offs[i + 1])]
                for i in range(len(chunks))]


    @staticmethod
    def _as_u8(buf) -> np.ndarray:
        a = np.frombuffer(buf, dtype=np.uint8) if not isinstance(
            buf, np.ndarray) else buf
        if a.dtype != np.uint8:
            a = a.view(np.uint8)
        return np.ascontiguousarray(a).reshape(-1)

    def compress_iov(self, frames: list) -> tuple:
        """Zero-copy scatter-gather compression: frames go over the FFI as
        (pointer, length) pairs; results land in one preallocated numpy
        buffer. No b''.join, no from_buffer_copy, no .raw re-copy — the
        write path's staging copies were a measured compaction hot spot."""
        n = len(frames)
        if n == 0:
            return np.zeros(0, np.uint8), np.zeros(0, np.int64), \
                np.zeros(0, np.int64)
        self._prepare()
        arrs = [self._as_u8(f) for f in frames]
        lens = np.array([a.nbytes for a in arrs], dtype=np.int64)
        dst_offs = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([self._max(int(l)) for l in lens], out=dst_offs[1:])
        dst = np.empty(int(dst_offs[-1]), dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        ptrs = (u8p * n)(*[a.ctypes.data_as(u8p) for a in arrs])
        sizes = np.zeros(n, dtype=np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        r = self._compress_iov(
            ptrs, lens.ctypes.data_as(i64p), dst.ctypes.data_as(u8p),
            dst_offs.ctypes.data_as(i64p), sizes.ctypes.data_as(i64p), n)
        if r < 0:
            raise RuntimeError(f"{self.name}: iov compression failed")
        return dst, dst_offs[:-1], sizes

    def decompress_iov(self, src: np.ndarray, src_offs, src_lens,
                       dsts: list) -> None:
        n = len(dsts)
        if n == 0:
            return
        self._prepare()
        src = np.ascontiguousarray(src.view(np.uint8).reshape(-1))
        src_offs = np.ascontiguousarray(src_offs, dtype=np.int64)
        src_lens = np.ascontiguousarray(src_lens, dtype=np.int64)
        arrs = []
        for d in dsts:
            a = d.reshape(-1).view(np.uint8)
            if not a.flags.c_contiguous:
                raise ValueError("decompress_iov needs contiguous dsts")
            arrs.append(a)
        lens = np.array([a.nbytes for a in arrs], dtype=np.int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        ptrs = (u8p * n)(*[a.ctypes.data_as(u8p) for a in arrs])
        r = self._decompress_iov(
            src.ctypes.data_as(u8p), src_offs.ctypes.data_as(i64p),
            src_lens.ctypes.data_as(i64p), ptrs,
            lens.ctypes.data_as(i64p), n)
        if r < 0:
            raise ValueError(f"{self.name}: corrupt chunk in iov batch")


class LZ4Compressor(_NativeCompressor):
    name = "LZ4Compressor"
    _prefix = "lz4"


class SnappyCompressor(_NativeCompressor):
    name = "SnappyCompressor"
    _prefix = "snappy"


class DeflateCompressor(Compressor):
    name = "DeflateCompressor"

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, 6)

    def uncompress(self, data: bytes, uncompressed_length: int) -> bytes:
        out = zlib.decompress(data)
        if len(out) != uncompressed_length:
            raise ValueError("DeflateCompressor: corrupt chunk")
        return out


class ZstdNativeCompressor(_NativeCompressor):
    """Zstd over the system libzstd, dlopen'd by the C++ layer (the
    reference's zstd-jni role). Raises at construction when libzstd is
    absent — the registry falls back to the Python binding."""
    name = "ZstdCompressor"
    _prefix = "zstd"

    def __init__(self, level: int = 3):
        super().__init__()
        if not self._lib.zstd_available():
            raise RuntimeError("libzstd unavailable")
        self.level = level

    def _prepare(self) -> None:
        # the native level is THREAD-LOCAL; syncing it before every FFI
        # entry keeps instances with different levels independent
        self._lib.zstd_set_level(self.level)


class ZstdPythonCompressor(Compressor):
    name = "ZstdCompressor"

    def __init__(self, level: int = 3):
        if _zstd is None:
            raise RuntimeError("zstandard module unavailable")
        self.level = level
        self._c = _zstd.ZstdCompressor(level=level)
        self._d = _zstd.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def uncompress(self, data: bytes, uncompressed_length: int) -> bytes:
        out = self._d.decompress(data, max_output_size=uncompressed_length)
        if len(out) != uncompressed_length:
            raise ValueError("ZstdCompressor: corrupt chunk")
        return out


def ZstdCompressor(level: int = 3) -> Compressor:
    """Factory: native libzstd when present, else the Python binding."""
    try:
        return ZstdNativeCompressor(level)
    except Exception:
        return ZstdPythonCompressor(level)


class NoopCompressor(Compressor):
    name = "NoopCompressor"

    def compress(self, data: bytes) -> bytes:
        return data

    def uncompress(self, data: bytes, uncompressed_length: int) -> bytes:
        if len(data) != uncompressed_length:
            raise ValueError("NoopCompressor: length mismatch")
        return data


_REGISTRY = {
    "LZ4Compressor": LZ4Compressor,
    "SnappyCompressor": SnappyCompressor,
    "DeflateCompressor": DeflateCompressor,
    "ZstdCompressor": ZstdCompressor,
    "NoopCompressor": NoopCompressor,
}


class SegmentPacker:
    """Front-end over the fused native write path (segment_pack): one
    GIL-released call does lane delta + order check + compress-or-raw +
    CRC32 + sequential placement. Returns None from `create` when the
    codec has no native id (Deflate) or the library is unavailable —
    callers fall back to the per-block Python chain."""

    _CODEC_IDS = {"NoopCompressor": 0, "LZ4Compressor": 1,
                  "SnappyCompressor": 2, "ZstdCompressor": 3}

    @classmethod
    def create(cls, compressor: Compressor) -> "SegmentPacker | None":
        cid = cls._CODEC_IDS.get(compressor.name)
        if cid is None:
            return None
        if cid == 3 and not isinstance(compressor, ZstdNativeCompressor):
            return None
        try:
            lib = native_build.load()
        except Exception:
            return None
        return cls(lib, cid, getattr(compressor, "level", 0))

    def __init__(self, lib, codec_id: int, zstd_level: int = 0):
        self._lib = lib
        self._cid = codec_id
        self._zstd_level = zstd_level
        self._u8p = ctypes.POINTER(ctypes.c_uint8)
        self._i64p = ctypes.POINTER(ctypes.c_int64)
        self._u32p = ctypes.POINTER(ctypes.c_uint32)
        # per-THREAD shuffle scratch: one packer instance serves every
        # worker of the parallel compress pool concurrently (the native
        # zstd level is already thread-local on the C side)
        self._tls = threading.local()

    def _scratch_for(self, need: int) -> np.ndarray:
        buf = getattr(self._tls, "scratch", None)
        if buf is None or buf.nbytes < need:
            buf = np.empty(need, dtype=np.uint8)
            self._tls.scratch = buf
        return buf

    def pack(self, blocks: list[np.ndarray], attempt: list[bool],
             max_compressed_length: int, shuffle_block: int,
             lane_width: int, out: np.ndarray):
        """Pack `blocks` into `out`. Returns (total, sizes, rawflags,
        crcs); raises ValueError on an order violation in the shuffled
        block."""
        n = len(blocks)
        arrs = [np.ascontiguousarray(b.reshape(-1).view(np.uint8))
                for b in blocks]
        lens = np.array([a.nbytes for a in arrs], dtype=np.int64)
        scratch = self._scratch_for(int(lens[shuffle_block])
                                    if shuffle_block >= 0 else 0)
        sizes = np.zeros(n, dtype=np.int64)
        raws = np.zeros(n, dtype=np.uint8)
        crcs = np.zeros(n, dtype=np.uint32)
        att = np.array([1 if a else 0 for a in attempt], dtype=np.uint8)
        ptrs = (self._u8p * n)(*[a.ctypes.data_as(self._u8p)
                                 for a in arrs])
        if self._cid == 3:
            self._lib.zstd_set_level(self._zstd_level)
        total = self._lib.segment_pack(
            self._cid, ptrs, lens.ctypes.data_as(self._i64p), n,
            att.ctypes.data_as(self._u8p), max_compressed_length,
            shuffle_block, lane_width,
            scratch.ctypes.data_as(self._u8p),
            out.ctypes.data_as(self._u8p), out.nbytes,
            sizes.ctypes.data_as(self._i64p),
            raws.ctypes.data_as(self._u8p),
            crcs.ctypes.data_as(self._u32p))
        if total == -3:
            raise ValueError("appended cells out of order")
        if total < 0:
            raise RuntimeError("segment_pack failed")
        return int(total), sizes, raws, crcs


def lanes_unshuffle(planes: np.ndarray, lanes_out: np.ndarray) -> None:
    """Byte planes -> [n, K] u32 rows (reader side of the segment_pack
    shuffle transform)."""
    n, k = lanes_out.shape
    if n == 0:
        return
    try:
        lib = native_build.load()
        lib.lanes_unshuffle(
            planes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lanes_out.view(np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)), n, k)
    except Exception:
        lanes_out.view(np.uint8).reshape(n, 4 * k)[:] = \
            planes.reshape(4 * k, n).T


def lanes_shuffle(lanes: np.ndarray) -> np.ndarray:
    """[n, K] u32 rows -> byte planes (numpy path — used by writers that
    cannot take the fused native call, e.g. encrypted tables)."""
    n, k = lanes.shape
    return np.ascontiguousarray(
        lanes.astype(np.uint32, copy=False).view(np.uint8)
        .reshape(n, 4 * k).T).ravel()
_instances: dict[str, Compressor] = {}


def get_compressor(name: str) -> Compressor:
    """Resolve by class name (schema/CompressionParams.java loads the class
    reflectively; this registry is the equivalent seam)."""
    short = name.rsplit(".", 1)[-1]
    if short not in _instances:
        if short not in _REGISTRY:
            raise ValueError(f"unknown compressor: {name}")
        _instances[short] = _REGISTRY[short]()
    return _instances[short]


class CompressionParams:
    """Per-table compression options (schema/CompressionParams.java:45)."""
    DEFAULT_CHUNK_LENGTH = 16 * 1024

    def __init__(self, compressor: str = "LZ4Compressor",
                 chunk_length: int = DEFAULT_CHUNK_LENGTH,
                 min_compress_ratio: float = 0.0,
                 enabled: bool = True):
        if chunk_length & (chunk_length - 1):
            raise ValueError("chunk_length must be a power of two")
        self.compressor_name = compressor
        self.chunk_length = chunk_length
        self.min_compress_ratio = min_compress_ratio
        self.enabled = enabled

    @property
    def max_compressed_length(self) -> int:
        """Chunks that compress worse than min_compress_ratio are stored
        uncompressed (CompressedSequentialWriter.java:160-175)."""
        if self.min_compress_ratio <= 0:
            return 1 << 62
        return int(self.chunk_length / self.min_compress_ratio)

    def compressor(self) -> Compressor:
        return get_compressor(self.compressor_name)

    def to_dict(self) -> dict:
        return {"class": self.compressor_name,
                "chunk_length_in_kb": self.chunk_length // 1024,
                "min_compress_ratio": self.min_compress_ratio,
                "enabled": self.enabled}

    @classmethod
    def from_dict(cls, d: dict) -> "CompressionParams":
        if not d:
            return cls("NoopCompressor", enabled=False)
        p = cls(d.get("class", "LZ4Compressor").rsplit(".", 1)[-1],
                int(d.get("chunk_length_in_kb", 16)) * 1024,
                float(d.get("min_compress_ratio", 0.0)),
                enabled=bool(d.get("enabled", True)))
        return p

    def compressor_or_noop(self) -> Compressor:
        return self.compressor() if self.enabled else get_compressor("NoopCompressor")
