"""SSTable write/read round-trips (reference test model:
io/sstable/SSTableReaderTest, CompressedSequentialWriterTest)."""
import os
import random

import numpy as np
import pytest

from cassandra_tpu.ops.codec import CompressionParams
from cassandra_tpu.schema import COL_REGULAR_BASE, TableParams, make_table
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.sstable import (Component, Descriptor,
                                           SSTableReader, SSTableWriter)


def make_t(compressor="LZ4Compressor"):
    return make_table("ks", "t", pk=["id"], ck=["c"],
                      cols={"id": "int", "c": "int", "v": "text"},
                      params=TableParams(
                          compression=CompressionParams(compressor)))


def sorted_batch(table, n_parts=50, n_cks=20, seed=3):
    rng = random.Random(seed)
    b = cb.CellBatchBuilder(table)
    idt = table.columns["id"].cql_type
    for p in range(n_parts):
        for c in range(n_cks):
            b.add_cell(idt.serialize(p), table.serialize_clustering([c]),
                       COL_REGULAR_BASE,
                       f"value-{p}-{c}-{rng.random()}".encode(), 1000 + c)
    return cb.merge_sorted([b.seal()])


@pytest.mark.parametrize("compressor", ["LZ4Compressor", "SnappyCompressor",
                                        "ZstdCompressor", "DeflateCompressor",
                                        "NoopCompressor"])
def test_roundtrip(tmp_path, compressor):
    t = make_t(compressor)
    batch = sorted_batch(t)
    desc = Descriptor(str(tmp_path), 1)
    w = SSTableWriter(desc, t, segment_cells=256)  # force many segments
    w.append(batch)
    stats = w.finish()
    assert stats["n_cells"] == len(batch)
    assert stats["n_partitions"] == 50

    r = SSTableReader(desc)
    assert r.n_cells == len(batch)
    assert r.verify_digest()
    # full scan == original batch
    got = cb.CellBatch.concat(list(r.scanner()))
    np.testing.assert_array_equal(got.lanes, batch.lanes)
    np.testing.assert_array_equal(got.ts, batch.ts)
    np.testing.assert_array_equal(got.payload, batch.payload)
    r.close()


def test_point_reads(tmp_path):
    t = make_t()
    batch = sorted_batch(t, n_parts=100, n_cks=10)
    desc = Descriptor(str(tmp_path), 1)
    w = SSTableWriter(desc, t, segment_cells=128)
    w.append(batch)
    w.finish()
    r = SSTableReader(desc)
    idt = t.columns["id"].cql_type
    for p in (0, 7, 50, 99):
        part = r.read_partition(idt.serialize(p))
        assert part is not None and len(part) == 10
        for i in range(len(part)):
            assert part.partition_key(i) == idt.serialize(p)
            assert part.cell_value(i).startswith(f"value-{p}-".encode())
    assert r.read_partition(idt.serialize(100000)) is None
    r.close()


def test_partition_spanning_segments(tmp_path):
    t = make_t()
    # one huge partition crossing many segments
    b = cb.CellBatchBuilder(t)
    idt = t.columns["id"].cql_type
    for c in range(1000):
        b.add_cell(idt.serialize(1), t.serialize_clustering([c]),
                   COL_REGULAR_BASE, f"v{c}".encode(), 1)
    batch = cb.merge_sorted([b.seal()])
    desc = Descriptor(str(tmp_path), 1)
    w = SSTableWriter(desc, t, segment_cells=64)
    w.append(batch)
    stats = w.finish()
    assert stats["n_partitions"] == 1
    r = SSTableReader(desc)
    part = r.read_partition(idt.serialize(1))
    assert len(part) == 1000
    vals = {part.cell_value(i) for i in range(1000)}
    assert vals == {f"v{c}".encode() for c in range(1000)}
    r.close()


def test_multiple_appends_and_order_guard(tmp_path):
    t = make_t()
    batch = sorted_batch(t, n_parts=20, n_cks=5)
    half = len(batch) // 2
    first = batch.apply_permutation(np.arange(half))
    first.pk_map = batch.pk_map
    second = batch.apply_permutation(np.arange(half, len(batch)))
    second.pk_map = batch.pk_map
    desc = Descriptor(str(tmp_path), 1)
    w = SSTableWriter(desc, t, segment_cells=32)
    w.append(first)
    w.append(second)
    w.finish()
    r = SSTableReader(desc)
    got = cb.CellBatch.concat(list(r.scanner()))
    np.testing.assert_array_equal(got.lanes, batch.lanes)
    r.close()
    # out-of-order append must raise
    desc2 = Descriptor(str(tmp_path), 2)
    w2 = SSTableWriter(desc2, t, segment_cells=32)
    w2.append(second)
    with pytest.raises(ValueError):
        w2.append(first)
        w2.finish()
    w2.abort()


def test_corruption_detected(tmp_path):
    t = make_t()
    desc = Descriptor(str(tmp_path), 1)
    w = SSTableWriter(desc, t, segment_cells=256)
    w.append(sorted_batch(t))
    w.finish()
    # flip a byte in Data.db
    p = desc.path(Component.DATA)
    data = bytearray(open(p, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(p, "wb").write(bytes(data))
    r = SSTableReader(desc)
    assert not r.verify_digest()
    from cassandra_tpu.storage.sstable.reader import CorruptSSTableError
    with pytest.raises((CorruptSSTableError, ValueError)):
        list(r.scanner())
    r.close()


def test_discovery_and_generations(tmp_path):
    t = make_t()
    assert Descriptor.next_generation(str(tmp_path)) == 1
    for gen in (1, 2):
        w = SSTableWriter(Descriptor(str(tmp_path), gen), t)
        w.append(sorted_batch(t, n_parts=5, n_cks=2, seed=gen))
        w.finish()
    descs = Descriptor.list_in(str(tmp_path))
    assert [d.generation for d in descs] == [1, 2]
    assert Descriptor.next_generation(str(tmp_path)) == 3
    # aborted writer leaves no trace
    w = SSTableWriter(Descriptor(str(tmp_path), 3), t)
    w.append(sorted_batch(t, n_parts=3, n_cks=2))
    w.abort()
    assert [d.generation for d in Descriptor.list_in(str(tmp_path))] == [1, 2]


def test_tombstones_and_stats(tmp_path):
    t = make_t()
    b = cb.CellBatchBuilder(t)
    idt = t.columns["id"].cql_type
    b.add_cell(idt.serialize(1), t.serialize_clustering([1]),
               COL_REGULAR_BASE, b"x", 100)
    b.add_tombstone(idt.serialize(1), t.serialize_clustering([2]),
                    COL_REGULAR_BASE, 200, 5000)
    batch = cb.merge_sorted([b.seal()])
    desc = Descriptor(str(tmp_path), 1)
    w = SSTableWriter(desc, t)
    w.append(batch)
    stats = w.finish()
    assert stats["tombstones"] == 1
    assert stats["min_ts"] == 100 and stats["max_ts"] == 200
    r = SSTableReader(desc)
    part = r.read_partition(idt.serialize(1))
    assert len(part) == 2
    assert bool(part.flags[1] & cb.FLAG_TOMBSTONE)
    r.close()
