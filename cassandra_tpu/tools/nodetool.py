"""nodetool: operator commands over a node/engine.

Reference counterpart: tools/nodetool/ (161 JMX subcommands over
NodeProbe). This framework exposes the same operations as direct Python
API on the Node/StorageEngine (the JMX transport is replaced by in-process
calls; a remote admin protocol can wrap these functions); `python -m
cassandra_tpu.tools.nodetool <cmd> --data <dir>` drives a local engine.

Implemented commands: status, info, flush, compact, compactionstats,
tablestats, repair, cleanup, gettraces? (tracing via session), ring.
"""
from __future__ import annotations

import argparse
import json
import sys


def status(node) -> list[dict]:
    """nodetool status: per-endpoint liveness + ownership."""
    out = []
    for ep, toks in node.ring.endpoints.items():
        out.append({"endpoint": ep.name, "dc": ep.dc, "rack": ep.rack,
                    "status": "UN" if node.is_alive(ep) else "DN",
                    "tokens": len(toks)})
    return out


def info(engine) -> dict:
    """nodetool info: storage totals."""
    tables = {}
    for cfs in engine.stores.values():
        tables[cfs.table.full_name()] = {
            "sstables": len(cfs.live_sstables()),
            "memtable_cells": len(cfs.memtable),
            "disk_bytes": sum(s.size_bytes for s in cfs.live_sstables()),
        }
    return {"tables": tables}


def flush(engine, keyspace: str | None = None,
          table: str | None = None) -> int:
    n = 0
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        if cfs.flush() is not None:
            n += 1
    return n


def compact(engine, keyspace: str | None = None,
            table: str | None = None) -> list[dict]:
    """nodetool compact: major compaction."""
    from ..compaction import CompactionManager, get_strategy
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        task = get_strategy(cfs).major_task()
        if task is not None:
            out.append(task.execute())
    return out


def compactionstats(engine) -> list[dict]:
    out = []
    for cfs in engine.stores.values():
        out.extend(cfs.compaction_history)
    return out


def tablestats(engine, keyspace: str | None = None) -> dict:
    out = {}
    for cfs in engine.stores.values():
        t = cfs.table
        if keyspace and t.keyspace != keyspace:
            continue
        live = cfs.live_sstables()
        out[t.full_name()] = {
            "sstable_count": len(live),
            "space_used_bytes": sum(s.size_bytes for s in live),
            "cells": sum(s.n_cells for s in live),
            "partitions_estimate": sum(s.n_partitions for s in live),
            "tombstones": sum(s.n_tombstones for s in live),
            "memtable_cells": len(cfs.memtable),
            "reads": cfs.metrics["reads"],
            "writes": cfs.metrics["writes"],
            "flushes": cfs.metrics["flushes"],
        }
    return out


def repair(node, keyspace: str, table: str | None = None,
           full: bool = False) -> list[dict]:
    """nodetool repair — incremental by default: validation still covers
    the FULL data set (unrepaired-only trees diverge once repaired
    status differs across replicas), but afterwards the validated
    unrepaired sstables are ANTICOMPACTED and stamped repairedAt so the
    compaction split applies; --full skips the stamping entirely."""
    out = []
    ks = node.schema.keyspaces[keyspace]
    for name in ([table] if table else list(ks.tables)):
        out.append({"table": f"{keyspace}.{name}",
                    **node.repair.repair_table(keyspace, name,
                                               incremental=not full)})
    return out


def ring(node) -> list[dict]:
    out = []
    for ep, toks in sorted(node.ring.endpoints.items(),
                           key=lambda kv: kv[0].name):
        for t in sorted(toks):
            out.append({"token": t, "endpoint": ep.name})
    return out


def snapshot(engine, keyspace: str | None = None,
             table: str | None = None, tag: str | None = None) -> list[str]:
    """nodetool snapshot."""
    from ..storage import snapshot as snap
    out = []
    for cfs in engine.stores.values():
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        cfs.flush()   # snapshots must include memtable contents
        out.append(f"{cfs.table.full_name()}:{snap.snapshot(cfs, tag)}")
    return out


def listsnapshots(engine) -> list[dict]:
    from ..storage import snapshot as snap
    out = []
    for cfs in engine.stores.values():
        out.extend(snap.list_snapshots(cfs))
    return out


def clearsnapshot(engine, tag: str | None = None) -> int:
    from ..storage import snapshot as snap
    return sum(snap.clear_snapshot(cfs, tag)
               for cfs in engine.stores.values())


def scrub(engine, keyspace: str | None = None,
          table: str | None = None) -> list[dict]:
    """nodetool scrub: rewrite each sstable keeping every readable
    segment, dropping corrupt ones (io/sstable/format/
    SortedTableScrubber role). The unreadable cells are gone either way;
    scrub turns a read-aborting sstable into a clean one."""
    from ..storage.lifecycle import LifecycleTransaction
    from ..storage.sstable import Descriptor, SSTableReader, SSTableWriter
    from ..storage.sstable.reader import CorruptSSTableError
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        for sst in list(cfs.live_sstables()):
            kept = dropped = 0
            txn = LifecycleTransaction(cfs.directory)
            gen = cfs.next_generation()
            desc = Descriptor(cfs.directory, gen)
            txn.track_new(gen)
            w = SSTableWriter(desc, cfs.table,
                              estimated_partitions=sst.n_partitions)
            w.repaired_at = sst.repaired_at
            w.level = sst.level
            try:
                for i in range(sst.n_segments):
                    try:
                        seg = sst._read_segment(i)
                    except CorruptSSTableError:
                        dropped += 1
                        continue
                    w.append(seg)
                    kept += 1
                w.finish()
                new = SSTableReader(desc, cfs.table)
                txn.track_obsolete(sst.desc.generation)
                replacement = []
                if new.n_cells > 0:
                    replacement = [new]
                else:               # nothing salvageable: drop entirely
                    new.close()
                    txn.track_obsolete(gen)
                txn.commit()
                cfs.tracker.replace([sst], replacement)
                sst.release()
            except BaseException:
                w.abort()
                txn.abort()
                raise
            out.append({"table": cfs.table.full_name(),
                        "generation": sst.desc.generation,
                        "segments_kept": kept,
                        "segments_dropped": dropped})
    return out


def garbagecollect(engine, keyspace: str | None = None,
                   table: str | None = None) -> list[dict]:
    """Single-sstable rewrite dropping gc-able tombstones
    (nodetool garbagecollect)."""
    from ..compaction.task import CompactionTask
    out = []
    for cfs in list(engine.stores.values()):
        if keyspace and cfs.table.keyspace != keyspace:
            continue
        if table and cfs.table.name != table:
            continue
        for sst in cfs.live_sstables():
            out.append(CompactionTask(cfs, [sst]).execute())
    return out


def main(argv=None):
    p = argparse.ArgumentParser(prog="nodetool")
    p.add_argument("command", choices=["info", "flush", "compact",
                                       "compactionstats", "tablestats",
                                       "garbagecollect", "scrub"])
    p.add_argument("--data", required=True, help="data directory")
    p.add_argument("--keyspace")
    p.add_argument("--table")
    args = p.parse_args(argv)

    from ..schema import Schema
    from ..storage.engine import StorageEngine
    engine = StorageEngine(args.data, Schema())
    fn = globals()[args.command]
    import inspect
    kwargs = {}
    sig = inspect.signature(fn)
    if "keyspace" in sig.parameters:
        kwargs["keyspace"] = args.keyspace
    if "table" in sig.parameters:
        kwargs["table"] = args.table
    print(json.dumps(fn(engine, **kwargs), indent=2, default=str))
    engine.close()


if __name__ == "__main__":
    main()
