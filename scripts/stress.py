#!/usr/bin/env python
"""cassandra-stress-style multi-connection WIRE driver.

Reference counterpart: tools/stress/ (Stress.java) driving the native
protocol over real sockets — unlike tools/stress.py (which calls a
Session in-process), every operation here crosses the event-loop server
(cassandra_tpu/transport/): prepared statements, admission control,
per-client rate limiting and the v5 segment framing are all on the path.

Workloads: write / read / mixed (--write-ratio) over a fixed integer
key space, keys drawn uniform / zipf (hot-partition skew) / sequential
(disjoint per-connection ranges — deterministic, the smoke mode's
correctness base). One OS thread per connection issues synchronous
requests, so `--connections` IS the offered concurrency; latencies land
in a shared service/metrics.LatencyHistogram (the same decaying
histogram the server exports) plus exact numpy percentiles.

Errors are classified by wire code: OVERLOADED (0x1001) shed by the
permit gate / overload signals vs rate-limited (same code, rate-limit
message) vs UNPREPARED (0x2500) vs other. The caller decides whether
they are failures: the bench's overload run REQUIRES them.

`--smoke` is the tier-2 drill (exit 1 on violation, seconds-long,
deterministic; CI runs it alongside chaos_storage.py): in-process
server, then (1) concurrent writes land and read back exactly,
(2) serving 64 connections creates no new server threads (the
event-loop contract), (3) with the permit cap pinched the server sheds
with OVERLOADED while in-flight never exceeds the cap and the server
stays responsive, (4) the per-client rate limiter sheds and hot-reloads
off again.

Usage:
  python scripts/stress.py --profile mixed --connections 64 --ops 8192
  python scripts/stress.py --host 10.0.0.5 --port 9042 --profile read
  python scripts/stress.py --smoke
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

KEYSPACE = "stress"
TABLE = "frontdoor"
DDL = (f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE} WITH replication = "
       "{'class': 'SimpleStrategy', 'replication_factor': 1}",
       f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.{TABLE} "
       "(key int PRIMARY KEY, v blob)")
INSERT = f"INSERT INTO {KEYSPACE}.{TABLE} (key, v) VALUES (?, ?)"
SELECT = f"SELECT v FROM {KEYSPACE}.{TABLE} WHERE key = ?"


def _client_table():
    """Client-side mirror of the stress table for wire serialization
    (the driver serializes bind values against CQL types itself)."""
    from cassandra_tpu.schema import make_table
    return make_table(KEYSPACE, TABLE, pk=["key"],
                      cols={"key": "int", "v": "blob"})


def _classify(msg: str) -> str:
    if "0x1001" in msg:
        return "rate_limited" if "rate limit" in msg.lower() \
            else "overloaded"
    if "0x2500" in msg:
        return "unprepared"
    return "other"


def _keys(dist: str, n: int, key_space: int, rng, worker: int,
          workers: int) -> np.ndarray:
    """The per-worker key stream — a pure function of (dist, seed-derived
    rng, worker, workers), so --seed makes the whole run's key/op stream
    reproducible."""
    if dist == "sequential":
        # partition the KEY SPACE per connection: worker w walks its own
        # balanced slice [w*ks//workers, (w+1)*ks//workers) and wraps
        # within it (they used to walk [w*n, w*n+n), which ignored
        # key_space entirely). For workers <= key_space the slices are
        # disjoint and their union is [0, key_space) exactly — even
        # when key_space % workers != 0 — so with per-worker ops >= the
        # slice width the smoke read-back covers every key. With MORE
        # workers than keys, disjointness is impossible: zero-width
        # slices widen to one shared key.
        ks = max(key_space, 1)
        w = max(workers, 1)
        lo = worker * ks // w
        width = max((worker + 1) * ks // w - lo, 1)
        return (lo % ks) + (np.arange(n) % width)
    if dist == "zipf":
        # zipf-skewed hot partitions clipped into the key space
        return np.minimum(rng.zipf(1.3, n), key_space) - 1
    return rng.integers(0, key_space, n)


def _worker(idx: int, host: str, port: int, profile: str, n_ops: int,
            dist: str, key_space: int, value_bytes: int,
            write_ratio: float, seed: int, workers: int, hist,
            barrier, results: list) -> None:
    from cassandra_tpu.client import Cluster, DriverError, \
        serialize_params
    rng = np.random.default_rng(seed * 100_000 + idx)
    table = _client_table()
    lats: list = []
    errs: dict = {}
    ok = 0
    # connect + prepare BEFORE the barrier so every worker reaches it
    # exactly once (a broken barrier strands the whole run); a failed
    # connection just records itself and sits the run out
    sess = None
    try:
        sess = Cluster(host, port).connect()
        wq = sess.prepare(INSERT)
        rq = sess.prepare(SELECT)
    except Exception as e:
        errs["connection"] = 1
        errs["connection_detail"] = f"{type(e).__name__}: {e}"
        if sess is not None:   # connected but a PREPARE failed: close,
            try:               # don't leak the socket into the server
                sess.close()
            except Exception:
                pass
        sess = None
    keys = _keys(dist, n_ops, key_space, rng, idx, workers)
    if profile == "mixed":
        is_write = rng.random(n_ops) < write_ratio
    else:
        is_write = np.full(n_ops, profile == "write")
    vals = rng.integers(0, 256, (n_ops, value_bytes), dtype=np.uint8)
    barrier.wait()
    if sess is not None:
        for i in range(n_ops):
            k = int(keys[i])
            t0 = time.perf_counter()
            try:
                if is_write[i]:
                    sess.execute_prepared(
                        wq, serialize_params(table, ["key", "v"],
                                             [k, vals[i].tobytes()]))
                else:
                    sess.execute_prepared(
                        rq, serialize_params(table, ["key"], [k]))
                ok += 1
            except DriverError as e:
                kind = _classify(str(e))
                errs[kind] = errs.get(kind, 0) + 1
                continue   # shed ops are near-instant round trips:
                # counting them into lats would inflate ops/s and
                # deflate tail latency exactly when the server sheds
            except Exception as e:   # dead socket mid-run
                errs["connection"] = errs.get("connection", 0) + 1
                errs.setdefault("connection_detail",
                                f"{type(e).__name__}: {e}")
                break
            us = (time.perf_counter() - t0) * 1e6
            lats.append(us)
            hist.update_us(us)
        try:
            sess.close()
        except Exception:
            pass
    results[idx] = (lats, errs, ok)


def _spawn_and_aggregate(connections: int, target, make_args):
    """The shared drive loop both wire drivers use: spawn one worker
    thread per connection, release them together through the barrier,
    time the joined run, and merge the per-worker (lats, errs, ok)
    triples (a worker that never reported counts as one connection
    error; connection_detail keeps the first). Returns
    (wall_s, lats, errors, ok)."""
    barrier = threading.Barrier(connections + 1)
    results: list = [None] * connections
    threads = [threading.Thread(
        target=target, daemon=True,
        args=make_args(i, barrier, results))
        for i in range(connections)]
    for t in threads:
        t.start()
    barrier.wait()               # all sessions connected and prepared
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats: list = []
    errors: dict = {}
    ok = 0
    for r in results:
        if r is None:
            errors["connection"] = errors.get("connection", 0) + 1
            continue
        w_lats, w_errs, w_ok = r
        lats += w_lats
        ok += w_ok
        for k, v in w_errs.items():
            if k == "connection_detail":
                errors.setdefault(k, v)
            else:
                errors[k] = errors.get(k, 0) + v
    return wall, lats, errors, ok


def run_stress(host: str, port: int, *, profile: str = "mixed",
               connections: int = 16, ops: int = 4096,
               dist: str = "uniform", key_space: int = 4096,
               value_bytes: int = 64, write_ratio: float = 0.5,
               seed: int = 1, setup: bool = True) -> dict:
    """Drive `ops` total operations over `connections` concurrent wire
    connections; returns ops/s + exact p50/p99 + the decaying-histogram
    summary + error counts by class."""
    from cassandra_tpu.client import Cluster
    from cassandra_tpu.service.metrics import LatencyHistogram
    if setup:
        s = Cluster(host, port).connect()
        for ddl in DDL:
            s.execute(ddl)
        s.close()
    per_conn = max(1, ops // connections)
    hist = LatencyHistogram()
    wall, lats, errors, ok = _spawn_and_aggregate(
        connections, _worker,
        lambda i, barrier, results: (
            i, host, port, profile, per_conn, dist, key_space,
            value_bytes, write_ratio, seed, connections, hist,
            barrier, results))
    arr = np.array(lats) if lats else np.array([0.0])
    attempted = ok + sum(v for k, v in errors.items()
                         if isinstance(v, int))
    return {
        "profile": profile, "connections": connections,
        "dist": dist, "ops": attempted, "ok": ok,
        "errors": {k: v for k, v in errors.items() if v},
        "wall_s": round(wall, 3),
        # throughput and percentiles cover SERVED ops only: shed
        # requests are near-instant errors and counting them would
        # overstate capacity precisely when the server is shedding
        "ops_s": round(ok / wall, 1) if wall > 0 else 0.0,
        "p50_us": round(float(np.percentile(arr, 50)), 1),
        "p99_us": round(float(np.percentile(arr, 99)), 1),
        "hist": hist.summary(),
    }


# ------------------------------------------------------------- smoke -----

def _server_thread_count(port: int) -> int:
    from cassandra_tpu.transport.server import server_thread_count
    return server_thread_count(port)


def smoke() -> int:
    """Tier-2 drill: deterministic, seconds-long, exit 1 on violation."""
    import shutil
    import tempfile

    from cassandra_tpu.client import Cluster, serialize_params
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.transport import CQLServer

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    base = tempfile.mkdtemp(prefix="ctpu-stress-smoke-")
    engine = StorageEngine(os.path.join(base, "d"), Schema(),
                           commitlog_sync="periodic")
    srv = CQLServer(engine)
    table = _client_table()
    try:
        fixed = _server_thread_count(srv.port)
        check(fixed == len(srv.event_loops) + len(srv.dispatcher.threads),
              f"server runs a fixed thread set ({fixed})")

        # 1. concurrent writes land: 8 connections, disjoint sequential
        # key ranges, then every key reads back over a fresh connection
        n_conns, per = 8, 40
        w = run_stress("127.0.0.1", srv.port, profile="write",
                       connections=n_conns, ops=n_conns * per,
                       dist="sequential", key_space=n_conns * per,
                       value_bytes=32, seed=7)
        check(w["ok"] == n_conns * per and not w["errors"],
              f"8-connection write run clean ({w['ok']} ops)")
        s = Cluster("127.0.0.1", srv.port).connect()
        rq = s.prepare(SELECT)
        missing = sum(
            1 for k in range(n_conns * per)
            if not s.execute_prepared(
                rq, serialize_params(table, ["key"], [k])).rows)
        check(missing == 0, "every written key reads back "
              f"({n_conns * per - missing}/{n_conns * per})")

        # 2. event-loop contract: 64 concurrent connections, no new
        # server threads
        r = run_stress("127.0.0.1", srv.port, profile="read",
                       connections=64, ops=256, dist="uniform",
                       key_space=n_conns * per, seed=8, setup=False)
        check(r["ok"] > 0 and not r["errors"],
              f"64-connection read run clean ({r['ok']} ops)")
        check(_server_thread_count(srv.port) == fixed,
              "thread count unchanged at 64 connections")

        # 3. overload: pinch the permit cap; the server must SHED with
        # OVERLOADED (not queue, not collapse) and stay responsive
        engine.settings.set("native_transport_max_concurrent_requests", 1)
        srv.permits.reset_high_water()
        o = run_stress("127.0.0.1", srv.port, profile="write",
                       connections=16, ops=400, dist="uniform",
                       key_space=512, value_bytes=32, seed=9,
                       setup=False)
        shed = o["errors"].get("overloaded", 0)
        check(shed > 0, f"permit exhaustion sheds OVERLOADED ({shed})")
        check(o["ok"] > 0, f"server keeps serving under overload "
              f"({o['ok']} ok)")
        check(srv.permits.high_water <= 1,
              f"in-flight never exceeded the cap "
              f"(hwm={srv.permits.high_water})")
        engine.settings.set("native_transport_max_concurrent_requests",
                            256)
        probe = s.execute_prepared(
            rq, serialize_params(table, ["key"], [1]))
        check(bool(probe.rows), "server responsive after overload run")

        # 4. per-client rate limiting, hot-reloaded on and off.
        # rate=2: a NEW connection's bucket starts with a 2-token burst
        # — exactly the worker's two PREPAREs — so every subsequent op
        # competes for a 2 ops/s refill and the shed assertion holds
        # unless a trivial SELECT takes 500 ms (vs ~1 ms measured), not
        # latency-tuned like a generous rate would be
        engine.settings.set("native_transport_rate_limit_ops", 2)
        rl = run_stress("127.0.0.1", srv.port, profile="read",
                        connections=1, ops=60,
                        dist="uniform", key_space=n_conns * per,
                        seed=10, setup=False)
        check(rl["errors"].get("rate_limited", 0) > 0,
              f"rate limiter sheds "
              f"({rl['errors'].get('rate_limited', 0)} of "
              f"{rl['ops']})")
        engine.settings.set("native_transport_rate_limit_ops", 0)
        rl2 = run_stress("127.0.0.1", srv.port, profile="read",
                         connections=1, ops=60, dist="uniform",
                         key_space=n_conns * per, seed=11, setup=False)
        check(not rl2["errors"],
              "rate limit hot-reloads off (clean run)")
        s.close()
    finally:
        srv.close()
        engine.close()
        shutil.rmtree(base, ignore_errors=True)
    if failures:
        print(f"\nsmoke FAILED: {len(failures)} violation(s)")
        return 1
    print("\nsmoke OK")
    return 0


# ------------------------------------------------- saturation matrix -----
#
# ROADMAP item 5: the scenario matrix that certifies "millions of
# users" end to end instead of implying it. Key streams
# (zipf / sequential / uniform) crossed with the workload classes the
# engine supports but never benched under load — wide partitions,
# TTL-heavy time series on TWCS, counters, LWT, logged batches, mixed
# read-modify-write — every leg driven through the WIRE (prepared
# statements, admission control, v5 framing all on the path) against a
# 3-node RF=3 LocalCluster with hints and speculative retry live. The
# SLO layer (service/slo.py) polls during every leg: per-leg verdicts
# report p99 vs target and error-budget remaining, and the chaos leg
# (faultfs storage faults mid-run) must end with a breach-triggered
# flight-recorder bundle carrying the `slo.breach` event and the
# scenario id. bench.py's `saturation` section is run_matrix() output.

SAT_KEYSPACE = "sat"

SAT_DDL = [
    f"CREATE KEYSPACE IF NOT EXISTS {SAT_KEYSPACE} WITH replication = "
    "{'class': 'SimpleStrategy', 'replication_factor': 3}",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.kv "
    "(key int PRIMARY KEY, v blob)",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.wide "
    "(pk int, ck int, v blob, PRIMARY KEY (pk, ck))",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.ts "
    "(series int, at bigint, v blob, PRIMARY KEY (series, at)) "
    "WITH compaction = {'class': 'TimeWindowCompactionStrategy'}",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.cnt "
    "(key int PRIMARY KEY, hits counter)",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.lwt "
    "(key int PRIMARY KEY, v blob)",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.batched "
    "(key int PRIMARY KEY, v text)",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.rmw "
    "(key int PRIMARY KEY, v text)",
    f"CREATE TABLE IF NOT EXISTS {SAT_KEYSPACE}.facts "
    "(key int PRIMARY KEY, bucket int, score int)",
]


def _sat_tables():
    """Client-side schema mirrors for wire bind serialization."""
    from cassandra_tpu.schema import make_table
    ks = SAT_KEYSPACE
    return {
        "kv": make_table(ks, "kv", pk=["key"],
                         cols={"key": "int", "v": "blob"}),
        "wide": make_table(ks, "wide", pk=["pk"], ck=["ck"],
                           cols={"pk": "int", "ck": "int", "v": "blob"}),
        "ts": make_table(ks, "ts", pk=["series"], ck=["at"],
                         cols={"series": "int", "at": "bigint",
                               "v": "blob"}),
        "cnt": make_table(ks, "cnt", pk=["key"],
                          cols={"key": "int", "hits": "counter"}),
        "lwt": make_table(ks, "lwt", pk=["key"],
                          cols={"key": "int", "v": "blob"}),
        "batch": make_table(ks, "batch", pk=["key"],
                            cols={"key": "int", "v": "text"}),
        "rmw": make_table(ks, "rmw", pk=["key"],
                          cols={"key": "int", "v": "text"}),
        "facts": make_table(ks, "facts", pk=["key"],
                            cols={"key": "int", "bucket": "int",
                                  "score": "int"}),
    }


def _scn_kv(sess, tables):
    from cassandra_tpu.client import serialize_params
    t = tables["kv"]
    wq = sess.prepare(f"INSERT INTO {SAT_KEYSPACE}.kv (key, v) "
                      "VALUES (?, ?)")
    rq = sess.prepare(f"SELECT v FROM {SAT_KEYSPACE}.kv WHERE key = ?")

    def op(k, i, rng, is_write, worker, cl):
        if is_write:
            sess.execute_prepared(
                wq, serialize_params(t, ["key", "v"],
                                     [k, rng.bytes(32)]),
                consistency=cl)
        else:
            sess.execute_prepared(
                rq, serialize_params(t, ["key"], [k]), consistency=cl)
    return op


def _scn_wide(sess, tables):
    """Wide partitions: the key stream lands on FEW partitions (k % 32)
    with the key as clustering, so partitions grow to thousands of rows
    and reads fetch whole wide partitions."""
    from cassandra_tpu.client import serialize_params
    t = tables["wide"]
    wq = sess.prepare(f"INSERT INTO {SAT_KEYSPACE}.wide (pk, ck, v) "
                      "VALUES (?, ?, ?)")
    rq = sess.prepare(f"SELECT ck FROM {SAT_KEYSPACE}.wide WHERE pk = ?")

    def op(k, i, rng, is_write, worker, cl):
        pk = k % 32
        if is_write:
            sess.execute_prepared(
                wq, serialize_params(t, ["pk", "ck", "v"],
                                     [pk, k, rng.bytes(24)]),
                consistency=cl)
        else:
            sess.execute_prepared(
                rq, serialize_params(t, ["pk"], [pk]), consistency=cl)
    return op


def _scn_timeseries(sess, tables):
    """TTL-heavy time series on TWCS: every cell written with a TTL,
    appended in time order per series; reads fetch a series."""
    from cassandra_tpu.client import serialize_params
    t = tables["ts"]
    wq = sess.prepare(f"INSERT INTO {SAT_KEYSPACE}.ts (series, at, v) "
                      "VALUES (?, ?, ?) USING TTL 120")
    rq = sess.prepare(f"SELECT at FROM {SAT_KEYSPACE}.ts "
                      "WHERE series = ?")

    def op(k, i, rng, is_write, worker, cl):
        if is_write:
            # per-worker disjoint time points keep appends unique and
            # deterministic under --seed
            sess.execute_prepared(
                wq, serialize_params(
                    t, ["series", "at", "v"],
                    [int(k) % 16, worker * 1_000_000 + i,
                     rng.bytes(24)]),
                consistency=cl)
        else:
            sess.execute_prepared(
                rq, serialize_params(t, ["series"], [int(k) % 16]),
                consistency=cl)
    return op


def _scn_counter(sess, tables):
    """Counter increments route through the counter-leader path, not
    the plain write path — zipf hot keys contend on the leader lock."""
    from cassandra_tpu.client import serialize_params
    t = tables["cnt"]
    wq = sess.prepare(f"UPDATE {SAT_KEYSPACE}.cnt SET hits = hits + 1 "
                      "WHERE key = ?")
    rq = sess.prepare(f"SELECT hits FROM {SAT_KEYSPACE}.cnt "
                      "WHERE key = ?")

    def op(k, i, rng, is_write, worker, cl):
        sess.execute_prepared(
            wq if is_write else rq,
            serialize_params(t, ["key"], [k]), consistency=cl)
    return op


def _scn_lwt(sess, tables):
    """LWT: IF NOT EXISTS through Paxos; under zipf most proposals lose
    the race and return applied=False — still a served op."""
    from cassandra_tpu.client import serialize_params
    t = tables["lwt"]
    wq = sess.prepare(f"INSERT INTO {SAT_KEYSPACE}.lwt (key, v) "
                      "VALUES (?, ?) IF NOT EXISTS")
    rq = sess.prepare(f"SELECT v FROM {SAT_KEYSPACE}.lwt WHERE key = ?")

    def op(k, i, rng, is_write, worker, cl):
        if is_write:
            sess.execute_prepared(
                wq, serialize_params(t, ["key", "v"],
                                     [k, rng.bytes(16)]),
                consistency=cl)
        else:
            sess.execute_prepared(
                rq, serialize_params(t, ["key"], [k]), consistency=cl)
    return op


def _scn_batch(sess, tables):
    """Logged batches: 4 inserts per batch through the batchlog (the
    atomicity machinery, not just 4 writes)."""
    from cassandra_tpu.client import serialize_params
    t = tables["batch"]
    rq = sess.prepare(f"SELECT v FROM {SAT_KEYSPACE}.batched "
                      "WHERE key = ?")

    def op(k, i, rng, is_write, worker, cl):
        if is_write:
            stmts = "; ".join(
                f"INSERT INTO {SAT_KEYSPACE}.batched (key, v) "
                f"VALUES ({int(k) + j}, 'w{worker}-{i}-{j}')"
                for j in range(4))
            sess.execute(f"BEGIN BATCH {stmts}; APPLY BATCH",
                         consistency=cl)
        else:
            sess.execute_prepared(
                rq, serialize_params(t, ["key"], [k]), consistency=cl)
    return op


def _scn_rmw(sess, tables):
    """Mixed read-modify-write: every op is a SELECT followed by an
    INSERT derived from what it read — one logical op, two round
    trips, the latency clients actually see for app-level RMW."""
    from cassandra_tpu.client import serialize_params
    t = tables["rmw"]
    wq = sess.prepare(f"INSERT INTO {SAT_KEYSPACE}.rmw (key, v) "
                      "VALUES (?, ?)")
    rq = sess.prepare(f"SELECT v FROM {SAT_KEYSPACE}.rmw WHERE key = ?")

    def op(k, i, rng, is_write, worker, cl):
        rows = sess.execute_prepared(
            rq, serialize_params(t, ["key"], [k]), consistency=cl).rows
        n = 0
        if rows and rows[0][0]:
            try:
                n = int(str(rows[0][0]).rsplit("-", 1)[-1])
            except ValueError:
                n = 0
        sess.execute_prepared(
            wq, serialize_params(t, ["key", "v"],
                                 [k, f"w{worker}-{n + 1}"]),
            consistency=cl)
    return op


def _scn_analytical(sess, tables):
    """HTAP mix: OLTP point inserts into a fact table interleaved with
    selective ALLOW FILTERING scans and key-space aggregate folds —
    the analytical pushdown lane (zone maps + device kernels) under
    concurrent write pressure, where flushes keep minting fresh zone
    maps while scans consult them."""
    from cassandra_tpu.client import serialize_params
    t = tables["facts"]
    wq = sess.prepare(
        f"INSERT INTO {SAT_KEYSPACE}.facts (key, bucket, score) "
        "VALUES (?, ?, ?)")

    def op(k, i, rng, is_write, worker, cl):
        if is_write:
            sess.execute_prepared(
                wq, serialize_params(
                    t, ["key", "bucket", "score"],
                    [k, int(k) % 64, int(rng.integers(0, 1000))]),
                consistency=cl)
        elif i % 3 == 0:
            # aggregate pushdown: folds on keys, zero rows host-side
            sess.execute(
                f"SELECT count(*) FROM {SAT_KEYSPACE}.facts "
                f"WHERE bucket = {int(k) % 64} ALLOW FILTERING",
                consistency=cl)
        else:
            # selective row pushdown (~1/64 of the table matches)
            sess.execute(
                f"SELECT key FROM {SAT_KEYSPACE}.facts "
                f"WHERE bucket = {int(k) % 64} ALLOW FILTERING",
                consistency=cl)
    return op


# scenario -> (setup factory, default write ratio). write_ratio None =
# the op is intrinsically mixed (rmw)
SCENARIOS = {
    "kv": (_scn_kv, 0.5),
    "wide": (_scn_wide, 0.5),
    "timeseries": (_scn_timeseries, 0.8),
    "counter": (_scn_counter, 0.7),
    "lwt": (_scn_lwt, 0.7),
    "batch": (_scn_batch, 0.5),
    "rmw": (_scn_rmw, None),
    "analytical": (_scn_analytical, 0.7),
}

# the default matrix: every workload class, with the kv baseline run
# under all three key streams (the full cross is available via
# --matrix-legs / run_matrix(legs=...))
DEFAULT_LEGS = [
    ("kv", "zipf"), ("kv", "uniform"), ("kv", "sequential"),
    ("wide", "uniform"), ("timeseries", "sequential"),
    ("counter", "zipf"), ("lwt", "zipf"), ("batch", "uniform"),
    ("rmw", "zipf"), ("analytical", "uniform"),
]


def _sat_worker(idx, ports, scenario, n_ops, dist, key_space,
                write_ratio, seed, workers, cl, barrier,
                results) -> None:
    from cassandra_tpu.client import Cluster, DriverError
    rng = np.random.default_rng(seed * 100_000 + idx)
    lats: list = []
    errs: dict = {}
    ok = 0
    sess = None
    op = None
    try:
        # connections round-robin across the cluster's wire endpoints:
        # every node coordinates a share of the traffic
        sess = Cluster("127.0.0.1", ports[idx % len(ports)]).connect()
        op = SCENARIOS[scenario][0](sess, _sat_tables())
    except Exception as e:
        errs["connection"] = 1
        errs["connection_detail"] = f"{type(e).__name__}: {e}"
        if sess is not None:
            # a failed PREPARE must not leak the connected socket into
            # the server's client registry for the rest of the matrix
            try:
                sess.close()
            except Exception:
                pass
        sess = None
    keys = _keys(dist, n_ops, key_space, rng, idx, workers)
    ratio = SCENARIOS[scenario][1] if write_ratio is None else write_ratio
    if ratio is None:
        is_write = np.zeros(n_ops, dtype=bool)   # rmw: op is both
    else:
        is_write = rng.random(n_ops) < ratio
    barrier.wait()
    if sess is not None:
        for i in range(n_ops):
            t0 = time.perf_counter()
            try:
                op(int(keys[i]), i, rng, bool(is_write[i]), idx, cl)
                ok += 1
            except DriverError as e:
                kind = _classify(str(e))
                errs[kind] = errs.get(kind, 0) + 1
                continue
            except Exception as e:
                errs["connection"] = errs.get("connection", 0) + 1
                errs.setdefault("connection_detail",
                                f"{type(e).__name__}: {e}")
                break
            lats.append((time.perf_counter() - t0) * 1e6)
        try:
            sess.close()
        except Exception:
            pass
    results[idx] = (lats, errs, ok)


def run_scenario(ports, scenario, *, connections=6, ops=240,
                 dist="zipf", key_space=512, write_ratio=None,
                 cl="QUORUM", seed=1) -> dict:
    """One matrix leg: drive `ops` scenario operations over
    `connections` wire connections spread across `ports`. Client-side
    percentiles come from the exact latency list; the server-side view
    is the client_requests hists the SLO service watches."""
    if scenario not in SCENARIOS:
        # validate BEFORE spawning: a worker dying on the lookup after
        # the try block would strand the start barrier forever (the
        # same invariant _worker documents)
        raise ValueError(f"unknown scenario {scenario!r} "
                         f"(have: {', '.join(sorted(SCENARIOS))})")
    if dist not in ("zipf", "uniform", "sequential"):
        # _keys treats anything unrecognized as uniform — a typo'd leg
        # would silently run (and be labeled) with the wrong key stream
        raise ValueError(f"unknown key dist {dist!r} "
                         "(zipf, uniform, sequential)")
    per_conn = max(1, ops // connections)
    wall, lats, errors, ok = _spawn_and_aggregate(
        connections, _sat_worker,
        lambda i, barrier, results: (
            i, list(ports), scenario, per_conn, dist, key_space,
            write_ratio, seed, connections, cl, barrier, results))
    arr = np.array(lats) if lats else np.array([0.0])
    return {
        "scenario": scenario, "dist": dist, "cl": cl,
        "connections": connections, "ok": ok,
        "errors": {k: v for k, v in errors.items() if v},
        "wall_s": round(wall, 3),
        "ops_s": round(ok / wall, 1) if wall > 0 else 0.0,
        "p50_us": round(float(np.percentile(arr, 50)), 1),
        "p99_us": round(float(np.percentile(arr, 99)), 1),
    }


def run_matrix(base_dir: str, *, connections: int = 6,
               ops_per_leg: int = 240, key_space: int = 512,
               legs=None, chaos: bool = True, seed: int = 1,
               target_ms: float = 250.0,
               chaos_target_ms: float = 2.0,
               slo_poll_s: float = 0.05) -> dict:
    """The full saturation matrix against a 3-node RF=3 LocalCluster,
    every leg through the wire with hints and speculative retry live,
    the SLO service polling throughout. Returns the bench `saturation`
    section: per-leg throughput/latency + SLO verdicts, and the chaos
    leg's breach-triggered flight-recorder bundle."""
    import json as json_mod

    from cassandra_tpu.client import Cluster
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.service import diagnostics
    from cassandra_tpu.service.metrics import GLOBAL as METRICS
    from cassandra_tpu.transport import CQLServer
    from cassandra_tpu.utils import faultfs

    legs = list(legs) if legs is not None else list(DEFAULT_LEGS)
    cluster = LocalCluster(3, base_dir, rf=3)
    servers = [CQLServer(n) for n in cluster.nodes]
    ports = [srv.port for srv in servers]
    n1 = cluster.node(1)
    # the coordinator node under observation: its engine carries the
    # SLO registry and the flight recorder the chaos bundle lands in
    settings = n1.engine.settings
    settings.set("diagnostic_events_enabled", True)
    svc = n1.engine.slo
    out: dict = {"cluster": {"nodes": 3, "rf": 3,
                             "hinted_handoff": True,
                             "speculative_retry": True},
                 "legs": {}}
    try:
        # coordinate at the CL the legs declare on the wire (QUORUM) —
        # digest reads, blocking read repair and speculative retry are
        # all on the path; write rounds keep the default 2 s budget
        # (node engines run batch commit + one inbound messaging worker,
        # so concurrent QUORUM acks genuinely queue on this box)
        from cassandra_tpu.cluster.replication import ConsistencyLevel
        for nn in cluster.nodes:
            nn.default_cl = ConsistencyLevel.QUORUM
        s = Cluster("127.0.0.1", ports[0]).connect()
        for ddl in SAT_DDL:
            s.execute(ddl)
        s.close()
        svc.start(slo_poll_s)
        read_objs = ("client_requests.read", "client_requests.read.quorum")
        write_objs = ("client_requests.write",
                      "client_requests.write.quorum")
        for scenario, dist in legs:
            leg_id = f"{scenario}:{dist}"
            # leg boundary, in poller-race-safe order: stamp the new
            # scenario FIRST, re-baseline every objective (compliant /
            # full budget — the shared decaying hists would otherwise
            # carry a previous leg's breaching state across), and only
            # THEN retarget through the hot-reload knob machinery (the
            # same path nodetool/settings vtable writes take). A poll
            # landing anywhere in this window either sees the old
            # generous targets or a fresh transition already carrying
            # this leg's id.
            svc.set_context(scenario=leg_id)
            svc.reset()
            settings.set("slo_targets",
                         {name: target_ms
                          for name in read_objs + write_objs})
            before = {v["objective"]: v["breaches"]
                      for v in svc.snapshot()}
            r = run_scenario(ports, scenario, connections=connections,
                             ops=ops_per_leg, dist=dist,
                             key_space=key_space, cl="QUORUM",
                             seed=seed)
            verdicts = {v["objective"]: v for v in svc.check()}
            slo = {}
            breached = False
            for name, v in verdicts.items():
                new = v["breaches"] - before.get(name, 0)
                if new or v["breaching"]:
                    breached = True
                slo[name] = {"p99_us": v["p99_us"],
                             "target_us": v["target_us"],
                             "breaches": new,
                             "budget_remaining_s":
                                 v["budget_remaining_s"]}
            r["slo"] = slo
            r["verdict"] = "breach" if breached else "ok"
            out["legs"][leg_id] = r
            svc.clear_context()

        # ---- hints live: a replica's storage goes dark mid-traffic;
        # QUORUM writes keep succeeding and the failed sends hint
        hints_before = dict(n1.hints.metrics)
        cluster.stop_node(3)
        hr = run_scenario(ports[:2], "kv", connections=connections,
                          ops=max(ops_per_leg // 2, 32), dist="uniform",
                          key_space=key_space, write_ratio=1.0,
                          cl="QUORUM", seed=seed + 7)
        # failed sends to the dark node expire on the reaper after the
        # write timeout — wait them out before counting hints
        time.sleep(float(n1.proxy.write_timeout) + 0.3)
        hinted = sum(nn.hints.has_hints(cluster.node(3).endpoint)
                     for nn in cluster.nodes[:2])
        cluster.restart_node(3)
        for nn in cluster.nodes[:2]:
            nn.hint_round()
        out["hints_leg"] = {
            "writes_ok": hr["ok"], "errors": hr["errors"],
            "nodes_holding_hints": int(hinted),
            "hints_written_delta":
                n1.hints.metrics.get("written", 0)
                - hints_before.get("written", 0),
            "replayed_total": n1.hints.metrics.get("replayed", 0),
        }

        # ---- elasticity leg: a 4th node bootstraps over the sessioned
        # streaming path while QUORUM write traffic stays live and the
        # SLO poller stays armed. The key stream is sequential with
        # key_space <= per-connection ops, so every key in
        # [0, elastic_space) is written at least once; zero write errors
        # plus a full QUORUM read-back of that range = zero lost writes.
        elastic_id = "elastic:kv:sequential"
        svc.set_context(scenario=elastic_id)
        svc.reset()
        settings.set("slo_targets",
                     {name: target_ms
                      for name in read_objs + write_objs})
        e_ops = max(ops_per_leg, 2 * connections)
        elastic_space = max(e_ops // 2, connections)
        eh: dict = {}

        def _elastic_traffic():
            eh["r"] = run_scenario(
                ports, "kv", connections=connections, ops=e_ops,
                dist="sequential", key_space=elastic_space,
                write_ratio=1.0, cl="QUORUM", seed=seed + 17)

        et = threading.Thread(target=_elastic_traffic, daemon=True)
        et.start()
        time.sleep(0.05)   # writes in flight before the join starts
        n4 = cluster.add_node()
        et.join()
        er = eh["r"]
        sessions_done = sum(
            1 for rec in n4.streams.sessions
            if rec.get("status") == "complete")
        rb = Cluster("127.0.0.1", ports[0]).connect()
        try:
            lost = [k for k in range(elastic_space)
                    if not rb.execute(
                        f"SELECT v FROM {SAT_KEYSPACE}.kv "
                        f"WHERE key = {k}",
                        consistency="QUORUM").rows]
        finally:
            rb.close()
        everdicts = {v["objective"]: v for v in svc.check()}
        out["elasticity_leg"] = {
            "joined_node": n4.endpoint.name,
            "writes_ok": er["ok"], "errors": er["errors"],
            "ops_s": er["ops_s"], "p99_us": er["p99_us"],
            "bootstrap_sessions_completed": sessions_done,
            "keys_checked": elastic_space, "keys_lost": len(lost),
            "slo": {name: {"p99_us": v["p99_us"],
                           "breaches": v["breaches"]}
                    for name, v in everdicts.items()},
            "verdict": "ok" if not er["errors"] and not lost
            else ("write_errors" if er["errors"] else "lost_writes"),
        }
        svc.clear_context()

        # ---- chaos leg: faultfs storage faults mid-run on node2's
        # sstables + a tightened read target — must end in a
        # breach-triggered bundle stamped with the scenario id
        if chaos:
            chaos_id = "chaos:kv:zipf"
            # preload + flush so reads cross the sstable.read
            # checkpoint on real files
            run_scenario(ports, "kv", connections=connections,
                         ops=ops_per_leg, dist="uniform",
                         key_space=key_space, write_ratio=1.0,
                         cl="QUORUM", seed=seed + 11)
            for nn in cluster.nodes:
                for cfs in list(nn.engine.stores.values()):
                    try:
                        cfs.flush()
                    except Exception:
                        pass
            from cassandra_tpu.storage import chunk_cache
            chunk_cache.GLOBAL.clear()
            # node2 reacts to the injected EIO with disk_failure_policy
            # `stop`: its storage goes terminal on the first fault, so
            # for the rest of the leg it is a live-but-sick replica —
            # every read against it fails fast, the coordinator's
            # speculative retry fails over, and failed writes hint
            cluster.node(2).engine.settings.set(
                "disk_failure_policy", "stop")
            # same poller-race-safe order as the leg loop: context,
            # reset, THEN the tightened targets — a poll between the
            # tighten and the reset would otherwise publish an
            # unstamped breach whose dump dedup-suppresses the stamped
            # one this leg must end with
            svc.set_context(scenario=chaos_id)
            svc.reset()   # the chaos breach must be a fresh transition
            settings.set("slo_targets",
                         {"client_requests.read": chaos_target_ms,
                          "client_requests.read.quorum":
                              chaos_target_ms})
            spec0 = METRICS.counter("reads.speculative_retries")
            won0 = METRICS.counter("reads.speculative_retries_won")
            node2_dir = cluster.node(2).engine.data_dir
            faultfs.arm("sstable.read", "error", times=256,
                        path_substr=node2_dir)
            try:
                cr = run_scenario(ports, "kv", connections=connections,
                                  ops=ops_per_leg, dist="zipf",
                                  key_space=key_space, write_ratio=0.1,
                                  cl="QUORUM", seed=seed + 13)
            finally:
                faultfs.disarm("sstable.read")
            verdicts = {v["objective"]: v for v in svc.check()}
            breach_evs = [e for e in
                          diagnostics.GLOBAL.events("slo.breach")
                          if e.fields.get("scenario") == chaos_id]
            bundle = next((p for p in reversed(svc.recorder.dumps)
                           if "slo_breach" in p), None)
            bundle_has_event = scenario_in_bundle = False
            if bundle is not None:
                with open(bundle) as f:
                    b = json_mod.load(f)
                evs = [e for e in b.get("events", [])
                       if e.get("type") == "slo.breach"]
                bundle_has_event = bool(evs)
                scenario_in_bundle = any(
                    e.get("scenario") == chaos_id for e in evs)
            ro = verdicts.get("client_requests.read", {})
            out["chaos"] = {
                **cr, "scenario_id": chaos_id,
                "faults_injected":
                    "sstable.read EIO on node2 (times<=256)",
                "read_p99_us": ro.get("p99_us"),
                "read_target_us": ro.get("target_us"),
                "breach_events": len(breach_evs),
                "breached": bool(breach_evs),
                "budget_remaining_s": ro.get("budget_remaining_s"),
                "bundle": bundle,
                "bundle_has_breach_event": bundle_has_event,
                "scenario_id_in_bundle": scenario_in_bundle,
                "speculative_retries_fired":
                    METRICS.counter("reads.speculative_retries") - spec0,
                "speculative_retries_won":
                    METRICS.counter("reads.speculative_retries_won")
                    - won0,
            }
            svc.clear_context()
        out["slo_totals"] = {
            "checks": svc.checks,
            "breaches": METRICS.counter("slo.breaches"),
            "budget_exhausted": METRICS.counter("slo.budget_exhausted"),
            "recorder_dumps": METRICS.counter("slo.recorder_dumps"),
        }
        out["workload_classes"] = sorted(
            {scn for scn, _ in legs} | ({"kv"} if chaos else set()))
        return out
    finally:
        svc.stop()
        svc.clear_context()
        for srv in servers:
            try:
                srv.close()
            except Exception:
                pass
        cluster.shutdown()


# -------------------------------------------------------------- CLI ------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="stress")
    p.add_argument("--profile", choices=("write", "read", "mixed"),
                   default="mixed")
    p.add_argument("--connections", type=int, default=16)
    p.add_argument("--ops", type=int, default=4096)
    p.add_argument("--dist", choices=("uniform", "zipf", "sequential"),
                   default="uniform")
    p.add_argument("--key-space", type=int, default=4096)
    p.add_argument("--value-bytes", type=int, default=64)
    p.add_argument("--write-ratio", type=float, default=0.5)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--host", default=None,
                   help="drive an EXISTING server (with --port); "
                        "default spins one up in-process")
    p.add_argument("--port", type=int, default=9042)
    p.add_argument("--smoke", action="store_true",
                   help="tier-2 drill: deterministic seconds-long "
                        "correctness + overload + rate-limit checks")
    p.add_argument("--matrix", action="store_true",
                   help="saturation matrix: every workload class "
                        "through the wire against a 3-node RF=3 "
                        "cluster with SLO verdicts + chaos leg")
    p.add_argument("--matrix-legs", default=None,
                   help="comma-separated scenario:dist legs "
                        "(default: the DEFAULT_LEGS matrix; scenarios: "
                        + ",".join(SCENARIOS) + ")")
    p.add_argument("--no-chaos", action="store_true",
                   help="matrix: skip the fault-injection leg")
    args = p.parse_args(argv)
    if args.smoke:
        return smoke()
    if args.matrix:
        import shutil
        import tempfile
        legs = None
        if args.matrix_legs:
            legs = [tuple(leg.split(":", 1))
                    for leg in args.matrix_legs.split(",")]
        base = tempfile.mkdtemp(prefix="ctpu-sat-")
        try:
            print(json.dumps(run_matrix(
                base, connections=args.connections,
                ops_per_leg=args.ops, key_space=args.key_space,
                legs=legs, chaos=not args.no_chaos, seed=args.seed)))
        finally:
            shutil.rmtree(base, ignore_errors=True)
        return 0

    srv = engine = None
    base = None
    if args.host is None:
        import shutil
        import tempfile

        from cassandra_tpu.schema import Schema
        from cassandra_tpu.storage.engine import StorageEngine
        from cassandra_tpu.transport import CQLServer
        base = tempfile.mkdtemp(prefix="ctpu-stress-")
        engine = StorageEngine(os.path.join(base, "d"), Schema(),
                               commitlog_sync="periodic")
        srv = CQLServer(engine)
        host, port = "127.0.0.1", srv.port
    else:
        host, port = args.host, args.port
    try:
        if args.profile == "read":     # preload the key space
            run_stress(host, port, profile="write",
                       connections=min(8, args.connections),
                       ops=args.key_space, dist="sequential",
                       key_space=args.key_space,
                       value_bytes=args.value_bytes, seed=args.seed)
        out = run_stress(host, port, profile=args.profile,
                         connections=args.connections, ops=args.ops,
                         dist=args.dist, key_space=args.key_space,
                         value_bytes=args.value_bytes,
                         write_ratio=args.write_ratio, seed=args.seed)
        print(json.dumps(out))
    finally:
        if srv is not None:
            srv.close()
            engine.close()
            import shutil
            shutil.rmtree(base, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
