"""TCM over real processes: the ring is materialized from the epoch log,
joins are multi-step logged sequences, and a node that crashes between
start_join and finish_join resumes from its log on restart.

Reference: tcm/Startup.java:85 (initialize: first CMS node vs join),
tcm/sequences/BootstrapAndJoin.java (resumable multi-step op),
tcm/ClusterMetadata.java:81 (epoch-ordered log)."""
import json
import os
import socket
import subprocess
import sys
import time
import uuid

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TABLE_ID = uuid.uuid5(uuid.NAMESPACE_DNS, "ctpu.test.tcm")
DDL = [
    "CREATE KEYSPACE ks WITH replication = "
    "{'class': 'SimpleStrategy', 'replication_factor': 2}",
    f"CREATE TABLE ks.kv (k int PRIMARY KEY, v text) "
    f"WITH id = {TABLE_ID}",
]


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _spawn(cfg_path, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, "-m", "cassandra_tpu.tools.noded", str(cfg_path)],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env)


@pytest.mark.slow
def test_join_crash_resume(tmp_path):
    p1_port, p2_port, obs_port = _free_ports(3)
    seed = {"name": "node1", "host": "127.0.0.1", "port": p1_port}
    cfg1 = {"name": "node1", "host": "127.0.0.1", "port": p1_port,
            "data_dir": str(tmp_path / "node1"), "auto_join": True,
            "seed_nodes": [], "gossip_interval": 0.1,
            "jax_platform": "cpu", "ddl": DDL, "vnodes": 4}
    cfg2 = {"name": "node2", "host": "127.0.0.1", "port": p2_port,
            "data_dir": str(tmp_path / "node2"), "auto_join": True,
            "seed_nodes": [seed], "gossip_interval": 0.1,
            "jax_platform": "cpu", "vnodes": 4}
    (tmp_path / "n1.json").write_text(json.dumps(cfg1))
    (tmp_path / "n2.json").write_text(json.dumps(cfg2))

    procs = []
    try:
        p1 = _spawn(tmp_path / "n1.json")
        procs.append(p1)
        line = p1.stdout.readline()
        assert line.startswith("READY"), (line, p1.stderr.read())

        # seed some data through the first node's native path: drive an
        # in-process observer that pulls the log and coordinates writes
        from cassandra_tpu.cluster.node import Node
        from cassandra_tpu.cluster.replication import ConsistencyLevel
        from cassandra_tpu.cluster.ring import Endpoint, Ring
        from cassandra_tpu.cluster.schema_sync import SchemaSync
        from cassandra_tpu.cluster.tcp import TcpTransport
        from cassandra_tpu.schema import Schema

        seed_ep = Endpoint("node1", host="127.0.0.1", port=p1_port)
        obs_ring = Ring()
        obs = Node(Endpoint("observer", host="127.0.0.1", port=obs_port),
                   str(tmp_path / "observer"), Schema(), obs_ring,
                   TcpTransport(), seeds=[seed_ep], gossip_interval=0.1)
        obs.cluster_nodes = [obs]
        obs.schema_sync = SchemaSync(obs, str(tmp_path / "observer"))
        obs.schema_sync.pull_from_peers(timeout=10.0, peers=[seed_ep])
        assert any(e.name == "node1" for e in obs_ring.endpoints), \
            "observer did not learn node1 from the log"
        assert obs.schema.get_table("ks", "kv") is not None
        obs.gossiper.start()
        deadline = time.time() + 20
        while time.time() < deadline and not obs.is_alive(seed_ep):
            time.sleep(0.2)
        assert obs.is_alive(seed_ep), "gossip to node1 never converged"
        s = obs.session()
        s.keyspace = "ks"
        obs.default_cl = ConsistencyLevel.ONE
        for i in range(30):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")

        # node2 crashes between start_join and the stream (staged fault)
        p2 = _spawn(tmp_path / "n2.json",
                    {"CTPU_TEST_CRASH_AFTER_START_JOIN": "1"})
        procs.append(p2)
        assert p2.wait(timeout=60) == 42, p2.stderr.read()
        # node2's log holds the start_join; node1's ring shows it pending
        log2 = (tmp_path / "node2" / "schema_log.jsonl").read_text()
        assert "start_join" in log2 and "finish_join" not in log2

        # restart WITHOUT the fault: the daemon must resume and finish
        p2b = _spawn(tmp_path / "n2.json")
        procs.append(p2b)
        deadline = time.time() + 90
        resumed = False
        while time.time() < deadline:
            line = p2b.stdout.readline()
            if not line:
                break
            if "resumed interrupted topology op" in line:
                resumed = True
            if line.startswith("READY"):
                break
        assert resumed, p2b.stderr.read()
        log2 = (tmp_path / "node2" / "schema_log.jsonl").read_text()
        assert "finish_join" in log2

        # the observer re-pulls: node2 is now a full member
        obs.schema_sync.pull_from_peers(timeout=10.0, peers=[seed_ep])
        assert any(e.name == "node2" for e in obs_ring.endpoints), \
            "node2 not promoted in the replicated ring"
        assert not obs_ring.pending
        # data is fully available with both members up (CL=ALL)
        node2_ep = next(e for e in obs_ring.endpoints
                        if e.name == "node2")
        deadline = time.time() + 20
        while time.time() < deadline and not obs.is_alive(node2_ep):
            time.sleep(0.2)
        assert obs.is_alive(node2_ep), "gossip to node2 never converged"
        obs.default_cl = ConsistencyLevel.ALL
        for i in (0, 7, 29):
            assert s.execute(f"SELECT v FROM kv WHERE k = {i}").rows == \
                [(f"v{i}",)]
        # describecluster surfaces the metadata epoch
        from cassandra_tpu.tools import nodetool
        info = nodetool.describecluster(obs)
        assert info["metadata_epoch"] and info["metadata_epoch"] >= 4
        obs.shutdown()
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
