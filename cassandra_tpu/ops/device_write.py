"""Device-resident compaction rounds: merge → purge → segment-cut →
serialize without bouncing cell columns through the host.

LUDA (PAPERS.md, arxiv 2004.03054) gets its GPU-LSM win by keeping cell
data accelerator-resident across decode → merge → pack instead of
round-tripping the host per stage. This module is that mode for the
device merge engine: one fused program per round runs the LSD sort, the
reconcile/purge masks AND the kept-cell compaction (stable partition +
column gather) on the device, so the CellBatch's fixed-width columns
(lanes / ts / ldt / ttl / flags / frame offsets) never come back to the
host as columns. They stay resident in a device-side pending buffer
across rounds; segment cuts slice them on-device; and a second fused
kernel serializes each full segment's META block (including the "ce"
ts-delta pre-transform, format.py) byte-identically to the host
serializer (storage/sstable/writer.py build_meta_block). The host
receives only the FINISHED blocks the compress pool consumes — the
META bytes and the row-major LANES matrix `segment_pack` wants — plus
the variable-length payload, which never went to the device (ragged
bytes gather through the native C++ path, storage/cellbatch.py).

Byte identity with the serial host path is absolute, not statistical:
rounds the device cannot reproduce exactly fall back to the host
materialization path per ROUND —

  * equal-(identity, ts) duplicate runs (the device sort does not order
    the Cells.resolveRegular tie-break lanes; the host resolves them
    with full values),
  * kept expired-TTL cells (tombstone conversion rewrites flags AND
    drops the value bytes — a payload rewrite),
  * counter cells / range-tombstone bounds (host-only reconcile),

and `scripts/check_compaction_ab.py`'s device legs pin the whole-file
sha256 equality. Scalar counts of those conditions are computed in the
same fused program, so the decision costs three tiny transfers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..storage.cellbatch import (DEATH_FLAGS, FLAG_COUNTER,
                                 FLAG_RANGE_BOUND, CellBatch)
from . import device_compress
from . import merge as dmerge

_U32 = jnp.uint32
_BIAS_H = 0x80000000  # high u32 word of the 2^63 timestamp bias


# ------------------------------------------------------------- operands --

def build_resident_operands(cat: CellBatch, gc_before: int, now: int,
                            purgeable_ts_fn):
    """The v1 packed operands (merge.build_operands) extended with the
    serialize-side columns: full flags byte, ttl, u32 frame lengths and
    value offsets. Returns (operands, pts_host) or None when a frame
    exceeds the u32 lanes (the host path raises its loud error
    instead)."""
    n = len(cat)
    N = dmerge._bucket(n)
    lens64 = cat.off[1:] - cat.off[:-1]
    vrel64 = cat.val_start - cat.off[:-1]
    if n and (int(lens64.max()) >= 1 << 32
              or int(vrel64.max()) >= 1 << 32):
        return None
    pts_host = None
    if purgeable_ts_fn is not None:
        pts_host = purgeable_ts_fn(cat).astype(np.int64)
        fn = lambda _c: pts_host
    else:
        fn = None
    operands = dmerge.build_operands(cat, gc_before=gc_before, now=now,
                                     purgeable_ts_fn=fn, bucket=N)
    flags8 = np.zeros(N, dtype=np.uint8)
    flags8[:n] = cat.flags
    ttl = np.zeros(N, dtype=np.int32)
    ttl[:n] = cat.ttl
    fl = np.zeros(N, dtype=np.uint32)
    fl[:n] = lens64.astype(np.uint32)
    vr = np.zeros(N, dtype=np.uint32)
    vr[:n] = vrel64.astype(np.uint32)
    operands["flags8"] = jnp.asarray(flags8)
    operands["ttl"] = jnp.asarray(ttl)
    operands["fl"] = jnp.asarray(fl)
    operands["vr"] = jnp.asarray(vr)
    return operands, pts_host


RESIDENT_COLS = ("lanes", "ts_h", "ts_l", "ldt", "ttl", "flags8",
                 "fl", "vr")


@jax.jit
def _resident_program(operands):
    """One dispatch: LSD sort, reconcile+purge, kept-cell compaction and
    column gather — the merged round stays on the device, in output
    order, kept cells first. Returns (n_keep, n_amb, n_exp_kept,
    perm_out, cols, perm, packed); the last two feed the host fallback
    when the scalar counts demand it."""
    perm = dmerge.device_sort_perm(operands)
    packed = dmerge.reconcile_kernel(operands, perm)
    keep = (packed & 1) != 0
    amb = (packed & 2) != 0
    expired = (packed & 4) != 0
    n_keep = jnp.sum(keep).astype(jnp.int32)
    n_amb = jnp.sum(amb).astype(jnp.int32)
    n_exp_kept = jnp.sum(expired & keep).astype(jnp.int32)
    N = keep.shape[0]
    # stable partition: kept cells to the front, SORTED ORDER preserved
    # (stability) — the device-side analog of np.flatnonzero(keep)
    _, ord_ = jax.lax.sort(
        (jnp.where(keep, jnp.uint32(0), jnp.uint32(1)),
         jnp.arange(N, dtype=jnp.int32)), num_keys=1, is_stable=True)
    perm_out = perm[ord_]
    cols = {k: operands[k][perm_out] for k in RESIDENT_COLS}
    return n_keep, n_amb, n_exp_kept, perm_out, cols, perm, packed


# ------------------------------------------------------ serialize kernel --

@jax.jit
def _meta_block_kernel(ts_h, ts_l, ldt, ttl, flags8, fl, vr):
    """Fused META-block serialize for one FULL segment: the "ce"
    ts-delta pre-transform + the 25 B/cell section layout emitted as
    one u8 buffer, plus the segment's stats reductions — all in a
    single device program, byte-identical to the host
    build_meta_block (pinned by test).

    ts planes arrive BIASED (uts = ts + 2^63 mod 2^64, the sort form);
    bias cancels in differences, so the wraparound deltas of the u32
    pairs ARE the i64 deltas, and cell 0's absolute stamp is its uts
    minus the bias — one XOR on the high word."""
    n = ts_h.shape[0]
    prev_h = jnp.concatenate(
        [jnp.full((1,), _BIAS_H, dtype=jnp.uint32), ts_h[:-1]])
    prev_l = jnp.concatenate([jnp.zeros(1, dtype=jnp.uint32), ts_l[:-1]])
    d_l = ts_l - prev_l
    borrow = (ts_l < prev_l).astype(jnp.uint32)
    d_h = ts_h - prev_h - borrow

    def u32_bytes(a):
        return jax.lax.bitcast_convert_type(a, jnp.uint8).reshape(-1)

    # (n, 2) u32 little-endian pair -> the 8 LE bytes of each i64 delta
    ts_b = jax.lax.bitcast_convert_type(
        jnp.stack([d_l, d_h], axis=1), jnp.uint8).reshape(-1)
    meta = jnp.concatenate([
        ts_b, u32_bytes(ldt), u32_bytes(ttl), flags8,
        u32_bytes(fl), u32_bytes(vr)])

    # stats reductions (biased-pair lexicographic min/max for ts)
    max_h = jnp.max(ts_h)
    max_l = jnp.max(jnp.where(ts_h == max_h, ts_l, jnp.uint32(0)))
    min_h = jnp.min(ts_h)
    min_l = jnp.min(jnp.where(ts_h == min_h, ts_l, _U32(0xFFFFFFFF)))
    tombs = jnp.sum((flags8 & jnp.uint8(DEATH_FLAGS)) != 0)
    return meta, (min_h, min_l, max_h, max_l,
                  jnp.min(ldt), jnp.max(ldt), tombs)


def _uts_pair_to_i64(h: int, l: int) -> int:
    return int(np.int64(np.uint64((int(h) << 32) | int(l))
                        ^ np.uint64(1 << 63)))


# --------------------------------------------------------------- rounds --

class DeviceRound:
    """One merged round whose fixed-width columns live on the device
    (padded; `n` is the kept length). The payload side — the only
    ragged data — stays host-resident: gathering variable-length frames
    is exactly what the native C++ gather does well and what device
    memory layouts do badly."""

    __slots__ = ("n", "cols", "payload", "off", "val_start", "pk_map",
                 "ck_fits_prefix")

    def __init__(self, n, cols, payload, off, val_start, pk_map,
                 ck_fits_prefix):
        self.n = n
        self.cols = cols
        self.payload = payload
        self.off = off
        self.val_start = val_start
        self.pk_map = pk_map
        self.ck_fits_prefix = ck_fits_prefix

    def __len__(self) -> int:
        return self.n


class ResidentHandle:
    __slots__ = ("mode", "result", "cat", "n", "out", "pts",
                 "gc_before", "now", "prof", "fallback")


# test seam: {round_seq: seconds} delay applied at collect time BEFORE
# the device result is consumed — reverses the completion order of
# in-flight rounds (tests/test_device_resident.py); None in production.
_TEST_COLLECT_DELAY = None
_collect_seq = 0


def submit_merge_resident(batches: list[CellBatch], gc_before: int = 0,
                          now: int = 0, purgeable_ts_fn=None,
                          prof: dict | None = None,
                          device=None) -> ResidentHandle:
    """Dispatch one device-resident round (async). Rounds the resident
    formulation cannot encode (counters, range bounds, oversized
    frames) dispatch through the regular submit_merge path instead —
    collect_merge_resident returns a host CellBatch for those."""
    import time as _time

    h = ResidentHandle()
    h.gc_before, h.now, h.prof = gc_before, now, prof
    h.fallback = None
    cat = CellBatch.concat(batches)
    h.cat, h.n = cat, len(cat)
    if h.n == 0:
        h.mode, h.result = "done", cat
        return h
    if ((cat.flags & (FLAG_RANGE_BOUND | FLAG_COUNTER)) != 0).any():
        h.mode = "host"
        h.fallback = dmerge.submit_merge(batches, gc_before, now,
                                         purgeable_ts_fn, prof=prof)
        return h
    t0 = _time.perf_counter()
    built = build_resident_operands(cat, gc_before, now, purgeable_ts_fn)
    if built is None:   # >= 4 GiB frame: let the host path fail loudly
        h.mode = "host"
        h.fallback = dmerge.submit_merge(batches, gc_before, now,
                                         purgeable_ts_fn, prof=prof)
        return h
    operands, h.pts = built
    if device is not None:
        operands = {k: jax.device_put(v, device)
                    for k, v in operands.items()}
    t1 = _time.perf_counter()
    h.out = _resident_program(operands)
    from ..service.profiling import GLOBAL as _kprof
    if _kprof.record_dispatch(
            "merge.resident",
            (int(operands["lanes"].shape[0]),
             int(operands["lanes"].shape[1])),
            _time.perf_counter() - t1):
        _kprof.maybe_record_cost("merge.resident", _resident_program,
                                 (operands,))
    h.mode = "resident"
    if prof is not None:
        prof["pack"] = prof.get("pack", 0.0) + (t1 - t0)
    return h


def collect_merge_resident(h: ResidentHandle):
    """Block on a resident round. Returns a DeviceRound (columns still
    on device) for rounds the device reproduced exactly, else a host
    CellBatch computed through the pinned byte-identical fallback."""
    import time as _time

    global _collect_seq
    if _TEST_COLLECT_DELAY is not None:
        _time.sleep(_TEST_COLLECT_DELAY.get(_collect_seq, 0.0))
    _collect_seq += 1
    if h.mode == "done":
        return promote_round(h.result)
    if h.mode == "host":
        return promote_round(dmerge.collect_merge(h.fallback))
    cat, prof = h.cat, h.prof
    n_keep_d, n_amb_d, n_exp_d, perm_out_d, cols, perm_d, packed_d = h.out
    t0 = _time.perf_counter()
    n_keep = int(n_keep_d)          # blocks until the program finishes
    n_amb = int(n_amb_d)
    n_exp_kept = int(n_exp_d)
    t1 = _time.perf_counter()
    from ..service.profiling import GLOBAL as _kprof
    _kprof.record_execute("merge.resident", t1 - t0)
    if prof is not None:
        prof["device"] = prof.get("device", 0.0) + (t1 - t0)

    if n_amb or n_exp_kept:
        # exact-resolution round: equal-(identity, ts) runs need the
        # host's full-value tie-break, kept expired cells need the
        # tombstone conversion's payload rewrite — materialize on the
        # host exactly like ops/merge.py's v1/v2 collect
        n = h.n
        perm = np.asarray(perm_d).astype(np.int64)[:n]
        keep, amb, expired, shadowed = dmerge.unpack_masks(
            np.asarray(packed_d)[:n])
        pts_sorted = h.pts[perm] if h.pts is not None else None
        if amb.any():
            dmerge.host_tiebreak(cat, perm, keep, amb, shadowed,
                                 expired, h.gc_before, pts_sorted)
        out = dmerge.finalize_merged(cat, perm, keep, expired, shadowed)
        if prof is not None:
            prof["gather"] = prof.get("gather", 0.0) \
                + (_time.perf_counter() - t1)
        return promote_round(out)

    # resident round: pull ONLY the kept permutation (the payload
    # gather's index vector) — the columns stay on the device
    perm_kept = np.asarray(perm_out_d).astype(np.int64)[:n_keep]
    payload, off, val_start = _gather_payload(cat, perm_kept)
    if prof is not None:
        prof["gather"] = prof.get("gather", 0.0) \
            + (_time.perf_counter() - t1)
    return DeviceRound(n_keep, cols, payload, off, val_start,
                       dict(cat.pk_map), cat.ck_fits_prefix)


def promote_round(batch: CellBatch) -> DeviceRound:
    """Lift a host-materialized round (fallback rounds: ties, expired
    conversions, counters, range bounds) onto the device so the write
    lane consumes ONE ordered stream — interleaving host appends with
    device-pending cells would cut segments out of order. Values are
    copied verbatim, so the serialized bytes are identical to feeding
    the batch through the host writer."""
    n = len(batch)
    lens64 = batch.off[1:] - batch.off[:-1]
    vrel64 = batch.val_start - batch.off[:-1]
    if n and (int(lens64.max()) >= 1 << 32
              or int(vrel64.max()) >= 1 << 32):
        # mirror the host serializer's loud failure (writer._cut_segment)
        raise ValueError(
            f"cell frame exceeds the u32 offset lane "
            f"(max frame {int(lens64.max())} bytes)")
    with np.errstate(over="ignore"):
        uts = batch.ts.astype(np.uint64) ^ np.uint64(1 << 63)
    cols = {
        "lanes": jnp.asarray(np.ascontiguousarray(batch.lanes)),
        "ts_h": jnp.asarray((uts >> np.uint64(32)).astype(np.uint32)),
        "ts_l": jnp.asarray((uts & np.uint64(0xFFFFFFFF))
                            .astype(np.uint32)),
        "ldt": jnp.asarray(batch.ldt.astype(np.int32, copy=False)),
        "ttl": jnp.asarray(batch.ttl.astype(np.int32, copy=False)),
        "flags8": jnp.asarray(batch.flags.astype(np.uint8, copy=False)),
        "fl": jnp.asarray(lens64.astype(np.uint32)),
        "vr": jnp.asarray(vrel64.astype(np.uint32)),
    }
    return DeviceRound(n, cols, np.asarray(batch.payload),
                       np.asarray(batch.off, dtype=np.int64),
                       np.asarray(batch.val_start, dtype=np.int64),
                       dict(batch.pk_map), batch.ck_fits_prefix)


def _gather_payload(cat: CellBatch, perm: np.ndarray):
    """Host-side ragged payload gather (the one part of the round that
    never went to the device) — same native path apply_permutation
    uses, without touching the fixed-width columns."""
    from ..storage.cellbatch import _native_gather
    n = len(perm)
    starts = cat.off[:-1][perm]
    lens = (cat.off[1:] - cat.off[:-1])[perm]
    new_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=new_off[1:])
    total = int(new_off[-1])
    if total:
        payload = _native_gather(cat.payload, cat.off, perm, new_off)
        if payload is None:
            pos_in_cell = np.arange(total, dtype=np.int64) - \
                np.repeat(new_off[:-1], lens)
            payload = cat.payload[np.repeat(starts, lens) + pos_in_cell]
    else:
        payload = np.zeros(0, dtype=np.uint8)
    val_start = new_off[:-1] + (cat.val_start - cat.off[:-1])[perm]
    return payload, new_off, val_start


# ----------------------------------------------------------- write lane --

class DeviceWriteLane:
    """The device-resident write stage: accumulates rounds' columns in
    a device pending buffer, cuts segments on-device, serializes each
    full segment's META block with the fused kernel and hands the
    writer only finished blocks (writer._emit_segment — the exact tail
    the host path runs after its own serialize). The final partial
    segment assembles through the host build_meta_block on pulled
    column slices: one segment per output, and bit-equality with the
    kernel is the pinned contract, not an optimization target."""

    def __init__(self, writer):
        from ..storage.sstable.format import SEGMENT_CELLS
        self.writer = writer
        self.seg_cells = writer.segment_cells or SEGMENT_CELLS
        self.cols: dict | None = None     # device pending columns
        self.pending = 0                  # valid cells in self.cols
        self.payloads: list = []          # (payload, off, val_start)
        self.payload_cells = 0
        self.pk_map: dict = {}

    def append(self, r: DeviceRound) -> None:
        import time as _time
        t0 = _time.perf_counter()
        w = self.writer
        if w.K is None:
            w.K = int(r.cols["lanes"].shape[1])
        w._ck_fits = w._ck_fits and r.ck_fits_prefix
        take = {k: v[:r.n] for k, v in r.cols.items()}
        if self.cols is None or self.pending == 0:
            self.cols = take
        else:
            self.cols = {k: jnp.concatenate([self.cols[k][:self.pending],
                                             take[k]])
                         for k in RESIDENT_COLS}
        self.pending += r.n
        self.payloads.append((r.payload, r.off, r.val_start))
        self.payload_cells += r.n
        for k, v in r.pk_map.items():
            self.pk_map[k] = v
        w._acct("serialize", _time.perf_counter() - t0)
        while self.pending >= self.seg_cells:
            self._cut(self.seg_cells)

    def flush(self) -> None:
        """Cut everything left (the final partial segment) — the
        device-mode analog of finish()'s pending drain; call before
        writer.finish()/roll."""
        while self.pending >= self.seg_cells:
            self._cut(self.seg_cells)
        if self.pending:
            self._cut(self.pending)

    # ------------------------------------------------------------ internals

    def _take_payload(self, n: int):
        """Pop n cells' worth of payload frames (host side), mirroring
        SSTableWriter._take's slicing."""
        outs, got = [], 0
        while got < n:
            payload, off, val_start = self.payloads[0]
            avail = len(off) - 1
            need = n - got
            if avail <= need:
                outs.append((payload, off, val_start))
                self.payloads.pop(0)
                got += avail
            else:
                base = int(off[need])
                outs.append((payload[:base], off[:need + 1],
                             val_start[:need]))
                self.payloads[0] = (payload[base:], off[need:] - base,
                                    val_start[need:] - base)
                got = n
        self.payload_cells -= n
        if len(outs) == 1:
            payload, off, _vs = outs[0]
            return np.ascontiguousarray(payload[:int(off[-1])])
        return np.concatenate([payload[:int(off[-1])]
                               for payload, off, _vs in outs])

    def _cut(self, n: int) -> None:
        import time as _time
        w = self.writer
        t0 = _time.perf_counter()
        seg = {k: self.cols[k][:n] for k in RESIDENT_COLS}
        self.cols = {k: self.cols[k][n:] for k in RESIDENT_COLS}
        self.pending -= n
        lanes_np = np.ascontiguousarray(np.asarray(seg["lanes"]))
        dc_state = None
        dc_compress_s = 0.0
        if n == self.seg_cells:
            # full segment: the fused kernel serializes + reduces stats
            # in one device program; the host sees finished bytes
            t_k = _time.perf_counter()
            meta_d, st = _meta_block_kernel(
                seg["ts_h"], seg["ts_l"], seg["ldt"], seg["ttl"],
                seg["flags8"], seg["fl"], seg["vr"])
            from ..service.profiling import GLOBAL as _kprof
            if _kprof.record_dispatch("write.serialize", (n,),
                                      _time.perf_counter() - t_k):
                _kprof.maybe_record_cost(
                    "write.serialize", _meta_block_kernel,
                    (seg["ts_h"], seg["ts_l"], seg["ldt"], seg["ttl"],
                     seg["flags8"], seg["fl"], seg["vr"]))
            t_k = _time.perf_counter()
            meta = np.asarray(meta_d)
            _kprof.record_execute("write.serialize",
                                  _time.perf_counter() - t_k)
            stats = (_uts_pair_to_i64(st[0], st[1]),
                     _uts_pair_to_i64(st[2], st[3]),
                     int(st[4]), int(st[5]), int(st[6]))
            if w._device_compress_now():
                # second fused program: lane shuffle + order check +
                # the policy match scans; the host keeps only the LZ4
                # wire emission (O(sequences)) and the pwrite pump
                t_c = _time.perf_counter()
                try:
                    planes_d, mbl, mbd, lbl, lbd, order_ok = \
                        device_compress.segment_scan_kernel(
                            meta_d, seg["lanes"])
                    if _kprof.record_dispatch(
                            "write.compress", (n,),
                            _time.perf_counter() - t_c):
                        _kprof.maybe_record_cost(
                            "write.compress",
                            device_compress.segment_scan_kernel,
                            (meta_d, seg["lanes"]))
                    t_e = _time.perf_counter()
                    ok = bool(order_ok)
                    planes_np = np.asarray(planes_d)
                    scans = ((np.asarray(mbl), np.asarray(mbd)),
                             (np.asarray(lbl), np.asarray(lbd)))
                    _kprof.record_execute("write.compress",
                                          _time.perf_counter() - t_e)
                except Exception:
                    # per-segment fallback: the host compress leg takes
                    # this one; output bytes identical either way
                    from ..service.metrics import GLOBAL as _METRICS
                    _METRICS.incr("compaction.device_compress_fallback")
                else:
                    if not ok:
                        raise ValueError("appended cells out of order")
                    dc_state = (planes_np, scans)
                dc_compress_s = _time.perf_counter() - t_c
        else:
            # final partial segment: host assembly through the one
            # shared META builder (byte-identical layout by definition)
            from ..storage.sstable.writer import build_meta_block
            h = np.asarray(seg["ts_h"]).astype(np.uint64)
            l = np.asarray(seg["ts_l"]).astype(np.uint64)
            ts = ((h << np.uint64(32)) | l) ^ np.uint64(1 << 63)
            ts = ts.astype(np.int64)
            ldt = np.asarray(seg["ldt"])
            ttl = np.asarray(seg["ttl"])
            flags = np.asarray(seg["flags8"])
            meta = build_meta_block(ts, ldt, ttl, flags,
                                    np.asarray(seg["fl"]).astype("<u4"),
                                    np.asarray(seg["vr"]).astype("<u4"))
            stats = (int(ts.min()), int(ts.max()),
                     int(ldt.min()), int(ldt.max()),
                     int(((flags & DEATH_FLAGS) != 0).sum()))
        payload_np = self._take_payload(n)
        w._acct("serialize", _time.perf_counter() - t0 - dc_compress_s)
        if dc_compress_s:
            w._acct("compress", dc_compress_s)
        device_pack = None
        if dc_state is not None:
            planes_np, scans = dc_state

            def device_pack(attempt, maxlen, _m=meta, _p=planes_np,
                            _s=scans, _pl=payload_np):
                return device_compress.pack_device_segment(
                    _m, _p, _s, _pl, attempt, maxlen)
        w._emit_segment(n, meta, lanes_np, payload_np, self.pk_map,
                        stats, device_pack=device_pack)
