"""Unified pipeline ledger: one per-stage accounting primitive for every
hand-rolled multi-stage pipeline in the repo.

TPIE (PAPERS.md, arxiv 1710.10091) makes per-stage instrumentation the
organizing principle of external-memory pipelines: you cannot balance a
decode→merge→compress→write chain you cannot see. Before this module,
each pipeline (compaction's compress-pool chain, the flush drain, mesh
fanout lanes, the transport dispatch executor) carried its own ad-hoc
counters — or none. Now they all report through one `Stage` shape:

    busy_s       seconds the stage spent doing its own work
    stall_s      seconds the stage spent BLOCKED on a downstream stage
                 (full queue, exhausted buffer pool — backpressure paid)
    idle_s       seconds the stage spent waiting for upstream input
    items/bytes  units of work through the stage
    queue_hwm    high-water occupancy of the stage's inbound queue

Interpretation rule (docs/observability.md): the stage with the highest
busy_s is the pipeline's capacity bound; a large stall_s on the stage
FEEDING it is the same fact seen from upstream. The where-did-the-wall-go
table bench.py's `pipeline` section prints is exactly this.

The registry is process-global (like the metrics registry): stages
accumulate across tasks under stable `pipeline/stage` names, surfaced as
`pipeline.<pipeline>.<stage>.<stat>` metric gauges, the
`system_views.pipelines` virtual table and `nodetool pipelinestats`.
Recording costs two float adds under a per-stage lock — cheap enough to
stay armed always (the bench's paired A/B pins the data plane within
noise of the un-instrumented path).
"""
from __future__ import annotations

import threading
from . import lockwitness
import time

# ctpulint: clock-injectable
# patchable monotonic clock for the stage timers: tests / a simulated
# deployment swap this for a virtual clock (the timeutil.CLOCK
# pattern); production leaves time.perf_counter. _Timer reads it at
# enter/exit time, so a swap takes effect immediately.
CLOCK = time.perf_counter


class Stage:
    """Accounting for one stage of one pipeline. All mutators take the
    stage lock; they run a handful of times per SEGMENT/SHARD/REQUEST
    (never per cell), so the lock is uncontended noise."""

    __slots__ = ("pipeline", "name", "busy_s", "stall_s", "idle_s",
                 "items", "bytes", "queue_hwm", "_lock")

    def __init__(self, pipeline: str, name: str):
        self.pipeline = pipeline
        self.name = name
        self.busy_s = 0.0
        self.stall_s = 0.0
        self.idle_s = 0.0
        self.items = 0
        self.bytes = 0
        self.queue_hwm = 0
        self._lock = lockwitness.make_lock("pipeline.stage")

    # ------------------------------------------------------------ record --

    def add_busy(self, dt: float) -> None:
        with self._lock:
            self.busy_s += dt

    def add_stall(self, dt: float) -> None:
        with self._lock:
            self.stall_s += dt

    def add_idle(self, dt: float) -> None:
        with self._lock:
            self.idle_s += dt

    def add_items(self, n: int = 1, nbytes: int = 0) -> None:
        with self._lock:
            self.items += n
            self.bytes += nbytes

    def note_queue(self, depth: int) -> None:
        """Record the stage's inbound-queue occupancy at an enqueue
        instant; only the high-water survives (the bound the queue
        actually needed, vs the bound it was given)."""
        if depth > self.queue_hwm:
            with self._lock:
                if depth > self.queue_hwm:
                    self.queue_hwm = depth

    def busy(self) -> "_Timer":
        """`with stage.busy(): ...` — timed busy work."""
        return _Timer(self.add_busy)

    def stall(self) -> "_Timer":
        return _Timer(self.add_stall)

    def idle(self) -> "_Timer":
        return _Timer(self.add_idle)

    # ------------------------------------------------------------- read --

    def snapshot(self) -> dict:
        with self._lock:
            return {"busy_s": round(self.busy_s, 6),
                    "stall_s": round(self.stall_s, 6),
                    "idle_s": round(self.idle_s, 6),
                    "items": self.items, "bytes": self.bytes,
                    "queue_hwm": self.queue_hwm}

    def reset(self) -> None:
        with self._lock:
            self.busy_s = self.stall_s = self.idle_s = 0.0
            self.items = self.bytes = 0
            self.queue_hwm = 0


class _Timer:
    __slots__ = ("_sink", "_t0")

    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        self._t0 = CLOCK()
        return self

    def __exit__(self, *exc):
        self._sink(CLOCK() - self._t0)


class PipelineLedger:
    """Ordered stage registry for one named pipeline. Stage creation is
    idempotent, so every writer/task/worker touching the pipeline calls
    `stage(name)` and accumulates into the same accounting."""

    def __init__(self, name: str):
        self.name = name
        self._stages: dict[str, Stage] = {}
        self._lock = threading.Lock()

    def stage(self, name: str) -> Stage:
        st = self._stages.get(name)
        if st is None:
            with self._lock:
                st = self._stages.get(name)
                if st is None:
                    st = Stage(self.name, name)
                    self._stages[name] = st
                    _register_stage_gauges(st)
        return st

    def stages(self) -> list[Stage]:
        with self._lock:
            return list(self._stages.values())

    def snapshot(self) -> dict:
        return {s.name: s.snapshot() for s in self.stages()}

    def reset(self) -> None:
        for s in self.stages():
            s.reset()


# ---------------------------------------------------------------- registry

_LOCK = lockwitness.make_lock("pipeline.registry")
_LEDGERS: dict[str, PipelineLedger] = {}


def ledger(name: str) -> PipelineLedger:
    """Get-or-create the process-global ledger for one pipeline name.
    Established pipelines (docs/observability.md): `compaction` and
    `flush` (SSTableWriter write legs: serialize/compress/io_write +
    the flush `drain` stage), `mesh` (fanout lanes: decode/merge),
    `compress_pool` (shared worker: pack), `transport` (the request
    dispatch executor), `messaging` (the internode verb-dispatch
    pool: `dispatch` plus one lazily-created stage per handled verb)
    and `stream` (the sessioned-transfer legs: read/net/land)."""
    led = _LEDGERS.get(name)
    if led is None:
        with _LOCK:
            led = _LEDGERS.get(name)
            if led is None:
                led = _LEDGERS[name] = PipelineLedger(name)
    return led


def snapshot_all() -> dict:
    """{pipeline: {stage: stats}} — the system_views.pipelines vtable,
    `nodetool pipelinestats` and bench.py's `pipeline` section all read
    this."""
    with _LOCK:
        ledgers = list(_LEDGERS.values())
    return {led.name: led.snapshot() for led in ledgers}


def reset_all() -> None:
    """Zero every stage (bench legs / test isolation). Stages stay
    registered — their metric gauges keep reporting, from zero."""
    with _LOCK:
        ledgers = list(_LEDGERS.values())
    for led in ledgers:
        led.reset()


def _register_stage_gauges(st: Stage) -> None:
    """Export one stage as `pipeline.<pipeline>.<stage>.<stat>` gauges
    in the process-global metrics registry (snapshot / Prometheus /
    system_views.metrics)."""
    from ..service.metrics import GLOBAL

    p, n = st.pipeline, st.name
    GLOBAL.register_gauge(f"pipeline.{p}.{n}.busy_s",
                          lambda: round(st.busy_s, 6))
    GLOBAL.register_gauge(f"pipeline.{p}.{n}.stall_s",
                          lambda: round(st.stall_s, 6))
    GLOBAL.register_gauge(f"pipeline.{p}.{n}.idle_s",
                          lambda: round(st.idle_s, 6))
    GLOBAL.register_gauge(f"pipeline.{p}.{n}.items", lambda: st.items)
    GLOBAL.register_gauge(f"pipeline.{p}.{n}.bytes", lambda: st.bytes)
    GLOBAL.register_gauge(f"pipeline.{p}.{n}.queue_hwm",
                          lambda: st.queue_hwm)
