"""Variable-length integer encodings used by the on-disk formats.

Semantics mirror the reference's vint encoding
(reference: src/java/org/apache/cassandra/utils/vint/VIntCoding.java):
unsigned vints store the value in 1-9 bytes with the count of extra bytes
unary-encoded in the first byte's leading ones; signed vints zigzag first.
"""
from __future__ import annotations


def write_unsigned_vint(value: int, out: bytearray) -> None:
    if value < 0:
        raise ValueError("unsigned vint must be >= 0")
    if value < 0x80:
        out.append(value)
        return
    # minimal size: first byte holds (7 - extra) value bits
    extra = 0
    while extra < 8:
        if value < (1 << (8 * extra + (7 - extra))):
            break
        extra += 1
    if extra == 8:
        out.append(0xFF)
        out.extend(value.to_bytes(8, "big"))
        return
    first = (0xFF << (8 - extra)) & 0xFF
    first |= value >> (8 * extra)
    out.append(first)
    out.extend((value & ((1 << (8 * extra)) - 1)).to_bytes(extra, "big"))


def read_unsigned_vint(buf, pos: int) -> tuple[int, int]:
    first = buf[pos]
    if first < 0x80:
        return first, pos + 1
    # count leading ones
    extra = 0
    b = first
    while b & 0x80:
        extra += 1
        b = (b << 1) & 0xFF
    if extra == 8:
        return int.from_bytes(buf[pos + 1: pos + 9], "big"), pos + 9
    value = first & (0xFF >> extra)
    for i in range(extra):
        value = (value << 8) | buf[pos + 1 + i]
    return value, pos + 1 + extra


def zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def write_signed_vint(value: int, out: bytearray) -> None:
    write_unsigned_vint(zigzag(value) & 0xFFFFFFFFFFFFFFFF, out)


def read_signed_vint(buf, pos: int) -> tuple[int, int]:
    v, pos = read_unsigned_vint(buf, pos)
    return unzigzag(v), pos
