"""Topology changes: token move, replace-dead-node, and the epoch-logged
TCM sequences (reference: tcm/sequences/Move, replace_address flow,
service/StorageService.java:830 joinRing paths)."""
import os

import pytest

from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.cluster.ring import Ring, Endpoint, allocate_tokens
from cassandra_tpu.cluster.schema_sync import apply_topology_to_ring


@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(3, str(tmp_path), rf=2)
    for n in c.nodes:
        # generous budget: this box has one core and these tests never
        # rely on fast timeout failure — a tight budget only buys
        # flakes (round-3 verdict Weak #4)
        n.proxy.timeout = 10.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 2}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    yield c
    c.shutdown()


def _wait_convicted(cluster, dead_ep, timeout=15.0):
    """Event-driven conviction wait: liveness decisions must precede
    assertions that depend on them, not race the phi detector."""
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(not n.is_alive(dead_ep)
               for i, n in enumerate(cluster.nodes, start=1)
               if i not in cluster._stopped):
            return
        time.sleep(0.05)
    raise AssertionError(f"{dead_ep.name} never convicted")


def _write_rows(cluster, lo, hi, cl=ConsistencyLevel.QUORUM):
    s = cluster.session(1)
    s.keyspace = "ks"
    cluster.node(1).default_cl = cl
    for i in range(lo, hi):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")


def _assert_rows(cluster, node_i, lo, hi, cl=ConsistencyLevel.QUORUM):
    s = cluster.session(node_i)
    s.keyspace = "ks"
    cluster.node(node_i).default_cl = cl
    from cassandra_tpu.cluster.coordinator import TimeoutException

    def _read(q):
        # one retry absorbs a single slow-disk stall on this 1-core
        # box under full-suite load; correctness still requires the
        # row to be THERE
        try:
            return s.execute(q)
        except TimeoutException:
            return s.execute(q)
    for i in range(lo, hi):
        rows = _read(f"SELECT v FROM kv WHERE k = {i}").rows
        assert rows == [(f"v{i}",)], f"row {i} missing via node{node_i}"


def test_move_tokens_no_lost_rows(cluster):
    _write_rows(cluster, 0, 120)
    node2 = cluster.node(2)
    new_tokens = allocate_tokens(cluster.ring, vnodes=4)
    cluster.move_node(2, new_tokens)
    assert sorted(cluster.ring.endpoints[node2.endpoint]) == \
        sorted(new_tokens)
    assert node2.endpoint not in cluster.ring.pending
    # more writes after the move land correctly too
    _write_rows(cluster, 120, 150)
    _assert_rows(cluster, 1, 0, 150)
    _assert_rows(cluster, 3, 0, 150)


def test_move_with_concurrent_writes(cluster):
    """Writes racing the move are never lost: pending-range duplication
    covers the gained ranges until the flip."""
    _write_rows(cluster, 0, 60)
    node2 = cluster.node(2)
    old = list(cluster.ring.endpoints[node2.endpoint])
    new_tokens = allocate_tokens(cluster.ring, vnodes=4)
    # interleave: start the move's pending phase, write, then finish by
    # driving the same sequence the node would
    node2.topology_commit({"op": "start_move",
                           "node": node2._ep_dict(),
                           "tokens": [int(t) for t in new_tokens]})
    _write_rows(cluster, 60, 100)     # racing writes (duplicated)
    streamed = node2.bootstrap()
    assert streamed >= 0
    node2.topology_commit({"op": "finish_move",
                           "node": node2._ep_dict(),
                           "old_tokens": [int(t) for t in old]})
    _assert_rows(cluster, 1, 0, 100)


def test_replace_dead_node_converges_at_quorum(cluster):
    _write_rows(cluster, 0, 100, cl=ConsistencyLevel.ALL)
    cluster.stop_node(3)
    _wait_convicted(cluster, cluster.nodes[2].endpoint)
    replacement = cluster.replace_dead_node(3)
    dead_ep = cluster.nodes[2].endpoint
    assert dead_ep not in cluster.ring.endpoints
    assert replacement.endpoint in cluster.ring.endpoints
    # with node3 still down, QUORUM (RF=2) needs the replacement to
    # actually hold the streamed data
    _assert_rows(cluster, 1, 0, 100)
    # the replacement holds every row it now replicates locally
    t = cluster.schema.get_table("ks", "kv")
    from cassandra_tpu.cluster.replication import ReplicationStrategy
    ks = cluster.schema.keyspaces["ks"]
    strat = ReplicationStrategy.create(ks.params.replication)
    held = 0
    for i in range(100):
        pk = t.columns["k"].cql_type.serialize(i)
        tok = cluster.ring.token_of(pk)
        if replacement.endpoint in strat.replicas(cluster.ring, tok):
            batch = replacement.engine.store("ks", "kv").read_partition(pk)
            assert batch is not None and len(batch) > 0, f"row {i}"
            held += 1
    assert held > 0


def test_replace_alive_node_refused(cluster):
    with pytest.raises(ValueError, match="alive"):
        cluster.replace_dead_node(2)
    # nothing half-applied
    assert not cluster.ring.replacing


def test_writes_during_replace_reach_replacement(cluster):
    _write_rows(cluster, 0, 30, cl=ConsistencyLevel.ALL)
    cluster.stop_node(3)
    dead = cluster.nodes[2].endpoint
    _wait_convicted(cluster, dead)
    # drive the replace in steps so we can write mid-way
    from cassandra_tpu.cluster.gossip import EndpointState
    i = len(cluster.nodes) + 1
    ep = Endpoint(f"node{i}")
    from cassandra_tpu.cluster.node import Node
    node = Node(ep, os.path.join(cluster.base_dir, ep.name),
                cluster.schema, cluster.ring, cluster.transport,
                seeds=[cluster.nodes[0].endpoint],
                gossip_interval=cluster.nodes[0].gossiper.interval)
    node.cluster_nodes = cluster.nodes
    dst = cluster.nodes[0].gossiper.states.get(dead)
    node.gossiper.force_convict(dead, dst.generation if dst else 1,
                                dst.version if dst else 0)
    for other in cluster.nodes[:2]:
        other.gossiper.force_convict(dead)
        node.gossiper.states.setdefault(other.endpoint,
                                        EndpointState(generation=1))
        node.gossiper.detector.report(
            other.endpoint, node.gossiper.states[other.endpoint],
            node.gossiper.clock())
        other.gossiper.states.setdefault(ep, EndpointState(generation=1))
        other.gossiper.detector.report(
            ep, other.gossiper.states[ep], other.gossiper.clock())
    node.topology_commit({"op": "start_replace", "node": node._ep_dict(),
                          "target": dead.name})
    # racing writes at ONE (RF=2 with a dead replica cannot meet QUORUM
    # until the replace commits); duplication still covers the newcomer
    _write_rows(cluster, 30, 60, cl=ConsistencyLevel.ONE)
    node.bootstrap()
    node.topology_commit({"op": "finish_replace",
                          "node": node._ep_dict()})
    cluster.nodes.append(node)
    _assert_rows(cluster, 1, 0, 60)
    cluster.shutdown()


def test_topology_ops_pure_ring():
    """apply_topology_to_ring is the single transformation definition:
    exercise each op against a bare Ring."""
    r = Ring()
    n1 = {"name": "n1", "dc": "dc1", "rack": "r1",
          "host": "127.0.0.1", "port": 1}
    n2 = {"name": "n2", "dc": "dc1", "rack": "r1",
          "host": "127.0.0.1", "port": 2}
    n3 = {"name": "n3", "dc": "dc1", "rack": "r1",
          "host": "127.0.0.1", "port": 3}
    apply_topology_to_ring(r, {"op": "register", "node": n1,
                               "tokens": [0, 100]})
    apply_topology_to_ring(r, {"op": "start_join", "node": n2,
                               "tokens": [50]})
    assert len(r.pending) == 1
    apply_topology_to_ring(r, {"op": "finish_join", "node": n2})
    assert len(r.endpoints) == 2 and not r.pending
    # move n2 50 -> 75
    apply_topology_to_ring(r, {"op": "start_move", "node": n2,
                               "tokens": [75]})
    apply_topology_to_ring(r, {"op": "finish_move", "node": n2,
                               "old_tokens": [50]})
    ep2 = next(e for e in r.endpoints if e.name == "n2")
    assert r.endpoints[ep2] == [75]
    # replace n1 with n3
    apply_topology_to_ring(r, {"op": "start_replace", "node": n3,
                               "target": "n1"})
    fut = r.future_ring()
    assert any(e.name == "n3" for e in fut.endpoints)
    assert not any(e.name == "n1" for e in fut.endpoints)
    apply_topology_to_ring(r, {"op": "finish_replace", "node": n3})
    names = {e.name for e in r.endpoints}
    assert names == {"n2", "n3"}
    ep3 = next(e for e in r.endpoints if e.name == "n3")
    assert sorted(r.endpoints[ep3]) == [0, 100]
