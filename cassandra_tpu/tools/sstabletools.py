"""Offline sstable tools for the ctpu format.

Reference counterpart: tools/SSTableExport (sstabledump),
SSTableMetadataViewer (sstablemetadata), StandaloneVerifier
(sstableverify). These operate on sstable files directly — no engine,
no commitlog — which is why SSTableReader tolerates a missing table
(schema-dependent decoding degrades to raw cell output).

Usage:
  python -m cassandra_tpu.tools.sstabletools dump --data <dir> \
      --keyspace ks --table t [--generation N]
  python -m cassandra_tpu.tools.sstabletools metadata ... | verify ...
"""
from __future__ import annotations

import argparse
import json
import sys


def _descriptors(engine_dir: str, keyspace: str, table: str):
    import glob
    import os

    from ..storage.sstable.format import Descriptor
    pattern = os.path.join(engine_dir, keyspace, f"{table}-*")
    dirs = glob.glob(pattern)
    if not dirs:
        raise SystemExit(f"no table directory matches {pattern}")
    out = []
    for d in dirs:
        out.extend(Descriptor.list_in(d))
    return sorted(out, key=lambda d: d.generation)


def _load_table(engine_dir: str, keyspace: str, table: str):
    """Schema from the engine's persisted schema.json (best effort)."""
    import os

    from ..schema import Schema, load_schema_dict
    path = os.path.join(engine_dir, "schema.json")
    if not os.path.exists(path):
        return None
    schema = Schema()
    with open(path) as f:
        load_schema_dict(schema, json.load(f))
    try:
        return schema.get_table(keyspace, table)
    except KeyError:
        return None


def dump(engine_dir: str, keyspace: str, table: str,
         generation: int | None = None) -> list[dict]:
    """sstabledump: rows as JSON (typed when the schema is available,
    raw cell tuples otherwise)."""
    from ..storage.rows import row_to_dict, rows_from_batch
    from ..storage.sstable import SSTableReader

    t = _load_table(engine_dir, keyspace, table)
    out = []
    for desc in _descriptors(engine_dir, keyspace, table):
        if generation is not None and desc.generation != generation:
            continue
        r = SSTableReader(desc, t)
        entry: dict = {"generation": desc.generation, "rows": []}
        if t is not None:
            for seg in r.scanner():
                for row in rows_from_batch(t, seg):
                    entry["rows"].append(row_to_dict(t, row))
        else:
            for seg in r.scanner():
                for i in range(len(seg)):
                    ck, path, value = seg.cell_payload(i)
                    entry["rows"].append({
                        "pk": seg.partition_key(i).hex(),
                        "ck": ck.hex(), "path": path.hex(),
                        "value": value.hex(), "ts": int(seg.ts[i]),
                        "flags": int(seg.flags[i])})
        r.close()
        out.append(entry)
    return out


def metadata(engine_dir: str, keyspace: str, table: str,
             generation: int | None = None) -> list[dict]:
    """sstablemetadata: the Statistics.db view per sstable."""
    from ..storage.sstable import SSTableReader
    out = []
    for desc in _descriptors(engine_dir, keyspace, table):
        if generation is not None and desc.generation != generation:
            continue
        r = SSTableReader(desc)
        out.append({
            "generation": desc.generation,
            "cells": r.n_cells, "partitions": r.n_partitions,
            "min_ts": r.min_ts, "max_ts": r.max_ts,
            "tombstones": r.n_tombstones, "level": r.level,
            "repaired_at": r.repaired_at,
            "min_token": r.min_token(), "max_token": r.max_token(),
            "data_bytes": r.data_size, "total_bytes": r.size_bytes,
        })
        r.close()
    return out


def verify(engine_dir: str, keyspace: str, table: str,
           generation: int | None = None,
           quarantine: bool = False) -> list[dict]:
    """sstableverify: full-file digest check + segment CRC walk.
    quarantine=True moves every failing sstable's components into the
    table directory's quarantine/ set (storage/failures.py layout) so a
    failed verify never leaves a known-corrupt file live for the next
    engine open to trip over."""
    from ..storage.failures import quarantine_descriptor_files
    from ..storage.sstable import SSTableReader
    from ..storage.sstable.reader import CorruptSSTableError
    out = []
    for desc in _descriptors(engine_dir, keyspace, table):
        if generation is not None and desc.generation != generation:
            continue
        status = "ok"
        try:
            r = SSTableReader(desc)
            try:
                if not r.verify_digest():
                    status = "digest mismatch"
                else:
                    for _ in r.scanner():   # every segment, CRC-checked
                        pass
            finally:
                r.close()
        except (CorruptSSTableError, OSError) as e:
            status = f"corrupt: {e}"
        entry = {"generation": desc.generation, "status": status}
        if status != "ok" and quarantine:
            entry["quarantined"] = quarantine_descriptor_files(
                desc, reason=status)["path"]
        out.append(entry)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(prog="sstabletools")
    p.add_argument("command", choices=["dump", "metadata", "verify"])
    p.add_argument("--data", required=True)
    p.add_argument("--keyspace", required=True)
    p.add_argument("--table", required=True)
    p.add_argument("--generation", type=int)
    p.add_argument("--quarantine", action="store_true",
                   help="verify only: move failing sstables into the "
                        "table's quarantine/ set")
    args = p.parse_args(argv)
    fn = {"dump": dump, "metadata": metadata, "verify": verify}[args.command]
    kw = {"quarantine": args.quarantine} if args.command == "verify" else {}
    print(json.dumps(fn(args.data, args.keyspace, args.table,
                        args.generation, **kw), indent=2, default=str))


if __name__ == "__main__":
    main()
