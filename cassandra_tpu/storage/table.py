"""ColumnFamilyStore equivalent: per-table store owning the memtable, the
live SSTable set, and the flush machinery.

Reference counterpart: db/ColumnFamilyStore.java (switchMemtable:1038,
inner Flush:1180, forceFlush:1089), db/lifecycle/Tracker.java:85 (the
atomic view of live memtables+sstables).
"""
from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..schema import TableMetadata
from ..utils import timeutil
from .cellbatch import (CellBatch, merge_sorted,
                        truncate_live_rows)
from .memtable import Memtable
from .mutation import Mutation
from .sstable import Descriptor, SSTableReader, SSTableWriter


class Tracker:
    """Atomic view of the live data sources (db/lifecycle/Tracker.java:85).
    Mutated under a lock; readers grab a consistent snapshot list."""

    def __init__(self):
        self._lock = threading.RLock()
        self.sstables: list[SSTableReader] = []

    def view(self) -> list[SSTableReader]:
        with self._lock:
            return list(self.sstables)

    def add(self, reader: SSTableReader) -> None:
        with self._lock:
            self.sstables.append(reader)
            self.sstables.sort(key=lambda r: r.desc.generation)

    def replace(self, removed: list[SSTableReader],
                added: list[SSTableReader]) -> None:
        with self._lock:
            keep = [s for s in self.sstables if s not in removed]
            self.sstables = sorted(keep + added,
                                   key=lambda r: r.desc.generation)


class RowCache:
    """Partition-level row cache (cache/RowCache + RowCacheKey role):
    caches the MERGED partition at the replica, invalidated on write to
    the key and on truncate. Flush/compaction never invalidate — they
    preserve logical content. Partitions holding TTL cells are never
    cached: their liveness depends on the read clock. Enabled per table
    via `WITH caching = {'rows_per_partition': 'ALL'}`."""

    def __init__(self, capacity: int = 1024):
        from collections import OrderedDict
        self.capacity = capacity
        self._d: "OrderedDict[bytes, CellBatch]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # bumped by every invalidation. A reader captures it BEFORE
        # snapshotting its sources and put() refuses the entry if it
        # moved — otherwise a read racing a write could re-cache its
        # pre-write merge AFTER the writer's invalidate and serve stale
        # data forever (the reference row cache's sentinel protocol)
        self.generation = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def keys(self) -> list[bytes]:
        """LRU-ordered pks (oldest first) — AutoSavingCache snapshot."""
        with self._lock:
            return list(self._d)

    def get(self, pk: bytes):
        with self._lock:
            batch = self._d.get(pk)
            if batch is None:
                self.misses += 1
                return None
            self._d.move_to_end(pk)
            self.hits += 1
            return batch

    def put(self, pk: bytes, batch: CellBatch,
            read_generation: int) -> None:
        from .cellbatch import FLAG_EXPIRING
        if len(batch) and (batch.flags & FLAG_EXPIRING).any():
            return
        with self._lock:
            if self.generation != read_generation:
                return    # an invalidation raced this read: don't cache
            self._d[pk] = batch
            self._d.move_to_end(pk)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def invalidate(self, pk: bytes) -> None:
        with self._lock:
            self.generation += 1
            self._d.pop(pk, None)

    def clear(self) -> None:
        with self._lock:
            self.generation += 1
            self._d.clear()


class ColumnFamilyStore:
    DEFAULT_FLUSH_THRESHOLD = 64 * 1024 * 1024  # bytes of live memtable data

    def __init__(self, table: TableMetadata, data_dir: str,
                 commitlog=None, flush_threshold: int | None = None):
        self.table = table
        self.directory = os.path.join(
            data_dir, table.keyspace,
            f"{table.name}-{table.id.hex[:8]}")
        os.makedirs(self.directory, exist_ok=True)
        self.commitlog = commitlog
        self.flush_threshold = flush_threshold or self.DEFAULT_FLUSH_THRESHOLD
        self.tracker = Tracker()
        self.memtable = Memtable(table)
        self._flush_lock = threading.Lock()
        self._switch_lock = threading.RLock()
        self.metrics = {"writes": 0, "reads": 0, "flushes": 0,
                        "bytes_flushed": 0}
        # per-table latency group (TableMetrics role): decaying
        # read/write latency hists under table.<ks>.<name>.* — counters
        # stay in the plain dict above (the metrics vtable merges both).
        # Hists are resolved ONCE: the hot paths touch only the per-hist
        # lock, never the global registry lock.
        from ..service.metrics import GLOBAL as _METRICS
        self.latency = _METRICS.group(
            f"table.{table.keyspace}.{table.name}")
        self.read_hist = self.latency.hist("read_latency")
        self.write_hist = self.latency.hist("write_latency")
        from .lifecycle import replay_directory
        replay_directory(self.directory)
        for desc in Descriptor.list_in(self.directory):
            self.tracker.add(SSTableReader(desc, self.table))
        self.compaction_listener = None  # set by CompactionManager
        self.compaction_history: list[dict] = []
        self.row_cache = RowCache() if table.params.caching.get(
            "rows_per_partition", "NONE") != "NONE" else None
        self._gen_lock = threading.Lock()
        self._last_gen = max(
            [d.generation for d in Descriptor.list_in(self.directory)],
            default=0)

    def reload_sstables(self) -> None:
        """Pick up sstables written into the directory out-of-band
        (bulk load / sstableloader role). NOT safe concurrently with
        in-process flush/compaction — those register their outputs with
        the tracker themselves; calling this mid-write can double-add a
        generation. Quiesce writes first."""
        with self._gen_lock:
            known = {s.desc.generation for s in self.tracker.view()}
            for desc in Descriptor.list_in(self.directory):
                if desc.generation not in known:
                    self.tracker.add(SSTableReader(desc, self.table))
                    self._last_gen = max(self._last_gen, desc.generation)
        if self.row_cache is not None:
            self.row_cache.clear()   # bulk-loaded data changes content

    def next_generation(self) -> int:
        """Race-free generation allocation shared by flush + compaction
        (a directory re-scan alone is a TOCTOU between writers)."""
        with self._gen_lock:
            self._last_gen = max(self._last_gen + 1,
                                 Descriptor.next_generation(self.directory))
            return self._last_gen

    # ------------------------------------------------------------- write --

    def apply(self, mutation: Mutation, commitlog=None,
              durable: bool = True) -> None:
        """Commitlog append + memtable put as one unit against a single
        memtable epoch (Keyspace.applyInternal ordering). Holding the
        switch lock across both makes every write either fully before a
        flush's switch point (old memtable, CL position < flush position)
        or fully after (new memtable, CL position >= flush position) —
        the role of the reference's OpOrder write barrier
        (db/ColumnFamilyStore.java:1180-1240)."""
        with self._switch_lock:
            if commitlog is not None and durable:
                commitlog.add(mutation)
            self.memtable.apply(mutation)
            self.metrics["writes"] += 1
        if self.row_cache is not None:
            self.row_cache.invalidate(mutation.pk)

    def should_flush(self) -> bool:
        return self.memtable.live_bytes >= self.flush_threshold

    # ------------------------------------------------------------- flush --

    def flush(self) -> SSTableReader | None:
        """Switch the memtable and write it out (ColumnFamilyStore.Flush).
        Returns the new sstable reader (None if memtable was empty)."""
        with self._flush_lock:
            with self._switch_lock:
                old = self.memtable
                if old.is_empty:
                    return None
                flush_pos = self.commitlog.current_position() \
                    if self.commitlog else None
                self.memtable = Memtable(self.table)
            batch = old.flush_batch()
            gen = self.next_generation()
            desc = Descriptor(self.directory, gen)
            writer = SSTableWriter(
                desc, self.table,
                estimated_partitions=len(old._partitions))
            try:
                writer.append(batch)
                stats = writer.finish()
            except BaseException:
                writer.abort()
                raise
            reader = SSTableReader(desc, self.table)
            self.tracker.add(reader)
            if getattr(self, "backup_enabled", lambda: False)():
                self._backup_sstable(desc)
            self.metrics["flushes"] += 1
            self.metrics["bytes_flushed"] += reader.data_size
            if self.commitlog and flush_pos:
                self.commitlog.discard_completed(self.table.id, flush_pos)
            if self.compaction_listener:
                self.compaction_listener(self)
            return reader

    def _backup_sstable(self, desc) -> None:
        """Hardlink a freshly-flushed sstable's components into
        backups/ (incremental_backups: every flushed sstable is
        retained there until the operator clears it — zero copy cost,
        links share the immutable data blocks)."""
        bdir = os.path.join(self.directory, "backups")
        os.makedirs(bdir, exist_ok=True)
        prefix = f"{desc.version}-{desc.generation}-"
        for fn in os.listdir(self.directory):
            if fn.startswith(prefix):
                dst = os.path.join(bdir, fn)
                if not os.path.exists(dst):
                    try:
                        os.link(os.path.join(self.directory, fn), dst)
                    except OSError:
                        import shutil
                        shutil.copy2(os.path.join(self.directory, fn),
                                     dst)

    # -------------------------------------------------------------- read --

    def read_partition(self, pk: bytes, now: int | None = None,
                       limits=None) -> CellBatch:
        """Merged view of one partition across memtable + sstables
        (SinglePartitionReadCommand.queryMemtableAndDisk role).
        `limits` (cellbatch.DataLimits) truncates the RETURNED view at
        the limit-th live row — the full merge still happens (and still
        feeds the row cache); truncation spares downstream assembly and,
        replica-side, the wire."""
        self.metrics["reads"] += 1
        _t0 = time.perf_counter()
        from ..service.tracing import active, trace
        now = now if now is not None else timeutil.now_seconds()
        read_gen = None
        if self.row_cache is not None:
            cached = self.row_cache.get(pk)
            if cached is not None:
                if active() is not None:
                    trace("Row cache hit")
                if limits is not None:
                    cached, _ = truncate_live_rows(cached, limits)
                self.read_hist.update_us(
                    (time.perf_counter() - _t0) * 1e6)
                return cached
            # captured BEFORE the source snapshot (see RowCache.put)
            read_gen = self.row_cache.generation
        sources = []
        with self._switch_lock:
            mem = self.memtable
        m = mem.read_partition(pk)
        if m is not None:
            sources.append(m)
        for sst in self.tracker.view():
            part = sst.read_partition(pk)
            if part is not None:
                sources.append(part)
        if active() is not None:   # tracing off: zero-cost path
            trace(f"Merging {len(sources)} source(s) for partition read")
        if not sources:
            from .cellbatch import lanes_for_table
            merged = CellBatch.empty(lanes_for_table(self.table))
        else:
            merged = merge_sorted(sources, now=now)
        if self.row_cache is not None:
            self.row_cache.put(pk, merged, read_gen)
        if limits is not None:
            merged, _ = truncate_live_rows(merged, limits)
        self.read_hist.update_us((time.perf_counter() - _t0) * 1e6)
        return merged

    def scan_all(self, now: int | None = None) -> CellBatch:
        """Full-table merged view (range-read building block; small data)."""
        now = now if now is not None else timeutil.now_seconds()
        sources = [self.memtable.scan()]
        for sst in self.tracker.view():
            segs = list(sst.scanner())
            if segs:
                cat = CellBatch.concat(segs)
                cat.sorted = True
                sources.append(cat)
        return merge_sorted([s for s in sources if len(s)] or sources[:1],
                            now=now)

    def scan_window(self, lo: int, hi: int,
                    now: int | None = None) -> CellBatch:
        """Merged view of partitions with token in (lo, hi] — the bounded
        range-read primitive behind paging (service/pager/QueryPagers
        role: read a window, not the table)."""
        now = now if now is not None else timeutil.now_seconds()
        sources = [self.memtable.scan_window(lo, hi)]
        for sst in self.tracker.view():
            w = sst.scan_tokens(lo, hi)
            if w is not None and len(w):
                sources.append(w)
        sources = [s for s in sources if len(s)]
        if not sources:
            from .cellbatch import lanes_for_table
            return CellBatch.empty(lanes_for_table(self.table))
        return merge_sorted(sources, now=now)

    def next_partition_tokens(self, after: int, k: int) -> list[int]:
        """The first k distinct partition tokens > after, across the
        memtable and every sstable's partition directory — how the pager
        sizes its next window without scanning data."""
        cands: set[int] = set()
        side = "left" if after == -(1 << 63) else "right"
        from .cellbatch import batch_tokens
        mem = self.memtable.scan()
        if len(mem):
            toks = batch_tokens(mem)
            i = int(np.searchsorted(toks, after, side=side))
            uniq = np.unique(toks[i:])
            cands.update(int(t) for t in uniq[:k])
        for sst in self.tracker.view():
            toks = sst.partition_tokens
            i = int(np.searchsorted(toks, after, side=side))
            cands.update(int(t) for t in toks[i:i + k])
        return sorted(cands)[:k]

    def iter_scan(self, now: int | None = None, after: int = -(1 << 63),
                  window_parts: int = 64, limits=None):
        """Yield merged CellBatches window by window, each window covering
        up to window_parts partitions — full scans in bounded memory.
        `limits` truncates each window at its live-row bound (the local
        leg of the DataLimits range pushdown — spares row assembly)."""
        now = now if now is not None else timeutil.now_seconds()
        pos = after
        while True:
            toks = self.next_partition_tokens(pos, window_parts)
            if not toks:
                return
            hi = toks[-1]
            batch = self.scan_window(pos, hi, now=now)
            if limits is not None:
                # local leg of the range DataLimits pushdown: spare the
                # row assembly beyond the limit (distributed stores
                # truncate replica-side and track `more` themselves)
                batch, _ = truncate_live_rows(batch, limits)
            if len(batch):
                yield batch
            pos = hi

    # --------------------------------------------------------------- misc --

    def live_sstables(self) -> list[SSTableReader]:
        return self.tracker.view()

    def truncate(self) -> None:
        if self.row_cache is not None:
            self.row_cache.clear()
        with self._switch_lock:
            self.memtable = Memtable(self.table)
            old = self.tracker.view()
            self.tracker.replace(old, [])
            from .chunk_cache import GLOBAL as chunk_cache
            for sst in old:
                sst.close()
                chunk_cache.invalidate_generation(sst.desc.directory,
                                                  sst.desc.generation)
                # the whole generation family: standard components AND
                # attached index components (Index_<col>.db)
                prefix = f"{sst.desc.version}-{sst.desc.generation}-"
                for fn in os.listdir(self.directory):
                    if fn.startswith(prefix):
                        os.remove(os.path.join(self.directory, fn))
        if self.row_cache is not None:
            # again AFTER the switch: a read that raced the truncate
            # may have re-cached pre-truncate content
            self.row_cache.clear()
