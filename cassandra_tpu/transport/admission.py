"""Admission control for the native-protocol front door.

Reference counterparts: transport/Dispatcher.java's concurrent-request
permits (native_transport_max_concurrent_requests), the OverloadedException
shedding path in CQLMessageHandler, and the per-client request-rate
limiting of RateLimitingRequestCallback (cassandra 4.1's
native_transport_rate_limiting_enabled).

Three gates, all consulted on the EVENT LOOP before a request reaches the
dispatch executor — a request that cannot be admitted is answered with a
v4/v5 OVERLOADED error immediately instead of queueing forever (the same
bounded-buffer discipline the TPIE-style pipeline applies to bulk I/O):

  PermitGate        a counted permit per in-flight request (queued or
                    executing); cap hot-reloads from the
                    `native_transport_max_concurrent_requests` setting.
                    Tracks a high-water mark so the bench/overload run
                    can PROVE in-flight never exceeded the cap.
  OverloadSignals   server-busy conditions fed by the data plane: a
                    recent `storage.write_stall` (a writer paid an
                    inline threshold flush) or a commitlog sync backlog
                    (pending group-commit syncs above a threshold).
                    Probes are cached (PROBE_INTERVAL_S) so per-request
                    cost is a clock read and a comparison.
  per-client rate   utils/ratelimit.RateLimiter in ops/s (unit=1), one
                    bucket per connection, non-blocking try_acquire;
                    rate hot-reloads from `native_transport_rate_limit_ops`
                    exactly like compaction_throughput_mib_per_sec.
"""
from __future__ import annotations

import threading
import time


class PermitGate:
    """Counted in-flight-request permits (Dispatcher's concurrent-request
    limit). cap <= 0 disables the gate. `high_water` records the maximum
    concurrently-held permits ever observed."""

    def __init__(self, cap: int):
        self._lock = threading.Lock()
        self.cap = int(cap)
        self.active = 0
        self.high_water = 0

    def set_cap(self, cap: int) -> None:
        """Hot-reload (settings listener). Shrinking below the current
        in-flight count only affects NEW admissions — held permits drain
        naturally."""
        with self._lock:
            self.cap = int(cap)

    def try_acquire(self) -> bool:
        with self._lock:
            if self.cap > 0 and self.active >= self.cap:
                return False
            self.active += 1
            if self.active > self.high_water:
                self.high_water = self.active
            return True

    def release(self) -> None:
        with self._lock:
            self.active -= 1

    def reset_high_water(self) -> None:
        """Start a fresh high-water measurement window (the bench's
        overload run proves in-flight <= cap with this)."""
        with self._lock:
            self.high_water = self.active


class OverloadSignals:
    """Server-busy signal derived from the storage engine's own
    backpressure metrics. `reason()` returns a human-readable cause while
    the server should shed, else None.

    Signals (docs/native-transport.md discusses the thresholds):
      - REPEATED write stalls (engine.write_stalls — the engine-scoped
        count behind the storage.write_stall histogram): at least two
        stalls within STALL_WINDOW_S seconds. One stall is a routine
        threshold flush — every healthy node ingesting data pays one
        per memtable's worth of writes, and shedding 5 s of ALL traffic
        for it would turn normal sustained load into a rolling outage;
        a SECOND stall inside the window means writers are outrunning
        the flush pipeline for real. Engine-scoped deliberately: in a
        multi-node-in-one-process deployment, one node's stall must not
        shed a co-hosted idle node's traffic (the histogram is
        process-global);
      - commitlog pending syncs (parked group-commit writers + retired
        segments awaiting fsync) above PENDING_SYNCS_MAX: the durability
        path is behind.

    The probe itself runs at most every PROBE_INTERVAL_S; between probes
    the cached verdict is served, so the per-request cost stays at a
    clock read."""

    PROBE_INTERVAL_S = 0.1
    STALL_WINDOW_S = 5.0
    PENDING_SYNCS_MAX = 128

    def __init__(self, backend, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._reason: str | None = None
        self._last_probe = -1e18
        self._stall_seen_at = -1e18
        self._prev_stall_at = -1e18
        # the engine sits behind a cluster Node as .engine; a bare
        # StorageEngine carries .commitlog itself
        engine = backend if hasattr(backend, "commitlog") \
            else getattr(backend, "engine", None)
        self._engine = engine
        # only stalls AFTER the server came up count as overload
        self._stall_count = self._stalls_now()

    def _stalls_now(self) -> int:
        return int(getattr(self._engine, "write_stalls", 0) or 0)

    def _pending_syncs(self) -> int:
        cl = getattr(self._engine, "commitlog", None)
        if cl is None:
            return 0
        try:
            return int(getattr(cl, "_waiting", 0)) \
                + len(getattr(cl, "_retiring", ()) or ())
        except Exception:
            return 0

    def reason(self) -> str | None:
        now = self._clock()
        with self._lock:
            if now - self._last_probe < self.PROBE_INTERVAL_S:
                return self._reason
            prior_probe = self._last_probe
            self._last_probe = now
            c = self._stalls_now()
            if c > self._stall_count:
                # a single new stall only arms the window; several
                # stalls landing between two probes count as repeated
                # ONLY if that gap was itself short — probes run on
                # request arrival, so after an idle stretch two stalls
                # in the delta may be minutes apart
                if c - self._stall_count > 1 \
                        and now - prior_probe < self.STALL_WINDOW_S:
                    self._prev_stall_at = now
                else:
                    self._prev_stall_at = self._stall_seen_at
                self._stall_count = c
                self._stall_seen_at = now
            if now - self._prev_stall_at < self.STALL_WINDOW_S:
                self._reason = "server overloaded: memtable flush " \
                    "backpressure (storage.write_stall)"
            elif self._pending_syncs() > self.PENDING_SYNCS_MAX:
                self._reason = "server overloaded: commitlog sync backlog"
            else:
                self._reason = None
            return self._reason
