"""CQL tokenizer.

Reference counterpart: the ANTLR lexer (src/antlr/Lexer.g). Hand-written
here: CQL's token set is small and a generated lexer buys nothing on this
path. Supports: identifiers ("quoted" preserves case), string literals
('' escape and $$..$$ bodies), integers/floats (incl. exponent), hex blobs
(0x..), uuids, bind markers (? and :name), operators, and -- // /* */
comments.
"""
from __future__ import annotations

import re
import uuid as uuid_mod
from dataclasses import dataclass

KEYWORDS = {
    "select", "from", "where", "and", "insert", "into", "values", "update",
    "set", "delete", "create", "drop", "alter", "table", "keyspace", "use",
    "primary", "key", "if", "not", "exists", "with", "limit", "order",
    "by", "asc", "desc", "allow", "filtering", "begin", "batch", "apply",
    "unlogged", "logged", "counter", "truncate", "in", "using", "ttl",
    "timestamp", "type", "index", "on", "add", "to", "rename", "static",
    "distinct", "as", "contains", "per", "partition", "is", "null", "token",
    "or", "replace", "materialized", "view", "custom", "options", "role",
    "user", "grant", "revoke", "of", "list", "function", "aggregate",
    "returns", "language", "trigger", "like",
}

UUID_RE = re.compile(
    r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}"
    r"-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}")


@dataclass
class Token:
    kind: str     # IDENT KEYWORD STRING INT FLOAT HEX UUID OP MARKER EOF
    value: object
    pos: int

    def __repr__(self):
        return f"{self.kind}:{self.value!r}"


class LexError(ValueError):
    pass


def tokenize(text: str) -> list[Token]:
    out: list[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if text.startswith("--", i) or text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        m = UUID_RE.match(text, i)
        if m:
            out.append(Token("UUID", uuid_mod.UUID(m.group()), i))
            i = m.end()
            continue
        if c == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise LexError(f"unterminated string at {i}")
                if text[j] == "'":
                    if j + 1 < n and text[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            out.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if text.startswith("$$", i):
            j = text.find("$$", i + 2)
            if j < 0:
                raise LexError(f"unterminated $$ string at {i}")
            out.append(Token("STRING", text[i + 2:j], i))
            i = j + 2
            continue
        if c == '"':
            j = text.find('"', i + 1)
            if j < 0:
                raise LexError(f"unterminated quoted identifier at {i}")
            out.append(Token("IDENT", text[i + 1:j], i))
            i = j + 1
            continue
        if text.startswith("0x", i) or text.startswith("0X", i):
            j = i + 2
            while j < n and text[j] in "0123456789abcdefABCDEF":
                j += 1
            out.append(Token("HEX", bytes.fromhex(text[i + 2:j]), i))
            i = j
            continue
        if c.isdigit() or (c == "-" and i + 1 < n and text[i + 1].isdigit()
                           and _prev_is_operand_start(out)):
            m = re.match(r"-?\d+\.\d*(?:[eE][+-]?\d+)?|-?\d+[eE][+-]?\d+|-?\d+",
                         text[i:])
            lit = m.group()
            if "." in lit or "e" in lit or "E" in lit:
                out.append(Token("FLOAT", float(lit), i))
            else:
                out.append(Token("INT", int(lit), i))
            i += len(lit)
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            low = word.lower()
            if low in KEYWORDS:
                out.append(Token("KEYWORD", low, i))
            else:
                out.append(Token("IDENT", low, i))  # unquoted: case-folded
            i = j
            continue
        if c == "?":
            out.append(Token("MARKER", None, i))
            i += 1
            continue
        if c == ":" and i + 1 < n and (text[i + 1].isalpha()
                                       or text[i + 1] == "_"):
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            out.append(Token("MARKER", text[i + 1:j].lower(), i))
            i = j
            continue
        for op in ("<=", ">=", "!=", "+=", "-="):
            if text.startswith(op, i):
                out.append(Token("OP", op, i))
                i += 2
                break
        else:
            if c in "()[]{},.;=<>*+-/%:":
                out.append(Token("OP", c, i))
                i += 1
            else:
                raise LexError(f"unexpected character {c!r} at {i}")
    out.append(Token("EOF", None, n))
    return out


def _prev_is_operand_start(out: list[Token]) -> bool:
    """'-5' is a negative literal only where an operand may start."""
    if not out:
        return True
    t = out[-1]
    return not (t.kind in ("INT", "FLOAT", "IDENT", "UUID", "HEX", "STRING")
                or (t.kind == "OP" and t.value in (")", "]")))
