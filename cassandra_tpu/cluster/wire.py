"""Wire codec for internode messages: a small self-describing binary
format for the payload shapes the verbs actually exchange — scalars,
str/bytes, tuples/lists/dicts, numpy arrays (columnar CellBatch fields
travel as raw dtype+shape+buffer), and Endpoints.

Reference counterpart: net/Message.java serializer + the per-verb
serializers (net/Verb.java payload serializers). Deliberately NOT pickle:
network input is untrusted, and pickle is an RCE surface
(the reference's serializers are likewise explicit per-type codecs).

Frame layout (tcp.py): [u32 length][u32 crc32(body)][body]
Body: encoded tuple (id, reply_to, verb, sender, to, payload,
trace_session, trace_events) — the trailing tracing headers are None
when the request is untraced; decoders tolerate legacy 6-tuples.
"""
from __future__ import annotations

import struct

import numpy as np

from ..utils import varint as vi
from .ring import Endpoint

_T_NONE = 0
_T_FALSE = 1
_T_TRUE = 2
_T_INT = 3          # signed vint
_T_FLOAT = 4        # f64
_T_STR = 5
_T_BYTES = 6
_T_TUPLE = 7
_T_LIST = 8
_T_DICT = 9
_T_NDARRAY = 10     # dtype-str, ndim, shape..., raw buffer
_T_ENDPOINT = 11
_T_BIGINT = 12      # arbitrary precision (ts values fit vint; uuids don't)

_MAX_DEPTH = 16


def _enc(obj, out: bytearray, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("wire object too deeply nested")
    if obj is None:
        out.append(_T_NONE)
    elif obj is False:
        out.append(_T_FALSE)
    elif obj is True:
        out.append(_T_TRUE)
    elif isinstance(obj, int):
        if -(1 << 62) <= obj < (1 << 62):
            out.append(_T_INT)
            vi.write_signed_vint(obj, out)
        else:
            out.append(_T_BIGINT)
            raw = obj.to_bytes((obj.bit_length() + 8) // 8 + 1, "big",
                               signed=True)
            vi.write_unsigned_vint(len(raw), out)
            out += raw
    elif isinstance(obj, float):
        out.append(_T_FLOAT)
        out += struct.pack("<d", obj)
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(_T_STR)
        vi.write_unsigned_vint(len(b), out)
        out += b
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(_T_BYTES)
        vi.write_unsigned_vint(len(b), out)
        out += b
    elif isinstance(obj, tuple):
        out.append(_T_TUPLE)
        vi.write_unsigned_vint(len(obj), out)
        for x in obj:
            _enc(x, out, depth + 1)
    elif isinstance(obj, list):
        out.append(_T_LIST)
        vi.write_unsigned_vint(len(obj), out)
        for x in obj:
            _enc(x, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        vi.write_unsigned_vint(len(obj), out)
        for k, v in obj.items():
            _enc(k, out, depth + 1)
            _enc(v, out, depth + 1)
    elif isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        ds = a.dtype.str.encode()
        out.append(_T_NDARRAY)
        vi.write_unsigned_vint(len(ds), out)
        out += ds
        vi.write_unsigned_vint(a.ndim, out)
        for d in a.shape:
            vi.write_unsigned_vint(d, out)
        raw = a.tobytes()
        vi.write_unsigned_vint(len(raw), out)
        out += raw
    elif isinstance(obj, Endpoint):
        out.append(_T_ENDPOINT)
        for f in (obj.name, obj.dc, obj.rack, obj.host):
            b = f.encode()
            vi.write_unsigned_vint(len(b), out)
            out += b
        vi.write_unsigned_vint(obj.port, out)
    elif isinstance(obj, (np.integer,)):
        _enc(int(obj), out, depth)
    elif isinstance(obj, (np.floating,)):
        _enc(float(obj), out, depth)
    else:
        raise TypeError(f"wire codec cannot encode {type(obj).__name__}")


# sane ceilings so a malformed/hostile frame cannot demand absurd allocs
_MAX_ELEMS = 1 << 24
_MAX_BLOB = 1 << 31


def _dec(buf: bytes, pos: int, depth: int = 0):
    if depth > _MAX_DEPTH:
        raise ValueError("wire object too deeply nested")
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_INT:
        return vi.read_signed_vint(buf, pos)
    if tag == _T_BIGINT:
        n, pos = vi.read_unsigned_vint(buf, pos)
        if n > 64:
            raise ValueError("bigint too large")
        return int.from_bytes(buf[pos:pos + n], "big", signed=True), pos + n
    if tag == _T_FLOAT:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == _T_STR:
        n, pos = vi.read_unsigned_vint(buf, pos)
        if n > _MAX_BLOB:
            raise ValueError("string too large")
        return bytes(buf[pos:pos + n]).decode(), pos + n
    if tag == _T_BYTES:
        n, pos = vi.read_unsigned_vint(buf, pos)
        if n > _MAX_BLOB:
            raise ValueError("blob too large")
        return bytes(buf[pos:pos + n]), pos + n
    if tag in (_T_TUPLE, _T_LIST):
        n, pos = vi.read_unsigned_vint(buf, pos)
        if n > _MAX_ELEMS:
            raise ValueError("sequence too large")
        items = []
        for _ in range(n):
            v, pos = _dec(buf, pos, depth + 1)
            items.append(v)
        return (tuple(items) if tag == _T_TUPLE else items), pos
    if tag == _T_DICT:
        n, pos = vi.read_unsigned_vint(buf, pos)
        if n > _MAX_ELEMS:
            raise ValueError("dict too large")
        d = {}
        for _ in range(n):
            k, pos = _dec(buf, pos, depth + 1)
            v, pos = _dec(buf, pos, depth + 1)
            d[k] = v
        return d, pos
    if tag == _T_NDARRAY:
        n, pos = vi.read_unsigned_vint(buf, pos)
        ds = bytes(buf[pos:pos + n]).decode()
        pos += n
        ndim, pos = vi.read_unsigned_vint(buf, pos)
        if ndim > 4:
            raise ValueError("ndarray rank too large")
        shape = []
        for _ in range(ndim):
            d, pos = vi.read_unsigned_vint(buf, pos)
            shape.append(d)
        nb, pos = vi.read_unsigned_vint(buf, pos)
        if nb > _MAX_BLOB:
            raise ValueError("ndarray too large")
        dt = np.dtype(ds)
        if dt.hasobject:
            raise ValueError("object dtypes are not wire-safe")
        a = np.frombuffer(buf[pos:pos + nb], dtype=dt).reshape(shape).copy()
        return a, pos + nb
    if tag == _T_ENDPOINT:
        fields = []
        for _ in range(4):
            n, pos = vi.read_unsigned_vint(buf, pos)
            fields.append(bytes(buf[pos:pos + n]).decode())
            pos += n
        port, pos = vi.read_unsigned_vint(buf, pos)
        return Endpoint(fields[0], fields[1], fields[2], fields[3],
                        port), pos
    raise ValueError(f"unknown wire tag {tag}")


def encode_message(msg) -> bytes:
    out = bytearray()
    _enc((msg.id, msg.reply_to, msg.verb, msg.sender, msg.to, msg.payload,
          msg.trace_session, msg.trace_events), out)
    return bytes(out)


def decode_message(buf: bytes):
    from .messaging import Message
    fields, _ = _dec(buf, 0)
    # 6-tuple frames predate the tracing headers; tolerate both
    mid, reply_to, verb, sender, to, payload = fields[:6]
    trace_session = fields[6] if len(fields) > 6 else None
    trace_events = fields[7] if len(fields) > 7 else None
    return Message(verb, payload, sender, to, mid, reply_to,
                   trace_session=trace_session,
                   trace_events=trace_events)
