"""docs/ARCHITECTURE.md "Known gaps" enforcement: the list is checked
against the CODEBASE, not against itself, so it cannot rot.

Two directions:
  1. Every `gap:` token listed in the doc has a probe here that checks
     whether the feature actually shipped (file/symbol presence). A
     listed gap whose probe finds the feature fails the suite — the
     doc must be updated in the same change that ships the feature.
  2. A curated set of SHIPPED features (things past rounds delivered)
     is asserted absent from the gaps section — the failure mode of
     rounds 2–4, where shipped features stayed listed as gaps.

Adding a new gap bullet without a probe also fails: unprobed claims
are exactly the rot this test exists to stop.
"""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cassandra_tpu")
DOC = os.path.join(REPO, "docs", "ARCHITECTURE.md")


def _read(*rel):
    p = os.path.join(*rel)
    if not os.path.exists(p):
        return ""
    with open(p, encoding="utf-8") as f:
        return f.read()


def _gaps_section() -> str:
    text = _read(DOC)
    m = re.search(r"## Known gaps\n(.*)", text, re.S)
    assert m, "ARCHITECTURE.md lost its Known gaps section"
    return m.group(1)


# Each probe returns True when the feature EXISTS in the codebase
# (meaning the gap is closed and must leave the doc). Probes look at
# artifacts — files and load-bearing symbols — never at docs.
GAP_PROBES = {
    "gap:preview-repair": lambda: (
        "preview" in _read(PKG, "cluster", "repair.py")
        and "class RepairSessionStore" in _read(PKG, "cluster",
                                                "repair.py")),
    "gap:partitioner-breadth": lambda: (
        "ByteOrderedPartitioner" in _read(PKG, "utils",
                                          "partitioners.py")),
    "gap:snitch-breadth": lambda: (
        "GossipingPropertyFileSnitch" in _read(PKG, "cluster",
                                               "snitch.py")),
    "gap:big-bti-interop": lambda: (
        os.path.exists(os.path.join(PKG, "storage", "sstable",
                                    "big_format.py"))),
    "gap:nodetool-breadth": lambda: (
        # closed when the remote command registry crosses 120
        len(re.findall(r'^\s+\("[a-z]+", "(?:node|engine|none)"\),?',
                       _read(PKG, "tools", "nodetool.py"), re.M)) > 120
        or _read(PKG, "tools", "nodetool.py").count('("') > 240),
    "gap:datalimits-pushdown": lambda: (
        "class DataLimits" in _read(PKG, "cluster", "coordinator.py")
        or "short_read" in _read(PKG, "cluster", "coordinator.py")),
    "gap:deterministic-sim": lambda: (
        os.path.exists(os.path.join(PKG, "sim", "scheduler.py"))),
    "gap:ucs-vector": lambda: (
        "scaling_vector" in _read(PKG, "compaction", "strategies.py")),
    "gap:sstableloader": lambda: (
        os.path.exists(os.path.join(PKG, "tools", "sstableloader.py"))),
    "gap:harry-ttl": lambda: (
        "ttl" in _read(PKG, "tools", "harry.py").lower()
        and "no TTLs here" not in _read(PKG, "tools", "harry.py")),
    "gap:guardrails-breadth": lambda: (
        _read(PKG, "storage", "guardrails.py").count("def check_") >= 12
        or _read(PKG, "storage", "guardrails.py").count("Guardrail(")
        >= 15),
    "gap:compressed-commitlog": lambda: (
        "compress" in _read(PKG, "storage", "commitlog.py")),
}

# Features that SHIPPED (with their proving artifact) — none of these
# phrases may appear inside the Known-gaps section. This is the exact
# list rounds 2–4 kept mis-reporting.
SHIPPED = {
    "encryption at rest": os.path.join(PKG, "storage", "encryption.py"),
    "entire-sstable": os.path.join(PKG, "cluster", "streaming.py"),
    "SASI": os.path.join(PKG, "index", "manager.py"),
    "AutoSavingCache": os.path.join(PKG, "storage", "saved_caches.py"),
    "gossip/ring-driven": None,   # topology is epoch-logged now
    "epoch log covers DDL only": None,
}


def test_every_listed_gap_is_probed_and_still_open():
    gaps = _gaps_section()
    listed = set(re.findall(r"gap:[a-z-]+", gaps))
    assert listed, "Known gaps section lists no gap: tokens"
    unprobed = listed - set(GAP_PROBES)
    assert not unprobed, (
        f"gap tokens without probes (add one here): {sorted(unprobed)}")
    shipped_but_listed = [t for t in sorted(listed) if GAP_PROBES[t]()]
    assert not shipped_but_listed, (
        f"these gaps appear to be SHIPPED but are still listed in "
        f"docs/ARCHITECTURE.md Known gaps — update the doc: "
        f"{shipped_but_listed}")


def test_no_shipped_feature_listed_as_gap():
    gaps = _gaps_section().lower()
    for phrase, artifact in SHIPPED.items():
        if artifact is not None:
            assert os.path.exists(artifact), (
                f"SHIPPED registry stale: {artifact} vanished")
        assert phrase.lower() not in gaps, (
            f"shipped feature {phrase!r} is listed under Known gaps")


def test_closed_gaps_left_the_doc():
    """The inverse direction: any probe that fires must not have its
    token in the doc (covered above), AND tokens removed from the doc
    must correspond to a firing probe OR be absent from GAP_PROBES —
    i.e. you cannot 'close' a gap by deleting the bullet while the
    probe still reports it missing."""
    gaps = _gaps_section()
    listed = set(re.findall(r"gap:[a-z-]+", gaps))
    for token, probe in GAP_PROBES.items():
        if token not in listed:
            assert probe(), (
                f"{token} was removed from Known gaps but its probe "
                f"says the feature is still missing — restore the "
                f"bullet or ship the feature")
