"""Storage fault tolerance: fault-injection filesystem, disk/commit
failure policies, corrupt-sstable quarantine.

(Reference test model: the corruption/FSError dtests —
CorruptedSSTablesCompactionsTest, OutOfSpaceTest, the
JVMStabilityInspector unit tests — driven here through the faultfs
checkpoints instead of byteman.)
"""
import os

import pytest

from cassandra_tpu.config import Config, ConfigError, Settings
from cassandra_tpu.schema import COL_ROW_LIVENESS, Schema, make_table
from cassandra_tpu.service.metrics import GLOBAL as METRICS
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.failures import (CommitLogStoppedError,
                                            FailureHandler,
                                            StorageStoppedError)
from cassandra_tpu.storage.mutation import Mutation
from cassandra_tpu.storage.sstable import Component
from cassandra_tpu.storage.sstable.format import FORMAT_VERSION as FMT
from cassandra_tpu.storage.sstable.reader import CorruptSSTableError
from cassandra_tpu.utils import faultfs, timeutil


@pytest.fixture(autouse=True)
def _disarm_all_faults():
    """faultfs is process-global: a leaked arm must never poison the
    next test."""
    faultfs.disarm()
    yield
    faultfs.disarm()


def new_engine(path, disk_policy="best_effort", commit_policy="ignore",
               **kw):
    schema = Schema()
    schema.create_keyspace("ks")
    t = make_table("ks", "t", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "text"})
    schema.add_table(t)
    settings = Settings(Config.load({
        "disk_failure_policy": disk_policy,
        "commit_failure_policy": commit_policy}))
    eng = StorageEngine(str(path), schema, commitlog_sync="batch",
                        settings=settings, **kw)
    return eng, t


def put(eng, t, pk, c, v, ts=None):
    m = Mutation(t.id, t.columns["id"].cql_type.serialize(pk))
    ck = t.serialize_clustering([c])
    ts = ts or timeutil.now_micros()
    m.add(ck, COL_ROW_LIVENESS, b"", b"", ts)
    m.add(ck, t.columns["v"].column_id, b"",
          t.columns["v"].cql_type.serialize(v), ts)
    eng.apply(m)


def pk_of(t, v):
    return t.columns["id"].cql_type.serialize(v)


def seeded(eng, t, rounds=2, pks=12):
    """rounds × pks rows, one flush per round → `rounds` sstables."""
    cfs = eng.store("ks", "t")
    for r in range(rounds):
        for i in range(pks):
            put(eng, t, i, r, f"r{r}-{i}")
        cfs.flush()
    return cfs


def flip_on_disk(path, offset=None):
    raw = bytearray(open(path, "rb").read())
    raw[offset if offset is not None else len(raw) // 2] ^= 0x01
    open(path, "wb").write(bytes(raw))


# ------------------------------------------------------------- faultfs --

def test_faultfs_times_after_and_path_filter(tmp_path):
    fp = faultfs.arm("sstable.read", "error", times=1, after=1,
                     path_substr="wanted")
    # wrong path: no hit consumed
    faultfs.GLOBAL.check("sstable.read", "/other/file")
    assert fp.fires == 0
    # first matching hit skipped (after=1)
    faultfs.GLOBAL.check("sstable.read", "/wanted/file")
    assert fp.fires == 0
    with pytest.raises(OSError):
        faultfs.GLOBAL.check("sstable.read", "/wanted/file")
    assert fp.fires == 1
    # times=1: exhausted
    faultfs.GLOBAL.check("sstable.read", "/wanted/file")
    assert fp.fires == 1
    faultfs.disarm("sstable.read")
    assert not faultfs.GLOBAL.active


def test_faultfs_inject_context_manager():
    with faultfs.inject("hints.read", "error"):
        assert faultfs.GLOBAL.armed("hints.read") is not None
    assert faultfs.GLOBAL.armed("hints.read") is None


def test_policy_values_validated():
    with pytest.raises(ConfigError):
        FailureHandler(Settings(Config.load(
            {"disk_failure_policy": "bogus"})))
    s = Settings(Config())
    h = FailureHandler(s)
    with pytest.raises(ConfigError):
        s.set("commit_failure_policy", "nope")
    s.set("disk_failure_policy", "stop")     # hot-set reaches the handler
    assert h.disk_policy == "stop"
    h.close()


# ------------------------------------- per-policy read-path corruption --

def test_bitflip_data_best_effort_quarantines_and_serves(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    bad = gens[0]
    c0 = METRICS.counter("storage.corruption_detected")
    with faultfs.inject("sstable.read", "bitflip",
                        path_substr=f"-{bad}-Data.db"):
        batch = cfs.read_partition(pk_of(t, 3))
    # the read SUCCEEDED from the remaining sources (round-1 values)
    assert len(batch) > 0
    assert METRICS.counter("storage.corruption_detected") == c0 + 1
    assert [q["generation"] for q in cfs.quarantined] == [bad]
    assert bad not in [s.desc.generation for s in cfs.live_sstables()]
    # forensics: the components moved into quarantine/, gone from live dir
    qdir = cfs.quarantined[0]["path"]
    assert os.path.exists(os.path.join(qdir, f"{FMT}-{bad}-Data.db"))
    assert not os.path.exists(
        os.path.join(cfs.directory, f"{FMT}-{bad}-TOC.txt"))
    # vtable + nodetool surfaces
    vt = eng.virtual_tables.get("system_views", "quarantined_sstables")
    assert [r["generation"] for r in vt.rows()] == [bad]
    from cassandra_tpu.tools import nodetool
    assert [r["generation"] for r in nodetool.listquarantine(eng)] == [bad]
    # unaffected partitions and later reads keep working, fault disarmed
    assert len(cfs.read_partition(pk_of(t, 7))) > 0
    eng.close()


def test_bitflip_data_ignore_raises_and_stays_live(tmp_path):
    eng, t = new_engine(tmp_path, disk_policy="ignore")
    cfs = seeded(eng, t)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    with faultfs.inject("sstable.read", "bitflip",
                        path_substr=f"-{gens[0]}-Data.db"):
        with pytest.raises(CorruptSSTableError):
            cfs.read_partition(pk_of(t, 3))
    # pre-policy behavior: nothing quarantined, the sstable stays live
    assert cfs.quarantined == []
    assert gens == [s.desc.generation for s in cfs.live_sstables()]
    # and with the fault gone the same read works again
    assert len(cfs.read_partition(pk_of(t, 3))) > 0
    eng.close()


def test_bitflip_data_stop_takes_storage_out(tmp_path):
    eng, t = new_engine(tmp_path, disk_policy="stop")
    cfs = seeded(eng, t)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    with faultfs.inject("sstable.read", "bitflip",
                        path_substr=f"-{gens[0]}-Data.db"):
        with pytest.raises(CorruptSSTableError):
            cfs.read_partition(pk_of(t, 3))
    assert eng.failures.storage_stopped
    with pytest.raises(StorageStoppedError):
        cfs.read_partition(pk_of(t, 7))
    with pytest.raises(StorageStoppedError):
        cfs.scan_all()          # range reads are gated too
    with pytest.raises(StorageStoppedError):
        cfs.scan_window(-(1 << 63), (1 << 63) - 1)
    with pytest.raises(StorageStoppedError):
        put(eng, t, 99, 0, "nope")
    eng.close()


def test_corrupt_index_quarantined_at_store_open(tmp_path):
    """Index/Statistics corruption surfaces at OPEN, not read: a fresh
    engine over the directory must come up with the rotten sstable
    quarantined instead of crashing."""
    eng, t = new_engine(tmp_path)
    seeded(eng, t)
    eng._save_schema()
    cfs = eng.store("ks", "t")
    gens = [s.desc.generation for s in cfs.live_sstables()]
    directory = cfs.directory
    eng.close()
    # flip the header's lane-count field: the open-time
    # "index/stats lane mismatch" corruption check must fire
    # (mid-file index bytes carry no CRC and can rot silently)
    flip_on_disk(os.path.join(directory, f"{FMT}-{gens[0]}-Index.db"),
                 offset=4)
    c0 = METRICS.counter("storage.corruption_detected")
    eng2 = StorageEngine(str(tmp_path), Schema(), commitlog_sync="batch")
    cfs2 = eng2.store("ks", "t")
    assert [q["generation"] for q in cfs2.quarantined] == [gens[0]]
    assert METRICS.counter("storage.corruption_detected") == c0 + 1
    live = [s.desc.generation for s in cfs2.live_sstables()]
    assert gens[0] not in live and gens[1] in live
    assert len(cfs2.read_partition(pk_of(t, 3))) > 0
    eng2.close()


def test_corrupt_stats_quarantined_at_store_open(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    eng._save_schema()
    gens = [s.desc.generation for s in cfs.live_sstables()]
    directory = cfs.directory
    eng.close()
    # truncate Statistics.db to garbage: json decode error → corruption
    with open(os.path.join(directory,
                           f"{FMT}-{gens[1]}-Statistics.db"), "w") as f:
        f.write('{"n_lanes": 13, "broke')
    eng2 = StorageEngine(str(tmp_path), Schema(), commitlog_sync="batch")
    cfs2 = eng2.store("ks", "t")
    assert [q["generation"] for q in cfs2.quarantined] == [gens[1]]
    # quarantine records survive a SECOND restart (on-disk manifest)
    eng2.close()
    eng3 = StorageEngine(str(tmp_path), Schema(), commitlog_sync="batch")
    assert [q["generation"]
            for q in eng3.store("ks", "t").quarantined] == [gens[1]]
    eng3.close()


def test_corrupt_digest_verify_quarantine_handoff(tmp_path):
    """A flipped Digest.crc32 only surfaces at verify time; the
    --quarantine handoff must move the file out of the live set."""
    from cassandra_tpu.tools import nodetool
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    # rewrite the digest file with a wrong value
    dpath = os.path.join(cfs.directory, f"{FMT}-{gens[0]}-Digest.crc32")
    with open(dpath) as f:
        expected = int(f.read().strip())
    with open(dpath, "w") as f:
        f.write(str((expected + 1) & 0xFFFFFFFF))
    rep = nodetool.verify(eng, "ks", "t", quarantine=True)
    by_gen = {r["sstable"]: r for r in rep}
    assert by_gen[gens[0]]["ok"] is False
    assert by_gen[gens[0]].get("quarantined") is True
    assert by_gen[gens[1]]["ok"] is True
    assert gens[0] not in [s.desc.generation for s in cfs.live_sstables()]
    assert len(cfs.read_partition(pk_of(t, 3))) > 0
    eng.close()


# ------------------------------------------------------------ flush EIO --

def test_flush_eio_keeps_live_set_and_memtable(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "t")
    for i in range(10):
        put(eng, t, i, 0, f"v{i}")
    d0 = METRICS.counter("storage.disk_failures")
    faultfs.arm("flush.write", "error")
    with pytest.raises(OSError):
        cfs.flush()
    faultfs.disarm()
    assert METRICS.counter("storage.disk_failures") == d0 + 1
    # live set unchanged, no half-written sstable committed
    assert cfs.live_sstables() == []
    # the memtable is still readable — nothing acked was lost
    assert not cfs.memtable.is_empty
    assert len(cfs.read_partition(pk_of(t, 3))) == 2
    # writes that landed DURING the failed flush survive the restore
    r = cfs.flush()
    assert r is not None and r.n_cells > 0
    assert len(cfs.read_partition(pk_of(t, 3))) == 2
    eng.close()


def test_flush_eio_absorbs_writes_during_failed_flush(tmp_path):
    """A write applied between the memtable switch and the flush
    failure must survive the restore (Memtable.absorb)."""
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "t")
    put(eng, t, 1, 0, "before")
    old = cfs.memtable

    # fail the flush, but sneak a write into the REPLACEMENT memtable
    # first: patch flush_shards to write mid-flush deterministically
    orig = type(old).flush_shards

    def trapped(self):
        if self is old:
            put(eng, t, 2, 0, "during")
            raise OSError(5, "injected mid-flush failure")
        return orig(self)

    type(old).flush_shards = trapped
    try:
        with pytest.raises(OSError):
            cfs.flush()
    finally:
        type(old).flush_shards = orig
    assert len(cfs.read_partition(pk_of(t, 1))) == 2
    assert len(cfs.read_partition(pk_of(t, 2))) == 2
    r = cfs.flush()
    assert r is not None
    assert len(cfs.read_partition(pk_of(t, 1))) == 2
    assert len(cfs.read_partition(pk_of(t, 2))) == 2
    eng.close()


def test_flush_readback_failure_restores_memtable(tmp_path):
    """EIO while RE-OPENING the just-written sstable (after finish)
    must restore the memtable exactly like a write failure — otherwise
    acked writes vanish from reads while the sstable sits untracked."""
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "t")
    for i in range(10):
        put(eng, t, i, 0, f"v{i}")
    faultfs.arm("sstable.open", "error", path_substr=cfs.directory)
    with pytest.raises(OSError):
        cfs.flush()
    faultfs.disarm()
    assert cfs.live_sstables() == []
    assert not cfs.memtable.is_empty
    assert len(cfs.read_partition(pk_of(t, 3))) == 2
    # retry works and content stays correct (the orphan on-disk output
    # from the failed read-back reconciles away if ever reloaded)
    assert cfs.flush() is not None
    assert len(cfs.read_partition(pk_of(t, 3))) == 2
    eng.close()


def test_quarantined_generation_never_reused(tmp_path):
    """After a restart, generation allocation must skip quarantined
    generations (their files left the live directory) — re-minting one
    would corrupt the quarantine records and block a future quarantine
    of the new sstable."""
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    eng._save_schema()
    gens = [s.desc.generation for s in cfs.live_sstables()]
    bad_reader = next(s for s in cfs.live_sstables()
                      if s.desc.generation == gens[-1])
    cfs.quarantine_sstable(bad_reader, "test")
    eng.close()
    eng2 = StorageEngine(str(tmp_path), Schema(), commitlog_sync="batch")
    cfs2 = eng2.store("ks", "t")
    assert cfs2.next_generation() > gens[-1]
    for i in range(4):
        put(eng2, t, i, 9, "fresh")
    r = cfs2.flush()
    assert r.desc.generation > gens[-1]
    # the quarantine record still refers to the OLD generation only
    assert [q["generation"] for q in cfs2.quarantined] == [gens[-1]]
    eng2.close()


def test_torn_write_aborts_cleanly(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = eng.store("ks", "t")
    for i in range(10):
        put(eng, t, i, 0, f"v{i}")
    faultfs.arm("flush.write", "torn_write", tear_bytes=64)
    with pytest.raises(OSError):
        cfs.flush()
    faultfs.disarm()
    # the torn output never reached the live set; no TOC committed
    assert cfs.live_sstables() == []
    assert not any(fn.endswith("TOC.txt")
                   for fn in os.listdir(cfs.directory))
    assert cfs.flush() is not None
    eng.close()


# ----------------------------------------------------- compaction paths --

def test_compaction_corruption_aborts_task_not_executor(tmp_path):
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t, rounds=5)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    bad = gens[1]
    faultfs.arm("sstable.read", "bitflip", path_substr=f"-{bad}-Data.db")
    eng.compactions.submit_background(cfs)
    n = eng.compactions.run_pending()
    faultfs.disarm()
    # the corrupt input was quarantined and the strategy re-planned
    # WITHOUT it: the surviving inputs compacted in the same submission
    assert [q["generation"] for q in cfs.quarantined] == [bad]
    live = [s.desc.generation for s in cfs.live_sstables()]
    assert bad not in live
    assert n >= 1
    # the executor survived: another submission still runs
    seeded(eng, t, rounds=2)
    eng.compactions.submit_background(cfs)
    assert eng.compactions.run_pending() >= 0
    assert len(cfs.read_partition(pk_of(t, 3))) > 0
    eng.close()


def test_quarantined_excluded_from_next_compaction_round(tmp_path):
    from cassandra_tpu.compaction.strategies import get_strategy
    eng, t = new_engine(tmp_path)
    # 5 rounds so FOUR survive the quarantine (STCS min threshold)
    cfs = seeded(eng, t, rounds=5)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    bad_reader = next(s for s in cfs.live_sstables()
                      if s.desc.generation == gens[0])
    cfs.failures.handle_corruption(
        CorruptSSTableError("test", descriptor=bad_reader.desc))
    cfs.quarantine_sstable(bad_reader, "test")
    task = get_strategy(cfs).next_background_task()
    assert task is not None
    assert gens[0] not in {r.desc.generation for r in task.inputs}
    eng.close()


def test_compaction_corruption_ignore_policy_stops_replanning(tmp_path):
    eng, t = new_engine(tmp_path, disk_policy="ignore")
    cfs = seeded(eng, t, rounds=4)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    faultfs.arm("sstable.read", "bitflip",
                path_substr=f"-{gens[0]}-Data.db")
    eng.compactions.submit_background(cfs)
    n = eng.compactions.run_pending()   # must not raise or spin forever
    faultfs.disarm()
    assert n == 0
    assert cfs.quarantined == []
    assert gens == [s.desc.generation for s in cfs.live_sstables()]
    eng.close()


# -------------------------------------------------- commit failure policy --

def _fail_one_sync(eng, t):
    faultfs.arm("commitlog.fsync", "error", times=1)
    with pytest.raises(OSError):
        put(eng, t, 1, 1, "doomed")
    faultfs.disarm()


def test_commit_policy_ignore_keeps_accepting(tmp_path):
    eng, t = new_engine(tmp_path, commit_policy="ignore")
    c0 = METRICS.counter("storage.commit_failures")
    put(eng, t, 1, 0, "a")
    _fail_one_sync(eng, t)
    assert METRICS.counter("storage.commit_failures") == c0 + 1
    put(eng, t, 1, 2, "recovered")   # today's behavior: writes continue
    # 6 cells: the doomed write is memtable-visible even though its ack
    # failed (same as the reference — a failed write may still be seen)
    assert len(eng.store("ks", "t").read_partition(pk_of(t, 1))) == 6
    eng.close()


def test_commit_policy_stop_commit_halts_writes_serves_reads(tmp_path):
    eng, t = new_engine(tmp_path, commit_policy="stop_commit")
    put(eng, t, 1, 0, "a")
    _fail_one_sync(eng, t)
    assert eng.failures.commits_stopped
    with pytest.raises(CommitLogStoppedError):
        put(eng, t, 1, 2, "refused")
    # reads continue (CommitLogStoppedError is write-only); 4 cells:
    # the acked write plus the doomed-but-memtable-visible one — the
    # REFUSED write after the halt is absent
    assert len(eng.store("ks", "t").read_partition(pk_of(t, 1))) == 4
    eng.close()


def test_commit_policy_stop_halts_reads_and_writes(tmp_path):
    eng, t = new_engine(tmp_path, commit_policy="stop")
    put(eng, t, 1, 0, "a")
    _fail_one_sync(eng, t)
    assert eng.failures.storage_stopped
    with pytest.raises(StorageStoppedError):
        put(eng, t, 1, 2, "refused")
    with pytest.raises(StorageStoppedError):
        eng.store("ks", "t").read_partition(pk_of(t, 1))
    eng.close()


def test_commit_policy_die_marks_node_dead(tmp_path):
    eng, t = new_engine(tmp_path, commit_policy="die")
    died = []
    eng.failures.on_die(died.append)
    put(eng, t, 1, 0, "a")
    _fail_one_sync(eng, t)
    assert eng.failures.dead and len(died) == 1
    with pytest.raises(StorageStoppedError):
        put(eng, t, 1, 2, "refused")
    eng.close()


# ------------------------------------------------------------- hints --

def test_hint_replay_skips_corrupt_record(tmp_path):
    import struct
    import zlib

    from cassandra_tpu.cluster.hints import HintsService
    from cassandra_tpu.cluster.ring import Endpoint
    eng, t = new_engine(tmp_path / "e")
    hs = HintsService(str(tmp_path / "hints"))
    target = Endpoint("n2", "127.0.0.1", 7001)
    muts = []
    for i in range(3):
        m = Mutation(t.id, pk_of(t, i))
        m.add(t.serialize_clustering([0]), COL_ROW_LIVENESS, b"", b"",
              timeutil.now_micros())
        muts.append(m)
        hs.store(target, m)
    # flip one payload byte of the MIDDLE record (header intact)
    p = hs._path(target)
    raw = bytearray(open(p, "rb").read())
    l0, = struct.unpack_from("<I", raw, 0)
    raw[8 + l0 + 8] ^= 0x01      # first payload byte of record 2
    open(p, "wb").write(bytes(raw))
    h0 = METRICS.counter("hints.corrupt_records")
    got = []
    n = hs.dispatch(target, got.append)
    # records 1 and 3 replayed; the corrupt middle one skipped + counted
    assert n == 2 and len(got) == 2
    assert {m.pk for m in got} == {muts[0].pk, muts[2].pk}
    assert METRICS.counter("hints.corrupt_records") == h0 + 1
    assert not hs.has_hints(target)
    eng.close()


def test_hint_read_eio_fault_point(tmp_path):
    from cassandra_tpu.cluster.hints import HintsService
    from cassandra_tpu.cluster.ring import Endpoint
    eng, t = new_engine(tmp_path / "e")
    hs = HintsService(str(tmp_path / "hints"))
    target = Endpoint("n2", "127.0.0.1", 7001)
    m = Mutation(t.id, pk_of(t, 1))
    m.add(t.serialize_clustering([0]), COL_ROW_LIVENESS, b"", b"",
          timeutil.now_micros())
    hs.store(target, m)
    with faultfs.inject("hints.read", "error"):
        with pytest.raises(OSError):
            hs.dispatch(target, lambda _m: None)
    # the file survived the failed dispatch; a retry replays it
    assert hs.has_hints(target)
    assert hs.dispatch(target, lambda _m: None) == 1
    eng.close()


# ------------------------------------------------------------- scrub --

def test_scrub_snapshots_before_rewriting(tmp_path):
    from cassandra_tpu.storage.snapshot import list_snapshots
    from cassandra_tpu.tools import nodetool
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    pre_files = {fn for fn in os.listdir(cfs.directory)
                 if fn.endswith("Data.db")}
    rep = nodetool.scrub(eng, "ks", "t")
    tags = {r["snapshot"] for r in rep}
    assert len(tags) == 1 and next(iter(tags)).startswith("pre-scrub-")
    snaps = list_snapshots(cfs)
    assert len(snaps) == 1
    # every pre-scrub data file is preserved in the snapshot
    assert pre_files <= set(snaps[0]["files"])
    eng.close()


def test_scrub_quarantine_handoff_for_unopenable_sstable(tmp_path):
    from cassandra_tpu.tools import nodetool
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    gens = [s.desc.generation for s in cfs.live_sstables()]
    # segment-read corruption inside scrub's fill drops segments; an
    # OPEN-level error (rewrite re-reads via the live reader whose
    # decode hits EIO every time) can only abort — the handoff must
    # quarantine instead of leaving the file live
    faultfs.arm("sstable.read", "error",
                path_substr=f"-{gens[0]}-Data.db")
    rep = nodetool.scrub(eng, "ks", "t", quarantine=True)
    faultfs.disarm()
    by_gen = {r["generation"]: r for r in rep}
    assert by_gen[gens[0]].get("quarantined") is True
    assert gens[0] not in [s.desc.generation for s in cfs.live_sstables()]
    assert len(cfs.read_partition(pk_of(t, 3))) > 0
    eng.close()


def test_sstableverify_offline_quarantine(tmp_path):
    from cassandra_tpu.tools import sstabletools
    eng, t = new_engine(tmp_path)
    cfs = seeded(eng, t)
    eng._save_schema()
    gens = [s.desc.generation for s in cfs.live_sstables()]
    directory = cfs.directory
    data_dir = eng.data_dir
    eng.close()
    flip_on_disk(os.path.join(directory, f"{FMT}-{gens[0]}-Data.db"))
    rep = sstabletools.verify(data_dir, "ks", "t", quarantine=True)
    by_gen = {r["generation"]: r for r in rep}
    assert by_gen[gens[0]]["status"] != "ok"
    assert "quarantined" in by_gen[gens[0]]
    assert by_gen[gens[1]]["status"] == "ok"
    # the rotten generation left the live directory: a fresh engine
    # opens clean without tripping over it (commitlog replay may add a
    # NEW generation — only the quarantined one must stay gone)
    eng2 = StorageEngine(data_dir, Schema(), commitlog_sync="batch")
    cfs2 = eng2.store("ks", "t")
    live = [s.desc.generation for s in cfs2.live_sstables()]
    assert gens[0] not in live and gens[1] in live
    eng2.close()


# ------------------------------------------- coordinator failover path --

def test_replica_read_error_fails_over_to_spare(tmp_path):
    """A corrupt local replica (policy=ignore so the error surfaces)
    must produce a failed response that the coordinator's speculative
    retry turns into data from another replica — instead of burning
    the read timeout or crashing the client read."""
    import time as _time

    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    c = LocalCluster(2, str(tmp_path), rf=2)
    try:
        for n in c.nodes:
            n.proxy.timeout = 2.0
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        n1 = c.node(1)
        n1.engine.settings.set("disk_failure_policy", "ignore")
        s.execute("INSERT INTO kv (k, v) VALUES (1, 'payload')")
        for n in c.nodes:
            n.engine.store("ks", "kv").flush()
        t = c.schema.get_table("ks", "kv")
        pk = t.columns["k"].cql_type.serialize(1)
        # corrupt ONLY the coordinator's own replica
        faultfs.arm("sstable.read", "bitflip",
                    path_substr=n1.engine.data_dir)
        from cassandra_tpu.storage.chunk_cache import GLOBAL as chunks
        chunks.clear()
        t0 = _time.monotonic()
        merged = n1.proxy.read_partition("ks", "kv", pk,
                                         ConsistencyLevel.ONE)
        elapsed = _time.monotonic() - t0
        faultfs.disarm()
        assert len(merged) > 0          # served by the healthy replica
        assert elapsed < 1.5            # failover, not a timeout burn
    finally:
        faultfs.disarm()
        c.shutdown()


def test_stop_policy_leaves_the_ring(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    c = LocalCluster(2, str(tmp_path), rf=2)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        n1 = c.node(1)
        n1.engine.settings.set("disk_failure_policy", "stop")
        s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
        cfs = n1.engine.store("ks", "kv")
        cfs.flush()
        t = c.schema.get_table("ks", "kv")
        pk = t.columns["k"].cql_type.serialize(1)
        from cassandra_tpu.storage.chunk_cache import GLOBAL as chunks
        chunks.clear()
        with faultfs.inject("sstable.read", "bitflip",
                            path_substr=n1.engine.data_dir):
            with pytest.raises(CorruptSSTableError):
                cfs.read_partition(pk)
        assert n1.engine.failures.storage_stopped
        # the node left the ring: own gossip status flipped and the
        # gossiper no longer speaks
        st = n1.gossiper.states[n1.endpoint]
        assert st.app_states.get("status") == "shutdown"
        assert not n1.gossiper.is_running()
        with pytest.raises(StorageStoppedError):
            n1.engine.apply(Mutation(t.id, pk))
    finally:
        c.shutdown()
