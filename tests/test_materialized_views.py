"""Materialized views: DDL, derived writes, key-change moves, deletes,
backfill, restart (db/view/ViewUpdateGenerator, schema/ViewMetadata)."""
import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine


@pytest.fixture
def tmp_data(tmp_path):
    return str(tmp_path / "data")


@pytest.fixture
def engine(tmp_data):
    eng = StorageEngine(tmp_data, Schema(), commitlog_sync="batch")
    yield eng
    eng.close()


@pytest.fixture
def session(engine):
    s = Session(engine)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE users (id int PRIMARY KEY, city text, "
              "age int)")
    s.execute("CREATE MATERIALIZED VIEW users_by_city AS "
              "SELECT * FROM users WHERE city IS NOT NULL "
              "AND id IS NOT NULL PRIMARY KEY ((city), id)")
    return s


def test_view_reflects_inserts(session):
    session.execute("INSERT INTO users (id, city, age) VALUES "
                    "(1, 'paris', 30)")
    session.execute("INSERT INTO users (id, city, age) VALUES "
                    "(2, 'paris', 40)")
    session.execute("INSERT INTO users (id, city, age) VALUES "
                    "(3, 'oslo', 50)")
    rs = session.execute(
        "SELECT id, age FROM users_by_city WHERE city = 'paris'")
    assert sorted(rs.rows) == [(1, 30), (2, 40)]


def test_view_key_change_moves_row(session):
    session.execute("INSERT INTO users (id, city, age) VALUES "
                    "(7, 'rome', 20)")
    session.execute("UPDATE users SET city = 'lima' WHERE id = 7")
    assert session.execute(
        "SELECT id FROM users_by_city WHERE city = 'rome'").rows == []
    assert session.execute(
        "SELECT id, age FROM users_by_city WHERE city = 'lima'").rows \
        == [(7, 20)]


def test_view_row_follows_base_delete(session):
    session.execute("INSERT INTO users (id, city) VALUES (9, 'kyiv')")
    session.execute("DELETE FROM users WHERE id = 9")
    assert session.execute(
        "SELECT id FROM users_by_city WHERE city = 'kyiv'").rows == []


def test_view_null_key_excluded(session):
    session.execute("INSERT INTO users (id, age) VALUES (11, 60)")
    rs = session.execute("SELECT city, id FROM users_by_city")
    assert all(r[1] != 11 for r in rs.rows)
    session.execute("UPDATE users SET city = 'bern' WHERE id = 11")
    assert session.execute(
        "SELECT id FROM users_by_city WHERE city = 'bern'").rows == [(11,)]


def test_view_backfills_existing_data(session):
    for i in range(20, 25):
        session.execute(
            f"INSERT INTO users (id, city, age) VALUES ({i}, 'baku', 1)")
    session.execute("CREATE MATERIALIZED VIEW users_by_age AS "
                    "SELECT * FROM users WHERE age IS NOT NULL AND "
                    "id IS NOT NULL PRIMARY KEY ((age), id)")
    rs = session.execute("SELECT id FROM users_by_age WHERE age = 1")
    assert sorted(r[0] for r in rs.rows) == [20, 21, 22, 23, 24]


def test_view_write_rejected_and_drop(session):
    with pytest.raises(Exception, match="materialized view"):
        session.execute("INSERT INTO users_by_city (city, id) VALUES "
                        "('x', 1)")
    with pytest.raises(Exception, match="depend"):
        session.execute("DROP TABLE users")
    session.execute("DROP MATERIALIZED VIEW users_by_city")
    session.execute("DROP TABLE users")   # now allowed


def test_view_survives_restart(tmp_data, engine, session):
    session.execute("INSERT INTO users (id, city) VALUES (1, 'lviv')")
    engine.close()
    eng2 = StorageEngine(tmp_data, Schema(), commitlog_sync="batch")
    try:
        s2 = Session(eng2)
        s2.keyspace = "ks"
        assert s2.execute("SELECT id FROM users_by_city "
                          "WHERE city = 'lviv'").rows == [(1,)]
        s2.execute("INSERT INTO users (id, city) VALUES (2, 'lviv')")
        assert sorted(s2.execute(
            "SELECT id FROM users_by_city WHERE city = 'lviv'").rows) \
            == [(1,), (2,)]
    finally:
        eng2.close()


def test_view_across_cluster(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    c = LocalCluster(3, str(tmp_path), rf=3)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 3}")
        s.execute("USE ks")
        s.execute("CREATE TABLE ev (id int PRIMARY KEY, kind text)")
        s.execute("CREATE MATERIALIZED VIEW ev_by_kind AS SELECT * FROM ev "
                  "WHERE kind IS NOT NULL AND id IS NOT NULL "
                  "PRIMARY KEY ((kind), id)")
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        for i in range(10):
            s.execute(f"INSERT INTO ev (id, kind) VALUES ({i}, "
                      f"'k{i % 2}')")
        s2 = c.session(2)
        s2.keyspace = "ks"
        c.node(2).default_cl = ConsistencyLevel.QUORUM  # ONE could read a
        # replica outside the write quorum — legitimate CL semantics
        rs = s2.execute("SELECT id FROM ev_by_kind WHERE kind = 'k1'")
        assert sorted(r[0] for r in rs.rows) == [1, 3, 5, 7, 9]
    finally:
        c.shutdown()


def test_view_nulled_column_and_null_backfill(session):
    session.execute("INSERT INTO users (id, city, age) VALUES "
                    "(31, 'graz', 5)")
    session.execute("UPDATE users SET age = null WHERE id = 31")
    rs = session.execute("SELECT id, age FROM users_by_city "
                         "WHERE city = 'graz'")
    assert rs.rows == [(31, None)]
    # backfill over a row whose view key column is null must not crash
    session.execute("INSERT INTO users (id, age) VALUES (32, 9)")
    session.execute("CREATE MATERIALIZED VIEW by_city2 AS SELECT * "
                    "FROM users WHERE city IS NOT NULL AND id IS NOT "
                    "NULL PRIMARY KEY ((city), id)")
    rs = session.execute("SELECT id FROM by_city2 WHERE city = 'graz'")
    assert rs.rows == [(31,)]


def test_view_ttl_propagates(session):
    import time
    session.execute("INSERT INTO users (id, city) VALUES (41, 'turin') "
                    "USING TTL 1")
    assert session.execute(
        "SELECT id FROM users_by_city WHERE city = 'turin'").rows \
        == [(41,)]
    time.sleep(1.5)
    assert session.execute(
        "SELECT id FROM users_by_city WHERE city = 'turin'").rows == []


def test_view_timestamped_delete_shadows(session):
    session.execute("INSERT INTO users (id, city) VALUES (42, 'nice') "
                    "USING TIMESTAMP 100")
    session.execute("DELETE FROM users USING TIMESTAMP 200 WHERE id = 42")
    assert session.execute(
        "SELECT id FROM users_by_city WHERE city = 'nice'").rows == []


def test_view_logged_batch(session):
    session.execute("INSERT INTO users (id, city, age) VALUES "
                    "(51, 'rome', 99)")
    session.execute("BEGIN BATCH "
                    "UPDATE users SET age = 5 WHERE id = 51; "
                    "UPDATE users SET city = 'rome' WHERE id = 51; "
                    "APPLY BATCH;")
    rs = session.execute("SELECT id, age FROM users_by_city "
                         "WHERE city = 'rome'")
    assert rs.rows == [(51, 5)]
