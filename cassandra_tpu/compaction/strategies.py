"""Compaction strategies: which sstables to merge next.

Reference counterparts:
  AbstractCompactionStrategy.java:65 (SPI: getNextBackgroundTask)
  SizeTieredCompactionStrategy.java:41 (size buckets, :248 getBuckets)
  LeveledCompactionStrategy.java:47 + LeveledManifest.java:54
  TimeWindowCompactionStrategy.java:52 (windows :174, expired drop :128)

Strategies only *select*; CompactionTask does the work. Selection reads
each sstable's Statistics.db metadata (size, level, max timestamp,
max local-deletion-time).
"""
from __future__ import annotations

import time

from ..storage.sstable import SSTableReader
from ..utils import timeutil


class AbstractCompactionStrategy:
    def __init__(self, cfs, options: dict | None = None,
                 repaired: bool | None = None):
        self.cfs = cfs
        self.options = options or {}
        # repaired/unrepaired split (CompactionStrategyManager.java:107):
        # a strategy instance only ever sees ONE side of the boundary —
        # None (tools/tests constructing a strategy directly) sees all
        self.repaired = repaired
        self.min_threshold = int(self.options.get("min_threshold", 4))
        self.max_threshold = int(self.options.get("max_threshold", 32))

    def candidates(self) -> list[SSTableReader]:
        """The live sstables THIS strategy instance may select — never
        across the repaired/unrepaired boundary."""
        live = self.cfs.live_sstables()
        if self.repaired is None:
            return live
        return [s for s in live if s.is_repaired == self.repaired]

    def next_background_task(self):
        """Return a CompactionTask or None (getNextBackgroundTask)."""
        raise NotImplementedError

    def major_task(self):
        """Compact everything on THIS side of the repaired boundary."""
        from .task import CompactionTask
        live = self.candidates()
        if len(live) < 1:
            return None
        return CompactionTask(self.cfs, live)

    # ---- helpers

    def _fully_expired(self) -> list[SSTableReader]:
        """SSTables whose every cell is an expired tombstone older than
        gc grace with no overlap concern (TWCS-style drop;
        CompactionController.getFullyExpiredSSTables)."""
        gc_before = timeutil.now_seconds() - \
            self.cfs.table.params.gc_grace_seconds
        out = []
        live = self.cfs.live_sstables()   # overlap guard: ALL live
        cands = self.candidates()
        # the purge guard consults the memtable; dropping against a hot
        # memtable could rewrite the sstable unchanged and re-select it
        # forever (livelock) — wait for a flush instead
        if not self.cfs.memtable.is_empty:
            return out
        for s in cands:
            if s.max_ldt is None or s.max_ldt >= gc_before:
                continue
            if s.n_tombstones < s.n_cells:
                continue  # has live data
            # overlap guard: any other source with older data?
            others = [o for o in live if o is not s]
            if any(o.min_ts is not None and s.max_ts is not None
                   and o.min_ts <= s.max_ts and self._token_overlap(o, s)
                   for o in others):
                continue
            out.append(s)
        return out

    @staticmethod
    def _token_overlap(a: SSTableReader, b: SSTableReader) -> bool:
        return a.min_token() <= b.max_token() and b.min_token() <= a.max_token()


class SizeTieredCompactionStrategy(AbstractCompactionStrategy):
    """Bucket sstables of similar size; compact the biggest eligible
    bucket (hottest-first is a refinement we skip: reference :116)."""

    def __init__(self, cfs, options=None, repaired=None):
        super().__init__(cfs, options, repaired)
        self.bucket_low = float(self.options.get("bucket_low", 0.5))
        self.bucket_high = float(self.options.get("bucket_high", 1.5))
        self.min_sstable_size = int(self.options.get(
            "min_sstable_size", 50 * 1024 * 1024))

    def buckets(self) -> list[list[SSTableReader]]:
        ssts = sorted(self.candidates(), key=lambda s: s.data_size)
        buckets: list[tuple[float, list[SSTableReader]]] = []
        for s in ssts:
            size = s.data_size
            for i, (avg, items) in enumerate(buckets):
                if (self.bucket_low * avg <= size <= self.bucket_high * avg) \
                        or (size < self.min_sstable_size
                            and avg < self.min_sstable_size):
                    items.append(s)
                    buckets[i] = ((avg * (len(items) - 1) + size)
                                  / len(items), items)
                    break
            else:
                buckets.append((float(size), [s]))
        return [items for _, items in buckets]

    def next_background_task(self):
        from .task import CompactionTask
        candidates = [b for b in self.buckets()
                      if len(b) >= self.min_threshold]
        if not candidates:
            return None
        bucket = max(candidates, key=len)[: self.max_threshold]
        return CompactionTask(self.cfs, bucket)


class LeveledCompactionStrategy(AbstractCompactionStrategy):
    """Simplified leveled strategy: L0 (flushes) -> L1..: non-overlapping
    runs, each level `fanout` times larger (LeveledManifest semantics)."""

    def __init__(self, cfs, options=None, repaired=None):
        super().__init__(cfs, options, repaired)
        self.max_sstable_bytes = int(float(self.options.get(
            "sstable_size_in_mb", 160)) * 1024 * 1024)
        self.fanout = int(self.options.get("fanout_size", 10))
        self.l0_threshold = int(self.options.get("l0_threshold", 4))

    def _levels(self) -> dict[int, list[SSTableReader]]:
        levels: dict[int, list[SSTableReader]] = {}
        for s in self.candidates():
            levels.setdefault(s.level, []).append(s)
        return levels

    def _level_target_bytes(self, level: int) -> int:
        return self.max_sstable_bytes * (self.fanout ** level)

    def _overlapping(self, ssts, candidates):
        lo = min(s.min_token() for s in ssts)
        hi = max(s.max_token() for s in ssts)
        return [c for c in candidates
                if c.min_token() <= hi and lo <= c.max_token()]

    def next_background_task(self):
        from .task import CompactionTask
        levels = self._levels()
        # L0 -> L1 when enough flushes accumulated
        l0 = levels.get(0, [])
        if len(l0) >= self.l0_threshold:
            chosen = l0[: self.max_threshold]
            inputs = chosen + self._overlapping(chosen, levels.get(1, []))
            return CompactionTask(self.cfs, inputs,
                                  max_output_bytes=self.max_sstable_bytes,
                                  level=1)
        # level overflow: push one sstable into the next level
        for lvl in sorted(l for l in levels if l > 0):
            total = sum(s.data_size for s in levels[lvl])
            if total > self._level_target_bytes(lvl):
                victim = max(levels[lvl], key=lambda s: s.data_size)
                inputs = [victim] + self._overlapping([victim],
                                                      levels.get(lvl + 1, []))
                return CompactionTask(self.cfs, inputs,
                                      max_output_bytes=self.max_sstable_bytes,
                                      level=lvl + 1)
        return None


class TimeWindowCompactionStrategy(AbstractCompactionStrategy):
    """Time-series strategy: bucket by write-time window; STCS inside the
    current window, one sstable per older window, drop fully-expired
    sstables first (TimeWindowCompactionStrategy.java:83,128,174)."""

    _UNITS = {"MINUTES": 60, "HOURS": 3600, "DAYS": 86400}

    def __init__(self, cfs, options=None, repaired=None):
        super().__init__(cfs, options, repaired)
        unit = str(self.options.get("compaction_window_unit",
                                    "DAYS")).upper()
        size = int(self.options.get("compaction_window_size", 1))
        self.window_seconds = self._UNITS.get(unit, 86400) * size

    def _window_of(self, sst: SSTableReader) -> int:
        # max timestamp is micros; windows are in seconds
        return int((sst.max_ts or 0) // 1_000_000 // self.window_seconds)

    def next_background_task(self):
        from .task import CompactionTask
        expired = self._fully_expired()
        if expired:
            # dropping needs no merge: rewrite-free task over expired
            # only (task.py _execute_drop — deletes, never decodes)
            return CompactionTask(self.cfs, expired, drop_only=True)
        windows: dict[int, list[SSTableReader]] = {}
        for s in self.candidates():
            windows.setdefault(self._window_of(s), []).append(s)
        if not windows:
            return None
        newest = max(windows)
        for w, ssts in sorted(windows.items()):
            if w == newest:
                if len(ssts) >= self.min_threshold:
                    return CompactionTask(self.cfs,
                                          ssts[: self.max_threshold])
            elif len(ssts) > 1:
                return CompactionTask(self.cfs, ssts[: self.max_threshold])
        return None


class UnifiedCompactionStrategy(AbstractCompactionStrategy):
    """Unified strategy (reference UnifiedCompactionStrategy.java:66,
    unified/Controller.java:154, UnifiedCompactionStrategy.md):

    * `scaling_parameters` is a PER-LEVEL VECTOR ("T4, T8, N, L4"):
      level i uses W = vector[min(i, len-1)]. Positive W behaves tiered
      (fanout 2+W, threshold 2+W), negative behaves leveled (fanout
      2-W, threshold 2), N is the middle (fanout 2, threshold 2) —
      UnifiedCompactionStrategy.fanoutFromScalingParameter /
      thresholdFromScalingParameter.
    * SSTables form DENSITY levels: boundaries start at
      min_sstable_size x fanout(0) and each level's ceiling multiplies
      by ITS OWN fanout (Controller.getMaxLevelDensity) — so a mixed
      vector changes the level geometry, not just thresholds.
    * Outputs are sharded density-aware (Controller.getNumShards): a
      power-of-two multiple of `base_shard_count` chosen so each shard
      lands near `target_sstable_size` x density^sstable_growth, with
      the min-size clamp below the base count. The shard count is the
      knob that parallelises one logical compaction across cores/chips
      (ShardManager.java:33; parallel/mesh.py consumes these shards).
    """

    MAX_SHARD_SHIFT = 20

    def __init__(self, cfs, options=None, repaired=None):
        super().__init__(cfs, options, repaired)
        spec = str(self.options.get("scaling_parameters", "T4"))
        # the per-level W vector; levels beyond the end repeat the last
        self.scaling_vector = self.parse_scaling_vector(spec)
        self.base_shard_count = int(self.options.get("base_shard_count", 4))
        self.min_sstable_size = int(self.options.get(
            "min_sstable_size", 2 * 1024 * 1024))
        self.target_sstable_size = int(self.options.get(
            "target_sstable_size", 1 << 30))
        self.sstable_growth = float(self.options.get("sstable_growth",
                                                     0.333))

    # ------------------------------------------------ scaling vector --

    @staticmethod
    def parse_scaling_vector(spec: str) -> list:
        out = []
        for part in str(spec).split(","):
            part = part.strip().upper()
            if not part:
                continue
            if part == "N":
                out.append(0)
            elif part.startswith("T"):
                out.append(max(int(part[1:] or 4) - 2, 0))
            elif part.startswith("L"):
                out.append(-max(int(part[1:] or 4) - 2, 0))
            else:
                out.append(int(part))
        return out or [2]

    def scaling_w(self, level: int) -> int:
        v = self.scaling_vector
        return v[level] if level < len(v) else v[-1]

    def fanout(self, level: int) -> int:
        w = self.scaling_w(level)
        return 2 - w if w < 0 else 2 + w

    def threshold(self, level: int) -> int:
        w = self.scaling_w(level)
        return 2 if w <= 0 else 2 + w

    # ------------------------------------------------- density levels --

    def level_of(self, density: float) -> int:
        """The density level an sstable of `density` bytes falls in:
        level ceilings grow by each level's OWN fanout
        (Controller.getMaxLevelDensity iterated)."""
        ceiling = float(self.min_sstable_size) * self.fanout(0)
        lvl = 0
        while density >= ceiling and lvl < 64:
            lvl += 1
            ceiling *= self.fanout(lvl)
        return lvl

    def form_levels(self, sstables) -> dict:
        levels: dict[int, list] = {}
        for s in sstables:
            levels.setdefault(self.level_of(float(s.data_size)),
                              []).append(s)
        return levels

    # ------------------------------------------------ shard geometry --

    def num_shards(self, density: float) -> int:
        """Controller.getNumShards: power-of-two multiple of the base
        count targeting target_sstable_size x growth correction, with
        the min-size clamp below the base."""
        import math

        if self.min_sstable_size > 0:
            count = density / self.min_sstable_size
            if not count >= self.base_shard_count:
                # below the base: power-of-two DIVISOR of the base so
                # boundaries still align with higher levels
                low_bit = self.base_shard_count & -self.base_shard_count
                return min(1 << max(int(count) | 1, 1).bit_length() - 1,
                           low_bit)
        g = self.sstable_growth
        if g >= 1:
            return self.base_shard_count
        if g <= 0:
            count = density / (self.target_sstable_size * math.sqrt(0.5)
                               * self.base_shard_count)
            count = min(count, float(1 << self.MAX_SHARD_SHIFT))
            return self.base_shard_count *                 (1 << max(int(count) | 1, 1).bit_length() - 1)
        # partial growth: exponent of the density/target ratio scaled by
        # (1 - growth), rounded to the nearest power of two
        count = density / (self.target_sstable_size
                           * self.base_shard_count)
        if count <= 0:
            return self.base_shard_count
        exponent = int(max(0, min(
            math.floor(math.log2(count) * (1 - g) + 0.5),
            self.MAX_SHARD_SHIFT)))
        return self.base_shard_count * (1 << exponent)

    # -------------------------------------------------- task selection --

    def next_background_task(self):
        from .task import CompactionTask
        levels = self.form_levels(self.candidates())
        for lvl in sorted(levels):
            group = levels[lvl]
            if len(group) >= self.threshold(lvl):
                inputs = group[: self.max_threshold]
                total = float(sum(s.data_size for s in inputs))
                shards = self.num_shards(total)
                shard_bytes = max(int(total // shards),
                                  self.min_sstable_size)
                return CompactionTask(self.cfs, inputs,
                                      max_output_bytes=shard_bytes,
                                      level=lvl + 1)
        return None


STRATEGIES = {
    "SizeTieredCompactionStrategy": SizeTieredCompactionStrategy,
    "LeveledCompactionStrategy": LeveledCompactionStrategy,
    "TimeWindowCompactionStrategy": TimeWindowCompactionStrategy,
    "UnifiedCompactionStrategy": UnifiedCompactionStrategy,
}


class CompactionStrategyManager:
    """Holds one strategy instance per side of the repaired boundary and
    never lets a compaction cross it
    (db/compaction/CompactionStrategyManager.java:107). Background
    selection serves whichever side has work; major compaction runs each
    side as its own task."""

    def __init__(self, cfs, cls, opts):
        self.cfs = cfs
        self.unrepaired = cls(cfs, opts, repaired=False)
        self.repaired = cls(cfs, opts, repaired=True)

    def __getattr__(self, name):
        # strategy-specific helpers (tests/tools introspection) resolve
        # against the unrepaired instance
        return getattr(self.unrepaired, name)

    def next_background_task(self):
        return self.unrepaired.next_background_task() \
            or self.repaired.next_background_task()

    def major_task(self):
        tasks = [t for t in (self.unrepaired.major_task(),
                             self.repaired.major_task()) if t is not None]
        if not tasks:
            return None
        return _SequentialTasks(tasks)


class _SequentialTasks:
    """Several group-local tasks behind the single-task call surface."""

    def __init__(self, tasks):
        self.tasks = tasks
        self.inputs = [s for t in tasks for s in t.inputs]

    # executor plumbing (CompactionManager._execute_task assigns these):
    # forward to every wrapped task so the shared throttle and the
    # progress handle cover all groups, not just the wrapper object

    @property
    def limiter(self):
        return self.tasks[0].limiter if self.tasks else None

    @limiter.setter
    def limiter(self, v):
        for t in self.tasks:
            t.limiter = v

    @property
    def progress(self):
        return self.tasks[0].progress if self.tasks else None

    @progress.setter
    def progress(self, v):
        for t in self.tasks:
            t.progress = v

    def execute(self) -> dict:
        stats = None
        for t in self.tasks:
            st = t.execute()
            if stats is None:
                stats = st
            else:
                for k in ("bytes_read", "bytes_written", "cells_read",
                          "cells_written", "seconds"):
                    stats[k] += st[k]
                stats["outputs"] += st["outputs"]
                stats["inputs"] += st["inputs"]
        if stats and stats.get("seconds"):
            stats["read_mib_s"] = stats["bytes_read"] / stats["seconds"] \
                / 2**20
            stats["write_mib_s"] = stats["bytes_written"] \
                / stats["seconds"] / 2**20
        return stats


def get_strategy(cfs) -> CompactionStrategyManager:
    opts = dict(cfs.table.params.compaction)
    name = opts.pop("class", "SizeTieredCompactionStrategy").rsplit(".", 1)[-1]
    if name not in STRATEGIES:
        raise ValueError(f"unknown compaction strategy {name}")
    return CompactionStrategyManager(cfs, STRATEGIES[name], opts)
