"""Range tombstone semantics — the CompactionsPurgeTest-style corner cases
for clustering-range deletes (reference db/RangeTombstone.java,
db/RangeTombstoneList.java, test/unit/.../CompactionsPurgeTest.java)."""
import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema, make_table
from cassandra_tpu.storage import cellbatch as cb
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.storage.rangetomb import Slice, covering_ts


@pytest.fixture
def engine(tmp_path):
    eng = StorageEngine(str(tmp_path / "data"), Schema(),
                        commitlog_sync="batch")
    yield eng
    eng.close()


@pytest.fixture
def session(engine):
    s = Session(engine)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    return s


def rows(s, q):
    return s.execute(q).rows


def test_range_delete_basic(session):
    session.execute("CREATE TABLE t (k int, c int, v text, "
                    "PRIMARY KEY (k, c))")
    for c in range(10):
        session.execute(f"INSERT INTO t (k, c, v) VALUES (1, {c}, 'x{c}')")
    session.execute("DELETE FROM t WHERE k = 1 AND c > 2 AND c <= 6")
    got = sorted(r[0] for r in rows(session, "SELECT c FROM t WHERE k = 1"))
    assert got == [0, 1, 2, 7, 8, 9]


def test_range_delete_bound_kinds(session):
    session.execute("CREATE TABLE b (k int, c int, PRIMARY KEY (k, c))")
    for c in range(6):
        session.execute(f"INSERT INTO b (k, c) VALUES (1, {c})")
    session.execute("DELETE FROM b WHERE k = 1 AND c >= 4")
    assert sorted(r[0] for r in rows(session, "SELECT c FROM b WHERE k=1"))\
        == [0, 1, 2, 3]
    session.execute("DELETE FROM b WHERE k = 1 AND c < 2")
    assert sorted(r[0] for r in rows(session, "SELECT c FROM b WHERE k=1"))\
        == [2, 3]


def test_prefix_delete_two_clusterings(session):
    session.execute("CREATE TABLE p (k int, a int, b int, v int, "
                    "PRIMARY KEY (k, a, b))")
    for a in (1, 2):
        for b in (1, 2, 3):
            session.execute(
                f"INSERT INTO p (k, a, b, v) VALUES (1, {a}, {b}, 0)")
    session.execute("DELETE FROM p WHERE k = 1 AND a = 1")  # prefix delete
    got = rows(session, "SELECT a, b FROM p WHERE k = 1")
    assert sorted(got) == [(2, 1), (2, 2), (2, 3)]
    # inequality under an equality prefix
    session.execute("DELETE FROM p WHERE k = 1 AND a = 2 AND b >= 3")
    got = rows(session, "SELECT a, b FROM p WHERE k = 1")
    assert sorted(got) == [(2, 1), (2, 2)]


def test_newer_write_survives_range_delete(session):
    session.execute("CREATE TABLE n (k int, c int, v text, "
                    "PRIMARY KEY (k, c))")
    session.execute("INSERT INTO n (k, c, v) VALUES (1, 5, 'old') "
                    "USING TIMESTAMP 100")
    session.execute("DELETE FROM n USING TIMESTAMP 200 WHERE k = 1 AND c > 0")
    session.execute("INSERT INTO n (k, c, v) VALUES (1, 5, 'new') "
                    "USING TIMESTAMP 300")
    assert rows(session, "SELECT v FROM n WHERE k = 1") == [("new",)]


def test_range_delete_across_flush_and_compaction(session, engine):
    session.execute("CREATE TABLE f (k int, c int, v text, "
                    "PRIMARY KEY (k, c))")
    for c in range(8):
        session.execute(f"INSERT INTO f (k, c, v) VALUES (1, {c}, 'x')")
    cfs = engine.store("ks", "f")
    cfs.flush()                      # data lives in an sstable
    session.execute("DELETE FROM f WHERE k = 1 AND c >= 4")
    cfs.flush()                      # tombstone in a second sstable
    got = sorted(r[0] for r in rows(session, "SELECT c FROM f WHERE k=1"))
    assert got == [0, 1, 2, 3]
    # major compaction applies the range across sstables
    from cassandra_tpu.compaction.task import CompactionTask
    CompactionTask(cfs, cfs.tracker.view()).execute()
    got = sorted(r[0] for r in rows(session, "SELECT c FROM f WHERE k=1"))
    assert got == [0, 1, 2, 3]


def test_range_tombstone_purged_after_gc_grace(session, engine):
    session.execute("CREATE TABLE g (k int, c int, v text, "
                    "PRIMARY KEY (k, c)) WITH gc_grace_seconds = 0")
    cfs = engine.store("ks", "g")
    for c in range(6):
        session.execute(f"INSERT INTO g (k, c, v) VALUES (1, {c}, 'x')")
    cfs.flush()
    session.execute("DELETE FROM g WHERE k = 1 AND c >= 3")
    cfs.flush()
    import time
    time.sleep(1.2)   # purge needs ldt strictly below gcBefore (= now)
    from cassandra_tpu.compaction.task import CompactionTask
    CompactionTask(cfs, cfs.tracker.view()).execute()
    # covered rows gone AND the marker itself purged (gc_grace=0, no
    # overlapping sources)
    live = cfs.tracker.view()
    total = sum(r.n_cells for r in live)
    batch = cb.CellBatch.concat(
        [seg for r in live for seg in r.scanner()]) if total else None
    if batch is not None:
        assert not ((batch.flags & cb.FLAG_RANGE_BOUND) != 0).any()
    got = sorted(r[0] for r in rows(session, "SELECT c FROM g WHERE k=1"))
    assert got == [0, 1, 2]


def test_contained_older_slice_dropped(session, engine):
    session.execute("CREATE TABLE o (k int, c int, PRIMARY KEY (k, c))")
    session.execute("DELETE FROM o USING TIMESTAMP 100 "
                    "WHERE k = 1 AND c >= 3 AND c <= 4")
    session.execute("DELETE FROM o USING TIMESTAMP 200 "
                    "WHERE k = 1 AND c >= 1 AND c <= 8")
    cfs = engine.store("ks", "o")
    batch = cfs.read_partition(
        engine.schema.get_table("ks", "o").columns["k"]
        .cql_type.serialize(1))
    ranges = (batch.flags & cb.FLAG_RANGE_BOUND) != 0
    assert int(ranges.sum()) == 1          # contained slice reconciled away
    assert int(batch.ts[ranges][0]) == 200


def test_slice_primitives():
    T = make_table("ks", "s", pk=["k"], ck=["a", "b"],
                   cols={"k": "int", "a": "int", "b": "int", "v": "int"})
    enc = T.clustering_bytecomp
    full = lambda a, b: enc([a, b])
    sl = Slice(enc([1]), True, enc([1]), True, 50, 0)   # prefix a=1
    assert sl.covers_row(full(1, 1)) and sl.covers_row(full(1, 99))
    assert not sl.covers_row(full(2, 0)) and not sl.covers_row(full(0, 9))
    assert not sl.covers_row(b"")                        # static exempt
    sl2 = Slice(enc([1, 3]), False, enc([2]), True, 60, 0)
    assert not sl2.covers_row(full(1, 3))                # exclusive start
    assert sl2.covers_row(full(1, 4)) and sl2.covers_row(full(2, 7))
    assert covering_ts([sl, sl2], full(1, 4)) == 60
    big = Slice(enc([0]), True, enc([9]), True, 70, 0)
    assert big.contains(sl) and big.contains(sl2)
    assert not sl.contains(big)
