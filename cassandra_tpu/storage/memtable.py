"""Memtable: append-only columnar write buffer, sharded by token range.

Reference counterpart: db/memtable/Memtable.java:55 (pluggable interface;
put:193, getFlushSet:299) and TrieMemtable (whose core trick is the same
one used here: MEMTABLE SHARDS — TrieMemtable partitions its write state
into token-range shards so concurrent writers contend on a shard lock,
not a global one). The reference maintains a sorted structure per write;
the TPU-native design appends O(1) to columnar arrays and defers ALL
ordering to the batch sort at read/flush time — sorting is what the
device does best, and flush-time batch sort replaces per-write
comparisons entirely.

Sharding (the write fast lane, CTPU_WRITE_FASTPATH): each shard owns a
lock, a CellBatchBuilder and a per-partition hash index over a fixed
slice of the biased-token space, so N writers on different shards never
serialize. A partition's cells always land in exactly one shard (shard =
top bits of the biased token), and shard index order IS identity-lane
order — per-shard sorted batches concatenate into a globally sorted
batch, which is what the pipelined flush streams to the SSTableWriter
shard by shard. `apply_batch` takes each shard lock once per batch
instead of once per mutation. With the fast lane off the memtable
degrades to one shard — the exact serial structure it had before.
"""
from __future__ import annotations

import os
import threading

from ..schema import TableMetadata
from .cellbatch import (CellBatch, CellBatchBuilder, lanes_for_table,
                        merge_sorted, pk_lane_key)
from .commitlog import write_fastpath_enabled
from .mutation import Mutation

_BIAS = 1 << 63


def default_shard_count() -> int:
    """Shards for a new memtable: CTPU_MEMTABLE_SHARDS, else 8 with the
    write fast lane on, else 1 (serial reference behavior)."""
    env = os.environ.get("CTPU_MEMTABLE_SHARDS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return 8 if write_fastpath_enabled() else 1


class _Shard:
    """One token-range slice of the write state. All fields are guarded
    by `lock`; `version` increments per applied mutation so scan() can
    cache the shard's sorted view until it changes."""

    __slots__ = ("lock", "builder", "partitions", "live_bytes", "ops",
                 "version", "sorted_cache", "sorted_version")

    def __init__(self, table: TableMetadata):
        self.lock = threading.RLock()
        self.builder = CellBatchBuilder(table)
        self.partitions: dict[bytes, list[int]] = {}
        self.live_bytes = 0
        self.ops = 0
        self.version = 0
        self.sorted_cache: CellBatch | None = None
        self.sorted_version = -1


class Memtable:
    def __init__(self, table: TableMetadata, shards: int | None = None):
        self.table = table
        n = shards if shards is not None else default_shard_count()
        # power of two so shard selection is a shift of the biased token
        bits = 0
        while (1 << bits) < n:
            bits += 1
        self._shard_bits = bits
        self._shards = [_Shard(table) for _ in range(1 << bits)]
        self._scan_lock = threading.Lock()
        self._sorted_cache: CellBatch | None = None
        self._sorted_versions: tuple | None = None

    @property
    def shard_count(self) -> int:
        return len(self._shards)

    def _shard_index(self, pk: bytes) -> int:
        if not self._shard_bits:
            return 0
        from ..utils import partitioners
        biased = partitioners.token_of(pk) + _BIAS
        return biased >> (64 - self._shard_bits)

    def _shard_of(self, pk: bytes) -> _Shard:
        return self._shards[self._shard_index(pk)]

    def __len__(self):
        return sum(len(sh.builder) for sh in self._shards)

    @property
    def is_empty(self) -> bool:
        return all(len(sh.builder) == 0 for sh in self._shards)

    @property
    def live_bytes(self) -> int:
        return sum(sh.live_bytes for sh in self._shards)

    @property
    def ops(self) -> int:
        return sum(sh.ops for sh in self._shards)

    def partition_count(self) -> int:
        """Distinct partitions buffered (SSTableWriter bloom sizing)."""
        return sum(len(sh.partitions) for sh in self._shards)

    # ------------------------------------------------------------- write --

    @staticmethod
    def _apply_locked(sh: _Shard, mutation: Mutation) -> None:
        start = len(sh.builder)
        mutation.apply_to(sh.builder)
        end = len(sh.builder)
        if end == start:
            return
        lane4 = sh.builder._lanes[start][:4]
        key16 = b"".join(int(x).to_bytes(4, "big") for x in lane4)
        sh.partitions.setdefault(key16, []).extend(range(start, end))
        # note: all ops of one mutation share the partition (one pk)
        sh.live_bytes += mutation.size
        sh.ops += len(mutation.ops)
        sh.version += 1

    def apply(self, mutation: Mutation) -> None:
        sh = self._shard_of(mutation.pk)
        with sh.lock:
            self._apply_locked(sh, mutation)

    def apply_batch(self, mutations: list[Mutation]) -> None:
        """Apply a batch taking each involved shard lock ONCE — the
        memtable half of the batched write fast lane (coordinator /
        messaging / replay batches)."""
        by_shard: dict[int, list[Mutation]] = {}
        for m in mutations:
            by_shard.setdefault(self._shard_index(m.pk), []).append(m)
        # ascending shard order: a fixed acquisition order can never
        # deadlock against another batch (locks are taken one at a time
        # anyway; the order just keeps lock traffic predictable)
        for idx in sorted(by_shard):
            sh = self._shards[idx]
            with sh.lock:
                for m in by_shard[idx]:
                    self._apply_locked(sh, m)

    @staticmethod
    def _copy_rows(b: CellBatchBuilder, idxs, d: CellBatchBuilder) -> int:
        """Append rows `idxs` of builder `b` into builder `d` (the same
        row-copy _subset performs, but landing in another builder).
        Returns the payload bytes copied. Caller holds both shard
        locks."""
        copied = 0
        for i in idxs:
            frame = bytes(b._payload[b._value_off[i]:b._value_off[i + 1]])
            d._lanes.append(b._lanes[i])
            d._ts.append(b._ts[i])
            d._ldt.append(b._ldt[i])
            d._ttl.append(b._ttl[i])
            d._flags.append(b._flags[i])
            d._val_start.append(len(d._payload)
                                + (b._val_start[i] - b._value_off[i]))
            d._payload += frame
            d._value_off.append(len(d._payload))
            copied += len(frame)
        return copied

    def absorb(self, other: "Memtable") -> None:
        """Fold another memtable's buffered cells into this one — the
        flush FAILURE path: when the sstable write dies (EIO), the
        switched-out memtable is reinstated as active and the
        replacement's writes (applied while the doomed flush ran) are
        absorbed back so nothing acked is lost. Reconciliation is
        timestamp-based, so append order does not change read results.
        Caller must have quiesced writers on BOTH memtables (the
        ColumnFamilyStore holds its write barrier exclusively)."""
        for sh in other._shards:
            with sh.lock:
                b = sh.builder
                if not len(b):
                    continue
                for key16, idxs in sh.partitions.items():
                    pk = b.pk_map[key16]
                    dst = self._shard_of(pk)
                    with dst.lock:
                        d = dst.builder
                        start = len(d)
                        nbytes = self._copy_rows(b, idxs, d)
                        d._ck_fits = d._ck_fits and b._ck_fits
                        d.pk_map[key16] = pk
                        dst.partitions.setdefault(key16, []).extend(
                            range(start, len(d)))
                        dst.live_bytes += nbytes
                        dst.ops += len(idxs)
                        dst.version += 1

    # -------------------------------------------------------------- read --

    @staticmethod
    def _subset(sh: _Shard, indices: list[int]) -> CellBatch:
        b = sh.builder
        sub = CellBatchBuilder(b.table)
        for i in indices:
            lanes = b._lanes[i]
            frame = bytes(b._payload[b._value_off[i]:b._value_off[i + 1]])
            sub._lanes.append(lanes)
            sub._ts.append(b._ts[i])
            sub._ldt.append(b._ldt[i])
            sub._ttl.append(b._ttl[i])
            sub._flags.append(b._flags[i])
            sub._val_start.append(len(sub._payload)
                                  + (b._val_start[i] - b._value_off[i]))
            sub._payload += frame
            sub._value_off.append(len(sub._payload))
        sub.pk_map = b.pk_map
        return sub.seal()

    def contains(self, pk: bytes) -> bool:
        """O(1) partition-presence check (compaction purge guard)."""
        sh = self._shard_of(pk)
        with sh.lock:
            return pk_lane_key(pk) in sh.partitions

    def read_partition(self, pk: bytes) -> CellBatch | None:
        """The partition's cells, reconciled (newest versions only) —
        only the owning shard's lock is touched."""
        key16 = pk_lane_key(pk)
        sh = self._shard_of(pk)
        with sh.lock:
            idx = sh.partitions.get(key16)
            if not idx:
                return None
            return merge_sorted([self._subset(sh, idx)])

    def _shard_sorted(self, sh: _Shard) -> CellBatch:
        """Shard's sorted+reconciled view, cached until its next write.
        Caller holds sh.lock."""
        if sh.sorted_version != sh.version:
            sh.sorted_cache = merge_sorted([sh.builder.seal()])
            sh.sorted_version = sh.version
        return sh.sorted_cache

    def scan(self) -> CellBatch:
        """Whole memtable, sorted + reconciled (cached until next write).
        Shards cover disjoint ascending token ranges, so per-shard
        sorted views CONCATENATE into the global sorted order — no
        re-sort, and reconcile is partition-local so per-shard
        reconcile == global reconcile bit-for-bit."""
        with self._scan_lock:
            parts: list[CellBatch] = []
            versions = []
            for sh in self._shards:
                with sh.lock:
                    versions.append(sh.version)
                    parts.append(self._shard_sorted(sh))
            vt = tuple(versions)
            if self._sorted_cache is not None \
                    and self._sorted_versions == vt:
                return self._sorted_cache
            nonempty = [p for p in parts if len(p)]
            if not nonempty:
                out = CellBatch.empty(lanes_for_table(self.table))
                out.ck_comp = self.table.clustering_comp
            elif len(nonempty) == 1:
                out = nonempty[0]
            else:
                out = CellBatch.concat(nonempty)
                out.sorted = True
            self._sorted_cache = out
            self._sorted_versions = vt
            return out

    def scan_window(self, lo: int, hi: int) -> CellBatch:
        """Cells of partitions with token in (lo, hi] (paging windows)."""
        from .cellbatch import filter_token_range
        return filter_token_range(self.scan(), lo + 1 if lo > -(1 << 63)
                                  else lo, hi)

    # ------------------------------------------------------------- flush --

    def flush_batch(self) -> CellBatch:
        """Sorted, deduplicated cells for the flush writer
        (Memtable.getFlushSet / Flushing.writeSortedContents role)."""
        return self.scan()

    def flush_shards(self):
        """Yield per-shard sorted runs in ascending token order — the
        drain stage of the pipelined flush. LAZY on purpose: the flush
        pipeline runs this generator on a drain thread, so shard k+1's
        sort overlaps shard k's compress (native, GIL-released) and
        shard k-1's disk write (the writer's I/O thread). Call only on
        a RETIRED memtable (after the switch; no concurrent writes)."""
        for sh in self._shards:
            with sh.lock:
                if len(sh.builder):
                    yield self._shard_sorted(sh)
