#!/usr/bin/env python
"""CI check: write-path fast lane A/B — the same deterministic mutation
stream ingested with CTPU_WRITE_FASTPATH=0 (per-mutation inline fsync,
single-shard memtable, serial flush) and =1 (group-commit commitlog,
sharded memtable, pipelined flush) must produce IDENTICAL storage state.

The workload deliberately exercises every case the fast lane must not
change: plain writes across many partitions, overwrites, cell/row/
partition deletions, a range tombstone, TTL cells (explicit ldt so both
legs agree to the second), batched mutations through apply_batch,
mid-stream flushes (so sstables capture pipeline output), and a
simulated crash + commitlog replay (the data directory is copied while
the engine is live — exactly what a crash leaves — and recovered by a
fresh engine).

Compared per leg:
  - per-table content_digest of the fully merged view (scan_all) after
    flush_all — covers every reconcile-significant lane;
  - per-partition read_partition digests (the read path over the
    written state);
  - the same two digests again on the crash-replayed engine.

Run as a script (exit 1 on divergence) or through pytest
(tests/test_write_fastpath.py imports run_check).
"""
from __future__ import annotations

import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_PKS = 48
FIXED_NOW = 1_700_000_000          # merge clock (seconds), both legs
LDT = FIXED_NOW                    # deletion local-deletion-time


def _mutation_stream(t):
    """Deterministic list of (kind, payload) ops; kind 'm' = single
    mutation, 'b' = batch of mutations, 'f' = flush."""
    from cassandra_tpu.schema import (COL_PARTITION_DEL, COL_RANGE_TOMB,
                                      COL_ROW_DEL, COL_ROW_LIVENESS)
    from cassandra_tpu.storage.cellbatch import (FLAG_EXPIRING,
                                                 FLAG_PARTITION_DEL,
                                                 FLAG_RANGE_BOUND,
                                                 FLAG_ROW_DEL,
                                                 FLAG_ROW_LIVENESS,
                                                 FLAG_TOMBSTONE)
    from cassandra_tpu.storage.mutation import Mutation
    from cassandra_tpu.storage.rangetomb import Slice

    vcol = t.columns["v"].column_id
    ts0 = 1_000_000

    def write(pk_i, c, val, ts):
        m = Mutation(t.id, t.serialize_partition_key([pk_i]))
        ck = t.serialize_clustering([c])
        m.add(ck, COL_ROW_LIVENESS, b"", b"", ts, flags=FLAG_ROW_LIVENESS)
        m.add(ck, vcol, b"", val, ts)
        return m

    ops = []
    # round 0: base rows everywhere
    for k in range(N_PKS):
        for c in range(4):
            ops.append(("m", write(k, c, b"r0-%d-%d" % (k, c),
                                   ts0 + k * 10 + c)))
    ops.append(("f", None))
    # round 1: overwrites + deletions at every scope
    for k in range(0, N_PKS, 3):
        ops.append(("m", write(k, 1, b"r1-%d-1" % k, ts0 + 10_000 + k)))
    pd = Mutation(t.id, t.serialize_partition_key([2]))
    pd.add(b"", COL_PARTITION_DEL, b"", b"", ts0 + 20_000, ldt=LDT,
           flags=FLAG_PARTITION_DEL)
    ops.append(("m", pd))
    rd = Mutation(t.id, t.serialize_partition_key([3]))
    rd.add(t.serialize_clustering([1]), COL_ROW_DEL, b"", b"",
           ts0 + 20_001, ldt=LDT, flags=FLAG_ROW_DEL)
    ops.append(("m", rd))
    cd = Mutation(t.id, t.serialize_partition_key([4]))
    cd.add(t.serialize_clustering([2]), vcol, b"", b"", ts0 + 20_002,
           ldt=LDT, flags=FLAG_TOMBSTONE)
    ops.append(("m", cd))
    # range tombstone: pk 5, c > 1
    slc = Slice(t.clustering_bytecomp([1]), False, b"", False,
                ts0 + 20_003, LDT)
    rt = Mutation(t.id, t.serialize_partition_key([5]))
    rt.add(slc.start, COL_RANGE_TOMB, slc.encode_path(), b"",
           ts0 + 20_003, ldt=LDT,
           flags=FLAG_RANGE_BOUND | FLAG_TOMBSTONE)
    ops.append(("m", rt))
    ops.append(("f", None))
    # round 2: re-insert over the deleted partition + TTL cells with a
    # FIXED expiry second (no wall clock: legs must agree bit-for-bit)
    for c in range(2):
        ops.append(("m", write(2, c, b"r2-2-%d" % c, ts0 + 30_000 + c)))
    ttl_m = Mutation(t.id, t.serialize_partition_key([6]))
    ttl_m.add(t.serialize_clustering([9]), vcol, b"", b"ttl-live",
              ts0 + 30_010, ldt=FIXED_NOW + 3600, ttl=3600,
              flags=FLAG_EXPIRING)
    ttl_exp = Mutation(t.id, t.serialize_partition_key([6]))
    ttl_exp.add(t.serialize_clustering([10]), vcol, b"", b"ttl-dead",
                ts0 + 30_011, ldt=FIXED_NOW - 10, ttl=60,
                flags=FLAG_EXPIRING)
    ops.append(("b", [ttl_m, ttl_exp]))
    # batched writes (apply_batch: one commitlog barrier, one shard pass)
    batch = [write(k, 7, b"r2-%d-7" % k, ts0 + 40_000 + k)
             for k in range(0, N_PKS, 2)]
    ops.append(("b", batch))
    ops.append(("f", None))
    # memtable-only tail: lives only in the commitlog at "crash" time
    for k in range(8, 16):
        ops.append(("m", write(k, 8, b"tail-%d" % k, ts0 + 50_000 + k)))
    rd2 = Mutation(t.id, t.serialize_partition_key([9]))
    rd2.add(t.serialize_clustering([0]), COL_ROW_DEL, b"", b"",
            ts0 + 50_100, ldt=LDT, flags=FLAG_ROW_DEL)
    ops.append(("m", rd2))
    return ops


def _digests(engine, t) -> list[tuple[str, bytes]]:
    from cassandra_tpu.storage.cellbatch import content_digest
    cfs = engine.store("ab", "t")
    out = [("scan_all", content_digest(cfs.scan_all(now=FIXED_NOW)))]
    for k in range(N_PKS):
        pk = t.serialize_partition_key([k])
        out.append((f"pk={k}",
                    content_digest(cfs.read_partition(pk,
                                                      now=FIXED_NOW))))
    return out


def _run_leg(base_dir: str, fastpath: bool):
    """Ingest the stream, then return (live digests, sstable cell
    counts, crash-replayed digests)."""
    from cassandra_tpu.schema import Schema, make_table
    from cassandra_tpu.storage.engine import StorageEngine

    os.environ["CTPU_WRITE_FASTPATH"] = "1" if fastpath else "0"
    d = os.path.join(base_dir, "fast" if fastpath else "naive")
    schema = Schema()
    schema.create_keyspace("ab")
    t = make_table("ab", "t", pk=["id"], ck=["c"],
                   cols={"id": "int", "c": "int", "v": "blob"})
    schema.add_table(t)
    engine = StorageEngine(d, schema, commitlog_sync="group")
    engine._save_schema()
    cfs = engine.store("ab", "t")
    for kind, payload in _mutation_stream(t):
        if kind == "m":
            engine.apply(payload)
        elif kind == "b":
            engine.apply_batch(payload)
        else:
            cfs.flush()
    # crash snapshot BEFORE close: group/batch mode acked ⇒ durable, so
    # a byte-copy of the live directory is what a crash leaves behind
    crash = d + "-crash"
    shutil.copytree(d, crash)
    live = _digests(engine, t)
    cells = sorted((s.desc.generation, s.n_cells)
                   for s in cfs.live_sstables())
    engine.close()

    replayed = StorageEngine(crash, Schema(), commitlog_sync="group")
    rep = _digests(replayed, t)
    replayed.flush_all()
    rep_flushed = _digests(replayed, t)
    replayed.close()
    return live, cells, rep, rep_flushed


def run_check(base_dir: str) -> list[str]:
    """Run both legs over `base_dir`, return human-readable divergences
    (empty = pass)."""
    prev = os.environ.get("CTPU_WRITE_FASTPATH")
    try:
        naive = _run_leg(base_dir, fastpath=False)
        fast = _run_leg(base_dir, fastpath=True)
    finally:
        if prev is None:
            os.environ.pop("CTPU_WRITE_FASTPATH", None)
        else:
            os.environ["CTPU_WRITE_FASTPATH"] = prev
    diverged = []
    names = ("live state", "sstable cell counts", "crash replay",
             "crash replay + flush")
    for name, a, b in zip(names, naive, fast):
        if a != b:
            diverged.append(f"writepath fast lane diverged on {name}:\n"
                            f"  naive:    {a}\n  fastpath: {b}")
    return diverged


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="ctpu-writepath-ab-") as d:
        diverged = run_check(d)
    for msg in diverged:
        print(msg, file=sys.stderr)
    if diverged:
        print(f"FAIL: {len(diverged)} divergence(s)", file=sys.stderr)
        return 1
    print("writepath A/B: identical state (fastpath == naive), "
          "crash replay included")
    return 0


if __name__ == "__main__":
    sys.exit(main())
