"""Workload observatory (docs/observability.md layer 5): retained
metrics history (injected-clock determinism, ring eviction edges,
counter rates over ring wrap), per-table amplification accounting
(same bytes -> same WA/SA across every A/B leg of the data plane),
bounded compaction history, cluster-wide telemetry pulls (incl. the
dark-node staleness path), and the flight-recorder bundle's history
window + pipeline-ledger table."""
import json
import os
import time

import pytest

from cassandra_tpu.config import Config, Settings
from cassandra_tpu.service.history import MetricsHistoryService


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------ history rings --


def _svc(values: dict, clock=None, **kw):
    """A service with an injected clock and an injected capture source
    (the dict is read live, so tests mutate it between samples)."""
    kw.setdefault("raw_capacity", 6)
    kw.setdefault("raw_per_coarse", 3)
    kw.setdefault("coarse_capacity", 2)
    return MetricsHistoryService(clock=clock or _Clock(),
                                 collect_fn=lambda: dict(values), **kw)


def test_sample_downsample_query_round_trip():
    vals = {"x.counter": 1.0}
    clock = _Clock()
    svc = _svc(vals, clock)
    for v in (1.0, 5.0, 3.0):
        vals["x.counter"] = v
        clock.t += 10.0
        svc.sample()
    raw = svc.query("x.counter", "raw")
    assert [b["last"] for b in raw] == [1.0, 5.0, 3.0]
    assert all(b["min"] == b["max"] == b["last"] == b["sum"]
               and b["n"] == 1 for b in raw)
    assert [b["t1"] for b in raw] == [110.0, 120.0, 130.0]
    # 3 raw samples == raw_per_coarse: exactly one sealed coarse
    # bucket, min/max/last/sum/n-preserving
    coarse = svc.query("x.counter", "coarse")
    assert coarse == [{"t0": 110.0, "t1": 130.0, "min": 1.0,
                       "max": 5.0, "last": 3.0, "sum": 9.0, "n": 3}]
    assert svc.query("x.counter", "raw", limit=2) == raw[-2:]
    assert svc.query("nope", "raw") == []
    with pytest.raises(ValueError):
        svc.query("x.counter", "weekly")


def test_ring_eviction_edges_preserve_coarse_history():
    vals = {"x.c": 0.0}
    clock = _Clock()
    svc = _svc(vals, clock)
    for i in range(1, 9):   # 8 samples into a raw ring of 6
        vals["x.c"] = float(i)
        clock.t += 10.0
        svc.sample()
    raw = svc.query("x.c", "raw")
    assert [b["last"] for b in raw] == [3.0, 4.0, 5.0, 6.0, 7.0, 8.0]
    # coarse buckets sealed at samples 3 and 6 — the first one's raw
    # constituents (1, 2, 3) are PARTIALLY evicted from the raw ring,
    # yet the sealed bucket still carries them (fold-at-sample-time)
    coarse = svc.query("x.c", "coarse")
    assert [(b["min"], b["max"], b["sum"], b["n"]) for b in coarse] \
        == [(1.0, 3.0, 6.0, 3), (4.0, 6.0, 15.0, 3)]
    # coarse_capacity=2: a third sealed bucket evicts the oldest
    for i in range(9, 12):
        vals["x.c"] = float(i)
        clock.t += 10.0
        svc.sample()
    coarse = svc.query("x.c", "coarse")
    assert len(coarse) == 2
    assert coarse[0]["min"] == 4.0 and coarse[-1]["max"] == 9.0


def test_counter_rate_over_ring_wrap_and_reset():
    vals = {"c": 0.0}
    clock = _Clock()
    svc = _svc(vals, clock)
    for i in range(1, 11):   # 10 samples, ring keeps 6: wrapped
        vals["c"] = i * 20.0
        clock.t += 10.0
        svc.sample()
    rates = svc.rate("c")
    # rates only between RETAINED consecutive samples (5 pairs in a
    # 6-deep ring), each 20 units / 10 s = 2.0/s
    assert len(rates) == 5
    assert all(r["per_s"] == 2.0 for r in rates)
    # counter reset (engine restart): negative delta clamps to 0
    vals["c"] = 0.0
    clock.t += 10.0
    svc.sample()
    assert svc.rate("c")[-1]["per_s"] == 0.0
    assert svc.rate("nope") == []


def test_knob_wiring_and_zero_cost_off(tmp_path):
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    settings = Settings(Config())
    eng = StorageEngine(str(tmp_path), Schema(),
                        commitlog_sync="periodic", settings=settings)
    try:
        svc = eng.metrics_history
        # off by default: NO sampler thread exists (zero-cost rule)
        assert not svc.enabled
        before = [t.name for t in __import__("threading").enumerate()]
        assert "metrics-history" not in before
        settings.set("metrics_history_enabled", True)
        assert svc.enabled
        settings.set("metrics_history_interval", "50ms")
        assert svc.interval_s == 0.05
        deadline = time.time() + 5.0
        while time.time() < deadline and svc.samples < 2:
            time.sleep(0.02)
        assert svc.samples >= 2, "running sampler took no samples"
        settings.set("metrics_history_enabled", False)
        assert not svc.enabled
        # retained rings survive the disable
        assert svc.names()
    finally:
        eng.close()


# ------------------------------------------------- amplification A/B --


def _amplification_leg(base_dir, leg: str, monkeypatch) -> tuple:
    """One deterministic ingest->flush->compact run; returns the
    byte-counter tuple + derived WA/SA for identity comparison across
    data-plane legs."""
    from cassandra_tpu.schema import Schema, make_table
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.storage.mutation import Mutation

    overrides = {"compaction_throughput": 0}
    if leg == "naive":
        monkeypatch.setenv("CTPU_WRITE_FASTPATH", "0")
    else:
        monkeypatch.setenv("CTPU_WRITE_FASTPATH", "1")
    if leg == "mesh_pool":
        overrides["compaction_mesh_devices"] = 2
        overrides["compaction_compressor_threads"] = 2
    schema = Schema()
    schema.create_keyspace("amp")
    table = make_table("amp", "t", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "blob"})
    schema.add_table(table)
    eng = StorageEngine(os.path.join(base_dir, leg), schema,
                        commitlog_sync="periodic",
                        settings=Settings(Config.load(overrides)))
    try:
        cfs = eng.store("amp", "t")
        vcol = table.columns["v"].column_id
        for gen in range(3):
            muts = []
            for i in range(256):
                m = Mutation(table.id,
                             table.serialize_partition_key([i % 32]))
                m.add(table.serialize_clustering([gen * 256 + i]),
                      vcol, b"", bytes([i % 251]) * 64, 1_000_000 + i)
                muts.append(m)
            eng.apply_batch(muts)
            cfs.flush()
        eng.compactions.major_compaction(cfs)
        m = cfs.metrics
        amp = cfs.amplification()
        return ((m["bytes_ingested"], m["bytes_flushed"],
                 m["bytes_compacted_in"], m["bytes_compacted_out"]),
                (amp["write_amplification"],
                 amp["space_amplification"]))
    finally:
        eng.close()


@pytest.mark.slow
def test_amplification_identity_across_data_plane_legs(tmp_path,
                                                       monkeypatch):
    """Same bytes -> same WA/SA whichever leg of the data plane ran:
    the write fastpath off (serial flush), the default fast lane, and
    mesh-2 + compressor-pool-2. The byte counters ARE the gauges'
    only source, so A/B byte identity must make the gauges identical."""
    legs = {leg: _amplification_leg(str(tmp_path), leg, monkeypatch)
            for leg in ("fast", "naive", "mesh_pool")}
    counters = {leg: v[0] for leg, v in legs.items()}
    gauges = {leg: v[1] for leg, v in legs.items()}
    assert counters["fast"] == counters["naive"] == \
        counters["mesh_pool"], f"byte counters diverged: {counters}"
    assert gauges["fast"] == gauges["naive"] == gauges["mesh_pool"], \
        f"WA/SA diverged: {gauges}"
    assert gauges["fast"][0] > 0.0
    # a single post-major-compaction sstable has no overlap
    assert gauges["fast"][1] == 1.0


def test_amplification_reconciles_and_overlap_reads_above_one(
        tmp_path, monkeypatch):
    monkeypatch.setenv("CTPU_WRITE_FASTPATH", "1")
    from cassandra_tpu.schema import Schema, make_table
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.storage.mutation import Mutation
    schema = Schema()
    schema.create_keyspace("amp")
    table = make_table("amp", "t", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "blob"})
    schema.add_table(table)
    eng = StorageEngine(str(tmp_path), schema,
                        commitlog_sync="periodic",
                        settings=Settings(Config()))
    try:
        cfs = eng.store("amp", "t")
        vcol = table.columns["v"].column_id
        ingested = 0
        for gen in range(3):   # same keys every generation: overlap 3x
            muts = []
            for i in range(64):
                m = Mutation(table.id,
                             table.serialize_partition_key([i]))
                m.add(table.serialize_clustering([i]), vcol, b"",
                      b"x" * 32, 1_000_000 + gen)
                muts.append(m)
            for m in muts:
                ingested += m.size
            eng.apply_batch(muts)
            cfs.flush()
        m = cfs.metrics
        assert m["bytes_ingested"] == ingested
        amp = cfs.amplification()
        # 3 sstables holding the SAME 64 partitions: SA == 3 exactly
        assert amp["space_amplification"] == 3.0
        # no compaction ran yet: WA is flush-only
        assert amp["write_amplification"] == round(
            m["bytes_flushed"] / ingested, 6)
        assert m["bytes_compacted_in"] == 0
        stats = eng.compactions.major_compaction(cfs)
        assert m["bytes_compacted_in"] == stats["bytes_read"]
        assert m["bytes_compacted_out"] == stats["bytes_written"]
        amp = cfs.amplification()
        assert amp["space_amplification"] == 1.0
        assert amp["write_amplification"] == round(
            (m["bytes_flushed"] + m["bytes_compacted_out"])
            / ingested, 6)
        # the metrics vtable serves the same gauges
        rows = {r["name"]: r["value"] for r in
                eng.virtual_tables.get("system_views",
                                       "metrics").rows()}
        assert rows["table.amp.t.write_amplification"] == \
            amp["write_amplification"]
        assert rows["table.amp.t.space_amplification"] == 1.0
    finally:
        eng.close()


# ----------------------------------------- bounded compaction history --


def test_compaction_history_bounded_newest_kept(tmp_path):
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    settings = Settings(Config.load({"compaction_history_entries": 3}))
    eng = StorageEngine(str(tmp_path), Schema(),
                        commitlog_sync="periodic", settings=settings)
    try:
        from cassandra_tpu.schema import make_table
        eng.schema.create_keyspace("ks")
        cfs = eng.add_table(make_table(
            "ks", "t", pk=["k"], cols={"k": "int", "v": "text"}))
        for i in range(5):
            cfs.compaction_history.append({"marker": i})
        assert len(cfs.compaction_history) == 3
        assert [e["marker"] for e in cfs.compaction_history] \
            == [2, 3, 4]
        # hot-set rebinds live stores, newest kept
        settings.set("compaction_history_entries", 2)
        assert [e["marker"] for e in cfs.compaction_history] == [3, 4]
        # <= 0 = unbounded (the pre-bound behavior)
        settings.set("compaction_history_entries", 0)
        for i in range(500):
            cfs.compaction_history.append({"marker": i})
        assert len(cfs.compaction_history) == 502
    finally:
        eng.close()


# ------------------------------------------------- cluster telemetry --


def test_cluster_pull_with_dark_node(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    from cassandra_tpu.tools import nodetool
    c = LocalCluster(3, str(tmp_path), rf=3)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', "
                  "'replication_factor': 3}")
        s.execute("CREATE TABLE ks.t (k int PRIMARY KEY, v text)")
        c.node(1).default_cl = ConsistencyLevel.ALL
        s.keyspace = "ks"
        for i in range(16):
            s.execute(f"INSERT INTO ks.t (k, v) VALUES ({i}, 'v{i}')")
        out = nodetool.clusterstats(c.node(1), timeout=2.0)
        assert len(out["nodes"]) == 3
        assert out["keyspaces"]["ks"]["rf"] == 3
        assert all(r["fresh"] and r["snapshot"] for r in out["nodes"])
        by_ep = {r["endpoint"]: r for r in out["nodes"]}
        # replica-side writes visible per node (engine-scoped payload)
        assert by_ep["node3"]["snapshot"]["tables"]["ks.t"]["writes"] \
            >= 16
        assert by_ep["node2"]["snapshot"]["endpoint"] == "node2"
        # --- one node goes dark: bounded pull, staleness stamp
        c.stop_node(3)
        t0 = time.monotonic()
        out2 = nodetool.clusterstats(c.node(1), timeout=0.5)
        assert time.monotonic() - t0 < 5.0, "dark-node pull hung"
        row3 = {r["endpoint"]: r for r in out2["nodes"]}["node3"]
        assert row3["fresh"] is False
        assert row3["snapshot"] is not None   # last known snapshot
        assert row3["stale_s"] is not None and row3["stale_s"] > 0
        # the dispatch worker survived: traffic still flows (QUORUM)
        c.node(1).default_cl = ConsistencyLevel.QUORUM
        rs = s.execute("SELECT v FROM ks.t WHERE k = 3")
        assert len(list(rs)) == 1
        # and a repeat pull still answers
        out3 = nodetool.clusterstats(c.node(1), timeout=0.5)
        assert len(out3["nodes"]) == 3
    finally:
        c.shutdown()


# ------------------------------------------------- bundles & surfaces --


def test_flight_bundle_carries_history_window_and_ledger(tmp_path):
    from cassandra_tpu.schema import Schema, make_table
    from cassandra_tpu.storage.engine import StorageEngine
    eng = StorageEngine(str(tmp_path), Schema(),
                        commitlog_sync="periodic",
                        settings=Settings(Config()))
    try:
        eng.schema.create_keyspace("ks")
        cfs = eng.add_table(make_table(
            "ks", "t", pk=["k"], cols={"k": "int", "v": "text"}))
        from cassandra_tpu.storage.mutation import Mutation
        m = Mutation(cfs.table.id,
                     cfs.table.serialize_partition_key([1]))
        m.add(b"", cfs.table.columns["v"].column_id, b"", b"v",
              1_000_000)
        eng.apply(m)
        cfs.flush()
        # sampler knob OFF: the dump-time sample still guarantees a
        # non-empty window (the moment-of point)
        path = eng.flight_recorder.dump("test")
        with open(path) as fh:
            bundle = json.load(fh)
        win = bundle["metrics_history"]
        assert win and any(win.values())
        assert "table.ks.t.writes" in win
        assert "pipeline_ledger" in bundle
        # time-gated snapshots carry the ledger too
        assert "pipelines" in bundle["final"]
    finally:
        eng.close()


def test_metrics_history_vtable_and_nodetool(tmp_path):
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.tools import nodetool
    eng = StorageEngine(str(tmp_path), Schema(),
                        commitlog_sync="periodic",
                        settings=Settings(Config()))
    try:
        eng.metrics_history.sample()
        eng.metrics_history.sample()
        vt = eng.virtual_tables.get("system_views", "metrics_history")
        rows = vt.rows()
        assert rows
        raws = [r for r in rows if r["name"] == "history.samples"
                and r["resolution"] == "raw"]
        assert len(raws) == 2 and raws[-1]["last"] >= 1.0
        assert all(r["rate_per_s"] >= 0.0 for r in rows)
        st = nodetool.metricshistory(eng)
        assert st["samples"] == 2 and "history.samples" \
            in st["series_names"]
        one = nodetool.metricshistory(eng, name="history.samples",
                                      rate=True)
        assert len(one["buckets"]) == 2 and "rate_per_s" in one
    finally:
        eng.close()


def test_tablehistograms_latency_percentiles(tmp_path):
    from cassandra_tpu.cql import Session
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.storage.engine import StorageEngine
    from cassandra_tpu.tools import nodetool
    eng = StorageEngine(str(tmp_path), Schema(),
                        commitlog_sync="periodic",
                        settings=Settings(Config()))
    try:
        s = Session(eng)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', "
                  "'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE t (k int PRIMARY KEY, v text)")
        for i in range(16):
            s.execute(f"INSERT INTO t (k, v) VALUES ({i}, 'v{i}')")
        eng.store("ks", "t").flush()
        for i in range(16):
            s.execute(f"SELECT v FROM t WHERE k = {i}")
        th = nodetool.tablehistograms(eng, "ks", "t")["ks.t"]
        assert th["read_latency"]["count"] >= 16
        assert th["write_latency"]["count"] >= 16
        assert th["read_latency"]["p99_us"] > 0
        # sstables_per_read: every read consulted the one sstable
        assert th["sstables_per_read"]["count"] >= 16
        assert th["sstables_per_read"]["max"] >= 1.0
        # table filter actually filters
        assert nodetool.tablehistograms(eng, "ks", "nope") == {}
    finally:
        eng.close()
