"""Observability: end-to-end tracing, decaying metrics, device profiling.

Covers the ISSUE 2 acceptance surface: a traced multi-node read shows
coordinator AND replica events merged in one timeline (including a
dropped-message case), settraceprobability actually samples, the
decaying reservoir forgets old spikes, the exporter renders exposition
format, and the device profiler splits compile from execute.
"""
import time

import pytest

from cassandra_tpu.cluster.messaging import Verb
from cassandra_tpu.cluster.node import LocalCluster
from cassandra_tpu.cluster.replication import ConsistencyLevel
from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.service import profiling, tracing
from cassandra_tpu.service.metrics import (LatencyHistogram,
                                           MetricsRegistry,
                                           prometheus_text)
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.tools import nodetool


@pytest.fixture
def eng(tmp_path):
    e = StorageEngine(str(tmp_path / "d"), Schema(),
                      commitlog_sync="batch")
    yield e
    e.close()


@pytest.fixture
def cluster(tmp_path):
    c = LocalCluster(3, str(tmp_path), rf=3)
    for n in c.nodes:
        n.proxy.timeout = 1.0
    s = c.session(1)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 3}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    yield c
    c.shutdown()


# ------------------------------------------------------------- tracing --


def test_traced_read_merges_replica_events(cluster):
    """Coordinator + replica events land in ONE timeline: the session id
    propagates on READ_REQ, replicas record under their endpoint name,
    events ship back on the response and merge."""
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    rs = s.execute("SELECT v FROM kv WHERE k = 1", trace=True)
    assert rs.rows == [("x",)]
    sources = {src for _us, src, _a in rs.trace.events}
    # local coordinator events plus at least one replica's
    assert "local" in sources
    assert sources & {"node2", "node3"}, sources
    acts = [a for _us, _src, a in rs.trace.events]
    assert any("Sending READ_REQ" in a for a in acts)
    assert any("READ_REQ received from node1" in a for a in acts)
    # the session persisted to the coordinator's system_traces store
    assert cluster.node(1).trace_store.get(rs.trace.session_id)


def test_traced_write_replica_events(cluster):
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    s = cluster.session(1)
    s.keyspace = "ks"
    rs = s.execute("INSERT INTO kv (k, v) VALUES (9, 'w')", trace=True)
    acts = [a for _us, _src, a in rs.trace.events]
    assert any("Sending MUTATION_REQ" in a for a in acts)
    assert any("MUTATION_REQ received" in a for a in acts)
    # replica-side engine events recorded under the replica's name
    assert any(src in ("node2", "node3") and "commitlog" in a
               for _us, src, a in rs.trace.events)


def test_trace_drop_renders_failure_event(cluster):
    """MessageFilters.drop + replica timeout: the coordinator timeline
    still renders — local events intact plus the failure event — and
    nothing hangs."""
    n1 = cluster.node(1)
    n1.default_cl = ConsistencyLevel.ALL
    n1.proxy.timeout = 0.4
    s = cluster.session(1)
    s.keyspace = "ks"
    s.execute("INSERT INTO kv (k, v) VALUES (2, 'y')")
    victim = cluster.node(2).endpoint
    cluster.filters.drop(verb=Verb.READ_REQ, to=victim)
    try:
        with pytest.raises(Exception) as ei:
            s.execute("SELECT v FROM kv WHERE k = 2", trace=True)
        assert "Timeout" in type(ei.value).__name__ or \
            "timeout" in str(ei.value).lower()
    finally:
        cluster.filters.clear()
    # the failed request's timeline persisted anyway
    sessions = n1.trace_store.sessions()
    assert sessions, "trace of the failed read was lost"
    st = sessions[-1]
    acts = [a for _us, _src, a in st.events]
    assert any("Sending READ_REQ to node2" in a for a in acts)
    # the timeout event fires from the reaper shortly after the raise;
    # it merges into the session via the recent-tail registry
    deadline = time.time() + 5
    while time.time() < deadline:
        acts = [a for _us, _src, a in list(st.events)]
        if any("Failure/timeout" in a and "node2" in a for a in acts):
            break
        time.sleep(0.05)
    assert any("Failure/timeout" in a and "node2" in a for a in acts), acts


def test_settraceprobability_sampling(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    # p=0.0 (default): nothing sampled
    assert nodetool.gettraceprobability(eng) == {"trace_probability": 0.0}
    before = len(eng.trace_store.sessions())
    for i in range(5):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'a')")
    assert len(eng.trace_store.sessions()) == before
    # p=1.0: every statement samples into the store; the result set
    # stays untouched (no .trace attribute on background samples)
    nodetool.settraceprobability(eng, 1.0)
    rs = s.execute("SELECT * FROM kv WHERE k = 1")
    assert not hasattr(rs, "trace")
    got = len(eng.trace_store.sessions()) - before
    assert got >= 1
    stored = eng.trace_store.sessions()[-1]
    assert "SELECT" in stored.request
    # back to 0: sampling stops
    nodetool.settraceprobability(eng, 0.0)
    n = len(eng.trace_store.sessions())
    s.execute("SELECT * FROM kv WHERE k = 2")
    assert len(eng.trace_store.sessions()) == n
    with pytest.raises(ValueError):
        nodetool.settraceprobability(eng, 1.5)


def test_trace_vtables_and_gettraces(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    rs = s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')", trace=True)
    sid = rs.trace.session_id
    rows = s.execute("SELECT * FROM system_traces.sessions").dicts()
    assert any(r["session_id"] == sid for r in rows)
    evs = s.execute("SELECT * FROM system_traces.events "
                    f"WHERE session_id = '{sid}'").dicts()
    assert evs and all(e["session_id"] == sid for e in evs)
    assert any("commitlog" in e["activity"] for e in evs)
    out = nodetool.gettraces(eng)
    assert any(t["session_id"] == sid and t["events"] for t in out)


def test_slow_query_links_trace_session(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    eng.monitor.threshold_ms = 0.0   # everything is "slow"
    rs = s.execute("SELECT * FROM kv WHERE k = 1", trace=True)
    entries = eng.monitor.entries()
    linked = [e for e in entries if e.get("trace_session")]
    assert linked and linked[-1]["trace_session"] == rs.trace.session_id
    rows = s.execute("SELECT * FROM system_views.slow_queries").dicts()
    assert any(r["trace_session"] == rs.trace.session_id for r in rows)
    # untraced statements carry no link
    eng.monitor.threshold_ms = 0.0
    s.execute("SELECT * FROM kv WHERE k = 2")
    assert eng.monitor.entries()[-1]["trace_session"] is None


# ------------------------------------------------------------- metrics --


def test_decaying_histogram_forgets_old_spikes():
    clk = [0.0]
    h = LatencyHistogram(window_s=10.0, clock=lambda: clk[0])
    for _ in range(100):
        h.update_us(100)          # bucket 2^6
    h.update_us(1_000_000)        # the spike: bucket 2^19
    assert h.percentile(0.5) == 64.0
    assert h.max_us == 1_000_000
    assert h.summary()["p99_us"] >= 64.0
    # an hour later (way past 2 windows) the spike no longer pollutes
    clk[0] = 3600.0
    for _ in range(50):
        h.update_us(100)
    s = h.summary()
    assert s["p99_us"] == 64.0
    assert s["max_us"] == 100
    # lifetime count/mean are immortal
    assert s["count"] == 151
    assert h.count == 151


def test_snapshot_exports_all_percentiles_consistently():
    reg = MetricsRegistry()
    reg.incr("cql.select", 3)
    h = reg.hist("request.read")
    for us in (100, 200, 400, 800):
        h.update_us(us)
    snap = reg.snapshot()
    assert snap["cql.select"] == 3
    for suffix in ("count", "mean_us", "p50_us", "p95_us", "p99_us",
                   "max_us"):
        assert f"request.read.{suffix}" in snap
    assert snap["request.read.count"] == 4
    assert snap["request.read.max_us"] == 800


def test_metric_groups_and_gauges():
    reg = MetricsRegistry()
    g = reg.group("table.ks.kv")
    g.incr("writes", 2)
    with g.timer("write_latency"):
        pass
    assert reg.counter("table.ks.kv.writes") == 2
    assert reg.hist("table.ks.kv.write_latency").count == 1
    reg.register_gauge("cache.chunks.entries", lambda: 7)
    assert reg.snapshot()["cache.chunks.entries"] == 7
    reg.register_gauge("cache.bad.gauge", lambda: 1 / 0)
    assert "cache.bad.gauge" not in reg.snapshot()   # dead gauge skipped


def test_prometheus_exporter_format():
    reg = MetricsRegistry()
    reg.incr("cql.select", 5)
    reg.hist("request.read").update_us(512)
    reg.register_gauge("compaction.pending", lambda: 3)
    text = prometheus_text(reg, extra_gauges={"compaction.slots": 2})
    assert "# TYPE ctpu_cql_select counter" in text
    assert "ctpu_cql_select 5" in text
    assert 'ctpu_request_read_us{quantile="0.99"}' in text
    assert "ctpu_request_read_us_count 1" in text
    assert "# TYPE ctpu_compaction_pending gauge" in text
    assert "ctpu_compaction_slots 2" in text


def test_nodetool_exportmetrics(eng):
    from cassandra_tpu.service.metrics import GLOBAL
    GLOBAL.incr("storage.writes", 0)   # ensure at least one counter
    text = nodetool.exportmetrics(eng)
    assert "# TYPE ctpu_" in text
    assert text.endswith("\n")


def test_coordinator_request_latency_groups(cluster):
    from cassandra_tpu.service.metrics import GLOBAL
    s = cluster.session(1)
    s.keyspace = "ks"
    base_w = GLOBAL.hist("request.write").count
    base_r = GLOBAL.hist("request.read").count
    s.execute("INSERT INTO kv (k, v) VALUES (5, 'm')")
    s.execute("SELECT v FROM kv WHERE k = 5")
    assert GLOBAL.hist("request.write").count > base_w
    assert GLOBAL.hist("request.read").count > base_r
    # per-verb internode counters
    assert GLOBAL.counter("verb.read_req.received") >= 0


def test_metric_name_check_script():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "check_metric_names",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts",
            "check_metric_names.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.scan() == []            # the repo itself is clean
    assert mod.check_name("incr", "cql.request")
    assert mod.check_name("incr", "table.{ks}.{t}.writes")
    assert mod.check_name("hist", "read_latency")      # group member
    assert not mod.check_name("incr", "NoDots")
    assert not mod.check_name("incr", "Bad.Name")
    assert not mod.check_name("incr", "bad..name")


# ----------------------------------------------------------- profiling --


def test_kernel_profiler_splits_compile_from_execute():
    import numpy as np

    from cassandra_tpu.ops import merge as dmerge
    from cassandra_tpu.schema import make_table
    from cassandra_tpu.storage import cellbatch as cb
    from cassandra_tpu.tools import bulk
    profiling.GLOBAL.reset()
    table = make_table("ks", "kp", pk=["id"], ck=["c"],
                       cols={"id": "int", "c": "int", "v": "blob"})
    rng = np.random.default_rng(7)
    batches = []
    for _ in range(2):
        n = 512
        b = bulk.build_int_batch(
            table, rng.integers(0, 16, n), rng.integers(1, 50, n),
            rng.integers(0, 256, (n, 8), dtype=np.uint8),
            rng.integers(1, 1 << 40, n).astype(np.int64))
        batches.append(cb.merge_sorted([b]))
    a = dmerge.merge_sorted_device(batches)
    b2 = dmerge.merge_sorted_device(batches)
    assert len(a) == len(b2)
    snap = profiling.GLOBAL.snapshot()
    kernels = snap["kernels"]
    assert kernels, "no kernel recorded"
    name, k = next(iter(kernels.items()))
    assert name.startswith("merge.")
    assert k["calls"] == 2
    assert k["compiles"] == 1          # same shape: one compile only
    assert k["shapes"] == 1
    assert k["compile_s"] > 0
    assert k["execute_s"] > 0


def test_device_profile_vtable_and_phases(eng):
    profiling.GLOBAL.reset()
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    for gen in range(2):
        for i in range(20):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'g{gen}')")
        nodetool.flush(eng, "ks", "kv")
    res = nodetool.compact(eng, "ks", "kv")
    assert res
    rows = s.execute("SELECT * FROM system_views.device_profile").dicts()
    phases = {r["name"]: r for r in rows if r["kind"] == "phase"}
    # the pipelined writer's split phases from PR 1 feed the vtable
    assert "phase.compress" in phases
    assert "phase.io_write" in phases
    assert "phase.seal" in phases
    assert all(p["execute_seconds"] >= 0 for p in phases.values())
