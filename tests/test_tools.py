"""Virtual tables, metrics, tracing, nodetool, stress."""
import pytest

from cassandra_tpu.cql import Session
from cassandra_tpu.schema import Schema
from cassandra_tpu.storage.engine import StorageEngine
from cassandra_tpu.tools import nodetool, stress


@pytest.fixture
def eng(tmp_path):
    e = StorageEngine(str(tmp_path / "d"), Schema(), commitlog_sync="batch")
    yield e
    e.close()


def test_virtual_tables(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    for i in range(5):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'x')")
    eng.store("ks", "kv").flush()

    rs = s.execute("SELECT * FROM system.local")
    assert rs.dicts()[0]["partitioner"] == "Murmur3Partitioner"
    rs = s.execute("SELECT * FROM system_views.sstables")
    assert rs.dicts()[0]["table_name"] == "kv"
    assert rs.dicts()[0]["cells"] > 0
    rs = s.execute("SELECT name, value FROM system_views.metrics "
                   "WHERE name = 'table.ks.kv.writes'")
    assert rs.rows and rs.rows[0][1] >= 5.0


def test_tracing(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    rs = s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')", trace=True)
    acts = [a for _, _, a in rs.trace.events]
    assert any("commitlog" in a for a in acts)
    rs = s.execute("SELECT * FROM kv WHERE k = 1", trace=True)
    acts = [a for _, _, a in rs.trace.events]
    assert any("Merging" in a for a in acts)
    # untraced queries collect nothing
    rs = s.execute("SELECT * FROM kv WHERE k = 1")
    assert not hasattr(rs, "trace")


def test_nodetool(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    for gen in range(4):
        for i in range(10):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'g{gen}')")
        nodetool.flush(eng, "ks", "kv")
    ts = nodetool.tablestats(eng, "ks")
    assert ts["ks.kv"]["sstable_count"] == 4
    res = nodetool.compact(eng, "ks", "kv")
    assert res and res[0]["inputs"] == 4
    ts = nodetool.tablestats(eng, "ks")
    assert ts["ks.kv"]["sstable_count"] == 1
    cs = nodetool.compactionstats(eng)
    assert cs["completed_tasks"] >= 1 and cs["active_tasks"] == 0
    assert nodetool.info(eng)["tables"]["ks.kv"]["sstables"] == 1


def test_stress(eng):
    s = Session(eng)
    r = stress.write(s, 200)
    assert r["ops_s"] > 0
    r = stress.read(s, 100, keys=200)
    assert r["hits"] == 100
    r = stress.mixed(s, 100)
    assert r["n"] == 100


def test_nodetool_status_on_cluster(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    c = LocalCluster(3, str(tmp_path))
    try:
        st = nodetool.status(c.node(1))
        assert len(st) == 3
        assert all(r["status"] == "UN" for r in st)
        assert len(nodetool.ring(c.node(1))) == 12  # 3 nodes x 4 vnodes
        s = c.session(1)
        rs = s.execute("SELECT * FROM system.peers")
        assert len(rs.rows) == 2
    finally:
        c.shutdown()


def test_snapshots(tmp_path):
    from cassandra_tpu.storage import snapshot as snap
    eng = StorageEngine(str(tmp_path / "sn"), Schema(),
                        commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    for i in range(10):
        s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'v{i}')")
    cfs = eng.store("ks", "kv")
    cfs.flush()
    tag = snap.snapshot(cfs, "backup1")
    assert tag == "backup1"
    assert snap.list_snapshots(cfs)[0]["files"]
    # destroy the live table, restore from snapshot
    cfs.truncate()
    assert s.execute("SELECT * FROM kv").rows == []
    snap.restore_snapshot(cfs, "backup1")
    assert len(s.execute("SELECT * FROM kv").rows) == 10
    assert snap.clear_snapshot(cfs) == 1
    eng.close()


def test_guardrails(tmp_path):
    from cassandra_tpu.storage.guardrails import GuardrailViolation
    eng = StorageEngine(str(tmp_path / "gr"), Schema(),
                        commitlog_sync="batch")
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int, c int, v text, PRIMARY KEY (k, c))")
    # tombstone-overwhelming read fails
    eng.guardrails.tombstones_fail_per_read = 50
    for c in range(100):
        s.execute(f"INSERT INTO kv (k, c, v) VALUES (1, {c}, 'x')")
        s.execute(f"DELETE FROM kv WHERE k = 1 AND c = {c}")
    with pytest.raises(GuardrailViolation):
        s.execute("SELECT * FROM kv WHERE k = 1")
    # huge batches fail
    eng.guardrails.batch_statements_fail = 3
    with pytest.raises(GuardrailViolation):
        s.execute("BEGIN BATCH " + " ".join(
            f"INSERT INTO kv (k, c, v) VALUES (2, {i}, 'y');"
            for i in range(5)) + " APPLY BATCH")
    # table-count cap
    eng.guardrails.tables_fail_threshold = 2
    with pytest.raises(GuardrailViolation):
        s.execute("CREATE TABLE another (k int PRIMARY KEY)")
    eng.close()


def test_nodetool_cleanup_reclaims_foreign_ranges(tmp_path):
    """After a topology change, cleanup drops cells this node no longer
    replicates (CompactionManager.performCleanup role)."""
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.cluster.replication import ConsistencyLevel
    from cassandra_tpu.tools import nodetool
    c = LocalCluster(2, str(tmp_path), rf=1, gossip_interval=0.05)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        c.node(1).default_cl = ConsistencyLevel.ALL
        for i in range(40):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'x')")
        for n in c.nodes:
            n.engine.store("ks", "kv").flush()
        # grow the cluster: old nodes now hold ranges the new node owns
        c.add_node()
        rep1 = nodetool.cleanup(c.node(1), "ks")
        rep2 = nodetool.cleanup(c.node(2), "ks")
        assert sum(r["cells_dropped"] for r in rep1 + rep2) > 0
        # all data still readable (the new owner has its copies)
        got = {r[0] for r in s.execute("SELECT k FROM kv").rows}
        assert got == set(range(40))
        # second cleanup: nothing left to drop
        assert nodetool.cleanup(c.node(1), "ks") == []
    finally:
        c.shutdown()


def test_nodetool_info_commands(tmp_path):
    from cassandra_tpu.cluster.node import LocalCluster
    from cassandra_tpu.tools import nodetool
    c = LocalCluster(2, str(tmp_path), rf=2, gossip_interval=0.05)
    try:
        s = c.session(1)
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 2}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        eps = nodetool.getendpoints(c.node(1), "ks", "kv", "7")
        assert len(eps) == 2
        # the key converts by COLUMN TYPE: a text pk '7' must tokenize
        # as the stored utf8 bytes, matching where the write path put it
        s.execute("CREATE TABLE txt (k text PRIMARY KEY, v int)")
        s.execute("INSERT INTO txt (k, v) VALUES ('7', 1)")
        text_eps = nodetool.getendpoints(c.node(1), "ks", "txt", "7")
        strat_token = c.node(1).ring.token_of(b"7")
        from cassandra_tpu.cluster.replication import ReplicationStrategy
        strat = ReplicationStrategy.create(
            c.node(1).schema.keyspaces["ks"].params.replication)
        want = [e.name for e in strat.replicas(c.node(1).ring, strat_token)]
        assert text_eps == want
        # composite partition key: ':'-separated components, framed the
        # same way the write path frames them
        s.execute("CREATE TABLE comp (a int, b text, c int, "
                  "PRIMARY KEY ((a, b), c))")
        comp_eps = nodetool.getendpoints(c.node(1), "ks", "comp", "1:x")
        t = c.node(1).schema.get_table("ks", "comp")
        want = [e.name for e in strat.replicas(
            c.node(1).ring,
            c.node(1).ring.token_of(t.serialize_partition_key([1, "x"])))]
        assert comp_eps == want
        with pytest.raises(ValueError):
            nodetool.getendpoints(c.node(1), "ks", "comp", "1")
        gi = nodetool.gossipinfo(c.node(1))
        assert "node2" in gi
        dc = nodetool.describecluster(c.node(1))
        assert dc["partitioner"] == "Murmur3Partitioner"
        assert len(dc["endpoints"]) == 2
        assert nodetool.version()["cql"]
    finally:
        c.shutdown()


def test_nodetool_cleanup_single_token_ring_is_noop(tmp_path):
    """One node, ONE token: its lone (t, t] arc is the FULL ring, so
    cleanup must keep every cell — not interpret the degenerate range
    as empty and wipe the node."""
    from cassandra_tpu.cluster.node import Node
    from cassandra_tpu.cluster.ring import Endpoint, Ring
    from cassandra_tpu.schema import Schema
    from cassandra_tpu.tools import nodetool

    ep = Endpoint("n1", host="127.0.0.1", port=0)
    ring = Ring()
    ring.add_node(ep, [0])                      # num_tokens = 1
    from cassandra_tpu.cluster.messaging import LocalTransport
    node = Node(ep, str(tmp_path), Schema(), ring, LocalTransport(),
                seeds=[ep], gossip_interval=10.0)
    node.cluster_nodes = [node]
    try:
        s = node.session()
        s.execute("CREATE KEYSPACE ks WITH replication = "
                  "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        s.execute("USE ks")
        s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
        for i in range(20):
            s.execute(f"INSERT INTO kv (k, v) VALUES ({i}, 'x')")
        node.engine.store("ks", "kv").flush()
        assert nodetool.cleanup(node, "ks") == []   # nothing dropped
        got = {r[0] for r in s.execute("SELECT k FROM kv").rows}
        assert got == set(range(20))
    finally:
        node.engine.close()


def test_slow_query_monitor(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int PRIMARY KEY, v text)")
    nodetool.setslowquerythreshold(eng, 0.0)   # everything is "slow"
    s.execute("INSERT INTO kv (k, v) VALUES (1, 'x')")
    s.execute("SELECT * FROM kv WHERE k = 1")
    entries = eng.monitor.entries()
    assert any("SELECT" in e["query"] for e in entries)
    rs = s.execute("SELECT query, duration_ms FROM "
                   "system_views.slow_queries")
    assert rs.rows and all(r[1] >= 0 for r in rs.rows)
    nodetool.setslowquerythreshold(eng, 10_000.0)
    n = len(eng.monitor.entries())
    s.execute("SELECT * FROM kv WHERE k = 1")
    assert len(eng.monitor.entries()) == n     # under threshold


def test_upgradesstables_and_split(eng):
    s = Session(eng)
    s.execute("CREATE KEYSPACE ks WITH replication = "
              "{'class': 'SimpleStrategy', 'replication_factor': 1}")
    s.execute("USE ks")
    s.execute("CREATE TABLE kv (k int, c int, v text, "
              "PRIMARY KEY (k, c))")
    for k in range(40):
        for c in range(5):
            s.execute(f"INSERT INTO kv (k, c, v) VALUES ({k}, {c}, "
                      f"'{'x' * 100}')")
    eng.store("ks", "kv").flush()
    rep = nodetool.upgradesstables(eng, "ks", "kv")
    assert rep and rep[0]["to_generation"] != rep[0]["from_generation"]
    assert len(s.execute("SELECT * FROM kv").rows) == 200

    # split the (single) sstable into tiny chunks
    rep = nodetool.sstablesplit(eng, "ks", "kv", target_mib=0)
    [r] = rep
    assert len(r["outputs"]) >= 2
    assert len(eng.store("ks", "kv").live_sstables()) == len(r["outputs"])
    assert len(s.execute("SELECT * FROM kv").rows) == 200
    # every output holds whole partitions (no partition straddles files)
    seen = {}
    for sst in eng.store("ks", "kv").live_sstables():
        for tok in sst.partition_tokens:
            assert seen.setdefault(int(tok), sst.desc.generation) \
                == sst.desc.generation
